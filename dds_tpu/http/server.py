"""REST proxy: the 23-route encrypted query engine.

Counterpart of `dds/http/DDSRestServer.scala:153-948` — same 23 route
names, parameters, JSON shapes and status codes (plus additions of ours:
GET /_trace and the Prism encrypted-analytics family POST /MatVec,
/WeightedSum, /GroupBySum — see dds_tpu/analytics) — rebuilt around two
TPU-first ideas the reference lacks:

- all ciphertext arithmetic goes through the pluggable `CryptoBackend`
  (cpu | tpu); aggregate folds (`SumAll`, `MultAll`) become ONE batched
  tree-reduction over (K, limbs) tensors instead of K sequential
  BigInteger multiplies (`DDSRestServer.scala:412-430, 505-524`);
- storage access goes through the asyncio `AbdClient` quorum functions
  (core/quorum_client.py = `fetchSet`/`writeSet`, `:952-1050`).

Like the reference, the proxy is computation-only: it sees ciphertexts and
per-request public parameters (`nsqr`, `pubkey`), never keys. The other
side of that boundary is enforced too: decryption — the only computation
that touches key material — lives client-side on the Sanctum secret plane
(`dds_tpu/sanctum`), which the shared `CryptoBackend`/`ModCtx` machinery
this server compiles against can no longer carry even by accident
(`PaillierKey.decrypt_batch` refuses public backends;
`tools/secret_lint.py` rejects new flows statically).

Reference quirks deliberately FIXED (SURVEY.md §7 "replicate or fix"):
- `SumAll`/`MultAll`/`Search*` used `length-1 > position`, making the last
  column unreachable; we use `position < length` like `Sum`/`Mult` do.
- `SearchEntry` compared the JSON wrapper's string (`item.toString`)
  instead of the value; we compare the value.
- `SearchEntryAND` matched on 3 *distinct stored values*; we require each
  of the three query values to match (a real conjunction).
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import math
import ssl
import time
from dataclasses import dataclass, field
from typing import Optional

from dds_tpu.core.admission import (AdaptiveCoalescer, AdmissionController,
                                    TokenBucket)
from dds_tpu.core.errors import (
    AllBreakersOpenError,
    ByzantineError,
    WrongShardError,
)
from dds_tpu.core.quorum_client import AbdClient
from dds_tpu.core.tenant import (CANARY_TENANT, DEFAULT_TENANT, TenantError,
                                 validate_tenant)
from dds_tpu.http import json_protocol as J
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.http.miniserver import HttpServer, Request, Response, http_request
from dds_tpu.models.backend import CryptoBackend, get_backend
from dds_tpu.obs import context as obs_context
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import SIZE_BUCKETS, metrics
from dds_tpu.obs.slo import SloEngine
from dds_tpu.obs.watchtower import watchtower
from dds_tpu.utils import sigs
from dds_tpu.utils.retry import (
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
    retry_deadline,
)
from dds_tpu.utils.trace import tracer
from dds_tpu.utils.trust import NoTrustedNodesError

log = logging.getLogger("dds.rest")

# The per-request time budget, minted once in handle() and read by every
# nested storage helper (_fetch/_write/_fetch_stored and their audits) —
# deadline PROPAGATION without threading a parameter through 23 routes.
_REQ_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "dds_request_deadline", default=None
)

# The current request's validated tenant (Bastion) — set in handle() next
# to the deadline, read by the ownership checks and the data-plane helpers
# so tenancy needs no parameter threading through 23 routes either.
_REQ_TENANT: contextvars.ContextVar = contextvars.ContextVar(
    "dds_request_tenant", default=DEFAULT_TENANT
)

# transient storage-layer failures worth retrying; anything else (a
# programming error, a bad request) propagates immediately.
# WrongShardError is the Constellation fence: the router refreshes its
# shard map and the retry re-resolves the owner — during a live reshard
# the op stalls inside its Deadline budget until the new map activates,
# then lands on the new group. Never a silent misroute.
_RETRYABLE = (ByzantineError, WrongShardError, asyncio.TimeoutError,
              NoTrustedNodesError, OSError)

# Observability/control routes stay admission-exempt: operators must be
# able to see WHY the system is shedding while it sheds, so /health,
# /metrics, /slo, /shards (and the debug-gated /_trace, and the Meridian
# reshard control route) bypass the Bulwark gate entirely and keep
# answering through a full shed.
_ADMISSION_EXEMPT = frozenset({"health", "metrics", "slo", "shards",
                               "fleet", "profile", "_trace", "_reshard",
                               "_helmsman", "canary"})


@dataclass
class ProxyConfig:
    host: str = "127.0.0.1"
    port: int = 8443
    # Atlas ([fabric] region): the region this proxy process runs in,
    # surfaced on /health so operators (and the geo drills) see which
    # regional vantage a probe answers from
    region: str = ""
    # Deadline-propagated retry (utils/retry): every request gets ONE
    # overall budget minted at the REST edge; quorum attempts + exponential
    # full-jitter backoffs retry inside it, per-attempt timeouts shrink to
    # the remainder, and exhaustion degrades to 503 + Retry-After instead
    # of hanging. retry_backoff is the backoff BASE; retry_attempts > 0
    # restores a hard attempt cap on top (0 = deadline-governed, the
    # chaos-tolerant default).
    request_budget: float = 8.0
    retry_backoff: float = 0.3
    retry_max_delay: float = 2.0
    retry_attempts: int = 0
    # seconds clients should wait before retrying after a 503 (the
    # Retry-After header on every degraded response)
    retry_after_hint: float = 1.0
    # miniserver backstop (0 = off): cancels handlers that somehow outlive
    # the budget — OFF by default because ciphertext compute (device folds,
    # cold compiles) legitimately runs past the STORAGE budget
    handler_timeout: float = 0.0
    crypto_backend: str = "cpu"
    # tag-validated aggregate cache (see _fetch_stored): one batched
    # tag-only quorum round validates all cached sets per aggregate instead
    # of K full ABD re-reads. Off = reference behavior
    # (`DDSRestServer.scala:397-446` re-reads every set, cache-less).
    aggregate_cache: bool = True
    # per-aggregate audit sample: this many cache-served keys are also
    # re-read through a full quorum (random coordinator); any
    # non-corroborated mismatch flushes the cache. Bounds how long a
    # Byzantine COORDINATOR's forgery (valid proxy HMAC over a forged
    # value + the true tag) can persist — without the audit a forged entry
    # would keep validating by tag alone. The bound is probabilistic, and
    # deliberately so: full reads trust a single random coordinator, as the
    # reference's do (`DDSRestServer.scala:952-1000`), so a coordinator
    # holding the proxy secret can always poison the ONE read it serves;
    # what the cache must not add is *persistence*. Even with f colluding
    # coordinators defeating one corroboration round, a forged entry
    # survives future audits only until one samples it through an honest
    # coordinator. Quantified bound (Monte-Carlo-checked in
    # tests/test_tag_cache.py::test_audit_persistence_bound_monte_carlo):
    # detection per aggregate round is geometric with
    #   p = (audit/K) * (n-f)/n
    # (sampled AND audited through an honest coordinator), so expected
    # persistence = K/audit * n/(n-f) rounds — at K=8192, audit=2, n=4,
    # f=1: ~5,461 aggregate rounds; audit=4 halves it, 8 quarters it.
    # Measured throughput cost of raising it: benchmarks/audit_cost.py
    # (each audit key adds one full ABD read per aggregate).
    aggregate_cache_audit: int = 2
    # proxy->proxy key gossip (DDSRestServer.scala:118-136)
    key_sync_enabled: bool = False
    key_sync_warmup: float = 1.0
    key_sync_interval: float = 5.0
    peers: list[str] = field(default_factory=list)  # "host:port"
    # Cross-request fold coalescing: concurrent SumAll/MultAll folds that
    # individually sit below the backend's device-batch crossover are
    # gathered for coalesce_window seconds and dispatched as ONE segmented
    # device fold (ops/foldmany), amortizing dispatch latency R ways. A
    # group of one falls back to the plain host path, so the window only
    # ever costs latency when there is something to gain. 0 disables.
    coalesce_window: float = 0.002
    # stored_keys durability. The reference keeps the aggregate key set
    # in-memory only (`DDSRestServer.scala:70`), so a proxy restart makes
    # every aggregate silently shrink until re-population — flagged as a
    # do-not-copy quirk (SURVEY.md §7). Two recovery sources, both opt-in:
    # - keys_path: JSON snapshot, written atomically (debounced ~200 ms
    #   after a mutation burst) and loaded at start();
    # - a one-shot GET /_sync pull from each gossip peer at start() (gated
    #   with key_sync_enabled), covering proxies deployed without a disk.
    # The set only names which records aggregates cover — values still come
    # from the replicated store through full quorum reads, so a stale
    # snapshot can at worst omit recent keys until gossip catches up, never
    # serve stale data.
    keys_path: str = ""
    # GET /_trace observability route. Default OFF: it reveals workload
    # shape (route counts, latencies, store size) to anyone who can reach
    # the client-facing listener — the reference gates observability
    # behind debug flags too (dds-system.conf:61-62). launch() enables it
    # for debug deployments.
    trace_route_enabled: bool = False
    # GET /metrics (Prometheus text, obs/metrics). Default ON: scrapers
    # are how the "production-scale" posture monitors this thing, and the
    # aggregated series reveal far less workload shape than /_trace's
    # per-span stats. Deployments that must hide even rates can turn it
    # off (config `obs.metrics_route = false`).
    metrics_route_enabled: bool = True
    # GET /slo (per-route objectives + error-budget burn state, plus the
    # Watchtower audit summary). Default ON for the same reason /metrics
    # is: it is the health surface operators page on, and it reveals no
    # more workload shape than the per-route metric series already do.
    slo_route_enabled: bool = True
    # GET /profile (Chronoscope per-route/per-stage pipe profile +
    # slow-trace exemplars, obs/chronoscope). Default ON like /slo — the
    # per-stage aggregate reveals less workload shape than /_trace; the
    # DDS_OBS_PIPE=0 env kill-switch disables profiling itself.
    profile_route_enabled: bool = True
    # Prism encrypted-analytics routes (analytics/prism.py): POST /MatVec,
    # /WeightedSum, /GroupBySum evaluate plaintext-weight x ciphertext
    # products server-side over public parameters only. The row cap bounds
    # per-request kernel work (DDS_ANALYTICS_MAX_ROWS env overrides it;
    # ops/flags.analytics_max_rows validates whichever wins); the byte cap
    # 413s oversized weight payloads before JSON parsing.
    analytics_enabled: bool = True
    analytics_max_rows: int = 256
    analytics_max_request_bytes: int = 1 << 20
    # Bulwark admission control (core/admission): an AdmissionConfig-shaped
    # object (utils/config.AdmissionConfig, or any duck-typed twin) with
    # enabled=True arms per-tenant/per-class token buckets and the
    # SLO-burn shedding ratchet at the edge — rejections answer 429/503 in
    # microseconds, BEFORE a Deadline is minted. None/disabled = the
    # pre-Bulwark behavior (every request admitted).
    admission: object = None
    # Bastion multi-tenancy (core/tenant, models/tenancy): a TenancyConfig-
    # shaped object with enabled=True makes the x-dds-tenant header an
    # isolation boundary — per-tenant key ownership (cross-tenant access
    # answers a typed 403), tenant-striped Lodestone pools and Spyglass
    # indexes, tenant-filtered aggregates/analytics, weighted-fair
    # admission with burn-driven per-tenant shedding, and per-tenant
    # SLO/usage attribution. None/disabled = the single-tenant behavior
    # byte-for-byte (every plane call maps to the anonymous "" stripe).
    tenancy: object = None
    # Lodestone resident ciphertext plane (dds_tpu/resident): a
    # ResidentConfig-shaped object with enabled=True pins per-shard-group
    # ciphertext limb pools device-side, ingests committed writes off the
    # request path, and turns sharded SumAll/MultAll into ONE fused
    # gather+fold dispatch instead of S per-group marshaling folds.
    # None/disabled = the pre-Lodestone paths exactly.
    resident: object = None
    # Spyglass encrypted search plane (dds_tpu/search): a SearchConfig-
    # shaped object with enabled=True serves Search*/Order*/Range from
    # per-group device-resident DET/OPE column indexes — ONE batched tag
    # round + one predicate kernel dispatch per query instead of the
    # legacy full-keyspace scan. None/disabled = the legacy scan exactly.
    search: object = None
    # Stratum tiered ciphertext storage (dds_tpu/storage): a
    # StorageConfig-shaped object with enabled=True layers a host-pinned
    # warm cache and an HMAC'd log-structured segment store under the
    # Lodestone pools, replacing capacity resets with eviction-to-warm
    # and splitting folds into resident + streamed-from-tier legs.
    # None/disabled = Lodestone-only behavior exactly.
    storage: object = None
    # active-replica refresh from supervisor (DDSRestServer.scala:139-147)
    replica_refresh_interval: float = 5.0
    supervisor: Optional[str] = None
    # Meridian (dds_tpu/fabric): cap on the `wait` a /shards long-poll may
    # request (If-None-Match + ?wait=N gossip — see the shards route), and
    # the POST /_reshard operator route gate (enabled on proxies launched
    # with a fabric controller; drives a cross-host Rebalancer.split)
    shards_wait_cap: float = 60.0
    reshard_route_enabled: bool = False
    # Heliograph active canary plane (dds_tpu/obs/heliograph): a
    # HeliographConfig-shaped object with enabled=True runs a supervised
    # prober owning the reserved __heliograph__ tenant, driving verified
    # golden transactions against this proxy's own edge (and any
    # configured targets). None/disabled = no prober; canary-tagged
    # traffic is still clamped + rate-bounded at the edge either way.
    heliograph: object = None
    ssl_server_context: object = None
    ssl_client_context: object = None


async def _cancel_task(task: asyncio.Task) -> None:
    """Cancel a background task and swallow its CancelledError."""
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass


class DDSRestServer:
    def __init__(self, abd: AbdClient, config: ProxyConfig | None = None,
                 local_replicas: dict | None = None,
                 slo: SloEngine | None = None,
                 gossip=None, reshard=None, fleet=None, helmsman=None):
        self.abd = abd
        self.cfg = config or ProxyConfig()
        # Meridian wiring: `gossip` is an EpochGossipHub parked /shards
        # long-polls sleep on (None = conditional GETs answer immediately);
        # `reshard` is the reshard controller behind POST /_reshard (gated
        # by reshard_route_enabled) — either an object with async
        # `split(source, target)` / `merge(source)` plus `retry_after()`
        # and `phase`, or a bare legacy split callable; `fleet` is the
        # Panopticon FleetCollector serving GET /fleet/* (None everywhere
        # but a fleet-enabled proxy role — the routes 404); `helmsman` is
        # the fleet autoscaler (report in /health, pin via /_helmsman)
        self._gossip = gossip
        self._reshard = reshard
        self._fleet = fleet
        self.helmsman = helmsman
        # one plan at a time: the in-flight (action, source, target) and
        # its task — identical repeats attach to it (idempotent), any
        # other reshape answers 409 + a phase-derived Retry-After
        self._reshard_inflight: dict | None = None
        # per-route SLO accounting (obs/slo): every request is classified
        # good/bad in handle(); run.launch passes an engine built from the
        # [obs] config, tests get the defaults
        self.slo = slo or SloEngine()
        # endpoint -> BFTABDNode for replicas hosted in THIS process (the
        # live dict from run.launch — redeploys mutate it in place), so
        # /health and /metrics can export the Aegis recovery surface:
        # anti-entropy divergence/sync age and snapshot generation/age
        self.local_replicas = local_replicas
        self.backend: CryptoBackend = get_backend(self.cfg.crypto_backend)
        self.stored_keys: set[str] = set()
        # key -> (tag, value): every entry comes from a COMPLETED quorum op
        # (read with write-back, or write), so value@tag is known to be
        # written to a full quorum — the invariant the tag-validation read
        # path relies on for linearizability.
        self._cache: dict[str, tuple] = {}
        # versions + memos for the aggregate hot path: between writes the
        # per-request O(K) bookkeeping (sorted keys, digests, fingerprints,
        # pairs/operand lists) is identical, so it is computed once per
        # (stored_keys, cache) state and reused. The tag-validation quorum
        # round and the audit still run on EVERY aggregate — the memos skip
        # recomputation, never revalidation.
        self._stored_version = 0   # bumps on stored_keys add/discard/sync
        self._cache_version = 0    # bumps when a cached (tag, value) changes
        self._agg_memo: tuple | None = None    # state -> keys/cached/digest/fp
        self._pairs_memo: tuple | None = None  # state -> [(key, value)] result
        self._operand_memo: tuple | None = None  # pairs identity -> operands
        self._http = HttpServer(
            self.cfg.host, self.cfg.port, self.handle, self.cfg.ssl_server_context,
            handler_timeout=self.cfg.handler_timeout,
        )
        self._tasks: list[asyncio.Task] = []
        self._keys_dirty = False
        self._keys_saver: asyncio.Task | None = None
        # modulus -> [(enqueue_t, operands, future, waiter trace ctx)];
        # drained by _drain_folds
        self._fold_pending: dict[int, list] = {}
        self._fold_drainer: asyncio.Task | None = None
        self._folds_inflight = 0  # folds currently executing (any path)
        # Constellation: a ShardRouter (duck-typed via its shard_manager)
        # turns point routes into one-group ops and aggregates into
        # scatter-gather per-shard folds; a plain AbdClient leaves every
        # path exactly as before
        self._shards = getattr(abd, "shard_manager", None)
        self._scatter_memo: tuple | None = None  # pairs identity -> shard operands
        self._owner_memo: tuple | None = None    # pairs identity -> (gid, ops)
        # Lodestone (dds_tpu/resident): per-group device-resident pools +
        # the fused single-dispatch sharded fold. Built from the
        # ResidentConfig-shaped cfg.resident; None when disabled — every
        # gate below is a cheap is-None check. The plane rides the
        # backend's kernel family/mesh when the backend exposes them
        # (TpuBackend.resident_plane); host backends get the portable
        # jnp plane (same math, same single dispatch).
        rescfg = self.cfg.resident
        self._resident = None
        self._resident_min_fold = 0
        self._resident_write_ingest = False
        self._resident_ingest_window = 0.005
        self._ingest_task: asyncio.Task | None = None
        if rescfg is not None and getattr(rescfg, "enabled", False):
            initial = getattr(rescfg, "initial_rows", 256)
            max_rows = getattr(rescfg, "max_rows", 65536)
            if hasattr(self.backend, "resident_plane"):
                self._resident = self.backend.resident_plane(initial, max_rows)
            else:
                from dds_tpu.resident import ResidentPlane

                self._resident = ResidentPlane(
                    initial_rows=initial, max_rows=max_rows
                )
            mf = getattr(rescfg, "min_fold", 0)
            self._resident_min_fold = (
                mf if mf > 0 else getattr(self.backend, "min_device_batch", 0)
            )
            self._resident_write_ingest = getattr(rescfg, "write_ingest", True)
            self._resident_ingest_window = max(
                0.0, getattr(rescfg, "ingest_window", 0.005)
            )
            group_ids = getattr(self.abd, "group_ids", None)
            if group_ids is not None:
                # deterministic group -> mesh-slice placement up front
                self._resident.register_groups(group_ids())
        # Spyglass (dds_tpu/search): per-group search indexes over the
        # DET/OPE column families, written from the request path (queued,
        # debounced — the Lodestone ingest pattern) and validated per
        # query with one batched read_tags round. None when disabled —
        # every Search*/Order*/Range gate below is a cheap is-None check
        # that falls through to the legacy scan.
        scfg = self.cfg.search
        self._search = None
        self._search_write_ingest = False
        self._search_ingest_window = 0.005
        self._search_ingest_task: asyncio.Task | None = None
        if scfg is not None and getattr(scfg, "enabled", False):
            from dds_tpu.search import SearchPlane

            self._search = SearchPlane(
                max_pending=getattr(scfg, "max_pending", 8192)
            )
            self._search_write_ingest = getattr(scfg, "write_ingest", True)
            self._search_ingest_window = max(
                0.0, getattr(scfg, "ingest_window", 0.005)
            )
            group_ids = getattr(self.abd, "group_ids", None)
            if group_ids is not None:
                self._search.register_groups(group_ids())
        # Stratum (dds_tpu/storage): the tier planner under Lodestone.
        # Built only when a resident plane exists — the hot tier IS the
        # pool; attach() rewires pool overflow from reset to eviction
        # and routes folds through the hot+warm+cold split. None when
        # disabled — every gate below is a cheap is-None check.
        stcfg = self.cfg.storage
        self._stratum = None
        if (
            stcfg is not None
            and getattr(stcfg, "enabled", False)
            and self._resident is not None
        ):
            from dds_tpu.storage import Stratum

            self._stratum = Stratum(
                self._resident,
                getattr(stcfg, "dir", "./stratum"),
                warm_bytes=getattr(stcfg, "warm_bytes", 64 << 20),
                chunk_rows=getattr(stcfg, "chunk_rows", 256),
                promote_score=getattr(stcfg, "promote_score", 2.0),
                max_promote=getattr(stcfg, "max_promote", 256),
                half_life=getattr(stcfg, "half_life", 60.0),
                keep=getattr(stcfg, "keep", 3),
                compact_segments=getattr(stcfg, "compact_segments", 8),
            )
            if self._search is not None:
                # Spyglass selections feed the tier directory: keys a
                # query keeps finding hold their fold rows hot
                self._search.touch_sink = self._stratum.touch_keys
        # Prism analytics engine (analytics/prism): same backend, same
        # public-parameter boundary; sharded proxies hand it the router's
        # owner resolver so weighted folds scatter-gather like SumAll,
        # and the resident plane so MatVec operands gather from pinned
        # rows instead of re-marshaling host ints
        if self.cfg.analytics_enabled:
            from dds_tpu.analytics import Prism
            from dds_tpu.ops.flags import analytics_max_rows

            self.prism: Prism | None = Prism(
                backend=self.backend,
                max_rows=analytics_max_rows(self.cfg.analytics_max_rows),
                owner=(self.abd.owner if self._shards is not None else None),
                resident=self._resident,
            )
        else:
            self.prism = None
        self._column_memo: tuple | None = None  # pairs identity -> columns
        # Bulwark (core/admission): the admission gate + shed ratchet, fed
        # by the SLO engine's burn alerts and the storage layer's breaker
        # census; and the adaptive coalescing window sized from observed
        # fold arrivals. Both None when admission is off — every gate
        # below is a cheap is-None check.
        # Bastion (core/tenant + models/tenancy): tenancy makes the
        # validated x-dds-tenant header an isolation boundary. The server
        # holds NO tenant keys (the TenantKeyring is client-side, like the
        # Sanctum decrypt plane) — its tenancy surface is ownership
        # enforcement (typed 403s), plane striping, tenant-filtered
        # aggregates, and attribution. `_tenant_owner` maps each stored
        # key to the tenant whose PutSet claimed it; it persists inside
        # the stored-keys snapshot (backward-compatible: legacy list
        # snapshots load as ownerless keys).
        tcfg = self.cfg.tenancy
        self._tenancy_enabled = bool(
            tcfg is not None and getattr(tcfg, "enabled", False)
        )
        self._tenant_owner: dict[str, str] = {}
        self._tenant_pairs_memo: dict[str, tuple] = {}
        acfg = self.cfg.admission
        self.admission: AdmissionController | None = None
        self._coalescer: AdaptiveCoalescer | None = None
        if acfg is not None and getattr(acfg, "enabled", False):
            self.admission = AdmissionController.from_config(
                acfg, alerts=self.slo.alerts, breakers=self._breaker_census,
                tenancy=(tcfg if self._tenancy_enabled else None),
            )
            if getattr(acfg, "adaptive_coalesce", True) and self.cfg.coalesce_window > 0:
                self._coalescer = AdaptiveCoalescer(
                    base_window=self.cfg.coalesce_window,
                    max_window=getattr(acfg, "coalesce_max_window", 0.02),
                    target_folds=getattr(acfg, "coalesce_target_folds", 8.0),
                )
        # Heliograph (obs/heliograph): the prober itself starts in
        # start() (it needs the resolved listen port), but the canary
        # admission carve-out exists UNCONDITIONALLY: anything claiming
        # the __heliograph__ identity bypasses tenant-fair admission yet
        # passes this dedicated bucket, so neither a wedged prober nor an
        # outsider squatting on the canary tenant can self-DoS the edge
        # (the reserved id grants zero data access beyond the canary's
        # own keyspace — see _tenant_pairs).
        hcfg = self.cfg.heliograph
        self.heliograph = None
        self._canary_bucket = TokenBucket(
            float(getattr(hcfg, "rate", 20.0) or 20.0),
            float(getattr(hcfg, "burst", 40.0) or 40.0),
        )
        # keys the canary tenant owns, tracked in BOTH tenancy modes: the
        # aggregate/search/analytics planes must never fold canary rows
        # into user answers (nor user rows into canary ground truth —
        # that scoping is what makes decrypt-and-compare sound).
        self._canary_keys: set[str] = set()

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        self._load_keys()
        await self._http.start()
        self.cfg.port = self._http.port  # resolve OS-assigned port 0
        if self.cfg.key_sync_enabled and self.cfg.peers:
            await self._bootstrap_keys_from_peers()
            self._tasks.append(supervised_task(self._key_sync_loop(),
                                               name="proxy.key_sync"))
        if self.cfg.supervisor:
            if self.abd.cfg.supervisor is None:
                self.abd.cfg.supervisor = self.cfg.supervisor  # pin ActiveReplicas source
            self._tasks.append(supervised_task(self._replica_refresh_loop(),
                                               name="proxy.replica_refresh"))
        if self.admission is not None:
            self._tasks.append(supervised_task(self._admission_loop(),
                                               name="proxy.admission"))
        hcfg = self.cfg.heliograph
        if hcfg is not None and getattr(hcfg, "enabled", False):
            # deferred import: the prober pulls the whole client crypto
            # stack, which most deployments (and tests) never need
            from dds_tpu.obs.heliograph import Heliograph

            self.heliograph = Heliograph(
                hcfg, self._canary_targets(hcfg), slo=self.slo,
                watchtower=watchtower,
                ssl_context=self.cfg.ssl_client_context,
            )
            self.heliograph.start()

    def _canary_targets(self, hcfg) -> list:
        """Probe targets: this proxy's own loopback edge first (the
        resolved port — start() runs after the listener binds), then any
        configured "host:port" / "region=host:port" entries — per-region
        / per-group targeting for fleets."""
        from dds_tpu.clt.canary import CanaryTarget, parse_canary_targets

        host = self.cfg.host
        if host in ("0.0.0.0", "::", ""):
            host = "127.0.0.1"
        targets = [CanaryTarget(host, self.cfg.port,
                                region=self.cfg.region or "")]
        extra, bad = parse_canary_targets(getattr(hcfg, "targets", []))
        for entry in bad:
            log.warning("heliograph: skipping malformed target %r", entry)
        return targets + extra

    async def stop(self) -> None:
        if self.heliograph is not None:
            self.heliograph.stop()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks.clear()
        if self._fold_drainer is not None and not self._fold_drainer.done():
            # resolve queued folds before teardown so no request future is
            # orphaned and no task outlives the server
            await _cancel_task(self._fold_drainer)
            err = ConnectionError("proxy stopping")
            for _, group in self._fold_pending.items():
                for _, _, fut, _ in group:
                    if not fut.done():
                        fut.set_exception(err)
            self._fold_pending.clear()
            self._fold_drainer = None
        if self._ingest_task is not None:
            await _cancel_task(self._ingest_task)
            self._ingest_task = None
        if self._search_ingest_task is not None:
            await _cancel_task(self._search_ingest_task)
            self._search_ingest_task = None
        if self._keys_saver is not None:
            await _cancel_task(self._keys_saver)
            self._keys_saver = None
        if self._keys_dirty:
            self._write_keys_snapshot()  # flush pending mutations on shutdown
        await self._http.stop()

    # ------------------------------------------------- stored_keys recovery

    def _load_keys(self) -> None:
        if not self.cfg.keys_path:
            return
        import json as _json
        import pathlib

        p = pathlib.Path(self.cfg.keys_path)
        if not p.exists():
            return
        try:
            keys = _json.loads(p.read_text())
        except (OSError, ValueError) as e:
            log.warning("ignoring unreadable stored-keys snapshot %s: %s", p, e)
            return
        owners = {}
        if isinstance(keys, dict):
            # Bastion snapshot shape: {"keys": [...], "tenants": {key: t}}
            owners = keys.get("tenants") or {}
            keys = keys.get("keys")
        if not isinstance(keys, list):  # hand-edited / corrupted snapshot
            log.warning("ignoring malformed stored-keys snapshot %s", p)
            return
        for k in keys:
            if isinstance(k, str):
                self.stored_keys.add(k)
        if isinstance(owners, dict):
            for k, t in owners.items():
                if isinstance(k, str) and isinstance(t, str):
                    self._tenant_owner[k] = t
        self._stored_version += 1
        log.info("recovered %d stored keys from %s", len(self.stored_keys), p)

    def _write_keys_snapshot(self) -> None:
        """Atomic write (tmp + rename): a crash mid-write must leave the
        previous snapshot intact, not a truncated JSON file."""
        import json as _json
        import os
        import pathlib

        self._keys_dirty = False
        p = pathlib.Path(self.cfg.keys_path)
        if self._tenant_owner:
            # ownership rides the snapshot: a restarted proxy must keep
            # refusing cross-tenant access to keys written before the crash
            body = {"keys": sorted(self.stored_keys),
                    "tenants": dict(self._tenant_owner)}
        else:
            body = sorted(self.stored_keys)  # legacy shape, byte-identical
        try:
            p.parent.mkdir(parents=True, exist_ok=True)
            tmp = p.with_name(p.name + ".tmp")
            tmp.write_text(_json.dumps(body))
            os.replace(tmp, p)
        except OSError as e:
            log.warning("stored-keys snapshot to %s failed: %s", p, e)

    def _save_keys_soon(self) -> None:
        """Debounced snapshot: coalesce a PutSet burst into one write."""
        if not self.cfg.keys_path:
            return
        self._keys_dirty = True
        if self._keys_saver is not None and not self._keys_saver.done():
            return

        async def _saver():
            while self._keys_dirty:
                await asyncio.sleep(0.2)
                # off-loop: a large stored_keys set must not stall request
                # handling during the write (stop() keeps the synchronous
                # call — the loop is tearing down anyway)
                await asyncio.to_thread(self._write_keys_snapshot)

        self._keys_saver = supervised_task(_saver(), name="proxy.keys_saver")

    async def _bootstrap_keys_from_peers(self) -> None:
        """One-shot key pull at start: a restarted proxy must not wait for
        a peer's next gossip push to see the store's aggregate keys.
        Pulls run concurrently so N dead peers cost one timeout, not N;
        any failure is opportunistic-best-effort — it must never turn a
        recovery optimization into a boot failure."""

        async def pull(peer: str) -> None:
            host, _, port = peer.partition(":")
            try:
                status, body = await http_request(
                    host, int(port), "GET", "/_sync",
                    ssl_context=self.cfg.ssl_client_context, timeout=5.0,
                )
                if status != 200:
                    return
                import json as _json

                before = len(self.stored_keys)
                for k in J.parse_keys(_json.loads(body)):
                    self._note_stored(k)
                log.info(
                    "bootstrapped %d stored keys from peer %s",
                    len(self.stored_keys) - before, peer,
                )
            except (OSError, ValueError, EOFError, asyncio.TimeoutError) as e:
                # EOFError covers IncompleteReadError (peer closed mid-body)
                log.debug("stored-keys bootstrap from %s failed: %s", peer, e)

        await asyncio.gather(*(pull(p) for p in self.cfg.peers))

    async def _key_sync_loop(self) -> None:
        await asyncio.sleep(self.cfg.key_sync_warmup)
        while True:
            for peer in self.cfg.peers:
                host, _, port = peer.partition(":")
                try:
                    import json as _json

                    await http_request(
                        host,
                        int(port),
                        "POST",
                        "/_sync",
                        _json.dumps(J.keys_result(sorted(self.stored_keys))).encode(),
                        ssl_context=self.cfg.ssl_client_context,
                        timeout=5.0,
                    )
                except ssl.SSLError as e:
                    # loud: under mutual TLS this usually means the peer has
                    # a different CA (per-node dev certs on a multi-host
                    # deployment — see SecurityConfig.tls_ca)
                    log.warning("key-sync peer %s TLS failure: %s", peer, e)
                except OSError:
                    log.debug("key-sync peer %s unreachable", peer)
                except asyncio.TimeoutError:
                    log.debug("key-sync peer %s timed out", peer)
            await asyncio.sleep(self.cfg.key_sync_interval)

    async def _replica_refresh_loop(self) -> None:
        while True:
            self.abd.refresh_from(self.cfg.supervisor)
            await asyncio.sleep(self.cfg.replica_refresh_interval)

    # ----------------------------------------------------------- ABD access

    def _request_deadline(self) -> Deadline:
        """The current request's budget; helpers invoked outside a request
        context (tests, background tasks) get a fresh full budget."""
        dl = _REQ_DEADLINE.get()
        return dl if dl is not None else Deadline(self.cfg.request_budget)

    def _retry_policy(self) -> RetryPolicy:
        attempts = self.cfg.retry_attempts
        return RetryPolicy(
            base=self.cfg.retry_backoff,
            max_delay=self.cfg.retry_max_delay,
            max_attempts=(attempts + 1) if attempts > 0 else None,
        )

    async def _retry(self, f, deadline: Deadline):
        return await retry_deadline(
            f, deadline, self._retry_policy(), retry_on=_RETRYABLE
        )

    def _cache_put(self, key: str, tag, value) -> None:
        """Remember a completed op's (tag, value); newest tag wins (two
        interleaved ops on one key may resolve out of order here)."""
        if tag is None or not self.cfg.aggregate_cache:
            return
        cur = self._cache.get(key)
        if cur is None or cur[0] < tag:
            self._cache[key] = (tag, value)
            self._cache_version += 1

    def _flush_cache(self) -> None:
        self._cache.clear()
        self._cache_version += 1
        if self._search is not None:
            # the search index inherits the cache's completed-op trust
            # argument, so an audit-triggered flush voids it too: the next
            # query rebuilds every entry from full quorum reads
            self._search.invalidate()

    def _note_stored(self, key: str) -> None:
        if key not in self.stored_keys:
            self.stored_keys.add(key)
            self._stored_version += 1
            self._save_keys_soon()

    # ------------------------------------------------------ Bastion tenancy

    def _req_tenant(self) -> str:
        """The current request's validated tenant (helpers invoked outside
        a request context — tests, background tasks — read the default)."""
        return _REQ_TENANT.get()

    def _plane_tenant(self, tenant: str | None = None) -> str:
        """Tenant id as the data planes see it: the default tenant maps to
        the anonymous "" stripe, so single-tenant deployments keep their
        pool keys, group indexes, and gauge label sets byte-identical."""
        if not self._tenancy_enabled:
            return ""
        t = tenant if tenant is not None else _REQ_TENANT.get()
        return "" if t == DEFAULT_TENANT else t

    def _key_tenant(self, key: str) -> str | None:
        """The tenant a key belongs to. Stored keys without an ownership
        record are legacy (pre-Bastion) data and belong to the default
        tenant; keys neither recorded nor stored are unclaimed (None) —
        free for any tenant's first write to claim."""
        t = self._tenant_owner.get(key)
        if t is not None:
            return t
        return DEFAULT_TENANT if key in self.stored_keys else None

    def _note_owner(self, key: str) -> None:
        """Record the writing tenant as `key`'s owner (first writer wins;
        _tenant_denied refuses the write before this runs otherwise).
        Canary ownership is tracked in BOTH tenancy modes: the visibility
        scoping in `_tenant_pairs` / `_tenant_stored_keys` depends on it
        (canary rows must never pollute user aggregates, untenanted
        deployments included)."""
        tenant = _REQ_TENANT.get()
        if tenant == CANARY_TENANT and key not in self._canary_keys:
            self._canary_keys.add(key)
            self._tenant_pairs_memo.clear()
        if not self._tenancy_enabled:
            return
        if self._tenant_owner.get(key) != tenant:
            self._tenant_owner[key] = tenant
            self._tenant_pairs_memo.clear()
            self._save_keys_soon()

    def _tenant_denied(self, *keys: str) -> Response | None:
        """Typed 403 when the request's tenant owns none of `keys` it
        touches; None admits. Unclaimed keys admit (a first PutSet claims
        one; reads of a nonexistent key 404 as always); stored keys
        without a record are legacy data under the default tenant, and
        nowhere else. The refusal is explicit and attributed: requests
        are NEVER silently served another tenant's ciphertexts."""
        if not self._tenancy_enabled:
            return None
        tenant = _REQ_TENANT.get()
        for key in keys:
            owner = self._key_tenant(key)
            if owner is not None and owner != tenant:
                metrics.inc(
                    "dds_tenant_denied_total", tenant=tenant,
                    help="cross-tenant key accesses refused with 403",
                )
                flight.record("tenant_denied", tenant=tenant, key=key)
                return Response.json(
                    {"error": "cross-tenant access denied",
                     "tenant": tenant, "key": key},
                    status=403,
                )
        return None

    def _tenant_pairs(self, pairs: list[tuple[str, list]]) -> list:
        """The aggregate/search view filtered to the request tenant's own
        records (tenancy off = the full view, same list identity — every
        downstream pairs-identity memo stays warm). Memoized per (tenant,
        pairs identity): between writes each tenant's filtered view is
        state-identical, and its stable identity is what the operand and
        column memos key on."""
        tenant = _REQ_TENANT.get()
        if not self._tenancy_enabled:
            # Heliograph scoping without Bastion: the canary tenant sees
            # exactly its own population (what makes decrypt-and-compare
            # exact) and everyone else sees everything BUT it. With no
            # canary keys stored this is the identical list object —
            # every pre-Heliograph memo identity stays warm.
            if tenant != CANARY_TENANT and not self._canary_keys:
                return pairs
            memo = self._tenant_pairs_memo.get(tenant)
            if memo is not None and memo[0] is pairs:
                return memo[1]
            ck = self._canary_keys
            if tenant == CANARY_TENANT:
                filtered = [(k, v) for k, v in pairs if k in ck]
            else:
                filtered = [(k, v) for k, v in pairs if k not in ck]
            self._tenant_pairs_memo[tenant] = (pairs, filtered)
            return filtered
        memo = self._tenant_pairs_memo.get(tenant)
        if memo is not None and memo[0] is pairs:
            return memo[1]
        own = self._key_tenant
        filtered = [(k, v) for k, v in pairs if own(k) == tenant]
        self._tenant_pairs_memo[tenant] = (pairs, filtered)
        return filtered

    def _tenant_stored_keys(self) -> list[str]:
        """Sorted stored keys scoped to the request tenant (the Spyglass
        query universe); tenancy off = all stored keys, as before."""
        tenant = _REQ_TENANT.get()
        if not self._tenancy_enabled:
            ck = self._canary_keys
            if tenant == CANARY_TENANT:
                return sorted(k for k in self.stored_keys if k in ck)
            if not ck:
                return sorted(self.stored_keys)
            return sorted(k for k in self.stored_keys if k not in ck)
        own = self._key_tenant
        return sorted(k for k in self.stored_keys if own(k) == tenant)

    def _agg_state(self):
        """(state, keys, cached, digest, fingerprint, cached_tags) for the
        current aggregate view, memoized per (stored, cache) version."""
        state = (self._stored_version, self._cache_version)
        memo = self._agg_memo
        if memo is not None and memo[0] == state:
            return memo
        keys = sorted(self.stored_keys)
        cached = [k for k in keys if k in self._cache]
        cached_tags = [self._cache[k][0] for k in cached]
        digest = sigs.key_from_set(cached)
        fp = sigs.tags_fingerprint(cached_tags)
        self._agg_memo = (state, keys, cached, digest, fp, cached_tags)
        return self._agg_memo

    async def _fetch_tagged(self, key: str, exclude=()):
        dl = self._request_deadline()
        value, tag, coord = await self._retry(
            lambda: self.abd.fetch_set_attributed(key, exclude, deadline=dl), dl
        )
        self._cache_put(key, tag, value)
        return value, tag, coord

    async def _fetch(self, key: str):
        return (await self._fetch_tagged(key))[0]

    async def _write(self, key: str, value):
        dl = self._request_deadline()
        k, tag = await self._retry(
            lambda: self.abd.write_set_tagged(key, value, deadline=dl), dl
        )
        self._cache_put(key, tag, value)
        self._note_resident_write(key, value)
        self._note_search_write(key, tag, value)
        return k

    # --------------------------------------------- Lodestone write ingest

    def _note_resident_write(self, key: str, value) -> None:
        """Queue a committed write's ciphertext columns for resident-pool
        ingest (dds_tpu/resident) — OFF the request's critical path,
        coalesced like folds — so a warm fleet's first post-write
        aggregate gathers every row device-side with zero ingest.
        Content addressing keeps this unconditionally safe: the full
        quorum read still decides which ciphertexts fold; the pool only
        pre-pays their limb conversion + transfer."""
        plane = self._resident
        if plane is None or not self._resident_write_ingest or not value:
            return
        ciphers = []
        for col in value:
            if isinstance(col, bool):
                continue
            if isinstance(col, int):
                ciphers.append(col)
            elif isinstance(col, str):
                try:
                    ciphers.append(int(col))
                except ValueError:
                    continue  # non-numeric column: never an aggregate operand
        if not ciphers:
            return
        gid = self.abd.owner(key) if self._shards is not None else ""
        tenant = self._plane_tenant()
        if self._stratum is not None:
            # popularity signal only (pure dict math, loop-safe): the
            # rewrite of a tiered row warms its directory score so the
            # next fold promotes it instead of streaming it, and the
            # key->cipher mapping lets later Spyglass hits do the same
            self._stratum.note_write(gid, ciphers, tenant=tenant, key=key)
        if plane.note_write(gid, ciphers, tenant=tenant):
            self._resident_ingest_soon()

    def _resident_ingest_soon(self) -> None:
        """Debounced drain: coalesce a write burst into few ingest
        dispatches (the _save_keys_soon pattern), each on a worker
        thread so limb conversion never stalls request handling."""
        if self._ingest_task is not None and not self._ingest_task.done():
            return

        async def _drain():
            while self._resident.pending_ingest():
                await asyncio.sleep(self._resident_ingest_window)
                await asyncio.to_thread(self._resident.ingest_pending)

        self._ingest_task = supervised_task(_drain(),
                                            name="proxy.resident_ingest")

    def tier_pressure(self) -> float:
        """Blended hot+warm occupancy in [0, 1] for Helmsman's
        pool-pressure signal: how close the fullest pool is to its
        max_rows, or the warm cache to its byte budget, whichever is
        tighter. 0.0 when Stratum is disabled — the autoscaler then
        steers on burn/queue alone, exactly as before."""
        if self._stratum is None:
            return 0.0
        try:
            return float(self._stratum.pressure())
        except Exception:
            return 0.0

    # ----------------------------------------- Spyglass encrypted search

    def _note_search_write(self, key: str, tag, value) -> None:
        """Queue a committed write's (tag, value) for search-index upsert
        (dds_tpu/search) — OFF the request path, like the resident
        ingest. value None (RemoveSet) becomes a tombstone so the index
        never resurrects a deleted record. A full queue is safe: the key
        just reads stale at the next query and is repaired there."""
        plane = self._search
        if plane is None or not self._search_write_ingest:
            return
        gid = self.abd.owner(key) if self._shards is not None else ""
        if plane.note_write(gid, key, tag, value,
                            tenant=self._plane_tenant()):
            self._search_ingest_soon()

    def _search_ingest_soon(self) -> None:
        """Debounced drain, one task at a time (the _resident_ingest_soon
        pattern): coalesce a write burst into few index-upsert batches on
        a worker thread."""
        if (self._search_ingest_task is not None
                and not self._search_ingest_task.done()):
            return
        # capture the plane: the drain sleeps between batches, and the
        # attribute can be unplugged (shutdown, tests) while it does
        plane = self._search

        async def _drain():
            while plane.pending_ingest():
                await asyncio.sleep(self._search_ingest_window)
                await asyncio.to_thread(plane.ingest_pending)

        self._search_ingest_task = supervised_task(
            _drain(), name="proxy.search_ingest"
        )

    def _search_owner(self, key: str) -> str:
        return self.abd.owner(key) if self._shards is not None else ""

    async def _spy_validate(self) -> list[str]:
        """Freshness for one indexed query: validate every stored key's
        index entry with ONE batched `read_tags` fingerprint round (the
        `_fetch_stored` linearizability argument verbatim — entries come
        from completed quorum ops, and honest replies can never deflate
        the quorum-max tag below a completed write). Only stale or
        missing keys take full ABD reads, re-ingesting as they land.
        Returns the sorted stored keys; afterwards every one has a
        validated index entry, so indexed results are bit-for-bit the
        legacy scan's."""
        plane = self._search
        pt = self._plane_tenant()
        keys = self._tenant_stored_keys()
        if not keys:
            return keys
        cached: list[str] = []
        cached_tags: list = []
        missing: list[str] = []
        for k in keys:
            t = plane.tag(self._search_owner(k), k, tenant=pt)
            if t is None:
                missing.append(k)
            else:
                cached.append(k)
                cached_tags.append(t)
        stale = list(missing)
        if cached:
            try:
                dl = self._request_deadline()
                digest = sigs.key_from_set(cached)
                fp = sigs.tags_fingerprint(cached_tags)
                tags = await self._retry(
                    lambda: self.abd.read_tags(
                        cached, digest=digest, fingerprint=fp,
                        cached_tags=cached_tags, deadline=dl,
                    ),
                    dl,
                )
                if tags is not cached_tags:
                    # identity return = every vote said "unchanged";
                    # otherwise compare per key
                    stale.extend(
                        k for k, t, ct in zip(cached, tags, cached_tags)
                        if t != ct
                    )
            except Exception as e:  # validation trouble => full refetch
                log.debug("search tag validation failed (%s); refetch", e)
                stale = list(keys)
        if stale:
            results = await asyncio.gather(
                *(self._fetch_tagged(k) for k in stale),
                return_exceptions=True,
            )
            for k, r in zip(stale, results):
                if isinstance(r, Exception):
                    raise r
                value, tag, _coord = r
                plane.upsert(self._search_owner(k), k, tag, value, tenant=pt)
        metrics.inc(
            "dds_search_index_total", max(0, len(keys) - len(stale)),
            outcome="hit", help="Spyglass index keys per query by outcome",
        )
        metrics.inc(
            "dds_search_index_total", max(0, len(stale) - len(missing)),
            outcome="stale", help="Spyglass index keys per query by outcome",
        )
        metrics.inc(
            "dds_search_index_total", len(missing), outcome="miss",
            help="Spyglass index keys per query by outcome",
        )
        return keys

    def _spy_partition(self, keys: list[str]) -> dict[str, list[str]]:
        """Stored keys by owning shard group (one anonymous group when
        unsharded) — the scatter side of a query's per-group dispatch."""
        if self._shards is None:
            return {"": keys}
        parts: dict[str, list[str]] = {}
        for k in keys:
            parts.setdefault(self.abd.owner(k), []).append(k)
        return parts

    async def _spy_filter(self, evalfn) -> list[str]:
        """One indexed selection query: validate, dispatch `evalfn` per
        group CONCURRENTLY (each group's predicate kernel runs on a
        worker thread), union the key sets, and return them in
        sorted-key order — exactly the legacy scan's output order."""
        keys = await self._spy_validate()
        if not keys:
            return []
        parts = self._spy_partition(keys)
        pt = self._plane_tenant()
        with tracer.span("proxy.search_eval", k=len(keys),
                         shards=len(parts)):
            sets = await asyncio.gather(
                *(
                    asyncio.to_thread(
                        evalfn, self._search.group(gid, tenant=pt)
                    )
                    for gid in parts
                )
            )
        selected = set().union(*sets)
        hits = [k for k in keys if k in selected]
        self._search.note_selected(hits, pt)
        return hits

    async def _spy_order(self, pos: int, descending: bool) -> list[str]:
        """One indexed order-by query: per-group device-sorted runs
        merged host-side. Run elements are (comparable, key) with the
        comparable negated for descending order, so `heapq.merge`
        reproduces the global stable sort — ties in ascending key order,
        like the legacy stable `sorted` over sorted-key pairs."""
        import heapq

        keys = await self._spy_validate()
        if not keys:
            return []
        parts = self._spy_partition(keys)
        pt = self._plane_tenant()
        with tracer.span("proxy.search_eval", k=len(keys),
                         shards=len(parts)):
            runs = await asyncio.gather(
                *(
                    asyncio.to_thread(
                        self._search.group(gid, tenant=pt).eval_order,
                        pos, descending,
                    )
                    for gid in parts
                )
            )
        stored = set(keys)
        ordered = [k for _, k in heapq.merge(*runs) if k in stored]
        self._search.note_selected(ordered, pt)
        return ordered

    @staticmethod
    def _page_params(req: Request) -> tuple[int, int | None]:
        """`offset`/`limit` pagination params (every search/order route,
        both paths): non-negative ints, ValueError -> 400 via handle()."""
        off = int(req.query.get("offset", 0))
        if off < 0:
            raise ValueError("offset must be >= 0")
        lim = req.query.get("limit")
        lim = int(lim) if lim is not None else None
        if lim is not None and lim < 0:
            raise ValueError("limit must be >= 0")
        return off, lim

    @staticmethod
    def _page_response(keyset: list[str],
                       page: tuple[int, int | None]) -> Response:
        off, lim = page
        end = None if lim is None else off + lim
        return Response.json(J.keys_result(keyset[off:end]))

    @staticmethod
    def _count_search(route: str, path: str) -> None:
        metrics.inc(
            "dds_search_requests_total", route=route, path=path,
            help="search/order/range requests by evaluation path",
        )

    async def _order_route(self, name: str, req: Request) -> Response:
        pos = self._pos(req)
        page = self._page_params(req)
        descending = name == "OrderLS"
        if self._search is not None:
            self._count_search(name, "indexed")
            return self._page_response(
                await self._spy_order(pos, descending), page
            )
        self._count_search(name, "legacy")
        pairs = await self._fetch_visible()
        # records without the column are EXCLUDED (the Search* convention)
        # instead of the old silent float("-inf") coercion; non-integer
        # columns raise -> 400, like every Search* int cast
        rows = [(int(v[pos]), k) for k, v in pairs if pos < len(v)]
        ordered = [
            k for _, k in
            sorted(rows, key=lambda t: t[0], reverse=descending)
        ]
        return self._page_response(ordered, page)

    async def _eq_route(self, name: str, req: Request) -> Response:
        from dds_tpu.models.det import DetKey

        pos = self._pos(req)
        item = str(J.parse_item(req.json()))
        page = self._page_params(req)
        want_eq = name == "SearchEq"
        if self._search is not None:
            self._count_search(name, "indexed")
            keyset = await self._spy_filter(
                lambda idx: idx.eval_eq(pos, item, want_eq)
            )
            return self._page_response(keyset, page)
        self._count_search(name, "legacy")
        pairs = await self._fetch_visible()
        keyset = [
            k for k, v in pairs
            if pos < len(v) and DetKey.compare(str(v[pos]), item) == want_eq
        ]
        return self._page_response(keyset, page)

    _CMP_OPS = {"SearchGt": "gt", "SearchGtEq": "ge",
                "SearchLt": "lt", "SearchLtEq": "le"}

    async def _cmp_route(self, name: str, req: Request) -> Response:
        pos = self._pos(req)
        item = int(J.parse_item(req.json()))
        page = self._page_params(req)
        if self._search is not None:
            self._count_search(name, "indexed")
            keyset = await self._spy_filter(
                lambda idx: idx.eval_compare(pos, self._CMP_OPS[name], item)
            )
            return self._page_response(keyset, page)
        self._count_search(name, "legacy")
        pairs = await self._fetch_visible()
        op = {
            "SearchGt": lambda e: e > item,
            "SearchGtEq": lambda e: e >= item,
            "SearchLt": lambda e: e < item,
            "SearchLtEq": lambda e: e <= item,
        }[name]
        keyset = [k for k, v in pairs if pos < len(v) and op(int(v[pos]))]
        return self._page_response(keyset, page)

    async def _range_route(self, req: Request) -> Response:
        pos = self._pos(req)
        lo_bound, hi_bound = J.parse_range(req.json())
        page = self._page_params(req)
        if self._search is not None:
            self._count_search("Range", "indexed")
            keyset = await self._spy_filter(
                lambda idx: idx.eval_range(pos, lo_bound, hi_bound)
            )
            return self._page_response(keyset, page)
        self._count_search("Range", "legacy")
        pairs = await self._fetch_visible()
        keyset = [
            k for k, v in pairs
            if pos < len(v) and lo_bound <= int(v[pos]) <= hi_bound
        ]
        return self._page_response(keyset, page)

    async def _entry_route(self, name: str, req: Request) -> Response:
        from dds_tpu.models.det import DetKey

        if name == "SearchEntry":
            vals = [str(J.parse_item(req.json()))]
        else:
            vals = [str(x) for x in J.parse_triplet(req.json())]
        mode = "all" if name == "SearchEntryAND" else "any"
        page = self._page_params(req)
        if self._search is not None:
            self._count_search(name, "indexed")
            keyset = await self._spy_filter(
                lambda idx: idx.eval_entry(vals, mode)
            )
            return self._page_response(keyset, page)
        self._count_search(name, "legacy")
        pairs = await self._fetch_visible()
        if mode == "all":
            keyset = [
                k for k, v in pairs
                if all(any(DetKey.compare(str(e), q) for e in v)
                       for q in vals)
            ]
        else:
            keyset = [
                k for k, v in pairs
                if any(DetKey.compare(str(e), q) for q in vals for e in v)
            ]
        return self._page_response(keyset, page)

    async def _fetch_visible(self) -> list[tuple[str, list]]:
        """`_fetch_stored` scoped to the request tenant (Bastion): the
        quorum/tag machinery still validates the FULL stored view (one
        shared round, whoever asks), then the tenant filter projects the
        caller's own records. Tenancy off returns the identical list."""
        return self._tenant_pairs(await self._fetch_stored())

    async def _fetch_stored(self) -> list[tuple[str, list]]:
        """Every stored (key, value), for the aggregate/search routes.

        With the aggregate cache on, ONE batched tag-only quorum round
        (`AbdClient.read_tags`) validates all cached entries: a cached value
        is served only when the quorum-max tag EQUALS its cached tag, which
        is linearizable because cached values come from completed ops (fully
        written back at that tag) and any completed later write would show a
        higher tag in every quorum (they intersect in an honest replica) —
        honest replies can therefore never DEFLATE the max below a completed
        write. What a credentialed Byzantine replica CAN do is confirm a
        cache entry that a Byzantine coordinator planted (by reporting the
        planted tag, or by echoing the request fingerprint as `unchanged`);
        that forgery class does not come from the tag round at all — a
        planting coordinator could always confirm its own tag — and is
        bounded by the per-round audit (see aggregate_cache_audit). Keys
        that fail validation (or were never cached) take the full ABD read,
        refilling the cache.

        The reference re-reads every set through full quorums per aggregate
        (`DDSRestServer.scala:397-446`); this replaces K 2-round-trip reads
        with 1 light round + reads for just the stale keys.
        """
        import random

        with tracer.span("proxy.fetch_stored"):
            return await self._fetch_stored_traced()

    async def _fetch_stored_traced(self) -> list[tuple[str, list]]:
        import random

        state, keys, cached, digest, fp, cached_tags = self._agg_state()
        if not keys:
            return []
        fresh: dict[str, object] = {}
        fresh_tags: dict[str, object] = {}
        if self.cfg.aggregate_cache and cached:
            try:
                dl = self._request_deadline()
                tags = await self._retry(
                    lambda: self.abd.read_tags(
                        cached, digest=digest, fingerprint=fp,
                        cached_tags=cached_tags, deadline=dl,
                    ),
                    dl,
                )
                if tags is cached_tags:
                    # identity return: every quorum vote said "unchanged",
                    # so the whole cache is fresh. With a memoized pairs
                    # list for this exact state only the audit remains —
                    # the steady-state aggregate does O(1) bookkeeping.
                    pm = self._pairs_memo
                    if pm is not None and pm[0] == state:
                        if await self._audit_cached(cached):
                            metrics.inc(
                                "dds_tag_cache_total", len(cached),
                                outcome="hit",
                                help="aggregate tag-cache keys by outcome",
                            )
                            return pm[1]
                        # audit flushed the cache: rebuild from quorum reads
                    else:
                        for k in cached:
                            ct, cv = self._cache[k]
                            fresh[k] = cv
                            fresh_tags[k] = ct
                else:
                    for k, t in zip(cached, tags):
                        ct, cv = self._cache[k]
                        if t == ct:
                            fresh[k] = cv
                            fresh_tags[k] = ct
            except Exception as e:  # validation trouble => plain full fetch
                log.debug("tag validation failed (%s); full refetch", e)

        # audit sample: re-read a few cache-served keys through a full
        # quorum under a (random) coordinator. A value mismatch at the SAME
        # tag means some past coordinator forged a cached value — flush
        # everything. A mismatch at a strictly NEWER tag is usually a benign
        # write that landed between the tag-validation round and the audit
        # re-read — but the newer tag is reported by the audited read
        # itself, so it is corroborated by an independent re-read before
        # being exempted from the flush.
        audit = random.sample(
            sorted(fresh), min(self.cfg.aggregate_cache_audit, len(fresh))
        )
        stale = [k for k in keys if k not in fresh or k in audit]
        results = await asyncio.gather(
            *(self._fetch_tagged(k) for k in stale), return_exceptions=True
        )
        fetched = {}
        for k, r in zip(stale, results):
            if isinstance(r, Exception):
                raise r
            fetched[k] = r  # (value, tag, coordinator)
        # cache effectiveness: keys served from the tag-validated cache vs
        # re-read through full quorums (audit re-reads count as misses —
        # they cost a full ABD round either way)
        metrics.inc("dds_tag_cache_total", max(0, len(keys) - len(stale)),
                    outcome="hit", help="aggregate tag-cache keys by outcome")
        metrics.inc("dds_tag_cache_total", len(stale), outcome="miss",
                    help="aggregate tag-cache keys by outcome")
        pre = {k: (fresh_tags[k], fresh[k]) for k in audit}
        forged = await self._audit_verdict(audit, pre, fetched)
        if forged:
            log.warning("aggregate cache audit mismatch: flushing cache")
            self._flush_cache()
            fresh.clear()  # serve only quorum-read data this round
            remaining = [k for k in keys if k not in fetched]
            more = await asyncio.gather(
                *(self._fetch_tagged(k) for k in remaining),
                return_exceptions=True,
            )
            for k, r in zip(remaining, more):
                if isinstance(r, Exception):
                    raise r
                fetched[k] = r
        out = []
        for k in keys:
            v = fetched[k][0] if k in fetched else fresh[k]
            if v is not None:
                out.append((k, v))
        # memoize the materialized pairs only if the (stored, cache) state
        # did not move while this round was in flight — the next fully-
        # unchanged round can then serve `out` after audit alone
        if (self._stored_version, self._cache_version) == state:
            self._pairs_memo = (state, out)
        return out

    async def _audit_verdict(
        self, audit: list[str], pre: dict, fetched: dict
    ) -> list[str]:
        """Shared forged/suspect classification for both audit paths.

        `pre[k] = (tag, value)` is what the cache served; `fetched[k] =
        (value, tag, coordinator)` is the audit's full quorum re-read. A
        value mismatch at the cached tag (or below) means some past
        coordinator forged a cached value -> forged. A strictly NEWER
        (value, tag) is usually a benign write that landed between the
        tag-validation round and the audit re-read — but the newer tag came
        from the very read being audited, so it is attacker-controllable:
        corroborate each with ONE more full quorum read through a DIFFERENT
        coordinator (the audited read's is excluded). Benign only if that
        independent read reproduces the same (value, tag); a failed
        corroboration degrades to the conservative flush rather than
        failing the aggregate."""
        forged, suspect = [], []
        for k in audit:
            value, tag, _coord = fetched[k]
            pre_tag, pre_value = pre[k]
            if value == pre_value:
                continue
            if tag is None or tag <= pre_tag:
                forged.append(k)
            else:
                suspect.append(k)
        if suspect:
            checks = await asyncio.gather(
                *(
                    self._fetch_tagged(k, exclude=(fetched[k][2],))
                    for k in suspect
                ),
                return_exceptions=True,
            )
            for k, r in zip(suspect, checks):
                if isinstance(r, Exception) or r[:2] != fetched[k][:2]:
                    forged.append(k)
        return forged

    async def _audit_cached(self, cached: list[str]) -> bool:
        """Audit a fully-cache-served aggregate round (the steady-state
        fast path): re-read a sample through full quorums and flush on a
        non-corroborated mismatch. Returns False when the cache was
        flushed."""
        import random

        audit = random.sample(
            cached, min(self.cfg.aggregate_cache_audit, len(cached))
        )
        if not audit:
            return True
        pre = {k: self._cache[k] for k in audit}
        results = await asyncio.gather(
            *(self._fetch_tagged(k) for k in audit), return_exceptions=True
        )
        fetched = {}
        for k, r in zip(audit, results):
            if isinstance(r, Exception):
                raise r
            fetched[k] = r
        forged = await self._audit_verdict(audit, pre, fetched)
        if forged:
            log.warning("aggregate cache audit mismatch: flushing cache")
            self._flush_cache()
            return False
        return True

    # -------------------------------------------------------------- routing

    def _breaker_census(self) -> tuple[int, list[float]]:
        """(trusted coordinator count, refusing-breaker half-open ETAs)
        from whatever storage client is behind this proxy; a client
        without the surface (test stubs) reads as healthy."""
        census = getattr(self.abd, "breaker_census", None)
        return census() if census is not None else (0, [])

    def _derive_retry_after(self, *candidates: float | None) -> int:
        """Satellite of ISSUE 7: Retry-After derived from actual recovery
        state — the nearest breaker half-open probe plus any
        caller-supplied candidate (token-bucket refill ETA, fast-fail
        ETA) — instead of the static config constant, which only remains
        as the fallback when nothing measurable is pending."""
        vals = [c for c in candidates if c is not None and 0 < c < math.inf]
        _, etas = self._breaker_census()
        vals.extend(e for e in etas if e > 0)
        eta = min(vals) if vals else self.cfg.retry_after_hint
        return max(1, math.ceil(eta))

    def _admission_reject(self, d, route: str, method: str) -> Response:
        """Format one Bulwark rejection: 429 (per-tenant throttle) or 503
        (shed). No Deadline was minted and no storage work ran — the
        request fails in microseconds with an honest Retry-After."""
        if d.status == 429:
            retry_after = max(1, math.ceil(d.retry_after)) \
                if 0 < d.retry_after < math.inf \
                else max(1, math.ceil(self.cfg.retry_after_hint))
        else:
            retry_after = self._derive_retry_after(d.retry_after)
        metrics.inc(
            "dds_http_requests_total", route=route or "root",
            method=method, status=str(d.status),
            help="REST requests by route and status",
        )
        # shed 503s burn the route's SLO budget (they are ours); throttle
        # 429s are the tenant's own rate and do not
        self.slo.observe(route or "root", d.status, 0.0)
        return Response(
            d.status,
            f"admission rejected ({d.reason})".encode(),
            headers={"Retry-After": str(retry_after)},
        )

    async def _admission_loop(self) -> None:
        """Controller heartbeat: decide() ticks the ratchet lazily under
        traffic, but recovery (un-shedding) must also happen when the
        shed class is the ONLY traffic — this timer guarantees
        evaluations keep flowing either way."""
        interval = max(0.05, self.admission.eval_interval)
        while True:
            await asyncio.sleep(interval)
            self.admission.evaluate()

    def _tenant_reject(self, e: TenantError, route: str,
                       method: str) -> Response:
        """Typed 400 for a malformed x-dds-tenant header: charset and
        length are clamped at the edge so wire garbage never becomes a
        metrics label, a pool stripe, or an ownership identity — and a
        garbled id never silently falls back into another keyspace."""
        metrics.inc(
            "dds_http_requests_total", route=route or "root",
            method=method, status="400",
            help="REST requests by route and status",
        )
        metrics.inc(
            "dds_tenant_header_rejects_total", reason=e.reason,
            help="malformed x-dds-tenant headers refused with 400",
        )
        return Response.json(
            {"error": "invalid tenant header", "reason": e.reason},
            status=400,
        )

    async def handle(self, req: Request) -> Response:
        route = req.path.split("/", 2)[1] if "/" in req.path else req.path
        header = self.admission.tenant_header \
            if self.admission is not None else "x-dds-tenant"
        try:
            tenant = validate_tenant(req.headers.get(header))
        except TenantError as e:
            return self._tenant_reject(e, route, req.method)
        adm_ms = None
        decision = None
        if tenant == CANARY_TENANT:
            # Heliograph carve-out: canary probes must get through WHILE
            # the fleet sheds (black-box evidence is worth the most
            # exactly then), so they bypass tenant-fair admission — but
            # through an explicit, rate-bounded gate: the dedicated
            # bucket 429s anything over the configured probe budget, so
            # the prober (or a canary-tenant squatter) can never self-DoS
            # the edge. Rejections are typed and counted.
            if (route not in _ADMISSION_EXEMPT
                    and not self._canary_bucket.try_acquire()):
                metrics.inc(
                    "dds_canary_throttled_total", route=route or "root",
                    help="canary requests refused by the rate-bounded "
                         "admission carve-out",
                )
                eta = self._canary_bucket.refill_eta()
                return Response(
                    429, b"canary rate bound exceeded",
                    headers={"Retry-After": (
                        "60" if not math.isfinite(eta)
                        else str(max(1, math.ceil(eta))))},
                )
        elif self.admission is not None and route not in _ADMISSION_EXEMPT:
            t_adm = time.perf_counter()
            decision = self.admission.decide(route, tenant)
            adm_ms = (time.perf_counter() - t_adm) * 1e3
            if not decision.admitted:
                return self._admission_reject(decision, route, req.method)
        # Trace root minted at the edge (or stitched under an upstream
        # caller's x-dds-trace header): every span recorded below — quorum
        # rounds, replica handlers scheduled over the transport, kernel
        # phases — links into this request's tree via obs.context.
        upstream = obs_context.from_header(req.headers.get("x-dds-trace", ""))
        ctx = obs_context.child(upstream) if upstream else obs_context.root()
        # ONE budget per request: every storage helper below reads it from
        # the context var, so nested retries and per-attempt timeouts all
        # shrink toward the same edge deadline
        token = _REQ_DEADLINE.set(Deadline(self.cfg.request_budget))
        ttoken = _REQ_TENANT.set(tenant)
        t0 = time.perf_counter()
        status = 500
        try:
            with tracer.span(f"http.{req.method}.{route or 'root'}", _ctx=ctx):
                if adm_ms is not None:
                    # decided before the trace root existed — backdate it
                    # into the tree as the admission stage
                    tracer.record("proxy.admission", adm_ms,
                                  _ctx=obs_context.child())
                resp = await self._route(req)
            status = resp.status
            return resp
        except (ValueError, KeyError, TypeError) as e:
            status = 400
            return Response.text(f"bad request: {e}", 400)
        except (DeadlineExceededError, NoTrustedNodesError,
                AllBreakersOpenError) as e:
            # graceful degradation: the quorum is unreachable within the
            # budget — tell the client WHEN to come back instead of hanging
            # or aborting opaquely. AllBreakersOpenError is the fast-fail
            # variant: it arrives in microseconds with the probe ETA.
            status = 503
            log.warning("degraded %s %s: %s", req.method, req.path, e)
            if isinstance(e, DeadlineExceededError):
                kind = "deadline_exceeded"
            elif isinstance(e, AllBreakersOpenError):
                kind = "all_breakers_open"
            else:
                kind = "no_trusted_nodes"
            metrics.inc(
                "dds_degraded_total", route=route or "root", kind=kind,
                help="requests degraded to 503 (budget exhausted / no quorum)",
            )
            # the faulting request's whole span tree, frozen for post-mortem
            await flight.record_async(
                kind, trace_id=ctx.trace_id, route=route or "root",
                method=req.method, error=str(e),
            )
            return self._unavailable(str(e), getattr(e, "eta", None))
        except Exception:
            log.exception("route failure %s %s", req.method, req.path)
            return Response(500)
        finally:
            _REQ_DEADLINE.reset(token)
            _REQ_TENANT.reset(ttoken)
            dur = time.perf_counter() - t0
            metrics.observe(
                "dds_http_request_seconds", dur,
                route=route or "root", method=req.method,
                help="REST request latency by route",
            )
            metrics.inc(
                "dds_http_requests_total", route=route or "root",
                method=req.method, status=str(status),
                help="REST requests by route and status",
            )
            if status != 304 and tenant != CANARY_TENANT:
                # a 304 is a deliberately-parked gossip long-poll (or a
                # free freshness probe) — its held duration is the design,
                # not latency badness, so it must not burn SLO budget.
                # Canary traffic is excluded wholesale: the prober feeds
                # its own synthetic canary.<kind> streams from VERIFIED
                # outcomes, and synthetic load must never dilute (or
                # burn) user-facing route objectives.
                self.slo.observe(
                    route or "root", status, dur,
                    tenant=(tenant if self._tenancy_enabled else None),
                )
            if self._tenancy_enabled and tenant != CANARY_TENANT:
                # Bastion attribution: the admitted request's outcome
                # feeds the burn-shed window (a flooding tenant's 5xxs
                # accumulate against ITS identity, not the fleet's), and
                # Chronoscope's per-tenant usage ledger
                if decision is not None:
                    self.admission.note_outcome(
                        tenant, decision.klass, status < 500
                    )
                from dds_tpu.obs.chronoscope import chronoscope
                chronoscope.note_usage(tenant, route or "root", dur)

    def _unavailable(self, why: str, eta: float | None = None) -> Response:
        return Response(
            503,
            f"service unavailable: {why}".encode(),
            headers={"Retry-After": str(self._derive_retry_after(eta))},
        )

    async def _route(self, req: Request) -> Response:
        parts = [p for p in req.path.split("/") if p]
        if not parts:
            return Response(404)
        name, arg = parts[0], (parts[1] if len(parts) > 1 else None)
        m = req.method

        match (m, name):
            case ("GET", "GetSet") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                value = await self._fetch(arg)
                if value is None:
                    return Response(404)
                return Response.json(J.dds_set(value))

            case ("POST", "PutSet"):
                body = req.json()
                if body is None:
                    key, value = sigs.random_key(), None
                else:
                    value = J.parse_set(body)
                    key = sigs.key_from_set(value)
                # content addressing makes cross-tenant PutSet of identical
                # content a key collision — first writer owns, the replay
                # by another tenant is refused like any cross-tenant access
                if (denied := self._tenant_denied(key)) is not None:
                    return denied
                await self._write(key, value)
                self._note_stored(key)
                self._note_owner(key)
                return Response.text(key)

            case ("DELETE", "RemoveSet") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                await self._write(arg, None)
                if arg in self.stored_keys:
                    self.stored_keys.discard(arg)  # stop aggregating/gossiping
                    self._stored_version += 1
                    self._save_keys_soon()
                if self._tenant_owner.pop(arg, None) is not None:
                    self._tenant_pairs_memo.clear()
                if arg in self._canary_keys:
                    self._canary_keys.discard(arg)
                    self._tenant_pairs_memo.clear()
                return Response(200)

            case ("PUT", "AddElement") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                item = J.parse_item(req.json())
                value = await self._fetch(arg)
                if value is None:
                    return Response(404)
                await self._write(arg, value + [item])
                return Response(200)

            case ("GET", "ReadElement") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                pos = self._pos(req)
                value = await self._fetch(arg)
                if value is None or pos > len(value) - 1:
                    return Response(404)
                return Response.json({"value": value[pos]})

            case ("PUT", "WriteElement") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                pos = self._pos(req)
                item = J.parse_item(req.json())
                value = await self._fetch(arg)
                if value is None:
                    return Response(404)
                new = list(value)
                if pos > len(new) - 1:
                    new.append(item)
                else:
                    new[pos] = item
                await self._write(arg, new)
                return Response(200)

            case ("POST", "IsElement") if arg:
                if (denied := self._tenant_denied(arg)) is not None:
                    return denied
                item = J.parse_item(req.json())
                value = await self._fetch(arg)
                if value is None:
                    return Response(404)
                # deterministic-HE compare degenerates to ciphertext equality
                found = any(str(elem) == str(item) for elem in value)
                return Response.json(J.value_result(found))

            # ---------------- ciphertext-compute aggregates ----------------

            case ("GET", "Sum"):
                return await self._pair_aggregate(req, "nsqr")

            case ("GET", "SumAll"):
                return await self._fold_aggregate(req, "nsqr")

            case ("GET", "Mult"):
                return await self._pair_aggregate(req, "pubkey")

            case ("GET", "MultAll"):
                return await self._fold_aggregate(req, "pubkey")

            # ------------- encrypted search (Spyglass indexed or legacy scan)

            case ("GET", "OrderLS") | ("GET", "OrderSL"):
                return await self._order_route(name, req)

            case ("POST", "SearchEq") | ("POST", "SearchNEq"):
                return await self._eq_route(name, req)

            case ("POST", "SearchGt") | ("POST", "SearchGtEq") | (
                "POST",
                "SearchLt",
            ) | ("POST", "SearchLtEq"):
                return await self._cmp_route(name, req)

            case ("POST", "Range"):
                return await self._range_route(req)

            case ("POST", "SearchEntry") | ("POST", "SearchEntryOR") | (
                "POST",
                "SearchEntryAND",
            ):
                return await self._entry_route(name, req)

            # ---------------- Prism encrypted analytics (PC-MM) ----------------

            case ("POST", "MatVec") | ("POST", "WeightedSum") | (
                "POST",
                "GroupBySum",
            ) if self.prism is not None:
                return await self._analytics(name, req)

            case ("POST", "_sync"):
                for k in J.parse_keys(req.json()):
                    self._note_stored(k)
                return Response(204)

            case ("GET", "_sync") if self.cfg.key_sync_enabled:
                # bootstrap pull: a (re)starting peer fetches the aggregate
                # key set instead of waiting for the next gossip push.
                # Gated like the push side: with gossip off this would hand
                # any client the full record-key set (workload shape) — the
                # same rationale that keeps /_trace off by default.
                return Response.json(J.keys_result(sorted(self.stored_keys)))

            case ("GET", "health"):
                # liveness/degradation probe: active-replica view, quorum
                # requirement, and per-coordinator breaker states. Always
                # on — it reveals cluster health, not workload shape (the
                # /_trace gating rationale does not apply).
                trusted = self.abd.replicas.get_trusted()
                breakers = self.abd.breaker_states()
                # reachable = trusted minus nodes whose breaker refuses
                # traffic right now (open, pre-half-open)
                reachable = [
                    n for n in trusted
                    if n not in self.abd.breakers or self.abd.breakers[n].allow()
                ]
                shards = None
                if self._shards is not None:
                    # sharded: the merged replica pool says nothing about
                    # quorum health — each GROUP must hold its own quorum
                    shards = self.abd.shards_health()
                    degraded = any(s["degraded"] for s in shards.values())
                else:
                    degraded = len(reachable) < self.abd.cfg.quorum_size
                health = {
                    "status": "degraded" if degraded else "ok",
                    "active_replicas": len(trusted),
                    "reachable_replicas": len(reachable),
                    "quorum_size": self.abd.cfg.quorum_size,
                    "breakers": breakers,
                    "stored_keys": len(self.stored_keys),
                    "request_budget": self.cfg.request_budget,
                }
                if self.cfg.region:
                    health["region"] = self.cfg.region
                if self._tenancy_enabled:
                    # Bastion surface: ownership footprint + who is
                    # currently shedding themselves (never the fleet)
                    health["tenants"] = {
                        "owned_keys": len(self._tenant_owner),
                        "shed": (self.admission.shed_tenants()
                                 if self.admission is not None else []),
                    }
                if shards is not None:
                    health["shards"] = shards
                    health["shard_epoch"] = self._shards.epoch
                    health["reshard_state"] = self._shards.state
                if self._resident is not None:
                    # Lodestone surface: per-pool residency, HBM bytes,
                    # reset churn, and the pending write-ingest queue
                    health["resident"] = self._resident.stats()
                if self._stratum is not None:
                    # Stratum surface: per-tier rows/bytes, directory
                    # residency counts, hit/eviction/cold-read tallies,
                    # and the blended occupancy pressure
                    health["storage"] = self._stratum.stats()
                if self._search is not None:
                    # Spyglass surface: per-group indexed keys/packs and
                    # the pending ingest queue
                    health["search"] = self._search.stats()
                if self.helmsman is not None:
                    # Helmsman surface: pin state, budget, streaks, and
                    # the recent decision history
                    health["helmsman"] = self.helmsman.report()
                recovery = self._recovery_status()
                if recovery is not None:
                    health["recovery"] = recovery
                # Heliograph surface: last probe age + per-kind verdicts,
                # read from in-memory ledger state only. A disabled or
                # wedged prober degrades this section to "disabled" /
                # "stale" — it can never block or slow the health probe.
                health["canary"] = (
                    self.heliograph.health_section()
                    if self.heliograph is not None else {"status": "disabled"}
                )
                resp = Response.json(health, status=503 if degraded else 200)
                if degraded:
                    resp.headers["Retry-After"] = str(self._derive_retry_after())
                return resp

            case ("GET", "metrics") if self.cfg.metrics_route_enabled:
                # Prometheus text exposition (obs/metrics). State gauges
                # (breakers, suspicion, membership) are sampled at scrape
                # time — cheaper than updating them on every transition,
                # and scrape-time freshness is all a gauge promises.
                self._sample_state_gauges()
                return Response(
                    200,
                    metrics.render().encode(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )

            case ("GET", "shards") if self._shards is not None:
                # operator inspection + Meridian gossip: the ACTIVE signed
                # map (epoch + HMAC, verifiable against the intranet
                # secret), reshard state, and per-group membership. Always
                # on when sharded — like /health it reveals topology, not
                # workload shape. Conditional freshness: `If-None-Match:
                # "<epoch>"` answers a near-free 304 when the epoch is
                # unchanged, and `?wait=N` parks the request on the gossip
                # hub so remote routers get the next epoch bump as a push
                # instead of hot-polling (see dds_tpu/fabric/gossip).
                return await self._shards_route(req)

            case ("POST", "_reshard") if (
                self.cfg.reshard_route_enabled and self._reshard is not None
            ):
                # operator control: drive a live split or merge through
                # the reshard controller. Body {"source": gid[, "target":
                # gid][, "action": "split"|"merge"]}; answers the
                # activated epoch, 409 {"aborted"} when the plan aborted
                # safely (old map back in force), or 409 {"busy"} + a
                # phase-derived Retry-After while a DIFFERENT plan holds
                # the controller. Repeating an identical request is
                # idempotent: in flight it attaches to the running plan;
                # completed it answers the current map.
                return await self._reshard_route(req)

            case ("POST", "_helmsman") if (
                self.cfg.reshard_route_enabled and self.helmsman is not None
            ):
                # manual override: {"pin": true} freezes the fleet shape
                # (autoscaling halts, dead-group promotion keeps running),
                # {"pin": false} resumes. Answers the controller report.
                body = req.json() or {}
                pin = body.get("pin")
                if not isinstance(pin, bool):
                    return Response.text("body must set pin: true|false",
                                         400)
                (self.helmsman.pin if pin else self.helmsman.unpin)()
                return Response.json(self.helmsman.report())

            case ("GET", "canary"):
                # Heliograph report: per-kind last verdicts/latencies,
                # typed-outcome counts, failure exemplars (trace ids
                # resolve via /_trace and /fleet/incidents), region
                # unreachable streaks. Admission-exempt like /health —
                # the canary view must answer while the canary is the
                # only thing still seeing the problem.
                if self.heliograph is None:
                    return Response.json({"enabled": False})
                return Response.json(self.heliograph.report())

            case ("GET", "slo") if self.cfg.slo_route_enabled:
                # per-route objective/burn state (obs/slo) plus the
                # Watchtower audit summary — the automated-verdict
                # surface: what is burning budget, what invariants broke,
                # and (when Bulwark is armed) what admission is doing
                # about it
                body = {"slo": self.slo.report(), "audit": watchtower.stats()}
                if self.admission is not None:
                    body["admission"] = self.admission.report()
                return Response.json(body)

            case ("GET", "fleet") if self._fleet is not None and arg:
                # Panopticon federation (obs/panopticon): every fleet
                # process's telemetry, served from the proxy's collector.
                # Admission-exempt like /metrics — the fleet views must
                # answer WHILE the fleet sheds.
                if arg == "metrics":
                    # relabeled merge of every source's exposition, each
                    # sample tagged host/role/shard, staleness-marked per
                    # source (dds_fleet_source_age_seconds/_stale)
                    self._sample_state_gauges()
                    self._fleet.sample_gauges()
                    return Response(
                        200,
                        self._fleet.fleet_metrics().encode(),
                        content_type=(
                            "text/plain; version=0.0.4; charset=utf-8"
                        ),
                    )
                if arg == "slo":
                    # per-host reports + fleet rollup: worst-of and
                    # sum-of burn per route/window, resident-pool
                    # pressure per group, shed level per host
                    return Response.json(self._fleet.fleet_slo())
                if arg == "incidents":
                    # fleet-wide flight incidents correlated by trace id,
                    # plus the collector-fed Watchtower's verdicts
                    tid = req.query.get("trace_id") or None
                    return Response.json(self._fleet.fleet_incidents(tid))
                if arg == "profile":
                    # Chronoscope rollup: every host's dds_pipe_* gauges
                    # (carried by the shipped metrics_text) merged into
                    # the fleet-wide bottleneck-stage verdict
                    self._sample_state_gauges()
                    return Response.json(self._fleet.fleet_profile())
                if arg == "canary":
                    # Heliograph rollup: every host's dds_canary_* gauges
                    # (carried by the shipped metrics_text) merged into
                    # per-host verdicts + the fleet-wide worst-of view,
                    # with failure exemplar trace ids resolvable against
                    # GET /fleet/incidents?trace_id=...
                    self._sample_state_gauges()
                    return Response.json(self._fleet.fleet_canary())
                return Response(404)

            case ("GET", "profile") if self.cfg.profile_route_enabled:
                # Chronoscope (obs/chronoscope): the per-route/per-stage
                # critical-path profile + slow-trace exemplars. ?fmt=folded
                # serves flamegraph folded text instead of the JSON
                # waterfall. Admission-exempt like /slo: the profile must
                # answer while the pipe is the problem.
                from dds_tpu.obs.chronoscope import chronoscope

                if req.query.get("fmt") == "folded":
                    return Response(
                        200, chronoscope.folded().encode(),
                        content_type="text/plain; charset=utf-8",
                    )
                return Response.json(chronoscope.profile())

            case ("GET", "_trace") if self.cfg.trace_route_enabled:
                # live observability (SURVEY §5.5): per-span timing summary
                # (count/total/mean/p50/p95 ms) from utils/trace, counters
                # under their OWN key (they are occurrences, not durations —
                # mixing them into span counts skewed every mean/percentile).
                # Config-gated (reveals workload shape); no ciphertexts or
                # keys leave — span metadata is aggregate timing only.
                return Response.json(
                    {
                        "spans": tracer.summary(),
                        "counters": tracer.counters(),
                        "stored_keys": len(self.stored_keys),
                    }
                )

        return Response(404)

    async def _reshard_route(self, req: Request) -> Response:
        import asyncio as _aio

        from dds_tpu.shard.rebalance import ReshardAborted
        from dds_tpu.utils.tasks import supervised_task

        body = req.json() or {}
        action = body.get("action", "split")
        if action not in ("split", "merge"):
            return Response.text("action must be split or merge", 400)
        source = body.get("source")
        if not isinstance(source, str) or not source:
            return Response.text("missing source group", 400)
        target = body.get("target")
        ctl = self._reshard
        split_fn = getattr(ctl, "split", ctl)
        merge_fn = getattr(ctl, "merge", None)
        if action == "merge" and merge_fn is None:
            return Response.text("merge is not supported by this "
                                 "controller", 400)

        smap = self._shards.current()
        # COMPLETED idempotency: the shape this request asks for already
        # holds, so answer the current map instead of failing the replay
        done = (
            (action == "split" and isinstance(target, str)
             and target in smap.groups and source in smap.groups)
            or (action == "merge" and source not in smap.groups)
        )
        if done and self._reshard_inflight is None:
            return Response.json({"epoch": smap.epoch,
                                  "groups": list(smap.groups),
                                  "idempotent": True})

        key = (action, source, target)
        inflight = self._reshard_inflight
        if inflight is not None and inflight["key"] != key:
            # a DIFFERENT plan holds the controller: refuse honestly,
            # with a Retry-After derived from its phase
            ra = getattr(ctl, "retry_after", None)
            retry = float(ra()) if callable(ra) else 5.0
            resp = Response.json(
                {"busy": {"action": inflight["key"][0],
                          "source": inflight["key"][1],
                          "target": inflight["key"][2]},
                 "phase": getattr(ctl, "phase", None)}, status=409,
            )
            resp.headers["Retry-After"] = str(max(1, int(retry + 0.5)))
            return resp
        if inflight is not None:
            task = inflight["task"]  # identical repeat: attach, no new plan
        else:
            async def run():
                # exceptions become results so an attached repeat sees
                # the same outcome instead of racing exception retrieval
                try:
                    if action == "merge":
                        return "ok", await merge_fn(source)
                    return "ok", await split_fn(source, target)
                except ReshardAborted as e:
                    return "aborted", str(e)
                except ValueError as e:
                    # operator error (unknown group, taken target): the
                    # request is wrong, not the fleet
                    return "invalid", str(e)

            task = supervised_task(run(), name=f"reshard-{action}-{source}")
            rec = {"key": key, "task": task}
            self._reshard_inflight = rec
            task.add_done_callback(
                lambda _t, rec=rec: (
                    setattr(self, "_reshard_inflight", None)
                    if self._reshard_inflight is rec else None
                )
            )
        # shield: an impatient client disconnecting must not cancel a
        # half-streamed migration
        status, result = await _aio.shield(task)
        if status == "invalid":
            return Response.text(result, 400)
        if status == "aborted":
            return Response.json(
                {"aborted": result, "epoch": self._shards.epoch}, status=409,
            )
        new_map = result if hasattr(result, "epoch") else self._shards.current()
        return Response.json(
            {"epoch": new_map.epoch, "groups": list(new_map.groups)}
        )

    async def _shards_route(self, req: Request) -> Response:
        """GET /shards with conditional-get + long-poll gossip semantics."""
        etag = req.headers.get("if-none-match", "").strip().strip('"')
        fresh = etag and etag == str(self._shards.epoch)
        if fresh:
            try:
                wait = float(req.query.get("wait", 0) or 0)
            except ValueError:
                wait = 0.0
            if wait > 0 and self._gossip is not None:
                await self._gossip.wait_change(
                    min(wait, self.cfg.shards_wait_cap)
                )
            if etag == str(self._shards.epoch):
                return Response(
                    304, headers={"ETag": f'"{self._shards.epoch}"'}
                )
        resp = Response.json(self.abd.status())
        resp.headers["ETag"] = f'"{self._shards.epoch}"'
        return resp

    _BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}

    def _sample_state_gauges(self) -> None:
        """Refresh scrape-time gauges: breaker + suspicion state per
        coordinator, membership counts, store size."""
        for node, state in self.abd.breaker_states().items():
            metrics.set(
                "dds_breaker_state", self._BREAKER_STATE_CODE.get(state, -1),
                node=node.rsplit("/", 1)[-1],
                help="per-coordinator breaker: 0=closed 1=half_open 2=open",
            )
        for node, strikes in self.abd.replicas.suspicions().items():
            metrics.set(
                "dds_replica_suspicion", strikes, node=node.rsplit("/", 1)[-1],
                help="permanent protocol-violation strikes per replica",
            )
        metrics.set(
            "dds_trusted_replicas", len(self.abd.replicas.get_trusted()),
            help="replicas under the 3-strike suspicion limit",
        )
        metrics.set("dds_stored_keys", len(self.stored_keys),
                    help="aggregate key-set size")
        if self._tenancy_enabled and self._tenant_owner:
            counts_t: dict[str, int] = {}
            for k in self.stored_keys:
                t = self._key_tenant(k)
                if t == CANARY_TENANT:
                    continue  # synthetic keyspace, not a tenant footprint
                counts_t[t] = counts_t.get(t, 0) + 1
            for t, n in counts_t.items():
                metrics.set(
                    "dds_tenant_stored_keys", n, tenant=t,
                    help="stored aggregate keys per tenant (proxy view)",
                )
        if self._shards is not None:
            smap = self._shards.current()
            metrics.set("dds_shard_epoch", smap.epoch,
                        help="active shard-map epoch")
            metrics.set(
                "dds_shard_reshard_state",
                1 if self._shards.state == "resharding" else 0,
                help="0=stable 1=resharding",
            )
            metrics.set("dds_shard_groups", len(smap.groups),
                        help="quorum groups in the active shard map")
            counts = {g: 0 for g in smap.groups}
            for k in self.stored_keys:  # the proxy's aggregate-key view
                counts[smap.owner(k)] = counts.get(smap.owner(k), 0) + 1
            for gid, n in counts.items():
                metrics.set(
                    "dds_shard_keys", n, shard=gid,
                    help="stored aggregate keys per shard (proxy view)",
                )
        # Bulwark admission surface: shed level is set at transition time
        # too, but a scrape between transitions still deserves the truth;
        # the coalescing window is pure scrape-time state
        if self.admission is not None:
            metrics.set(
                "dds_admission_shed_level", self.admission.shed_level,
                help="Bulwark shed level (0=none; higher sheds lower "
                     "priority classes first)",
            )
        if self._coalescer is not None:
            metrics.set(
                "dds_admission_coalesce_window_seconds",
                self._coalescer.window(),
                help="current adaptive fold-coalescing window",
            )
        if self._resident is not None:
            # Lodestone gauges: dds_resident_{rows,bytes,hit_ratio,
            # resets}{shard=...}, aggregated per group at scrape time
            self._resident.export_gauges(metrics)
        if self._stratum is not None:
            # Stratum gauges: dds_tier_{rows,bytes}{tier,shard} — tier
            # occupancy per shard group at scrape time
            self._stratum.export_gauges(metrics)
        if self._search is not None:
            # Spyglass gauges: dds_search_{index_keys,index_packs,
            # pending_ingest,...}, per group at scrape time
            self._search.export_gauges(metrics)
        # Chronoscope pipe profile (dds_pipe_*): per-route/per-stage
        # critical-path self-times, plus the fold-coalescer's queue depth
        # (entries parked awaiting the adaptive window)
        from dds_tpu.obs.chronoscope import chronoscope
        chronoscope.export_gauges(metrics)
        metrics.set(
            "dds_queue_depth",
            sum(len(g) for g in self._fold_pending.values()),
            queue="fold-coalescer",
            help="entries waiting in a bounded pipeline queue",
        )
        # registry self-observation: label sets folded into `overflow`
        # across all families — attribution decays silently once this
        # moves, so dashboards must be able to alarm on it directly
        metrics.set(
            "dds_metrics_dropped_series", metrics.overflow_total(),
            help="total label sets dropped into overflow series by the "
                 "per-family cardinality cap",
        )
        # Heliograph canary gauges: last verdict / last-ok age per probe
        # kind, rotating failure exemplars, region unreachable streaks
        if self.heliograph is not None:
            self.heliograph.export_gauges(metrics)
        # SLO burn/budget gauges + audit backlog (scrape-time freshness is
        # all a gauge promises; the violation COUNTER increments at
        # detection time in the auditor itself)
        self.slo.export_gauges(metrics)
        wt = watchtower.stats()
        metrics.set("dds_audit_traces_audited", wt["traces_audited"],
                    help="traces audited by the Watchtower since start")
        metrics.set("dds_audit_pending_traces", wt["pending_traces"],
                    help="in-flight traces buffered awaiting audit")
        # Aegis recovery surface (local replicas only): anti-entropy
        # divergence + sync age, snapshot generation + age
        for node in (self.local_replicas or {}).values():
            stats = node.antientropy.stats()
            metrics.set(
                "dds_antientropy_divergent_buckets",
                stats["divergent_buckets"], replica=node.name,
                help="divergent Merkle buckets seen in the last sync round",
            )
            if stats["last_sync_age"] is not None:
                metrics.set(
                    "dds_antientropy_last_sync_age_seconds",
                    stats["last_sync_age"], replica=node.name,
                    help="seconds since the last completed anti-entropy round",
                )
            sm = node.snapshot_meta
            if sm.get("generation") is not None:
                metrics.set(
                    "dds_snapshot_generation", sm["generation"],
                    replica=node.name,
                    help="latest snapshot generation written or loaded",
                )
            if sm.get("saved_at"):
                metrics.set(
                    "dds_snapshot_age_seconds",
                    max(0.0, time.time() - sm["saved_at"]), replica=node.name,
                    help="seconds since this replica's snapshot was written",
                )

    def _recovery_status(self) -> dict | None:
        """Per-local-replica Aegis view for /health: anti-entropy sync
        state and snapshot durability state."""
        if not self.local_replicas:
            return None
        out = {}
        for node in self.local_replicas.values():
            stats = node.antientropy.stats()
            sm = node.snapshot_meta
            out[node.name] = {
                "merkle_root": node.merkle.root()[:16],
                "tracked_keys": len(node.merkle),
                "anti_entropy": {
                    "rounds": stats["rounds"],
                    "repaired_keys": stats["repaired_keys"],
                    "divergent_buckets": stats["divergent_buckets"],
                    "last_sync_age": stats["last_sync_age"],
                    "running": stats["running"],
                },
                "snapshot": {
                    "generation": sm.get("generation"),
                    "age": (
                        max(0.0, round(time.time() - sm["saved_at"], 3))
                        if sm.get("saved_at") else None
                    ),
                    "verify_failures": metrics.value(
                        "dds_snapshot_verify_failures_total",
                        replica=node.name,
                    ) or 0,
                },
            }
        return out

    # ----------------------------------------------------- aggregate helpers

    async def _pair_aggregate(self, req: Request, modparam: str) -> Response:
        """`Sum` / `Mult`: combine one position of two records."""
        key1, key2 = req.query["key1"], req.query["key2"]
        if (denied := self._tenant_denied(key1, key2)) is not None:
            return denied
        pos = self._pos(req)
        mod = req.query.get(modparam)
        set1, set2 = await asyncio.gather(self._fetch(key1), self._fetch(key2))
        if set1 is None or set2 is None:
            return Response(404)
        if len(set1) - 1 < pos or len(set2) - 1 < pos:
            return Response(404)
        c1, c2 = int(set1[pos]), int(set2[pos])
        if mod:
            result = self.backend.modmul(c1, c2, self._parse_modulus(mod, modparam))
        else:
            result = c1 + c2 if modparam == "nsqr" else c1 * c2
        return Response.json(J.value_result(str(result)))

    async def _fold_aggregate(self, req: Request, modparam: str) -> Response:
        """`SumAll` / `MultAll`: fold one position across ALL stored records.

        This is the north-star workload (SURVEY.md §3.4): on the tpu
        backend the fold is one batched Montgomery tree-reduction.
        """
        pos = self._pos(req)
        mod = req.query.get(modparam)
        pairs = await self._fetch_visible()
        memo = self._operand_memo
        if memo is not None and memo[0] is pairs and memo[1] == pos:
            # identity match: _fetch_stored returned its memoized pairs
            # list, so the extracted column is unchanged too
            operands = memo[2]
        else:
            operands = [int(v[pos]) for _, v in pairs if pos < len(v)]
            self._operand_memo = (pairs, pos, operands)
        if not operands:
            return Response(404)
        metrics.observe(
            "dds_fold_batch_size", len(operands), buckets=SIZE_BUCKETS,
            help="aggregate fold width (operand count)",
        )
        if mod:
            modulus = self._parse_modulus(mod, modparam)
            result = None
            if (
                self._resident is not None
                and len(operands) >= self._resident_min_fold
            ):
                # Lodestone: route per-owner operand sets to their group
                # pools and run ONE fused gather+fold dispatch (per-group
                # local tree + the combine_partials tail tree, on-device)
                # instead of S separate marshaling folds. Falls through
                # (None) only when an operand set is wider than its pool
                # even after a reset.
                parts = self._owner_operands(pairs, pos)
                # Stratum routes the same call through the tier planner:
                # resident leg fused as before, warm/cold legs streamed
                # and merged exactly. Without it, plane folds directly.
                folder = (
                    self._stratum.fold_groups
                    if self._stratum is not None
                    else self._resident.fold_groups
                )
                with tracer.span("proxy.resident_fold", k=len(operands),
                                 shards=len(parts),
                                 backend=self.backend.name):
                    result = await asyncio.to_thread(
                        folder, parts, modulus, self._plane_tenant(),
                    )
            if result is not None:
                return Response.json(J.value_result(str(result)))
            shard_ops = (
                self._shard_operands(pairs, pos)
                if self._shards is not None else None
            )
            if shard_ops is not None and len(shard_ops) > 1:
                # Constellation scatter-gather: one coalescable fold per
                # shard, dispatched CONCURRENTLY so they share a single
                # segmented foldmany device dispatch (the coalescing
                # window sees them in flight together), then the partials
                # merge with the mesh plane's modular-product tail combine
                # — all shards share one Paillier modulus, so the result
                # is bit-identical to the unsharded fold.
                from dds_tpu.parallel.mesh import combine_partials

                with tracer.span("proxy.scatter_fold", k=len(operands),
                                 shards=len(shard_ops),
                                 backend=self.backend.name):
                    partials = await asyncio.gather(
                        *(self._fold(g, modulus) for g in shard_ops)
                    )
                    result = combine_partials(
                        [int(p) for p in partials], modulus
                    )
            else:
                # device-resident path when the backend has a cipher store:
                # quorum/tag validation above is still authoritative; the
                # store only memoizes limb conversion + transfer
                # (ops/store.py). The fold runs in a worker thread so
                # concurrent aggregate requests overlap their device
                # dispatches (and the event loop keeps serving) instead of
                # serializing on a blocking fetch.
                with tracer.span("proxy.fold", k=len(operands),
                                 backend=self.backend.name):
                    result = await self._fold(operands, modulus)
        elif modparam == "nsqr":
            result = sum(operands)
        else:
            result = 1
            for o in operands:
                result *= o
        return Response.json(J.value_result(str(result)))

    # ------------------------------------------------- Prism analytics routes

    def _columns(self, pairs, pos: int) -> tuple[list[str], list[int]]:
        """(keys, ciphertexts) of every stored record holding position
        `pos`, in sorted-key order — the operand column order the analytics
        routes expose (and echo back as `keys` so clients can line their
        weight matrices up). Memoized per pairs-identity like the flat
        operand memo."""
        memo = self._column_memo
        if memo is not None and memo[0] is pairs and memo[1] == pos:
            return memo[2], memo[3]
        keys = [k for k, v in pairs if pos < len(v)]
        ciphers = [int(v[pos]) for _, v in pairs if pos < len(v)]
        self._column_memo = (pairs, pos, keys, ciphers)
        return keys, ciphers

    async def _analytics(self, name: str, req: Request) -> Response:
        """`MatVec` / `WeightedSum` / `GroupBySum`: server-side
        Enc(W @ x) over the stored records' position-`pos` ciphertexts
        (analytics/prism.py). Validation failures raise ValueError ->
        400 via handle(); the body-size cap answers 413 before JSON
        parsing so an oversized weight blob never costs a parse."""
        cap = self.cfg.analytics_max_request_bytes
        if cap > 0 and len(req.body) > cap:
            return Response(
                413,
                f"analytics request body exceeds {cap} bytes".encode(),
            )
        pos = self._pos(req)
        n, n2 = self.prism.parse_nsqr(req.query["nsqr"])
        pairs = await self._fetch_visible()
        keys, ciphers = self._columns(pairs, pos)
        if not ciphers:
            return Response(404)
        body = req.json()
        labels = None
        if name == "MatVec":
            rows = J.parse_weight_matrix(body)
        elif name == "WeightedSum":
            rows = [J.parse_weight_row(body)]
        else:  # GroupBySum: 0/1 selector rollups over record keys
            labels, rows = self.prism.selector_rows(J.parse_groups(body), keys)
        encoded = self.prism.encode_weights(rows, n, cols=len(ciphers))
        out = await self.prism.evaluate(
            name, keys, ciphers, encoded, n2, tenant=self._plane_tenant()
        )
        if name == "WeightedSum":
            return Response.json({"result": str(out[0]), "keys": keys})
        if labels is not None:
            return Response.json(
                {"result": {lb: str(c) for lb, c in zip(labels, out)}}
            )
        return Response.json(
            {"result": [str(c) for c in out], "keys": keys}
        )

    def _owner_operands(self, pairs, pos: int) -> list[tuple[str, list[int]]]:
        """Aggregate operands partitioned by owning shard group, with the
        group id attached (the Lodestone pool key). Unsharded proxies get
        one anonymous group. Memoized per pairs-identity like the flat
        operand memo — between writes the partition is state-identical,
        and the stable operand-list identities are what the pools' row-
        index memos key on."""
        memo = self._owner_memo
        if memo is not None and memo[0] is pairs and memo[1] == pos:
            return memo[2]
        groups: dict[str, list[int]] = {}
        for k, v in pairs:
            if pos < len(v):
                gid = self.abd.owner(k) if self._shards is not None else ""
                groups.setdefault(gid, []).append(int(v[pos]))
        out = [(gid, g) for gid, g in groups.items() if g]
        self._owner_memo = (pairs, pos, out)
        return out

    def _shard_operands(self, pairs, pos: int) -> list[list[int]]:
        """Aggregate operands partitioned by owning shard group (memoized
        per pairs-identity like the flat operand memo — between writes the
        partition is state-identical)."""
        memo = self._scatter_memo
        if memo is not None and memo[0] is pairs and memo[1] == pos:
            return memo[2]
        out = [g for _, g in self._owner_operands(pairs, pos)]
        self._scatter_memo = (pairs, pos, out)
        return out

    def _backend_fold_fn(self):
        """The backend's single-aggregate fold entry point (the
        device-store-aware variant when the backend has one)."""
        return getattr(
            self.backend, "modmul_fold_resident", self.backend.modmul_fold
        )

    async def _fold(self, operands: list[int], modulus: int):
        """Dispatch one aggregate's fold: wide folds go straight to the
        backend on a worker thread; small folds (below the device-batch
        crossover, where dispatch latency beats the math) enter the
        coalescing window so CONCURRENT small aggregates share one
        segmented device dispatch (ProxyConfig.coalesce_window).

        A small fold only enters the window when other folds are already
        executing or queued — observed concurrency is the signal there is
        something to coalesce with; a lone request pays zero extra latency."""
        be = self.backend
        fold = self._backend_fold_fn()
        min_batch = getattr(be, "min_device_batch", 0)
        if self._coalescer is not None:
            # Bulwark adaptive coalescing: every fold arrival feeds the
            # rate estimate the window is sized from, whichever path it
            # takes below
            self._coalescer.note_fold(len(operands))
        concurrent = self._folds_inflight > 0 or bool(self._fold_pending)
        if (
            self.cfg.coalesce_window <= 0
            or not hasattr(be, "modmul_fold_many")
            or len(operands) >= min_batch
            or not concurrent
        ):
            self._folds_inflight += 1
            try:
                return await asyncio.to_thread(fold, operands, modulus)
            finally:
                self._folds_inflight -= 1
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # carry the waiter's trace context + enqueue time into the drain:
        # the dispatcher runs under the DRAINER task's context, so the
        # per-waiter coalesce-wait/fold spans must be re-homed explicitly
        self._fold_pending.setdefault(modulus, []).append(
            (time.perf_counter(), operands, fut, obs_context.current())
        )
        if self._fold_drainer is None or self._fold_drainer.done():
            self._fold_drainer = supervised_task(self._drain_folds(),
                                                 name="proxy.fold_drainer")
        return await fut

    def _coalesce_window(self) -> float:
        """The gather window for this drain cycle: adaptive (sized from
        observed fold arrival rate) when Bulwark armed it, else the
        config constant."""
        if self._coalescer is not None:
            return self._coalescer.window()
        return self.cfg.coalesce_window

    async def _drain_folds(self) -> None:
        await asyncio.sleep(self._coalesce_window())
        while self._fold_pending:
            # snapshot ALL pending groups and dispatch them concurrently:
            # different moduli must overlap their dispatches (the whole
            # point of folding in threads), and draining one at a time
            # would let a continuously re-queued hot modulus starve others
            groups = list(self._fold_pending.items())
            self._fold_pending.clear()
            await asyncio.gather(
                *(self._dispatch_fold_group(m, g) for m, g in groups)
            )

    async def _dispatch_fold_group(self, modulus: int, group: list) -> None:
        folds = [ops_ for _, ops_, _, _ in group]
        futs = [f for _, _, f, _ in group]
        t_start = time.perf_counter()
        for t_enq, ops_, _, wctx in group:
            # each waiter's sat-in-the-window time, in ITS OWN trace
            tracer.record(
                "proxy.coalesce_wait", (t_start - t_enq) * 1e3,
                _ctx=obs_context.child(wctx) if wctx is not None else None,
                batch=len(group), k=len(ops_),
            )
        self._folds_inflight += 1
        try:
            total = sum(len(f) for f in folds)
            if len(folds) == 1 or total < getattr(
                self.backend, "min_device_batch", 0
            ):
                # a lone fold, or a group whose COMBINED width is still
                # below the device crossover: host folds win there. One
                # worker thread per fold (not one serial loop): native
                # host folds release the GIL, so group members overlap
                # exactly as they would have without the window
                fold = self._backend_fold_fn()
                results = await asyncio.gather(
                    *(asyncio.to_thread(fold, f, modulus) for f in folds)
                )
            else:
                results = await asyncio.to_thread(
                    self.backend.modmul_fold_many, folds, modulus
                )
            t_done = time.perf_counter()
            for (_, ops_, _, wctx), _r in zip(group, results):
                # the shared device dispatch, visible from every waiter's
                # waterfall (self-time classifies as dispatch/execute)
                tracer.record(
                    "proxy.coalesced_fold", (t_done - t_start) * 1e3,
                    _ctx=obs_context.child(wctx) if wctx is not None
                    else None,
                    batch=len(group), k=len(ops_),
                )
            for f, r in zip(futs, results):
                if not f.cancelled():
                    f.set_result(r)
        except Exception as e:  # surface to every waiting request
            for f in futs:
                if not f.cancelled():
                    f.set_exception(e)
        finally:
            self._folds_inflight -= 1
            # a cancellation (e.g. stop() mid-dispatch) must not orphan
            # the group: its futures are no longer in _fold_pending, so
            # stop()'s sweep cannot see them — fail them here
            for f in futs:
                if not f.done():
                    f.set_exception(ConnectionError("proxy stopping"))

    @staticmethod
    def _pos(req: Request) -> int:
        """Parse the `position` query param; negative values are rejected
        (python negative indexing must not leak ciphertext columns)."""
        pos = int(req.query["position"])
        if pos < 0:
            raise ValueError("position must be >= 0")
        return pos

    @staticmethod
    def _parse_modulus(mod: str, modparam: str) -> int:
        """`nsqr` arrives as decimal n^2; `pubkey` as decimal RSA modulus n.

        (The reference ships an X509-encoded RSA key blob for `pubkey`
        (`DDSRestServer.scala:474-477`); our wire format is the bare modulus
        — same information, no Java key serialization.)
        """
        return int(mod)
