"""System bootstrap: the `Main.scala` equivalent.

Builds the full deployment from one typed config — transport, supervisor,
replicas (putting sentinels to sleep), REST proxy, N workload clients, and
the Trudy attack trigger — mirroring the boot call stack in SURVEY.md §3.1.

Run a self-contained node + workload:

    python -m dds_tpu.run --ops 100 --backend tpu
    python -m dds_tpu.run --config configs/default.toml
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
from dataclasses import dataclass, field

from dds_tpu.clt.client import ClientConfig, DDSHttpClient
from dds_tpu.clt.generator import generate
from dds_tpu.clt.instructions import Digest
from dds_tpu.core import messages as M
from dds_tpu.utils.sigs import generate_nonce as sigs_generate_nonce
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.core.transport import InMemoryNet, TcpNet
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.obs.slo import SloEngine
from dds_tpu.malicious.trudy import Trudy
from dds_tpu.models.facade import HomoProvider
from dds_tpu.utils.config import DDSConfig

log = logging.getLogger("dds.run")

SUPERVISOR_NAME = "supervisor"


@dataclass
class Deployment:
    cfg: DDSConfig
    net: object
    replicas: dict[str, BFTABDNode]
    supervisor: BFTSupervisor
    server: DDSRestServer
    trudy: Trudy
    ssl_client: object = None
    _stoppables: list = field(default_factory=list)
    # Constellation (shard.enabled): the sharded-plane handle — per-group
    # ShardGroup list lives on constellation.groups; `replicas` above is
    # the merged view (snapshots / anti-entropy / health reuse it as-is)
    constellation: object = None

    async def stop(self) -> None:
        if self.constellation is not None:
            await self.constellation.stop()
        if self.supervisor is not None:
            await self.supervisor.stop()
        await self.server.stop()
        for s in self._stoppables:
            await s.stop()
        # the Watchtower was configured for THIS deployment's quorum
        # geometry; left attached it would audit a later deployment (or a
        # test harness's cluster) against the wrong q/n and cry wolf
        from dds_tpu.obs.watchtower import watchtower

        if self.cfg.obs.audit_enabled:
            watchtower.detach()
        # Chronoscope is a process-wide singleton like the Watchtower:
        # detach so a later deployment (or test) starts with a clean feed
        from dds_tpu.obs.chronoscope import chronoscope

        chronoscope.detach()
        chronoscope.reset()


async def launch(cfg: DDSConfig | None = None) -> Deployment:
    cfg = cfg or DDSConfig()
    stoppables = []

    # Atlas [retry]: the per-region deadline/backoff overrides for THIS
    # process's [fabric] region land directly on the effective [proxy]
    # settings, so every downstream consumer (single-group boot, the
    # constellation, the Meridian roles) sees the derived budgets without
    # per-call-site plumbing. DEPLOY.md "Geo-distribution (Atlas)"
    # documents the rtt-ms derivation.
    if cfg.fabric.region:
        for k, v in cfg.retry.overrides_for(cfg.fabric.region).items():
            setattr(cfg.proxy, k, v)

    # Bastion [tenancy]: the metrics-cardinality ceiling applies process-
    # wide before any tenant-labeled series exists — a tenant flood must
    # overflow into the guard bucket, never balloon the registry
    if cfg.tenancy.enabled:
        from dds_tpu.obs.metrics import metrics as _metrics

        _metrics.max_series = int(cfg.tenancy.metrics_max_series)

    # Telescope wiring: hand the process-wide flight recorder its incident
    # directory (it stays disabled without one — fault-path disk writes
    # are opt-in)
    if cfg.obs.flight_dir:
        from dds_tpu.obs.flight import flight

        flight.configure(
            dir=cfg.obs.flight_dir,
            max_incidents=cfg.obs.flight_max_incidents,
            min_interval=cfg.obs.flight_min_interval,
        )

    # mutual TLS on the HTTP hops (SURVEY §2.14/§2.20 posture, configurable)
    sec = cfg.security
    ssl_server = ssl_client = None
    intranet_server = intranet_client = None
    if sec.tls_enabled or sec.intranet_tls_enabled:
        from dds_tpu.utils import tlsutil

        if sec.tls_ca and sec.tls_cert and sec.tls_key:
            ca, cert, key = sec.tls_ca, sec.tls_cert, sec.tls_key
        else:
            # dev fallback: per-node CA — single-host only (see SecurityConfig)
            paths = tlsutil.generate_ca_and_cert(
                sec.tls_dir,
                hosts=(cfg.proxy.host, cfg.transport.host, "localhost"),
            )
            ca, cert, key = paths["ca"], paths["cert"], paths["key"]
        if sec.tls_enabled:
            ssl_server = tlsutil.server_context(cert, key, ca)
            ssl_client = tlsutil.client_context(
                ca, cert, key, verify_hostname=sec.tls_verify_hostname
            )
        if sec.intranet_tls_enabled:
            # replica fabric mutual TLS — the netty-SSL intranet of the
            # reference (`dds-system.conf:18-58`): every hop presents a
            # CA-signed cert in both directions, giving the sender-keyed
            # quorum votes transport-level authenticity on top of frame MACs
            intranet_server = tlsutil.server_context(cert, key, ca)
            intranet_client = tlsutil.client_context(
                ca, cert, key, verify_hostname=sec.tls_verify_hostname
            )

    # transport fabric (SURVEY.md §5.8: control plane stays on CPU/asyncio)
    if cfg.transport.kind == "tcp":
        node_key = peer_keys = None
        if cfg.security.node_public_keys:
            from dds_tpu.utils import nodeauth

            if not cfg.security.node_key_path:
                raise ValueError(
                    "security.node_public_keys set but node_key_path empty"
                )
            node_key = nodeauth.load_or_create(cfg.security.node_key_path)
            peer_keys = nodeauth.registry(cfg.security.node_public_keys)
        net = TcpNet(
            cfg.transport.host,
            cfg.transport.port,
            ssl_server=intranet_server,
            ssl_client=intranet_client,
            frame_secret=cfg.security.transport_frame_secret.encode() or None,
            node_key=node_key,
            peer_keys=peer_keys,
            advertise=cfg.transport.advertise,
        )
        await net.start()
        cfg.transport.port = net.port  # resolve OS-assigned port 0
        stoppables.append(net)
        # Every endpoint must be a routable `host:port/name` address
        # (`TcpNet.split`): names map through `replicas.addresses`, the
        # per-host topology of `dds-system.conf:113-128`; unmapped names
        # live in this process. Always the ADVERTISED address — frames this
        # process signs carry it as src, and peers verify src against their
        # node_public_keys registry.
        local_hostport = net.advertised
        if peer_keys is not None and local_hostport not in cfg.security.node_public_keys:
            await net.stop()  # fail-fast must not leak the bound listener
            raise ValueError(
                f"per-node identity is on but this process's advertised "
                f"address {local_hostport!r} is not in "
                f"security.node_public_keys — peers could never verify its "
                f"frames (set transport.advertise to the registered address, "
                f"or register this one)"
            )

        def full(name: str) -> str:
            return f"{cfg.replicas.addresses.get(name, local_hostport)}/{name}"

    else:
        net = InMemoryNet()
        local_hostport = None

        def full(name: str) -> str:
            return name

    if cfg.attacks.chaos_enabled:
        # seeded fault fabric: every send traverses the ChaosNet schedule,
        # and Nemesis (below) gains partition/delay/flood/heal attacks.
        # The inner transport stays in `stoppables`; ChaosNet.stop only
        # cancels its own deferred deliveries.
        from dds_tpu.core.chaos import ChaosNet

        net = ChaosNet(net, seed=cfg.attacks.chaos_seed)
        stoppables.append(net)
        if cfg.chaos.profiles:
            # Atlas [chaos.profiles]: named WAN link matrix between
            # region pairs. Endpoint -> region assignments arrive later
            # (the constellation builder registers placements), which is
            # fine — links key on region names and resolve per send.
            from dds_tpu.geo import wan as _wan

            _wan.apply_profiles(net, cfg.chaos.profiles,
                                scale=cfg.chaos.scale)

    if cfg.shard.enabled:
        if cfg.transport.kind == "tcp":
            # Meridian (dds_tpu/fabric): the multi-host shard fabric —
            # per-[fabric]-role this process hosts the whole constellation,
            # one quorum group, or a remote proxy, over the authenticated
            # TcpNet, with the signed shard map distributed via
            # GET /shards bootstrap + epoch gossip
            from dds_tpu.fabric.deploy import launch_meridian

            try:
                return await launch_meridian(
                    cfg, net, stoppables, ssl_server, ssl_client
                )
            except Exception:
                # fail-fast must not leak the bound listener (or a chaos
                # wrapper's timers) — mirror the nodeauth check above
                for s in reversed(stoppables):
                    try:
                        await s.stop()
                    except Exception:
                        pass
                raise
        # Constellation: S independent quorum groups behind a shard router
        # over the in-process fabric (the shard-map install step is an
        # in-process config push; see utils/config.ShardConfig)
        return await _launch_constellation(
            cfg, net, stoppables, ssl_server, ssl_client
        )

    rcfg = ReplicaConfig(
        quorum_size=cfg.replicas.byz_quorum_size,
        nonce_increment=cfg.security.nonce_challenge_increment,
        abd_mac_secret=cfg.security.abd_mac_secret.encode(),
        proxy_mac_secret=cfg.security.proxy_mac_secret.encode(),
        debug=cfg.debug,
        allow_fault_injection=cfg.attacks.enabled,
    )

    endpoints = [full(e) for e in cfg.replicas.endpoints]
    sentinent_names = set(cfg.replicas.sentinent)
    sentinent = [full(e) for e in cfg.replicas.endpoints if e in sentinent_names]
    active = [e for e in endpoints if e not in set(sentinent)]

    # `Main.scala:90-99`: a process spawns only ITS replicas; the rest of
    # the quorum is reached over the fabric. Default = every name mapped to
    # this process (memory transport: all of them).
    if cfg.replicas.local:
        local_names = set(cfg.replicas.local)
    elif local_hostport is not None:
        local_names = {
            n for n in cfg.replicas.endpoints
            if cfg.replicas.addresses.get(n, local_hostport) == local_hostport
        }
    else:
        local_names = set(cfg.replicas.endpoints)

    sup_local = (
        local_hostport is None
        or not cfg.replicas.supervisor_address
        or cfg.replicas.supervisor_address == local_hostport
    )
    sup_addr = (
        SUPERVISOR_NAME
        if local_hostport is None
        else f"{cfg.replicas.supervisor_address or local_hostport}/{SUPERVISOR_NAME}"
    )

    replicas = {
        full(e): BFTABDNode(full(e), endpoints, sup_addr, net, rcfg)
        for e in cfg.replicas.endpoints
        if e in local_names
    }
    for e in sentinent:
        if e in replicas:
            replicas[e].behavior = "sentinent"  # Main.scala:96-98

    # optional snapshot restore + periodic save (core/snapshot.py v2:
    # authenticated generations; corrupt/forged files are quarantined by
    # load_all, never allowed to abort this boot)
    snap_secret = None
    if cfg.recovery.snapshot_dir:
        from dds_tpu.core import snapshot as snap

        snap_secret = snap.derive_secret(
            (cfg.recovery.snapshot_secret or cfg.security.abd_mac_secret).encode(),
            cfg.security.node_key_path or None,
        )
        restored = snap.load_all(
            replicas, cfg.recovery.snapshot_dir, secret=snap_secret
        )
        if restored:
            log.info("restored %d replica snapshots from %s", restored,
                     cfg.recovery.snapshot_dir)

    def _start_antientropy(node: BFTABDNode) -> None:
        node.antientropy.configure(
            interval=cfg.recovery.anti_entropy_interval,
            jitter=cfg.recovery.anti_entropy_jitter,
        )
        node.antientropy.start()

    def _rebuild_local(endpoint: str) -> None:
        old = replicas.get(endpoint)
        if old is not None:
            old.antientropy.cancel()  # the replaced node's loop must die
        replicas[endpoint] = BFTABDNode(endpoint, endpoints, sup_addr, net, rcfg)
        if cfg.recovery.anti_entropy_enabled:
            _start_antientropy(replicas[endpoint])

    # per-host node agent: honors the supervisor's Redeploy for replicas
    # THIS process owns — the `Main` process is what re-instantiates
    # actors in the reference's remote deployment too
    # (`BFTSupervisor.scala:130-149`). A target still on the transport is
    # NOT rebuilt (a stray/duplicate Redeploy must not wipe a live
    # replica's state); either way the agent acks so the supervisor's
    # reseed can proceed.
    async def _nodehost(sender: str, msg) -> None:
        if isinstance(msg, M.Redeploy) and msg.endpoint in replicas:
            if net.has_endpoint(msg.endpoint):
                log.info("nodehost: %s is alive, not rebuilding", msg.endpoint)
            else:
                log.info(
                    "nodehost rebuilding %s (asked by %s)", msg.endpoint, sender
                )
                _rebuild_local(msg.endpoint)
            net.send(full("nodehost"), sender, M.Redeployed(msg.endpoint))

    net.register(full("nodehost"), _nodehost)

    async def redeploy(endpoint: str) -> None:
        """Supervisor redeploy hook: rebuild locally when this process owns
        the endpoint, else ask the owning host's node agent over the
        fabric and await its Redeployed ack (retrying a couple of times —
        a silently dropped frame must not leave the supervisor reseeding
        a node that was never rebuilt)."""
        if endpoint in replicas:
            if not net.has_endpoint(endpoint):
                _rebuild_local(endpoint)
            return
        hostport = endpoint.rsplit("/", 1)[0]
        ack: asyncio.Future = asyncio.get_event_loop().create_future()
        tmp = full(f"redeploy-ack-{sigs_generate_nonce()}")

        async def on_ack(sender: str, msg) -> None:
            if (
                isinstance(msg, M.Redeployed)
                and msg.endpoint == endpoint
                and not ack.done()
            ):
                ack.set_result(True)

        net.register(tmp, on_ack)
        try:
            for _ in range(3):
                net.send(tmp, f"{hostport}/nodehost", M.Redeploy(endpoint))
                try:
                    await asyncio.wait_for(asyncio.shield(ack), 1.0)
                    return
                except asyncio.TimeoutError:
                    continue
            log.warning("redeploy of %s was never acknowledged", endpoint)
        finally:
            net.unregister(tmp)

    supervisor = None
    if sup_local:
        supervisor = BFTSupervisor(
            sup_addr,
            active,
            sentinent,
            net,
            SupervisorConfig(
                quorum_size=cfg.replicas.byz_quorum_size,
                proactive_recovery_warmup=cfg.recovery.warm_up,
                proactive_recovery_interval=cfg.recovery.interval,
                sentinent_awake_timeout=cfg.recovery.sentinent_awake_timeout,
                crashed_recovery_timeout=cfg.recovery.crashed_recovery_timeout,
                proactive_recovery_enabled=cfg.recovery.enabled,
                verified_transfer=cfg.recovery.verified_transfer,
                manifest_timeout=cfg.recovery.manifest_timeout,
                state_chunk_keys=cfg.recovery.state_chunk_keys,
                abd_mac_secret=cfg.security.abd_mac_secret.encode(),
                debug=cfg.debug,
            ),
            redeploy=redeploy,
        )
        supervisor.start()

    abd = AbdClient(
        full("proxy-0"),
        net,
        active,
        AbdClientConfig(
            proxy_mac_secret=cfg.security.proxy_mac_secret.encode(),
            nonce_increment=cfg.security.nonce_challenge_increment,
            request_timeout=cfg.proxy.intranet_request_timeout,
            abd_mac_secret=cfg.security.abd_mac_secret.encode(),
            quorum_size=cfg.replicas.byz_quorum_size,
            breaker_threshold=cfg.proxy.breaker_threshold,
            breaker_reset=cfg.proxy.breaker_reset,
            fast_fail_all_open=cfg.admission.fast_fail,
        ),
    )
    server = DDSRestServer(
        abd,
        ProxyConfig(
            host=cfg.proxy.host,
            port=cfg.proxy.port,
            region=cfg.fabric.region,
            request_budget=cfg.proxy.request_budget,
            retry_backoff=cfg.proxy.retry_backoff,
            retry_max_delay=cfg.proxy.retry_max_delay,
            retry_attempts=cfg.proxy.retry_attempts,
            retry_after_hint=cfg.proxy.retry_after_hint,
            handler_timeout=cfg.proxy.handler_timeout,
            crypto_backend=cfg.proxy.crypto_backend,
            key_sync_enabled=cfg.proxy.key_sync_enabled,
            key_sync_warmup=cfg.proxy.key_sync_warm_up,
            key_sync_interval=cfg.proxy.key_sync_interval,
            peers=cfg.proxy.remote_peers,
            keys_path=cfg.proxy.stored_keys_path,
            coalesce_window=cfg.proxy.coalesce_window,
            supervisor=sup_addr,
            trace_route_enabled=cfg.debug or cfg.obs.trace_route,
            metrics_route_enabled=cfg.obs.metrics_route,
            slo_route_enabled=cfg.obs.slo_route,
            analytics_enabled=cfg.analytics.enabled,
            analytics_max_rows=cfg.analytics.max_rows,
            analytics_max_request_bytes=cfg.analytics.max_request_bytes,
            admission=cfg.admission,
            tenancy=cfg.tenancy,
            resident=cfg.resident,
            search=cfg.search,
            storage=cfg.storage,
            heliograph=cfg.heliograph,
            ssl_server_context=ssl_server,
            ssl_client_context=ssl_client,
        ),
        local_replicas=replicas,
        slo=SloEngine.from_obs(cfg.obs),
    )
    await server.start()

    # Merkle anti-entropy loops: one pull agent per local replica, on a
    # jittered timer so the fleet's rounds spread out instead of thundering
    if cfg.recovery.anti_entropy_enabled:
        for node in replicas.values():
            _start_antientropy(node)

        class _AntiEntropyStopper:
            async def stop(self):
                for node in replicas.values():
                    await node.antientropy.stop()

        stoppables.append(_AntiEntropyStopper())

    if cfg.attacks.chaos_enabled:
        from dds_tpu.malicious.trudy import Nemesis

        trudy = Nemesis(net, active, cfg.replicas.byz_max_faults,
                        addr=full("trudy"))
    else:
        trudy = Trudy(net, active, cfg.replicas.byz_max_faults,
                      addr=full("trudy"))
    dep = Deployment(cfg, net, replicas, supervisor, server, trudy, ssl_client,
                     stoppables)

    # per-process identity: the dds_process_info gauge on /metrics and the
    # flight recorder's incident headers (obs/panopticon correlates by it)
    from dds_tpu.obs.flight import flight as _flight
    from dds_tpu.obs.panopticon import process_info

    _identity = {"host": local_hostport or "local", "role": "single"}
    if cfg.fabric.region:
        _identity["region"] = cfg.fabric.region
    _flight.configure(identity=_identity)
    process_info(role="single", region=cfg.fabric.region)

    if cfg.recovery.snapshot_dir and cfg.recovery.snapshot_interval > 0:
        from dds_tpu.core import snapshot as snap

        async def _snapshot_loop():
            while True:
                await asyncio.sleep(cfg.recovery.snapshot_interval)
                # off-loop: serializing large repositories must not stall
                # ABD handling or recovery timers
                await asyncio.to_thread(
                    snap.save_all, dict(dep.replicas),
                    cfg.recovery.snapshot_dir,
                    snap_secret, cfg.recovery.snapshot_keep,
                )

        task = supervised_task(_snapshot_loop(), name="run.snapshot_loop")

        class _TaskStopper:
            async def stop(self):
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

        stoppables.append(_TaskStopper())

    # Watchtower: the online invariant auditor rides the process tracer.
    # Attached LAST — once nothing else in this launch can fail — so an
    # aborted boot never leaves a mis-configured global auditor behind
    # (Deployment.stop detaches it again). Quorum-intersection checks are
    # only sound when every replica's handler spans land in THIS process's
    # ring; a multi-host topology (names mapped to other hosts) keeps the
    # tag/repair/state-machine checks and drops the quorum ones.
    if cfg.obs.audit_enabled:
        from dds_tpu.obs.watchtower import watchtower
        from dds_tpu.utils.trace import tracer as _tracer

        n_active = len(cfg.replicas.endpoints) - len(cfg.replicas.sentinent)
        all_local = not cfg.replicas.addresses and not cfg.replicas.local
        watchtower.configure(
            quorum_size=cfg.replicas.byz_quorum_size,
            n_replicas=n_active,
            check_quorum=cfg.obs.audit_quorum_checks and all_local,
        )
        watchtower.attach(_tracer)
    # Chronoscope rides the same process tracer (every span is local in a
    # single-process launch); DDS_OBS_PIPE=0 keeps it dormant
    from dds_tpu.obs.chronoscope import chronoscope

    chronoscope.attach()
    return dep


def shard_configs(cfg: DDSConfig):
    """(ReplicaConfig, SupervisorConfig, AbdClientConfig) for one quorum
    group of a Constellation — shared by the single-process sharded boot
    below and the Meridian multi-host roles (dds_tpu/fabric/deploy),
    which must derive IDENTICAL per-group stacks in every process."""
    sh = cfg.shard
    rcfg = ReplicaConfig(
        quorum_size=sh.quorum_size,
        nonce_increment=cfg.security.nonce_challenge_increment,
        abd_mac_secret=cfg.security.abd_mac_secret.encode(),
        proxy_mac_secret=cfg.security.proxy_mac_secret.encode(),
        debug=cfg.debug,
        allow_fault_injection=cfg.attacks.enabled,
    )
    sup_cfg = SupervisorConfig(
        quorum_size=sh.quorum_size,
        proactive_recovery_warmup=cfg.recovery.warm_up,
        proactive_recovery_interval=cfg.recovery.interval,
        sentinent_awake_timeout=cfg.recovery.sentinent_awake_timeout,
        crashed_recovery_timeout=cfg.recovery.crashed_recovery_timeout,
        proactive_recovery_enabled=cfg.recovery.enabled,
        verified_transfer=cfg.recovery.verified_transfer,
        manifest_timeout=cfg.recovery.manifest_timeout,
        state_chunk_keys=cfg.recovery.state_chunk_keys,
        abd_mac_secret=cfg.security.abd_mac_secret.encode(),
        debug=cfg.debug,
    )
    abd_cfg = AbdClientConfig(
        proxy_mac_secret=cfg.security.proxy_mac_secret.encode(),
        nonce_increment=cfg.security.nonce_challenge_increment,
        request_timeout=cfg.proxy.intranet_request_timeout,
        abd_mac_secret=cfg.security.abd_mac_secret.encode(),
        quorum_size=sh.quorum_size,
        breaker_threshold=cfg.proxy.breaker_threshold,
        breaker_reset=cfg.proxy.breaker_reset,
        fast_fail_all_open=cfg.admission.fast_fail,
        # Atlas read-local lease client knobs ([geo]); region + per-group
        # lease_ttl/replica_regions are stamped by the constellation
        # builder, which is also what flips lease_enabled on
        lease_renew_margin=cfg.geo.lease_renew_margin,
        local_read_timeout=cfg.geo.local_read_timeout,
    )
    return rcfg, sup_cfg, abd_cfg


def proxy_config(cfg: DDSConfig, supervisor, ssl_server, ssl_client,
                 **overrides) -> ProxyConfig:
    """The sharded proxy's ProxyConfig from the config tree (no gossip
    peers baked in — the Meridian roles layer those via `overrides`)."""
    kw = dict(
        host=cfg.proxy.host,
        port=cfg.proxy.port,
        region=cfg.fabric.region,
        request_budget=cfg.proxy.request_budget,
        retry_backoff=cfg.proxy.retry_backoff,
        retry_max_delay=cfg.proxy.retry_max_delay,
        retry_attempts=cfg.proxy.retry_attempts,
        retry_after_hint=cfg.proxy.retry_after_hint,
        handler_timeout=cfg.proxy.handler_timeout,
        crypto_backend=cfg.proxy.crypto_backend,
        keys_path=cfg.proxy.stored_keys_path,
        coalesce_window=cfg.proxy.coalesce_window,
        supervisor=supervisor,
        trace_route_enabled=cfg.debug or cfg.obs.trace_route,
        metrics_route_enabled=cfg.obs.metrics_route,
        slo_route_enabled=cfg.obs.slo_route,
        analytics_enabled=cfg.analytics.enabled,
        analytics_max_rows=cfg.analytics.max_rows,
        analytics_max_request_bytes=cfg.analytics.max_request_bytes,
        admission=cfg.admission,
        tenancy=cfg.tenancy,
        resident=cfg.resident,
        search=cfg.search,
        storage=cfg.storage,
        heliograph=cfg.heliograph,
        # operator reshape control (POST /_reshard, /_helmsman) — gated
        # exactly like the Meridian proxy role; without a reshard
        # controller wired the routes still 404
        reshard_route_enabled=cfg.fabric.admin_routes,
        ssl_server_context=ssl_server,
        ssl_client_context=ssl_client,
    )
    kw.update(overrides)
    return ProxyConfig(**kw)


class ConstellationReshard:
    """POST /_reshard controller for the in-process constellation: the
    same surface the Meridian controller presents (async split/merge +
    phase/retry_after for the route's 409 handling), delegating to the
    Constellation. An omitted split target lets the Constellation name
    the new group; naming one makes the request replayable (the route's
    completed-idempotency check needs the target to recognize a done
    split)."""

    def __init__(self, const):
        self._const = const

    @property
    def phase(self):
        return self._const.rebalancer.phase

    def retry_after(self) -> float:
        return self._const.rebalancer.retry_after()

    async def split(self, source: str, target: str | None = None):
        await self._const.split(source, target)
        return self._const.manager.current()

    async def merge(self, source: str):
        await self._const.merge(source)
        return self._const.manager.current()


async def _launch_constellation(cfg: DDSConfig, net, stoppables,
                                ssl_server, ssl_client) -> Deployment:
    """shard.enabled boot: S quorum groups + ShardRouter behind the proxy.

    Each group mirrors the single-group stack (replicas, spares,
    supervisor, anti-entropy, Trudy) with namespaced endpoints over the
    one transport; the REST server talks to the ShardRouter, which routes
    point ops by the signed epoch-versioned ShardMap and scatter-gathers
    aggregates. The Watchtower audits every group against ITS OWN quorum
    geometry via the per-group geometry table."""
    from dds_tpu.shard import build_constellation

    sh = cfg.shard
    rcfg, sup_cfg, abd_cfg = shard_configs(cfg)
    const = build_constellation(
        net,
        shard_count=sh.count,
        vnodes_per_group=sh.vnodes_per_group,
        secret=cfg.security.abd_mac_secret.encode(),
        manifest_timeout=sh.manifest_timeout,
        ack_timeout=sh.ack_timeout,
        chunk_keys=sh.migrate_chunk_keys,
        fence_lease=sh.fence_lease,
        journal_dir=sh.plan_dir or None,
        n_active=sh.replicas_per_group,
        n_sentinent=sh.sentinent_per_group,
        quorum=sh.quorum_size,
        max_faults=sh.max_faults,
        rcfg=rcfg,
        sup_cfg=sup_cfg,
        abd_cfg=abd_cfg,
        chaos=cfg.attacks.chaos_enabled,
        # Atlas: region-aware placement + read-local leases ([geo]); the
        # builder signs the region assignment onto the shard map and
        # homes this process's proxies at [fabric] region
        regions=list(cfg.geo.regions) if cfg.geo.enabled else None,
        placement=cfg.geo.placement,
        lease_ttl=cfg.geo.lease_ttl if cfg.geo.enabled else 0.0,
        client_region=cfg.fabric.region,
    )
    if sh.plan_dir:
        # a previous process may have died mid-reshard: resolve the
        # journaled plan (roll back before commit, forward after) before
        # any traffic or new plan touches the fleet
        await const.rebalancer.recover(const.group)
    replicas: dict[str, BFTABDNode] = {}
    for g in const.groups:
        replicas.update(g.replicas)

    if cfg.recovery.enabled:
        for g in const.groups:
            g.supervisor.start()
    if cfg.recovery.anti_entropy_enabled:
        for g in const.groups:
            for node in g.replicas.values():
                node.antientropy.configure(
                    interval=cfg.recovery.anti_entropy_interval,
                    jitter=cfg.recovery.anti_entropy_jitter,
                )
                if cfg.geo.enabled and g.replica_regions:
                    # Atlas: cross-region pull pairing — a biased share
                    # of rounds reaches across the WAN, extra-jittered so
                    # regional fleets don't thunder over the slow links
                    node.antientropy.configure(
                        regions=g.replica_regions,
                        cross_region_bias=cfg.geo.cross_region_bias,
                        cross_jitter=cfg.geo.cross_jitter,
                    )
                node.antientropy.start()

    server = DDSRestServer(
        const.router,
        proxy_config(cfg, const.groups[0].supervisor.addr,
                     ssl_server, ssl_client),
        local_replicas=replicas,
        slo=SloEngine.from_obs(cfg.obs),
        reshard=ConstellationReshard(const),
    )
    await server.start()

    if cfg.helmsman.enabled:
        from dds_tpu.fleet import Helmsman

        admission = server.admission
        hm = Helmsman.from_config(
            cfg.helmsman,
            load_census=const.router.load_census,
            slo_alerts=server.slo.alerts,
            shed_level=(lambda a=admission: a.shed_level if a else 0),
            breaker_census=const.router.breaker_census,
            split=(lambda gid, c=const: c.split(gid)),
            merge=(lambda gid, c=const: c.merge(gid)),
            promote=(lambda gid, c=const: c.promote(gid)),
            moved_bytes=lambda r=const.rebalancer: r.moved_bytes_total,
            reshard_busy=lambda r=const.rebalancer: r.lock.locked(),
            # Bastion: per-tenant burn attribution on every decision —
            # worst window per tenant, from the SLO engine's tenant bins
            tenant_burns=(lambda s=server.slo: {
                t: max(b) for t, b in s.tenant_burns().items() if b
            }) if cfg.tenancy.enabled else None,
            # Atlas: gid -> home region, read live so split-born groups
            # (which inherit the victim's region) appear without rewiring
            regions=(lambda c=const: {
                g.gid: g.home_region for g in c.groups if g.home_region
            }) if cfg.geo.enabled else None,
            # Heliograph: sustained canary unreachability from a region is
            # black-box promotion evidence — the probes exercise the real
            # serving path, so they fire even while heartbeats stay green
            canary_unreachable=(lambda s=server: (
                s.heliograph.unreachable_regions()
                if s.heliograph is not None else set()
            )) if cfg.heliograph.enabled else None,
            # Stratum: blended hot+warm tier occupancy — HBM-full now
            # reads as pressure the controller can split away, instead
            # of a silent pool reset the fleet never sees
            pool_pressure=(lambda s=server: s.tier_pressure())
            if cfg.storage.enabled else None,
        )
        if admission is not None:
            admission.subscribe(hm.on_admission)
        server.helmsman = hm
        hm.start()
        stoppables.append(hm)

    dep = Deployment(cfg, net, replicas, None, server,
                     const.groups[0].trudy, ssl_client, stoppables,
                     constellation=const)
    from dds_tpu.obs.flight import flight as _flight
    from dds_tpu.obs.panopticon import process_info

    _identity = {"host": "local", "role": "constellation"}
    if cfg.fabric.region:
        _identity["region"] = cfg.fabric.region
    _flight.configure(identity=_identity)
    process_info(role="constellation", region=cfg.fabric.region)
    if cfg.obs.audit_enabled:
        from dds_tpu.obs.watchtower import watchtower
        from dds_tpu.utils.trace import tracer as _tracer

        watchtower.configure(
            quorum_size=sh.quorum_size,
            n_replicas=sh.replicas_per_group,
            check_quorum=cfg.obs.audit_quorum_checks,
            group_geometry={
                g.gid: (g.quorum_size, len(g.active)) for g in const.groups
            },
            # Atlas: lease-tagged single-hop reads are audited against
            # the live lease tables instead of the quorum-size bound
            lease_lookup=(lambda name, c=const: any(
                g.lease_table is not None and g.lease_table.held_by(name)
                for g in c.groups
            )) if cfg.geo.enabled and cfg.geo.lease_ttl > 0 else None,
        )
        watchtower.attach(_tracer)
    from dds_tpu.obs.chronoscope import chronoscope

    chronoscope.attach()
    return dep


def mint_node_keys(count: int, directory: str = "certs",
                   hosts: list[str] | None = None,
                   host: str = "127.0.0.1", base_port: int = 2552) -> str:
    """Provision per-process transport identities for an N-process fleet:
    one Ed25519 key file per process (born 0600, existing files reused so
    re-running never rotates keys under a live fleet) plus the
    `[security]` TOML stanza wiring the public-key registry — the manual,
    error-prone step of DEPLOY.md §1 as one command:

        python -m dds_tpu.run --mint-node-keys 3 --mint-dir certs \\
            --mint-hosts 10.0.0.1:2552,10.0.0.2:2552,10.0.0.3:2552

    Returns (and `main` prints) the stanza; paste it into every process's
    config and point each process's `node-key-path` at ITS key file."""
    import pathlib

    from dds_tpu.utils import nodeauth

    if hosts:
        hostports = [
            hp if ":" in hp else f"{hp}:{base_port}" for hp in hosts
        ]
    else:
        hostports = [f"{host}:{base_port + i}" for i in range(count)]
    if count and hosts and len(hostports) != count:
        raise ValueError(
            f"--mint-node-keys {count} but {len(hostports)} hosts given"
        )
    d = pathlib.Path(directory)
    lines = ["# Meridian node identities — minted by --mint-node-keys.",
             "# Per process: set security.node-key-path to ITS OWN file:"]
    registry = []
    for i, hp in enumerate(hostports):
        path = d / f"node_{i}.key"
        key = nodeauth.load_or_create(path)
        lines.append(f"#   process {i} ({hp}): node-key-path = {str(path)!r}")
        registry.append(f'"{hp}" = "{nodeauth.public_hex(key)}"')
    lines.append("")
    lines.append("[security.node-public-keys]")
    lines.extend(registry)
    return "\n".join(lines) + "\n"


def load_provider(cfg: DDSConfig) -> HomoProvider:
    """Client HE keys per config: inline blob > keys file > fresh generation
    (persisted back to the file when a path is configured) — the
    `client.conf:81-88` reproducibility contract: a restarted client can
    re-attach to an existing store and still decrypt it."""
    import pathlib

    from dds_tpu.models.keys import HEKeys

    c = cfg.client
    if c.he_keys_inline:
        keys = HEKeys.from_json(c.he_keys_inline)
    elif c.he_keys_path and pathlib.Path(c.he_keys_path).exists():
        keys = HEKeys.from_json(pathlib.Path(c.he_keys_path).read_text())
    else:
        keys = HEKeys.generate(c.paillier_bits, c.rsa_bits)
        if c.he_keys_path:
            from dds_tpu.utils.nodeauth import write_secret_file

            # born 0600: these private keys decrypt the whole store
            write_secret_file(pathlib.Path(c.he_keys_path), keys.to_json())
    bulk = None
    if c.bulk_encrypt_backend:
        from dds_tpu.models.backend import get_backend

        bulk = get_backend(c.bulk_encrypt_backend)
    # Sanctum posture for the decrypt CRT legs: host-only unless the
    # operator explicitly opted in ([crypto] secret-device, or the
    # DDS_SECRET_DEVICE twin — validated loudly HERE, at construction,
    # per the DDS_PROD_TB pattern, so a typo'd opt-in/out never silently
    # changes where key material computes).
    from dds_tpu.ops.flags import secret_device

    secret = None
    if secret_device(default=cfg.crypto.secret_device):
        from dds_tpu.sanctum import SecretBackend

        secret = SecretBackend(device=True)
    return HomoProvider(
        keys, fast_blinding=c.fast_blinding, bulk_backend=bulk,
        secret_backend=secret,
    )


async def run_workload(dep: Deployment, provider: HomoProvider | None = None,
                       seed: int | None = None):
    """Spawn the configured clients and drive generated digests; returns reports."""
    cfg = dep.cfg
    provider = provider or load_provider(cfg)
    rng = random.Random(seed)
    if dep.trudy is not None:
        dep.trudy._rng = rng  # make --seed reproduce attack victim selection
    dt = cfg.client.data_table
    if cfg.attacks.enabled and dep.trudy is not None:
        # fire mid-run like the reference (Main.scala:187-193): the workload
        # below must complete correct quorums against a damaged cluster
        asyncio.get_event_loop().call_later(
            0.1, lambda: dep.trudy.trigger(cfg.attacks.type)
        )
    runs = []
    for i in range(cfg.client.nr_of_local_clients):
        client = DDSHttpClient(
            provider,
            ClientConfig(
                proxies=[f"{cfg.proxy.host}:{dep.server.cfg.port}"],
                request_timeout=cfg.client.http_requests_timeout,
                fixed_columns=dt.fixed_nr_of_columns,
                schema=dt.fixed_columns_hcrypt,
                ssl_context=dep.ssl_client,
            ),
            rng=random.Random(rng.getrandbits(64)),
        )
        ops = generate(
            cfg.client.nr_of_operations,
            cfg.client.proportions or None,
            dt.max_nr_of_columns,
            dt.fixed_columns_mappings,
            dt.fixed_columns_hcrypt,
            rng=random.Random(rng.getrandbits(64)),
        )
        runs.append(client.execute(Digest(ops)))
    # clients run concurrently, like the reference's N client actors
    return list(await asyncio.gather(*runs))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Run a DDS node + workload")
    ap.add_argument("--config", help="TOML/JSON config path")
    ap.add_argument("--ops", type=int, help="override nr-of-operations")
    ap.add_argument("--backend", choices=["cpu", "tpu", "native"], help="crypto backend")
    ap.add_argument("--port", type=int, help="proxy port (0 = auto)")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--serve", action="store_true", help="keep serving after workload")
    ap.add_argument("--role", help="override [fabric] role (all | proxy | group:N)")
    ap.add_argument("--mint-node-keys", type=int, metavar="N",
                    help="provision N per-process Ed25519 node keys + the "
                         "security.node-public-keys TOML stanza, then exit")
    ap.add_argument("--mint-dir", default="certs",
                    help="directory for --mint-node-keys files")
    ap.add_argument("--mint-hosts", default="",
                    help="comma-separated host:port per process for "
                         "--mint-node-keys (default 127.0.0.1:2552+i)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(name)s %(message)s")
    if args.mint_node_keys is not None:
        hosts = [h for h in args.mint_hosts.split(",") if h.strip()]
        print(mint_node_keys(args.mint_node_keys, args.mint_dir,
                             hosts or None), end="")
        return
    cfg = DDSConfig.load(args.config) if args.config else DDSConfig()
    if args.ops is not None:
        cfg.client.nr_of_operations = args.ops
    if args.backend:
        cfg.proxy.crypto_backend = args.backend
    if args.port is not None:
        cfg.proxy.port = args.port
    if args.role:
        cfg.fabric.role = args.role

    async def go():
        dep = await launch(cfg)
        try:
            # group-role fabric processes host replicas, not clients; a
            # proxy launched without a workload (ops 0) also just serves
            runs_workload = (
                dep.trudy is not None and cfg.client.nr_of_operations > 0
            )
            if cfg.shard.enabled and cfg.transport.kind == "tcp":
                from dds_tpu.fabric.deploy import parse_role

                if parse_role(cfg.fabric.role)[0] == "group":
                    runs_workload = False
            if runs_workload:
                reports = await run_workload(dep, seed=args.seed)
                for i, r in enumerate(reports):
                    print(
                        f"client {i}: {r.operations} ops in {r.wall_seconds:.2f}s "
                        f"-> {r.ops_per_second:.1f} ops/s "
                        f"({r.succeeded} ok, {r.not_found} miss, {r.failed} failed)"
                    )
            if args.serve:
                print(
                    f"serving on {dep.server.cfg.host}:{dep.server.cfg.port} "
                    f"(ctrl-c to stop)", flush=True,
                )
                await asyncio.Event().wait()
        finally:
            await dep.stop()

    asyncio.run(go())


if __name__ == "__main__":
    main()
