"""Spyglass plane: per-shard-group device-resident search indexes.

The legacy `Search*`/`Order*` routes answer every query with a full
keyspace materialization (`_fetch_stored`) followed by a host Python
filter loop — O(N) quorum-validated value traffic per query even when
nothing changed. Spyglass keeps a per-group, per-column index of the
DET (equality) and OPE (order/range) column families device-ready, so a
warm query costs ONE batched tag-validation round plus one predicate
kernel dispatch (ops/predicate), never a keyspace re-read.

Freshness is the aggregate cache's linearizability argument verbatim
(http/server._fetch_stored): every index entry carries the ABD tag of a
COMPLETED quorum op (the proxy's own write, or a full `fetch_tagged`
re-read), so value@tag is known fully written. A query validates all
entries with one `read_tags` fingerprint round; an entry is served only
when the quorum-max tag EQUALS its indexed tag, which honest replies can
never deflate below a completed write. Stale or missing keys alone fall
back to full ABD reads and are re-ingested — indexed results are
bit-for-bit what the legacy scan would return. The forged-entry class
(a Byzantine coordinator planting value@true-tag) is bounded exactly as
for the aggregate cache: by its per-round audits, whose flush also
invalidates this plane (the server couples `_flush_cache` to
`invalidate()`).

Device masks over digest lanes are CANDIDATE filters (64-bit digests can
collide); every candidate is confirmed against the exact ciphertext
string host-side through `DetKey.compare` (constant-time), so collisions
cost a stray confirm, never a wrong result. Packed OPE compares and
sorts are exact — the packing is the identity on [0, 2^52).

Writes reach the index off the request path through the Lodestone
pattern: `note_write` queues (group, key, tag, value) bounded by
`max_pending`, the server's debounced drain applies them on a worker
thread. A dropped or still-queued update just means the next query's tag
round sees that key as stale and repairs it — never a wrong answer.
"""

from __future__ import annotations

import logging
import operator
import threading

import numpy as np

from dds_tpu.models.det import DetKey
from dds_tpu.utils.queues import TimedQueue

log = logging.getLogger("dds.search")

_HOST_OPS = {
    "gt": operator.gt,
    "ge": operator.ge,
    "lt": operator.lt,
    "le": operator.le,
}


class GroupIndex:
    """One shard group's search index: key -> (tag, value) entries plus
    lazily-built per-(column, family) packs the predicate kernels consume.
    Any entry mutation drops the packs (epoch invalidation, like
    ResidentPool's reset) — they rebuild on the next query."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, tuple] = {}  # key -> (tag, value|None)
        self._packs: dict = {}

    # ------------------------------------------------------------ mutation

    def upsert(self, key: str, tag, value) -> None:
        """Remember a completed op's (tag, value); newest tag wins, like
        the server's `_cache_put`. value None is a tombstone: it keeps
        the tag validatable while excluding the key from every pack."""
        if tag is None:
            return
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None and not (cur[0] is None or cur[0] < tag):
                return
            self._entries[key] = (tag, value)
            self._packs.clear()

    def remove(self, key: str) -> None:
        with self._lock:
            if self._entries.pop(key, None) is not None:
                self._packs.clear()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._packs.clear()

    def tag(self, key: str):
        e = self._entries.get(key)
        return None if e is None else e[0]

    def __len__(self) -> int:
        return len(self._entries)

    def pack_count(self) -> int:
        return len(self._packs)

    # ---------------------------------------------------------- pack build

    def _pairs(self) -> list[tuple[str, list]]:
        """Live (key, value) rows in sorted-key order — the legacy scan's
        row order, which every tie-break below leans on."""
        return [
            (k, e[1]) for k, e in sorted(self._entries.items())
            if e[1] is not None
        ]

    def _ope_pack(self, pos: int) -> dict:
        from dds_tpu.ops import predicate

        pack = self._packs.get(("ope", pos))
        if pack is not None:
            return pack
        keys: list[str] = []
        vals: list[int] = []
        numeric = True
        for k, v in self._pairs():
            if pos < len(v):
                keys.append(k)
                try:
                    vals.append(int(v[pos]))
                except (TypeError, ValueError):
                    # the legacy scan's int() raises here too — the route
                    # answers 400 either way (eval re-raises per query)
                    numeric = False
                    break
        pack = {"keys": keys, "vals": vals, "numeric": numeric}
        if numeric and keys and all(predicate.packable(v) for v in vals):
            pack["hi"], pack["lo"] = predicate.pack_ints(vals)
        self._packs[("ope", pos)] = pack
        return pack

    def _det_pack(self, pos: int) -> dict:
        from dds_tpu.ops import predicate

        pack = self._packs.get(("det", pos))
        if pack is not None:
            return pack
        keys: list[str] = []
        svals: list[str] = []
        for k, v in self._pairs():
            if pos < len(v):
                keys.append(k)
                svals.append(str(v[pos]))
        pack = {"keys": keys, "svals": svals}
        if keys:
            pack["dhi"], pack["dlo"] = predicate.pack_digests(svals)
        self._packs[("det", pos)] = pack
        return pack

    def _entry_pack(self) -> dict:
        from dds_tpu.ops import predicate

        pack = self._packs.get(("entry",))
        if pack is not None:
            return pack
        keys: list[str] = []
        rows: list[list[str]] = []
        for k, v in self._pairs():
            keys.append(k)
            rows.append([str(e) for e in v])
        width = max((len(r) for r in rows), default=0)
        pack = {"keys": keys, "rows": rows, "width": width}
        if keys and width:
            dhi = np.zeros((len(keys), width), np.uint32)
            dlo = np.zeros((len(keys), width), np.uint32)
            valid = np.zeros((len(keys), width), bool)
            for i, r in enumerate(rows):
                for j, s in enumerate(r):
                    dhi[i, j], dlo[i, j] = predicate.digest_lanes(s)
                    valid[i, j] = True
            pack["dhi"], pack["dlo"], pack["valid"] = dhi, dlo, valid
        self._packs[("entry",)] = pack
        return pack

    # ------------------------------------------------------------- queries

    def eval_compare(self, pos: int, op: str, item: int) -> set[str]:
        """Keys whose position-`pos` int satisfies `op item` (op in
        gt/ge/lt/le)."""
        from dds_tpu.ops import predicate

        with self._lock:
            pack = self._ope_pack(pos)
            if not pack["numeric"]:
                raise ValueError(f"non-integer value at position {pos}")
            keys, vals = pack["keys"], pack["vals"]
            if not keys:
                return set()
            if "hi" in pack:
                # packed column is exact on [0, PACK_MAX]; out-of-band
                # thresholds resolve without a dispatch
                if item < 0:
                    return set(keys) if op in ("gt", "ge") else set()
                if item > predicate.PACK_MAX:
                    return set(keys) if op in ("lt", "le") else set()
                mask = predicate.compare_mask(pack["hi"], pack["lo"], op, item)
                return {keys[i] for i in np.nonzero(mask)[0]}
            opfn = _HOST_OPS[op]
            return {k for k, v in zip(keys, vals) if opfn(v, item)}

    def eval_range(self, pos: int, lo_bound: int, hi_bound: int) -> set[str]:
        """Keys with lo_bound <= value[pos] <= hi_bound."""
        from dds_tpu.ops import predicate

        with self._lock:
            pack = self._ope_pack(pos)
            if not pack["numeric"]:
                raise ValueError(f"non-integer value at position {pos}")
            keys, vals = pack["keys"], pack["vals"]
            if not keys or lo_bound > hi_bound:
                return set()
            if "hi" in pack:
                lo_c = max(lo_bound, 0)
                hi_c = min(hi_bound, predicate.PACK_MAX)
                if lo_c > hi_c:
                    return set()
                mask = predicate.range_mask(pack["hi"], pack["lo"], lo_c, hi_c)
                return {keys[i] for i in np.nonzero(mask)[0]}
            return {k for k, v in zip(keys, vals) if lo_bound <= v <= hi_bound}

    def eval_order(self, pos: int, descending: bool) -> list[tuple[int, str]]:
        """This group's sorted run: (comparable, key) tuples ascending by
        (comparable, key) — comparable is the value (or its negation for
        descending order), so `heapq.merge` across groups reproduces the
        global stable sort, ties in ascending key order. Records without
        the column are excluded (the Search* convention; the pre-Spyglass
        `-inf` coercion is gone — see the route)."""
        from dds_tpu.ops import predicate

        with self._lock:
            pack = self._ope_pack(pos)
            if not pack["numeric"]:
                raise ValueError(f"non-integer value at position {pos}")
            keys, vals = pack["keys"], pack["vals"]
            if not keys:
                return []
            if "hi" in pack:
                order = [int(i) for i in
                         predicate.sort_perm(pack["hi"], pack["lo"],
                                             descending)]
            else:
                order = sorted(range(len(keys)), key=vals.__getitem__,
                               reverse=descending)
            sign = -1 if descending else 1
            return [(sign * vals[i], keys[i]) for i in order]

    def eval_eq(self, pos: int, item: str, want_eq: bool) -> set[str]:
        """DET equality/inequality over position `pos`: device digest
        candidates, host-confirmed (collision-proof)."""
        from dds_tpu.ops import predicate

        with self._lock:
            pack = self._det_pack(pos)
            keys, svals = pack["keys"], pack["svals"]
            if not keys:
                return set()
            mask = predicate.eq_mask(pack["dhi"], pack["dlo"], item)
            matched = {
                keys[i] for i in np.nonzero(mask)[0]
                if DetKey.compare(svals[i], item)
            }
            return matched if want_eq else set(keys) - matched

    def eval_entry(self, queries: list[str], mode: str) -> set[str]:
        """Element-membership search over whole records: mode "any" keeps
        rows where any element matches any query (SearchEntry/EntryOR),
        "all" keeps rows where every query matches some element
        (SearchEntryAND). Device candidates, host-confirmed."""
        from dds_tpu.ops import predicate

        with self._lock:
            pack = self._entry_pack()
            keys, rows = pack["keys"], pack["rows"]
            if not keys or not pack["width"] or not queries:
                return set()
            mask = predicate.entry_mask(pack["dhi"], pack["dlo"],
                                        pack["valid"], queries, mode)
            out = set()
            for i in np.nonzero(mask)[0]:
                row = rows[i]
                if mode == "all":
                    ok = all(any(DetKey.compare(e, q) for e in row)
                             for q in queries)
                else:
                    ok = any(DetKey.compare(e, q)
                             for q in queries for e in row)
                if ok:
                    out.add(keys[i])
            return out


class SearchPlane:
    """All groups' indexes plus the bounded write-ingest queue (the
    Lodestone `note_write` pattern: queue on the request path, drain
    debounced on a worker thread). Dropped or still-queued updates are
    SAFE — the query-time tag round classifies those keys stale and
    repairs them through full quorum reads."""

    def __init__(self, max_pending: int = 8192):
        self._lock = threading.Lock()
        # (gid, tenant) -> index: the Bastion tenant stripe mirrors
        # Lodestone's — tenant id is part of the index address, so one
        # tenant's writes/invalidation churn cannot thrash another's
        # packs; tenant "" is the legacy/single-tenant stripe
        self._groups: dict[tuple[str, str], GroupIndex] = {}
        # queued (gid, tenant, key, tag, value) updates; enqueue-
        # timestamped so the drain attributes ingest-queue-wait, full-
        # queue drops are reason-labelled (the key reads stale and
        # repairs at next query)
        self._pending = TimedQueue("spyglass-ingest", maxlen=max_pending)
        self.max_pending = max_pending
        self._ingested = 0
        self._invalidations = 0
        # optional (keys, tenant) -> None popularity sink: Stratum wires
        # `touch_keys` here so every selection's hit set warms those
        # rows' fold ciphertexts in the tier directory (Zipf feed from
        # the search path; pure dict math, loop-safe)
        self.touch_sink = None

    def group(self, gid: str, tenant: str = "") -> GroupIndex:
        with self._lock:
            g = self._groups.get((gid, tenant))
            if g is None:
                g = self._groups[(gid, tenant)] = GroupIndex()
            return g

    def register_groups(self, gids) -> None:
        for gid in gids:
            self.group(gid)

    def note_selected(self, keys, tenant: str = "") -> None:
        """Report a query's selected keys to the tiered-storage
        popularity feed, when one is wired. Best-effort: a sink failure
        must never fail the query that fed it."""
        sink = self.touch_sink
        if sink is None or not keys:
            return
        try:
            sink(keys, tenant)
        except Exception:  # popularity is advisory, queries are not
            log.debug("search touch sink failed", exc_info=True)

    def group_ids(self) -> list[str]:
        return sorted({gid for gid, _t in self._groups})

    # ------------------------------------------------------- write ingest

    def note_write(self, gid: str, key: str, tag, value,
                   tenant: str = "") -> bool:
        """Queue one committed write for ingest; False = queue full (the
        key will read as stale and be repaired at the next query)."""
        return self._pending.offer((gid, tenant, key, tag, value))

    def pending_ingest(self) -> int:
        return self._pending.depth()

    def ingest_pending(self) -> int:
        batch = self._pending.drain()
        for gid, tenant, key, tag, value in batch:
            self.group(gid, tenant).upsert(key, tag, value)
        with self._lock:
            self._ingested += len(batch)
        return len(batch)

    # ---------------------------------------------------- direct mutation

    def upsert(self, gid: str, key: str, tag, value,
               tenant: str = "") -> None:
        self.group(gid, tenant).upsert(key, tag, value)

    def tag(self, gid: str, key: str, tenant: str = ""):
        g = self._groups.get((gid, tenant))
        return None if g is None else g.tag(key)

    def remove(self, gid: str, key: str, tenant: str = "") -> None:
        g = self._groups.get((gid, tenant))
        if g is not None:
            g.remove(key)

    def evict_tenant(self, tenant: str) -> int:
        """Drop every index in `tenant`'s stripe (crypto-shred data
        lifecycle: undecryptable entries are noise). Returns indexes
        dropped."""
        with self._lock:
            victims = [k for k in self._groups if k[1] == tenant]
            for k in victims:
                self._groups.pop(k, None)
        return len(victims)

    def invalidate(self) -> None:
        """Drop every entry and queued update (the `_flush_cache`
        coupling: an aggregate-cache audit mismatch means some completed-
        op provenance is in doubt — rebuild from quorum reads)."""
        with self._lock:
            groups = list(self._groups.values())
            self._invalidations += 1
        self._pending.clear(reason="invalidated")
        for g in groups:
            g.clear()

    # ------------------------------------------------------ observability

    def stats(self) -> dict:
        with self._lock:
            groups = dict(self._groups)
        return {
            "groups": {
                (f"{gid or '-'}|{tenant}" if tenant else gid or "-"):
                    {"keys": len(g), "packs": g.pack_count()}
                for (gid, tenant), g in groups.items()
            },
            "indexed_keys": sum(len(g) for g in groups.values()),
            "pending_ingest": self._pending.depth(),
            "ingested": self._ingested,
            "dropped": self._pending.dropped("full"),
            "invalidations": self._invalidations,
        }

    def export_gauges(self, registry) -> None:
        """Scrape-time `dds_search_*` gauges (the Lodestone convention:
        per-group series labelled shard=gid, '-' for the unsharded
        group), plus the ingest queue's dds_queue_* family."""
        self._pending.export_gauges(registry)
        st = self.stats()
        with self._lock:
            groups = dict(self._groups)
        per_shard: dict[str, list] = {}
        per_tenant: dict[str, list] = {}
        for (gid, tenant), g in groups.items():
            agg = per_shard.setdefault(gid or "-", [0, 0])
            agg[0] += len(g)
            agg[1] += g.pack_count()
            if tenant:
                tag = per_tenant.setdefault(tenant, [0, 0])
                tag[0] += len(g)
                tag[1] += g.pack_count()
        for gid, (keys, packs) in per_shard.items():
            registry.set("dds_search_index_keys", keys, shard=gid,
                         help="Spyglass indexed keys per shard group")
            registry.set("dds_search_index_packs", packs, shard=gid,
                         help="Spyglass built column packs per shard group")
        for tenant, (keys, packs) in per_tenant.items():
            registry.set("dds_tenant_search_keys", keys, tenant=tenant,
                         help="Spyglass indexed keys per tenant stripe")
            registry.set("dds_tenant_search_packs", packs, tenant=tenant,
                         help="Spyglass column packs per tenant stripe")
        registry.set("dds_search_pending_ingest", st["pending_ingest"],
                     help="Spyglass write-ingest queue depth")
        registry.set("dds_search_ingest_dropped", st["dropped"],
                     help="Spyglass ingest queue overflows "
                          "(keys repaired at next query)")
        registry.set("dds_search_invalidations", st["invalidations"],
                     help="Spyglass whole-plane invalidations")
