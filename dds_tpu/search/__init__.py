"""Spyglass: the device-resident encrypted search plane."""

from dds_tpu.search.plane import GroupIndex, SearchPlane

__all__ = ["GroupIndex", "SearchPlane"]
