"""Lodestone: the mesh-fused device-resident ciphertext plane.

`ResidentPlane` owns one `ResidentPool` per (shard group, modulus) and
turns a sharded aggregate — operand sets partitioned by owning
Constellation group — into ONE device dispatch: per-group rows gather
from their pools, fold locally (a halving tree per group slab), and the
per-group partials merge with the same log2(S) tail tree
`parallel/mesh.sharded_reduce_mul` runs across chips. Before this plane
the proxy dispatched S independent folds per sharded aggregate and
re-marshaled host limbs into every one of them; warm aggregates now
touch host ints only to look up row indices.

Placement: with a multi-device mesh each group's pool pins to its mesh
slice (`parallel/mesh.group_sharding`, NamedSharding/PartitionSpec) and
the fused fold runs the per-group slabs under `shard_map` with one
all_gather of (S, L) partials — the BTS-style lane partitioning where
ciphertext lanes stay memory-resident and host<->device traffic is
index-only. On a single device (the test fabric) everything degrades to
one jit over default-placed buffers: same math, same single dispatch.

R-power accounting for the fused tree (structure-independent, same
argument as `parallel/mesh._tree_reduce_local`): K real operands plus
any number of Montgomery-identity pads through any tree shape yield
prod * R^-(K-1); one final multiply by R^K mod n fixes the domain.

The write-path ingest queue (`note_write` / `ingest_pending`) lets the
proxy push committed ciphertexts into existing pools OFF the request's
critical path, coalesced like folds — a warm fleet's first post-write
aggregate then pays zero ingest. Content addressing makes this safe: an
ingested row is keyed by its value, so a racing aggregate either finds
the row (identical bytes) or ingests it itself; nothing can go stale.
"""

from __future__ import annotations

import threading

import numpy as np

from dds_tpu.obs import kprof
from dds_tpu.obs.metrics import metrics
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.flags import karatsuba_mode
from dds_tpu.ops.montgomery import ModCtx
from dds_tpu.resident.pool import ResidentPool
from dds_tpu.utils.queues import TimedQueue

KERNELS = ("jnp", "v1", "v2")

# jitted fused-fold executables, keyed by (modulus, S, kernel family,
# interpret, karatsuba mode, mesh, axis): shapes retrace per input under
# one entry (like parallel/mesh's "reduce" cache), the bounded FIFO caps
# client-driven modulus churn exactly like the other kernel caches.
_FN_CACHE: dict = {}
_FN_CACHE_MAX = 64
_FN_CACHE_LOCK = threading.Lock()


def _interpret_default() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def _fused_fold_fn(ctx: ModCtx, S: int, kernel: str, mesh, axis: str):
    """ONE compiled callable per (modulus, S, kernel, mesh): gathers each
    group's rows from its pool buffer, pads to the common power-of-two
    width with the Montgomery identity, tree-folds every group slab, and
    tail-combines the S partials — all inside a single dispatch."""
    import jax
    import jax.numpy as jnp

    interpret = _interpret_default()
    kmode = karatsuba_mode() if kernel == "v2" else None
    use_mesh = (
        mesh is not None and mesh.devices.size > 1
        and S % mesh.devices.size == 0
    )
    key = ("fused", ctx.n, S, kernel, interpret, kmode,
           mesh if use_mesh else None, axis)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("resident_fold", hit=fn is not None)
    if fn is not None:
        return fn

    from dds_tpu.ops.foldmany import _mul_bm
    from jax.sharding import PartitionSpec as P

    mul = _mul_bm(ctx, kernel, interpret)
    one_mont = jnp.asarray(ctx.one_mont)
    L = ctx.L

    def local_tree(stack):
        # (G, P2, L) -> (G, L): halving tree over the operand axis of
        # every group slab at once, no collectives
        t = stack
        while t.shape[1] > 1:
            h = t.shape[1] // 2
            t = mul(
                t[:, :h].reshape(-1, L), t[:, h : 2 * h].reshape(-1, L)
            ).reshape(t.shape[0], h, L)
        return t[:, 0]

    def tail(partials):
        # (S, L) -> (1, L): the combine_partials tail tree, on-device
        t = partials
        while t.shape[0] > 1:
            if t.shape[0] % 2:
                t = jnp.concatenate([t, one_mont[None, :]], axis=0)
            t = mul(t[0::2], t[1::2])
        return t

    if use_mesh:
        step = jax.shard_map(
            lambda local: tail(
                jax.lax.all_gather(local_tree(local), axis, tiled=True)
            ),
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(),  # replicated combined partial
            check_vma=False,
        )
    else:
        step = lambda stack: tail(local_tree(stack))  # noqa: E731

    def run(bufs, idxs, fix):
        P2 = 1
        for idx in idxs:
            P2 = max(P2, 1 << max(0, (idx.shape[0] - 1).bit_length()))
        slabs = []
        for buf, idx in zip(bufs, idxs):
            rows = jnp.take(buf, idx, axis=0)
            pad = P2 - rows.shape[0]
            if pad:
                rows = jnp.concatenate(
                    [rows, jnp.broadcast_to(one_mont, (pad, L))], axis=0
                )
            slabs.append(rows)
        return mul(step(jnp.stack(slabs)), fix)

    fn = jax.jit(run)
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn
    return fn


class ResidentPlane:
    """Per-group resident pools + the fused single-dispatch sharded fold.

    `kernel` picks the Montgomery multiply family for the fused fold
    (same rule as the backend's composite paths: v1/v2 on real TPU, the
    portable jnp scans elsewhere). `mesh`/`axis` enable mesh placement;
    None is the single-device fallback. `reduce_factory(modulus)`
    optionally supplies the per-pool single-fold reduce (backends inject
    theirs so lone-group folds use the same kernels as before)."""

    def __init__(self, kernel: str = "jnp", mesh=None, axis: str = "batch",
                 initial_rows: int = 256, max_rows: int = 1 << 20,
                 reduce_factory=None, max_pending: int = 8192):
        self.kernel = kernel if kernel in KERNELS else "jnp"
        self.mesh = mesh
        self.axis = axis
        self.initial_rows = int(initial_rows)
        self.max_rows = int(max_rows)
        self.max_pending = int(max_pending)
        self._reduce_factory = reduce_factory
        self._lock = threading.Lock()
        # (gid, tenant, modulus) -> pool: Bastion tenant striping puts the
        # tenant id in the pool address, so one tenant overflowing its
        # pool (capacity reset) can never reset another tenant's rows;
        # tenant "" is the legacy/single-tenant stripe
        self._pools: dict[tuple[str, str, int], ResidentPool] = {}
        self._order: dict[str, int] = {}  # gid -> mesh slice index
        # Stratum (dds_tpu/storage): when attached, every pool wires its
        # spill/evict_rank to the tier hierarchy at creation — capacity
        # overflow then demotes to the warm tier instead of resetting
        self.tier_sink = None
        # queued (gid, cipher) write ingests; enqueue-timestamped so the
        # drain can attribute ingest-queue-wait, drops reason-labelled
        self._pending = TimedQueue("lodestone-ingest", maxlen=self.max_pending)

    # ------------------------------------------------------------- topology

    def register_groups(self, gids) -> None:
        """Pin group -> mesh-slice assignment order up front (lazy
        first-use registration works too, but explicit registration keeps
        placement deterministic across proxy restarts)."""
        with self._lock:
            for gid in gids:
                self._order.setdefault(gid, len(self._order))

    def pool(self, gid: str, modulus: int, tenant: str = "") -> ResidentPool:
        with self._lock:
            idx = self._order.setdefault(gid, len(self._order))
            key = (gid, tenant, modulus)
            p = self._pools.get(key)
            if p is None:
                from dds_tpu.parallel.mesh import group_sharding

                p = self._pools[key] = ResidentPool(
                    modulus,
                    reduce=(
                        self._reduce_factory(modulus)
                        if self._reduce_factory is not None else None
                    ),
                    initial_rows=self.initial_rows,
                    max_rows=self.max_rows,
                    gid=(f"{gid}|{tenant}" if tenant else gid),
                    sharding=group_sharding(self.mesh, idx, self.axis),
                )
                if self.tier_sink is not None:
                    self.tier_sink.wire_pool(key, p)
            return p

    # ----------------------------------------------------- write-path ingest

    def note_write(self, gid: str, ciphers: list[int],
                   tenant: str = "") -> int:
        """Queue a committed write's ciphertext columns for ingest into
        this group's existing pools FOR THIS TENANT STRIPE (every modulus
        a past aggregate has established). Returns how many were queued;
        with no pool for the (group, tenant) yet there is nothing to
        convert against — the first aggregate ingests as before (a cold
        fleet stays cold-path, but the skipped entries are COUNTED as
        reason="no_pool" drops rather than vanishing silently). A full
        queue rejects with reason="full"; a dropped entry just re-ingests
        lazily at the next fold."""
        if not ciphers:
            return 0
        with self._lock:
            has_pool = any(
                g == gid and t == tenant for g, t, _ in self._pools
            )
        if not has_pool:
            self._pending.drop(len(ciphers), reason="no_pool")
            return 0
        return self._pending.offer_many((gid, tenant, c) for c in ciphers)

    def pending_ingest(self) -> int:
        return self._pending.depth()

    def ingest_pending(self) -> int:
        """Drain the write-ingest queue into the matching pools (run on a
        worker thread, coalesced by the proxy exactly like folds).
        Returns rows newly ingested across all pools."""
        batch = self._pending.drain()
        if not batch:
            return 0
        with self._lock:
            pools = list(self._pools.items())
        by_stripe: dict[tuple[str, str], list[int]] = {}
        for gid, tenant, cipher in batch:
            by_stripe.setdefault((gid, tenant), []).append(cipher)
        grew = 0
        for (gid, tenant), ciphers in by_stripe.items():
            for (g, t, _mod), pool in pools:
                if g == gid and t == tenant:
                    grew += pool.ingest(ciphers)
        return grew

    # ------------------------------------------------------------ evaluation

    def fold_groups(
        self, parts: list[tuple[str, list[int]]], modulus: int,
        tenant: str = "",
    ) -> int | None:
        """prod over every group's operands mod `modulus` in ONE fused
        dispatch, or None when any group's operand set cannot fit its
        pool even after a reset (callers fall back to the per-group
        marshaling paths)."""
        import jax.numpy as jnp

        parts = [(gid, ops) for gid, ops in parts if ops]
        if not parts:
            return 1 % modulus
        ctx = ModCtx.make(modulus)
        bufs, idxs, total = [], [], 0
        for gid, ops in parts:
            got = self.pool(gid, modulus, tenant).rows_for(ops)
            if got is None:
                return None
            buf, idx = got
            bufs.append(buf)
            idxs.append(jnp.asarray(idx))
            total += len(ops)
        fn = _fused_fold_fn(ctx, len(parts), self.kernel, self.mesh, self.axis)
        R = 1 << (bn.LIMB_BITS * ctx.L)
        fix = jnp.asarray(
            bn.int_to_limbs(pow(R % ctx.n, total, ctx.n), ctx.L)
        )[None, :]
        out = kprof.profiled(
            "resident_fold",
            lambda: fn(tuple(bufs), tuple(idxs), fix),
            k=total, shards=len(parts),
        )
        return bn.limbs_to_int(np.asarray(out)[0])

    def rows_for(self, gid: str, modulus: int, cs: list[int],
                 tenant: str = ""):
        """Gathered device rows (K, L) for `cs` from this group's pool —
        the Prism MatVec operand path — or None when the set is wider
        than the pool (callers marshal host ints as before)."""
        import jax.numpy as jnp

        if not cs:
            return None
        got = self.pool(gid, modulus, tenant).rows_for(cs)
        if got is None:
            return None
        buf, idx = got
        return jnp.take(buf, jnp.asarray(idx), axis=0)

    # --------------------------------------------------------------- surface

    def stats(self) -> dict:
        """Per-pool view for GET /health."""
        import time as _time

        with self._lock:
            pools = dict(self._pools)
        pending = self._pending.depth()
        # reset visibility (the silent fast-path loss): total resets and
        # the age of the most recent one, surfaced so operators see a
        # thrashing pool without scraping metrics or grepping logs
        resets = sum(p.resets for p in pools.values())
        last_ts = max(
            (p._last_reset_ts for p in pools.values()
             if p._last_reset_ts is not None),
            default=None,
        )
        return {
            "kernel": self.kernel,
            "mesh_devices": (
                int(self.mesh.devices.size) if self.mesh is not None else 1
            ),
            "pending_ingest": pending,
            "dropped_pending": self._pending.dropped(),
            "resets": resets,
            "last_reset_age_s": (
                round(_time.time() - last_ts, 1) if last_ts is not None
                else None
            ),
            "tiered": self.tier_sink is not None,
            "pools": [
                {"shard": gid or "-", "tenant": tenant or "-",
                 "modulus_bits": mod.bit_length(), **pool.stats()}
                for (gid, tenant, mod), pool in sorted(
                    pools.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2])
                )
            ],
        }

    def evict_tenant(self, tenant: str) -> int:
        """Drop every pool in `tenant`'s stripe (the data-lifecycle half
        of a crypto-shred: the keys are gone, so the resident rows are
        noise — free the HBM). Returns pools dropped."""
        with self._lock:
            victims = [k for k in self._pools if k[1] == tenant]
            for k in victims:
                self._pools.pop(k, None)
        if victims:
            metrics.inc("dds_tenant_pool_evictions_total",
                        n=len(victims),
                        help="resident pools dropped by tenant eviction "
                             "(crypto-shred data lifecycle)")
        return len(victims)

    def export_gauges(self, registry=metrics) -> None:
        """Scrape-time gauges: dds_resident_{rows,bytes,hit_ratio,resets}
        aggregated per shard label (pools for several moduli sum; the hit
        ratio weights by operands served), plus the write-ingest queue's
        dds_queue_* family."""
        self._pending.export_gauges(registry)
        with self._lock:
            pools = list(self._pools.items())
        per_gid: dict[str, list] = {}
        per_tenant: dict[str, list] = {}
        for (gid, tenant, _mod), pool in pools:
            agg = per_gid.setdefault(gid or "-", [0, 0, 0, [0, 0, 0]])
            agg[0] += pool.resident
            agg[1] += pool.nbytes()
            agg[2] += pool.resets
            for i in range(3):
                agg[3][i] += pool._served[i]
            if tenant:
                tag = per_tenant.setdefault(tenant, [0, 0, 0])
                tag[0] += pool.resident
                tag[1] += pool.nbytes()
                tag[2] += pool.resets
        for tenant, (rows, nbytes, resets) in per_tenant.items():
            registry.set("dds_tenant_resident_rows", rows, tenant=tenant,
                         help="ciphertext rows resident per tenant stripe")
            registry.set("dds_tenant_resident_bytes", nbytes, tenant=tenant,
                         help="device bytes pinned per tenant stripe")
            registry.set("dds_tenant_resident_resets", resets, tenant=tenant,
                         help="pool capacity resets per tenant stripe (one "
                              "tenant's overflow cannot reset another's)")
        for gid, (rows, nbytes, resets, served) in per_gid.items():
            registry.set("dds_resident_rows", rows, shard=gid,
                         help="ciphertext rows resident per shard group")
            registry.set("dds_resident_bytes", nbytes, shard=gid,
                         help="device bytes pinned by resident pools per "
                              "shard group")
            registry.set("dds_resident_resets", resets, shard=gid,
                         help="cumulative resident-pool capacity resets "
                              "per shard group")
            total = sum(served)
            if total:
                registry.set(
                    "dds_resident_hit_ratio", round(served[0] / total, 4),
                    shard=gid,
                    help="fraction of fold operands served from resident "
                         "rows per shard group",
                )
