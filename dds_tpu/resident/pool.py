"""Lodestone resident pools: per-group device-pinned ciphertext limb pools.

A `ResidentPool` is the content-addressed `(rows, L)` uint32 limb buffer
one shard group keeps in device memory for one modulus — the
generalization of the single-store `ops/store.DeviceCipherStore` (which
is now a thin alias of this class) into the per-group family the
Constellation needs. Each distinct ciphertext *value* is ingested once
(int -> 16-bit limbs -> device row); every subsequent aggregate gathers
resident rows on-device instead of re-marshaling host ints per fold —
the memory-residency move the HE-accelerator literature scales by (BTS,
arxiv 2112.15479; HEAAN-demystified, arxiv 2003.04510).

Content addressing (ciphertext int -> row) is what keeps the
dependability story intact: the proxy still performs full ABD quorum
reads per aggregate — the pool only memoizes the transfer/limb-conversion
of bytes the device has already seen, so a stale row cannot exist by
construction; the quorum read decides WHICH ciphertexts fold.

Capacity grows by doubling up to `max_rows`. Past that the behavior
depends on whether a tier sink is wired (`spill`, set by Stratum —
dds_tpu/storage): with one, the pool EVICTS its coldest rows to the
warm tier (coldest-first order from `evict_rank`, the directory's
decayed popularity) and keeps serving the fused fast path for the rows
that stay — the fast path degrades gradually instead of cliff-dropping.
Without a sink the legacy RESET remains (entries re-ingest on demand,
`epoch` bumps, every row-index memo invalidates) — simple, and an
aggregate after a reset pays exactly the one-time ingest cost again,
never wrong results; the reset now also files a `resident_reset` flight
incident and stamps `last_reset_ts` so /health surfaces the silent
fast-path loss instead of burying it in a log line.

Placement: `sharding` optionally pins the buffer device-side (a
`NamedSharding` built by `parallel/mesh.group_sharding` maps group i to
its slice of the mesh); None — the single-device fallback — is today's
default-placed buffer.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from dds_tpu.obs import context as obs_context
from dds_tpu.obs import kprof
from dds_tpu.obs.metrics import metrics
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.resident")


@dataclass
class ResidentPool:
    """Resident (rows, L) uint32 limb buffer for one (group, modulus).

    `reduce` is the device-level fold callable ((K, L) array -> (1, L));
    backends inject theirs (TpuBackend.reduce_mul_device) so kernel
    dispatch lives in exactly one place. Default: the jnp reference path.
    `gid` labels this pool's metric series (`shard=` label); empty = the
    unsharded single store.
    """

    modulus: int
    reduce: object = None
    initial_rows: int = 256
    max_rows: int = 1 << 20  # ~1 GiB of HBM at L=256
    gid: str = ""
    sharding: object = None  # jax Sharding pinning the buffer (None = default)
    # Stratum tier sink (dds_tpu/storage): `spill` receives the evicted
    # [(cipher, (L,) uint32 host row)] batch when capacity overflows;
    # `evict_rank` orders candidate ciphers coldest-first (the tier
    # directory's decayed popularity). Both None = legacy reset behavior.
    spill: object = None
    evict_rank: object = None
    _ctx: ModCtx = field(init=False, repr=False)
    _buf: object = field(init=False, repr=False)   # jnp (cap, L) uint32
    _index: dict[int, int] = field(init=False, repr=False)
    _count: int = field(init=False, default=0, repr=False)

    def __post_init__(self):
        self._ctx = ModCtx.make(self.modulus)
        if self.reduce is None:
            self.reduce = self._ctx.reduce_mul
        self._buf = self._place_zeros(self.initial_rows)
        self._index = {}
        # (cs-list identity, epoch, idx array): aggregates pass the same
        # operand list object while the proxy's caches validate unchanged,
        # so the O(K) big-int index lookups run once per distinct list.
        # The strong ref keeps the keyed list alive (identity stays unique);
        # epoch invalidates across capacity resets.
        self._idx_memo: tuple | None = None
        self._epoch = 0
        self._resets = 0
        self._last_reset_ts: float | None = None
        # rows evicted under the lock, delivered to `spill` after release
        # (the sink may write to disk; holding the pool lock across an
        # fsync would serialize concurrent folds on storage latency)
        self._spill_out: list[list] = []
        # cumulative operand accounting (resident / ingested / direct):
        # feeds the plane's dds_resident_hit_ratio gauge without a metrics
        # round-trip
        self._served = [0, 0, 0]
        # folds may run on proxy worker threads; ingest (index+buffer
        # mutation) must be serialized. Reads gather from an immutable
        # buffer snapshot, so only `ensure` needs the lock.
        self._lock = threading.Lock()

    # ------------------------------------------------------------ placement

    def _place(self, arr):
        import jax
        import jax.numpy as jnp

        if self.sharding is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, self.sharding)

    def _place_zeros(self, rows: int):
        import jax.numpy as jnp

        return self._place(jnp.zeros((rows, self._ctx.L), jnp.uint32))

    # -------------------------------------------------------------- surface

    @property
    def resident(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        return int(self._buf.shape[0])

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def resets(self) -> int:
        return self._resets

    def nbytes(self) -> int:
        """Device bytes this pool's buffer occupies (rows x L x 4)."""
        return self.capacity * self._ctx.L * 4

    def hit_ratio(self) -> float | None:
        """Fraction of fold operands served from resident rows (None
        until the pool has served any)."""
        total = sum(self._served)
        return (self._served[0] / total) if total else None

    def stats(self) -> dict:
        return {
            "rows": self._count,
            "capacity": self.capacity,
            "bytes": self.nbytes(),
            "epoch": self._epoch,
            "resets": self._resets,
            "last_reset_age_s": (
                round(time.time() - self._last_reset_ts, 1)
                if self._last_reset_ts is not None else None
            ),
            "hit_ratio": (
                round(self.hit_ratio(), 4)
                if self.hit_ratio() is not None else None
            ),
        }

    # --------------------------------------------------------------- ingest

    def _grow(self, need: int, protect=()) -> None:
        import jax.numpy as jnp

        cap = self.capacity
        while cap < need:
            cap *= 2
        if cap > self.max_rows:
            if self.spill is not None:
                # Stratum eviction-to-warm: demote the coldest resident
                # rows instead of resetting — the counter stays frozen
                # and the fused fast path degrades gradually
                self._evict(need, protect)
                return
            log.warning(
                "resident pool %s over max_rows (%d > %d): resetting",
                self.gid or "-", need, self.max_rows,
            )
            self._index.clear()
            self._count = 0
            self._epoch += 1  # row indices changed: invalidate idx memos
            self._resets += 1
            self._last_reset_ts = time.time()
            metrics.inc(
                "dds_resident_resets_total", shard=self.gid or "-",
                help="resident-pool capacity resets (entries re-ingest "
                     "on demand)",
            )
            self._file_reset_incident(need)
            cap = max(self.initial_rows, min(cap, self.max_rows))
            self._buf = self._place_zeros(cap)
            return
        pad = jnp.zeros((cap - self.capacity, self._ctx.L), jnp.uint32)
        self._buf = self._place(jnp.concatenate([self._buf, pad], axis=0))

    def _file_reset_incident(self, need: int) -> None:
        """A capacity reset silently drops the fused fast path until the
        working set re-ingests — incident-worthy, not just a log line.
        Loop-aware like Chronoscope's exemplar capture: pool calls run on
        worker threads (sync write is fine) but belt-and-braces for any
        on-loop caller the blocking write dispatches supervised."""
        import asyncio

        from dds_tpu.obs.flight import flight

        if not getattr(flight, "enabled", False):
            return
        info = {
            "shard": self.gid or "-", "need": need,
            "max_rows": self.max_rows, "resets": self._resets,
        }
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            try:
                flight.record("resident_reset", **info)
            except Exception:  # noqa: BLE001 — telemetry never breaks ingest
                log.exception("resident_reset incident write failed")
            return
        from dds_tpu.utils.tasks import supervised_task

        supervised_task(
            flight.record_async("resident_reset", **info),
            name="resident.reset_incident",
        )

    def _evict(self, need: int, protect=()) -> None:
        """Demote the coldest rows to the tier sink so `need` total rows
        fit under `max_rows` (caller holds `_lock`). `protect` (the
        operand set being ensured) is never evicted — evicting it would
        re-inflate `missing` and loop; |distinct protect| <= max_rows is
        guaranteed to fit because every non-protected row is evictable.
        The spilled batch is queued and delivered OUTSIDE the lock."""
        import jax.numpy as jnp

        protect = set(protect)
        if len(protect) > self.max_rows:
            return  # aggregate wider than the pool: ensure() answers None
        incoming = need - self._count
        if incoming > self.max_rows:
            return
        evictable = [c for c in self._index if c not in protect]
        # at least a quarter per wave: hysteresis against per-row thrash
        evict_n = max(need - self.max_rows, (self._count + 3) // 4)
        evict_n = min(evict_n, len(evictable))
        if evict_n <= 0:
            return
        if self.evict_rank is not None:
            try:
                ranked = [c for c in self.evict_rank(evictable)
                          if c in self._index and c not in protect]
            except Exception:  # noqa: BLE001 — a sink bug must not lose rows
                log.exception("evict_rank failed; falling back to FIFO")
                ranked = evictable
        else:
            ranked = evictable
        victims = list(dict.fromkeys(ranked))[:evict_n]
        if len(victims) < evict_n:
            seen = set(victims)
            for c in evictable:
                if c not in seen:
                    victims.append(c)
                    if len(victims) >= evict_n:
                        break
        vset = set(victims)
        host = np.asarray(self._buf[: self._count])  # one D2H copy
        spilled = [(c, host[self._index[c]].copy()) for c in victims]
        survivors = [c for c in self._index if c not in vset]
        cap = self.capacity
        while cap < len(survivors) + incoming and cap < self.max_rows:
            cap *= 2
        cap = min(max(cap, self.initial_rows), self.max_rows)
        newbuf = np.zeros((cap, self._ctx.L), np.uint32)
        if survivors:
            newbuf[: len(survivors)] = host[
                [self._index[c] for c in survivors]
            ]
        self._buf = self._place(jnp.asarray(newbuf))
        self._index = {c: i for i, c in enumerate(survivors)}
        self._count = len(survivors)
        self._epoch += 1  # row indices changed: invalidate idx memos
        self._spill_out.append(spilled)
        metrics.inc(
            "dds_resident_evictions_total", len(victims),
            shard=self.gid or "-",
            help="rows evicted from resident pools to the warm tier "
                 "(Stratum; replaces capacity resets)",
        )
        log.info(
            "resident pool %s evicted %d cold rows to warm tier "
            "(%d stay resident)",
            self.gid or "-", len(victims), len(survivors),
        )

    def _flush_spill(self) -> None:
        """Deliver queued evictions to the tier sink outside `_lock`."""
        sink = self.spill
        while True:
            with self._lock:
                if not self._spill_out:
                    return
                batch = self._spill_out.pop(0)
            if sink is None:
                continue
            try:
                sink(batch)
            except Exception:  # noqa: BLE001 — sink bugs must not break folds
                log.exception("tier spill sink failed (%d rows dropped "
                              "back to lazy re-ingest)", len(batch))

    def membership(self, cs: list[int]) -> list[bool]:
        """Per-operand hot-tier residency, one lock round — the Stratum
        planner's split primitive."""
        with self._lock:
            return [c in self._index for c in cs]

    def ensure(self, cs: list[int], pre: dict | None = None) -> np.ndarray | None:
        """Ingest any unseen ciphertexts; return row indices for all of cs.
        Caller must hold `_lock`. `pre` optionally maps ciphertext -> already
        limb-converted row (fold() precomputes these OUTSIDE the lock so the
        CPU-heavy conversion never serializes concurrent folds).

        Returns None when the distinct operands cannot fit even after a
        reset (aggregate wider than max_rows) — callers fall back to a
        direct, non-resident fold."""
        import jax
        import jax.numpy as jnp

        missing = sorted({c for c in cs if c not in self._index})
        if missing:
            if self._count + len(missing) > self.capacity:
                self._grow(self._count + len(missing), protect=cs)
                missing = sorted({c for c in cs if c not in self._index})
            if self._count + len(missing) > self.capacity:
                return None  # wider than max_rows even when empty
            if pre is not None and all(c in pre for c in missing):
                rows = np.stack([pre[c] for c in missing])
            else:
                rows = bn.ints_to_batch(
                    [c % self.modulus for c in missing], self._ctx.L
                )
            start = self._count
            self._buf = self._place(jax.lax.dynamic_update_slice(
                self._buf, jnp.asarray(rows), (start, 0)
            ))
            for i, c in enumerate(missing):
                self._index[c] = start + i
            self._count += len(missing)
        return np.asarray([self._index[c] for c in cs], dtype=np.int32)

    def ingest(self, cs: list[int]) -> int:
        """Ingest ciphertexts eagerly (the write-path entry point): limb
        conversion happens outside the lock, placement under it. Returns
        how many new rows landed; operands wider than the pool are simply
        skipped (they would only ever direct-fold anyway)."""
        distinct = list(dict.fromkeys(cs))
        missing = [c for c in distinct if c not in self._index]
        if not missing:
            return 0
        converted = bn.ints_to_batch(
            [c % self.modulus for c in missing], self._ctx.L
        )
        pre = {c: converted[i] for i, c in enumerate(missing)}
        t_h2d = time.perf_counter()
        with self._lock:
            missing_now = [c for c in missing if c not in self._index]
            self.ensure(missing, pre)
            # count placements, not the buffer delta: an eviction wave in
            # the same ensure() can shrink _count while rows still land
            grew = sum(1 for c in missing_now if c in self._index)
        self._flush_spill()
        if grew:
            # Chronoscope's host-to-device-transfer stage + bytes-moved
            # accounting: each placed row is L limbs of 4 bytes on device
            moved = grew * self._ctx.L * 4
            cur = obs_context.current()
            tracer.record(
                "ingest.h2d", (time.perf_counter() - t_h2d) * 1e3,
                _ctx=obs_context.child(cur) if cur is not None else None,
                rows=grew, bytes=moved, shard=self.gid or "-",
            )
            metrics.inc(
                "dds_ingest_h2d_bytes_total", moved, shard=self.gid or "-",
                help="bytes placed into device-resident pools (rows*L*4)",
            )
            metrics.inc(
                "dds_resident_ingest_total", grew, shard=self.gid or "-",
                path="write",
                help="rows ingested into resident pools by path",
            )
        return grew

    # ----------------------------------------------------------------- read

    def _account(self, n_resident: int, n_ingested: int, n_direct: int) -> None:
        self._served[0] += n_resident
        self._served[1] += n_ingested
        self._served[2] += n_direct
        # the pre-Lodestone series, kept for dashboards that scrape it;
        # the direct-fallback path is now honestly its own outcome instead
        # of being misreported as resident
        help_ = "fold operands served from device-resident rows vs ingested"
        if n_resident:
            metrics.inc("dds_cipher_store_total", n_resident,
                        outcome="resident", help=help_)
        if n_ingested:
            metrics.inc("dds_cipher_store_total", n_ingested,
                        outcome="ingested", help=help_)
            metrics.inc(
                "dds_resident_ingest_total", n_ingested,
                shard=self.gid or "-", path="fold",
                help="rows ingested into resident pools by path",
            )
        if n_direct:
            metrics.inc("dds_cipher_store_total", n_direct,
                        outcome="direct", help=help_)

    def rows_for(self, cs: list[int]):
        """(buffer snapshot, row indices) for `cs`, ingesting any unseen
        operands first — the gather half of `fold`, shared with the
        plane's fused multi-group dispatch and Prism's resident MatVec
        gather. Returns None when the distinct operands cannot fit even
        after a reset (callers fall back to direct marshaling). Accounts
        resident/ingested operands as a side effect."""
        with self._lock:
            m = self._idx_memo
            if m is not None and m[0] is cs and m[1] == self._epoch:
                self._account(len(cs), 0, 0)
                return self._buf, m[2]
            missing = sorted({c for c in cs if c not in self._index})
            if not missing:
                idx = np.asarray(
                    [self._index[c] for c in cs], dtype=np.int32
                )
                self._idx_memo = (cs, self._epoch, idx)
                self._account(len(cs), 0, 0)
                return self._buf, idx  # immutable jax array: safe outside
        # limb-convert the unseen operands OUTSIDE the lock (the
        # CPU-heavy part); placement/index update stays serialized.
        # Entries are only ever added, so `missing` can only shrink in
        # between; ensure() recomputes it under the lock (and converts
        # inline in the rare capacity-reset case where `pre` is short).
        converted = bn.ints_to_batch(
            [c % self.modulus for c in missing], self._ctx.L
        )
        pre = {c: converted[i] for i, c in enumerate(missing)}
        with self._lock:
            idx = self.ensure(cs, pre)
            if idx is None:
                self._account(0, 0, len(cs))
                out = None
            else:
                self._idx_memo = (cs, self._epoch, idx)
                self._account(len(cs) - len(missing), len(missing), 0)
                out = (self._buf, idx)
        # deliver any eviction wave to the tier sink outside the lock
        self._flush_spill()
        return out

    def fold(self, cs: list[int]) -> int:
        """prod(cs) mod modulus, gathering resident rows on-device."""
        import jax.numpy as jnp

        if not cs:
            return 1 % self.modulus
        got = self.rows_for(cs)
        if got is None:  # aggregate wider than the pool: direct fold
            rows = jnp.asarray(
                bn.ints_to_batch([c % self.modulus for c in cs], self._ctx.L)
            )
            resident = False
        else:
            buf, idx = got
            rows = jnp.take(buf, jnp.asarray(idx), axis=0)
            resident = True
        with tracer.span("kernel.fold", k=len(cs), resident=resident):
            # dispatch (trace/compile) timed apart from block_until_ready
            # device execution (obs/kprof) — the split the flat span hid
            out = kprof.profiled(
                "store.reduce", lambda: self.reduce(rows), k=len(cs),
            )
            return bn.limbs_to_int(np.asarray(out)[0])
