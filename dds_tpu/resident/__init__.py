"""Lodestone: mesh-fused device-resident ciphertext plane.

Per-shard-group content-addressed limb pools pinned in device memory
(`ResidentPool`), write-path incremental ingest, and single-dispatch
sharded gather+fold aggregates (`ResidentPlane.fold_groups`). See
DEPLOY.md "Resident ciphertext plane (Lodestone)".
"""

from dds_tpu.resident.plane import ResidentPlane
from dds_tpu.resident.pool import ResidentPool

__all__ = ["ResidentPlane", "ResidentPool"]
