"""Native host-side bignum runtime: build-on-demand C++ CIOS via ctypes.

This package is the framework's native runtime component, standing in for
the reference's closed-source crypto jar (`hlib.hj.mlib`, `lib/README.txt:1`)
on the host side: Paillier/RSA modexp and modmul for the principals that
hold private keys (clients: encrypt/decrypt, `clt/DDSHttpClient.scala:131-134`
trust model) and for accelerator-less hosts. The TPU Pallas kernels in
`ops/pallas_mont.py` remain the batched data-plane path.

The C++ source ships in-package and compiles once on first use with g++
(-O3, native __uint128 CIOS, no external dependencies); the .so is cached
next to the source. Every entry point falls back to python big-ints when
the toolchain is unavailable, so importing this module never fails.

API: `powmod`, `powmod_batch`, `fold` (modular product of a list), all for
odd moduli (Montgomery); even moduli fall back to python.
"""

from __future__ import annotations

import ctypes
import functools
import logging
import os
import pathlib
import subprocess
import threading

import numpy as np

log = logging.getLogger("dds.native")

_SRC = pathlib.Path(__file__).with_name("ddsbn.cpp")
_SO = pathlib.Path(__file__).with_name("_ddsbn.so")
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _build() -> pathlib.Path | None:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    # No -march=native: the .so is cached on shared storage and may be
    # loaded by other hosts; generic codegen avoids SIGILL on older ISAs.
    # Compile to a per-process temp and os.replace so concurrent replica
    # processes never observe a truncated library.
    tmp = _SO.with_name(f"_ddsbn.{os.getpid()}.tmp.so")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native bignum build failed (%s); using python ints", e)
        tmp.unlink(missing_ok=True)
        return None


def _load():
    global _LIB, _TRIED
    if _TRIED:  # lock-free fast path: _LIB is assigned before _TRIED flips
        return _LIB
    with _LOCK:
        if _TRIED:
            return _LIB
        lib = None
        disabled = os.environ.get("DDS_NATIVE", "").strip().lower() in (
            "0", "false", "off", "no")
        so = None if disabled else _build()
        if so is not None:
            try:
                lib = ctypes.CDLL(str(so))
                assert lib.ddsbn_abi_version() == 1
                u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
                lib.ddsbn_mont_mul.argtypes = [
                    ctypes.c_int, u64p, ctypes.c_uint64, u64p, u64p, u64p]
                lib.ddsbn_fold.argtypes = [
                    ctypes.c_int, u64p, ctypes.c_uint64, u64p, ctypes.c_long,
                    u64p, u64p]
                lib.ddsbn_exp.argtypes = [
                    ctypes.c_int, u64p, ctypes.c_uint64, u64p, u64p, u64p,
                    ctypes.c_int, u64p]
                lib.ddsbn_exp_batch.argtypes = [
                    ctypes.c_int, u64p, ctypes.c_uint64, u64p, u64p,
                    ctypes.c_long, u64p, ctypes.c_int, u64p]
            except (OSError, AssertionError, AttributeError) as e:
                log.warning("native bignum load failed (%s); using python ints", e)
                lib = None
        _LIB = lib
        _TRIED = True  # set after _LIB so fast-path readers see a settled value
        return _LIB


def available() -> bool:
    return _load() is not None


MAXL = 130  # must match ddsbn.cpp


def _words(x: int, L: int) -> np.ndarray:
    return np.frombuffer(x.to_bytes(L * 8, "little"), dtype="<u8").copy()


def _unwords(a: np.ndarray) -> int:
    return int.from_bytes(a.tobytes(), "little")


def mont_consts_uncached(n: int) -> tuple[int, int, int]:
    """(L, n0inv, R2 mod n) for odd modulus n — computed fresh, cached
    NOWHERE in this module. The entry point for callers that manage the
    lifetime of SECRET moduli themselves (dds_tpu.sanctum holds these per
    key and drops them with it); the lru-cached `_mont_consts` below must
    only ever see public moduli, because its entries outlive every key
    object (tools/secret_lint.py enforces the split)."""
    L = -(-n.bit_length() // 64)
    R = 1 << (64 * L)
    n0inv = (-pow(n % (1 << 64), -1, 1 << 64)) % (1 << 64)
    return L, n0inv, (R * R) % n


# public-parameter consts cache: bounds repeat host-side Montgomery setup
# for the handful of moduli a process serves (n, n^2, RSA n). Secret
# moduli route through mont_consts_uncached — see its docstring.
_mont_consts = functools.lru_cache(maxsize=256)(mont_consts_uncached)


def _usable(n: int) -> bool:
    return n % 2 == 1 and n > 1 and n.bit_length() <= 64 * MAXL and _load() is not None


def _exp_words(exp: int) -> tuple[np.ndarray, int]:
    """(little-endian u64 words, nibble count) for a positive exponent."""
    nibbles = -(-exp.bit_length() // 4)
    return _words(exp, -(-exp.bit_length() // 64)), nibbles


def powmod(base: int, exp: int, mod: int) -> int:
    """pow(base, exp, mod) on the native path (odd mod); python fallback."""
    if exp < 0 or not _usable(mod):
        return pow(base, exp, mod)
    if exp == 0:
        return 1 % mod
    L, n0, r2 = _mont_consts(mod)
    ew, nibbles = _exp_words(exp)
    out = np.zeros(L, dtype=np.uint64)
    _LIB.ddsbn_exp(L, _words(mod, L), n0, _words(r2, L),
                   _words(base % mod, L), ew, nibbles, out)
    return _unwords(out)


def _exp_batch_impl(bases: list[int], exp: int, mod: int,
                    consts: tuple[int, int, int]) -> list[int]:
    L, n0, r2 = consts
    ew, nibbles = _exp_words(exp)
    bw = np.stack([_words(b % mod, L) for b in bases])
    out = np.zeros_like(bw)
    _LIB.ddsbn_exp_batch(L, _words(mod, L), n0, _words(r2, L),
                         np.ascontiguousarray(bw), len(bases), ew, nibbles, out)
    return [_unwords(out[i]) for i in range(len(bases))]


def powmod_batch(bases: list[int], exp: int, mod: int) -> list[int]:
    """Shared-exponent batch modexp (GIL released for the whole batch).
    PUBLIC moduli only: consts are memoized module-wide (see
    mont_consts_uncached for the secret-material contract)."""
    if exp < 0 or not _usable(mod):
        return [pow(b, exp, mod) for b in bases]
    if exp == 0:
        return [1 % mod] * len(bases)
    if not bases:
        return []
    return _exp_batch_impl(bases, exp, mod, _mont_consts(mod))


def powmod_batch_with_consts(bases: list[int], exp: int, mod: int,
                             consts: tuple[int, int, int] | None) -> list[int]:
    """powmod_batch with CALLER-HELD Montgomery consts (from
    mont_consts_uncached): nothing about `mod` is retained in this module
    after the call — the host fast path for secret CRT moduli. `consts`
    None (or an unusable modulus / toolchain-less host) falls back to
    python pow, which also retains nothing."""
    if consts is None or exp < 0 or not _usable(mod):
        return [pow(b, exp, mod) for b in bases]
    if exp == 0:
        return [1 % mod] * len(bases)
    if not bases:
        return []
    return _exp_batch_impl(bases, exp, mod, consts)


def fold(cs: list[int], mod: int) -> int:
    """prod(cs) % mod (the CPU-side homomorphic-aggregate fold)."""
    if not cs:
        return 1 % mod
    if not _usable(mod):
        acc = 1
        for c in cs:
            acc = acc * c % mod
        return acc
    L, n0, _ = _mont_consts(mod)
    R = 1 << (64 * L)
    fix = _words(pow(R % mod, len(cs), mod), L)
    batch = np.stack([_words(c % mod, L) for c in cs])
    out = np.zeros(L, dtype=np.uint64)
    _LIB.ddsbn_fold(L, _words(mod, L), n0, np.ascontiguousarray(batch),
                    len(cs), fix, out)
    return _unwords(out)
