// ddsbn: native host-side big-number modular arithmetic for dds_tpu.
//
// The framework's native runtime component — the counterpart of the
// closed-source Java crypto jar the reference depends on (`hlib.hj.mlib`,
// see lib/README.txt:1 and utils/SJHomoLibProvider.scala:33-101): all
// host-side Paillier/RSA hot math (client-side encrypt, CRT decrypt, CPU
// replica-side folds) runs here instead of interpreter big-ints. The TPU
// Pallas kernels (ops/pallas_mont.py) remain the data-plane compute path;
// this library serves the principals that hold private keys and hosts
// without an accelerator.
//
// Representation: little-endian arrays of 64-bit words, L words per
// number. All moduli must be odd (Montgomery). Python computes the
// Montgomery constants (n0inv = -n^-1 mod 2^64, R^2 mod n, R^K fixups)
// with big-int ease and passes them in; C++ does only fixed-width CIOS.
//
// CIOS bound audit (standard): inputs canonical < n < 2^(64L); after each
// outer step t < 2n; final t fits L+1 words with t[L] in {0,1}; one
// conditional subtract returns the canonical residue.

#include <cstdint>
#include <cstring>

typedef uint64_t u64;
typedef unsigned __int128 u128;

static const int MAXL = 130;  // up to 8320-bit moduli (Paillier-4096 n^2)

extern "C" {

int ddsbn_abi_version() { return 1; }

// out = a * b * R^-1 mod n   (canonical, < n). t space: internal.
void ddsbn_mont_mul(int L, const u64* n, u64 n0, const u64* a, const u64* b,
                    u64* out) {
  u64 t[MAXL + 2];
  memset(t, 0, (size_t)(L + 2) * sizeof(u64));
  for (int i = 0; i < L; i++) {
    const u64 ai = a[i];
    u64 carry = 0;
    for (int j = 0; j < L; j++) {
      u128 cur = (u128)ai * b[j] + t[j] + carry;
      t[j] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    u128 s = (u128)t[L] + carry;
    t[L] = (u64)s;
    t[L + 1] += (u64)(s >> 64);

    const u64 m = t[0] * n0;
    u128 cur = (u128)m * n[0] + t[0];
    carry = (u64)(cur >> 64);
    for (int j = 1; j < L; j++) {
      cur = (u128)m * n[j] + t[j] + carry;
      t[j - 1] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    s = (u128)t[L] + carry;
    t[L - 1] = (u64)s;
    s = (u128)t[L + 1] + (u64)(s >> 64);
    t[L] = (u64)s;
    t[L + 1] = (u64)(s >> 64);  // 0 by the < 2n bound
  }
  // conditional subtract (t has L+1 words, t[L] in {0,1})
  u64 diff[MAXL];
  u64 borrow = 0;
  for (int j = 0; j < L; j++) {
    u128 d = (u128)t[j] - n[j] - borrow;
    diff[j] = (u64)d;
    borrow = (u64)(d >> 64) & 1;
  }
  const bool ge = t[L] || !borrow;
  for (int j = 0; j < L; j++) out[j] = ge ? diff[j] : t[j];
}

// out = prod(cs) mod n over K plain-domain inputs (cs: K rows of L words).
// fix must be R^K mod n (host-computed): the chain of K-1 Montgomery
// multiplies accumulates R^-(K-1), and the final multiply by fix lands the
// result back in the plain domain.
void ddsbn_fold(int L, const u64* n, u64 n0, const u64* cs, long K,
                const u64* fix, u64* out) {
  u64 acc[MAXL];
  memcpy(acc, cs, (size_t)L * sizeof(u64));
  for (long i = 1; i < K; i++)
    ddsbn_mont_mul(L, n, n0, acc, cs + (size_t)i * L, acc);
  ddsbn_mont_mul(L, n, n0, acc, fix, out);
}

// out = base^exp mod n, plain domain in/out. exp given as `nibbles` 4-bit
// digits, MSB-first iteration over exp's little-endian words; r2 = R^2 mod n.
void ddsbn_exp(int L, const u64* n, u64 n0, const u64* r2, const u64* base,
               const u64* exp, int nibbles, u64* out) {
  u64 table[16][MAXL];
  // table[0] = R mod n (Montgomery one) = mont_mul(1, r2)
  u64 one[MAXL];
  memset(one, 0, (size_t)L * sizeof(u64));
  one[0] = 1;
  ddsbn_mont_mul(L, n, n0, one, r2, table[0]);
  ddsbn_mont_mul(L, n, n0, base, r2, table[1]);  // base into Montgomery
  for (int d = 2; d < 16; d++)
    ddsbn_mont_mul(L, n, n0, table[d - 1], table[1], table[d]);

  u64 r[MAXL];
  memcpy(r, table[0], (size_t)L * sizeof(u64));
  for (int idx = nibbles - 1; idx >= 0; idx--) {
    for (int s = 0; s < 4; s++) ddsbn_mont_mul(L, n, n0, r, r, r);
    const int digit = (int)((exp[idx / 16] >> (4 * (idx % 16))) & 0xF);
    ddsbn_mont_mul(L, n, n0, r, table[digit], r);
  }
  ddsbn_mont_mul(L, n, n0, r, one, out);  // back to plain domain
}

// batch modexp with a shared exponent: bases/out are B rows of L words.
void ddsbn_exp_batch(int L, const u64* n, u64 n0, const u64* r2,
                     const u64* bases, long B, const u64* exp, int nibbles,
                     u64* out) {
  for (long i = 0; i < B; i++)
    ddsbn_exp(L, n, n0, r2, bases + (size_t)i * L, exp, nibbles,
              out + (size_t)i * L);
}

}  // extern "C"
