"""Constellation: the sharded keyspace plane.

Partitions the key->set keyspace across S independent BFT-ABD quorum
groups — each with its own replicas, spares, supervisor, anti-entropy
loop, and attack surface — behind a consistent-hash, epoch-versioned,
HMAC-signed `ShardMap` that every client->replica message carries and
every replica fences. Point ops route to exactly one group; aggregates
scatter per-shard folds and gather partials with the mesh plane's
modular-product tail combine. Live resharding streams keys through
Aegis-verified state-transfer frames under an epoch fence, so a split
never loses or misroutes a write. See DEPLOY.md "Sharding".
"""

from dds_tpu.shard.fabric import (
    Constellation,
    ShardGroup,
    build_constellation,
    build_group,
)
from dds_tpu.shard.rebalance import Rebalancer, ReshardAborted
from dds_tpu.shard.router import ShardRouter
from dds_tpu.shard.shardmap import (
    ShardManager,
    ShardMap,
    ShardState,
    moved_keys,
)

__all__ = [
    "Constellation", "ShardGroup", "build_constellation", "build_group",
    "Rebalancer", "ReshardAborted", "ShardRouter",
    "ShardManager", "ShardMap", "ShardState", "moved_keys",
]
