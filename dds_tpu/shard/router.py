"""Shard-aware storage router: point ops to one group, batches scattered.

Drop-in replacement for a single `AbdClient` at the REST proxy
(`DDSRestServer(abd=ShardRouter(...))`): it exposes the same storage
surface — fetch/write/read_tags plus the breaker/trust views the /health
and /metrics routes read — but resolves each key's owning quorum group
through the `ShardManager`'s active map and delegates to that group's
`AbdClient`. Every delegated client stamps its messages with the map's
epoch (AbdClient.shard_epoch), so replicas can fence stale routes; a
fenced op surfaces as `WrongShardError`, the router refreshes its map
(`refresh` hook — a no-op when the manager is in-process, a /shards pull
in a remote deployment) and the proxy's existing deadline-budgeted retry
re-resolves the owner on the next attempt. No silent misroutes, no new
retry machinery.

`read_tags` — the aggregate cache's validation primitive — is
scatter-gathered: keys partition by owner, each group runs its own
batched tag round concurrently, and the per-key vectors stitch back in
request order. The whole-cache `unchanged` identity contract is
preserved: when EVERY group answers "unchanged" for its slice, the
router returns the caller's `cached_tags` list by identity, so the
proxy's O(1) steady-state aggregate path survives sharding.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from dds_tpu.core.errors import WrongShardError
from dds_tpu.core.quorum_client import AbdClient
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.retry import Deadline
from dds_tpu.utils.trace import tracer


class _MergedTrust:
    """Read-only union of the per-group trusted-node lists, shaped like
    the TrustedNodesList surface /health and the state gauges consume."""

    def __init__(self, clients: dict[str, AbdClient]):
        self._clients = clients

    def get_trusted(self) -> list[str]:
        out = []
        for c in self._clients.values():
            out.extend(c.replicas.get_trusted())
        return out

    def get_all(self) -> list[str]:
        out = []
        for c in self._clients.values():
            out.extend(c.replicas.get_all())
        return out

    def suspicions(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for c in self._clients.values():
            out.update(c.replicas.suspicions())
        return out


class ShardRouter:
    def __init__(self, manager, clients: dict[str, AbdClient],
                 refresh=None):
        """`clients` maps group id -> that group's AbdClient (each built
        with its own replica set, supervisor, and `cfg.shard` label).
        `refresh` is invoked on every WrongShardError before the retry
        re-resolves — in-process the manager IS current so the default is
        a no-op; a remote router plugs a signed /shards fetch here."""
        self.shard_manager = manager
        self.clients = clients
        self.replicas = _MergedTrust(clients)
        self._refresh = refresh
        # cumulative routed-op count per group id — the Helmsman
        # controller diffs successive snapshots to see per-group load
        # share (hot/cold), so the counters never reset here
        self._op_counts: dict[str, int] = {}
        for gid, c in clients.items():
            # every delegated message carries the ACTIVE map's epoch —
            # late-bound so an activation mid-request stamps correctly
            c.shard_epoch = lambda m=manager: m.current().epoch
            if not c.cfg.shard:
                c.cfg.shard = gid

    # ------------------------------------------------------------- routing

    def owner(self, key: str) -> str:
        return self.shard_manager.current().owner(key)

    def group_ids(self) -> list[str]:
        """Current group ids in construction order — the stable
        group -> mesh-slice assignment Lodestone's resident pools pin
        their device placement by (split-born groups append, so existing
        placements never move)."""
        return list(self.clients)

    def _route(self, key: str) -> tuple[str, AbdClient]:
        gid = self.owner(key)
        client = self.clients.get(gid)
        if client is None:
            raise WrongShardError(key, sent_epoch=self.shard_manager.epoch)
        return gid, client

    def partition_keys(self, keys) -> dict[str, list]:
        """Keys grouped by owning group id (insertion-ordered)."""
        smap = self.shard_manager.current()
        out: dict[str, list] = {}
        for k in keys:
            out.setdefault(smap.owner(k), []).append(k)
        return out

    def _wrong_shard(self, gid: str, err: WrongShardError) -> None:
        metrics.inc(
            "dds_wrong_shard_retries_total", shard=gid,
            help="ops fenced by a replica group and re-routed after a "
                 "shard-map refresh",
        )
        tracer.event("shard.wrong_shard", shard=gid, key=err.key,
                     replica_epoch=err.replica_epoch)
        if self._refresh is not None:
            self._refresh()

    # ----------------------------------------------------------- point ops

    def _charge(self, gid: str, n: int = 1) -> None:
        self._op_counts[gid] = self._op_counts.get(gid, 0) + n

    def load_census(self) -> dict[str, int]:
        """Cumulative routed ops per group, with every CURRENT group
        present (zero-filled) so a cold group is visibly cold."""
        out = {gid: 0 for gid in self.clients}
        out.update(self._op_counts)
        return out

    async def _point(self, op: str, key: str, call):
        gid, client = self._route(key)
        self._charge(gid)
        t0 = time.perf_counter()
        try:
            return await call(client)
        except WrongShardError as e:
            self._wrong_shard(gid, e)
            raise
        finally:
            metrics.observe(
                "dds_shard_route_seconds", time.perf_counter() - t0,
                shard=gid, op=op,
                help="per-shard storage-op latency at the router",
            )

    async def fetch_set(self, key: str, deadline: Optional[Deadline] = None):
        return (await self.fetch_set_tagged(key, deadline=deadline))[0]

    async def fetch_set_tagged(self, key: str,
                               deadline: Optional[Deadline] = None):
        value, tag, _ = await self.fetch_set_attributed(key, deadline=deadline)
        return value, tag

    async def fetch_set_attributed(self, key: str, exclude=(),
                                   deadline: Optional[Deadline] = None):
        return await self._point(
            "fetch", key,
            lambda c: c.fetch_set_attributed(key, exclude, deadline=deadline),
        )

    async def write_set(self, key: str, value,
                        deadline: Optional[Deadline] = None) -> str:
        return (await self.write_set_tagged(key, value, deadline=deadline))[0]

    async def write_set_tagged(self, key: str, value,
                               deadline: Optional[Deadline] = None):
        return await self._point(
            "write", key,
            lambda c: c.write_set_tagged(key, value, deadline=deadline),
        )

    # ------------------------------------------------------------- batches

    async def read_tags(
        self,
        keys: list[str],
        digest: str | None = None,
        fingerprint: bytes | None = None,
        cached_tags: list | None = None,
        deadline: Optional[Deadline] = None,
    ):
        parts = self.partition_keys(keys)
        if len(parts) <= 1:
            # single-group: delegate verbatim so the caller's digest/
            # fingerprint and the `is cached_tags` identity contract pass
            # straight through
            (gid, sub) = next(iter(parts.items())) if parts else (None, [])
            if gid is None:
                return []
            try:
                return await self.clients[gid].read_tags(
                    list(keys), digest=digest, fingerprint=fingerprint,
                    cached_tags=cached_tags, deadline=deadline,
                )
            except WrongShardError as e:
                self._wrong_shard(gid, e)
                raise

        smap = self.shard_manager.current()
        index: dict[str, list[int]] = {}
        for i, k in enumerate(keys):
            index.setdefault(smap.owner(k), []).append(i)

        async def one(gid: str, idxs: list[int]):
            client = self.clients.get(gid)
            if client is None:
                raise WrongShardError(keys[idxs[0]], sent_epoch=smap.epoch)
            self._charge(gid, len(idxs))
            sub_keys = [keys[i] for i in idxs]
            sub_cached = None
            sub_fp = None
            if cached_tags is not None:
                sub_cached = [cached_tags[i] for i in idxs]
                # per-group fingerprint: the caller's covers the WHOLE
                # vector, which no single group can attest
                sub_fp = sigs.tags_fingerprint(sub_cached)
            try:
                return await client.read_tags(
                    sub_keys, fingerprint=sub_fp, cached_tags=sub_cached,
                    deadline=deadline,
                ), sub_cached
            except WrongShardError as e:
                self._wrong_shard(gid, e)
                raise

        results = await asyncio.gather(*(one(g, ix) for g, ix in index.items()))
        if cached_tags is not None and all(
            tags is sub_cached for tags, sub_cached in results
        ):
            return cached_tags  # every group said "unchanged": whole-cache hit
        out = [None] * len(keys)
        for (tags, _), idxs in zip(results, index.values()):
            for i, t in zip(idxs, tags):
                out[i] = t
        return out

    # -------------------------------------------------- health/metrics glue

    @property
    def cfg(self):
        """Group-representative config (quorum size, budgets): groups are
        homogeneous by construction in run.launch; heterogeneous health is
        served per-group by shards_health()."""
        return next(iter(self.clients.values())).cfg

    @property
    def breakers(self) -> dict:
        out = {}
        for c in self.clients.values():
            out.update(c.breakers)
        return out

    def breaker_states(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for c in self.clients.values():
            out.update(c.breaker_states())
        return out

    def breaker_census(self) -> tuple[int, list[float]]:
        """Fleet-wide (trusted coordinator count, refusing-breaker ETAs)
        for the Bulwark controller. Per-group fast-fail needs no router
        code: each delegated AbdClient raises AllBreakersOpenError for ITS
        group when all of that group's coordinators are open past the
        budget — a single dead group degrades its own keys immediately
        without shedding the healthy groups."""
        total, etas = 0, []
        for c in self.clients.values():
            n, e = c.breaker_census()
            total += n
            etas.extend(e)
        return total, etas

    def min_half_open_eta(self) -> float | None:
        _, etas = self.breaker_census()
        positive = [e for e in etas if e > 0]
        return min(positive) if positive else None

    def refresh_from(self, supervisor: str | None = None) -> None:
        """Refresh every group from ITS OWN supervisor (pinned on each
        client's config at build time); the argument — the single
        supervisor a non-sharded proxy would poll — is ignored."""
        for c in self.clients.values():
            if c.cfg.supervisor:
                c.refresh_from(c.cfg.supervisor)

    def shards_health(self) -> dict:
        """Per-group quorum health for GET /health."""
        smap = self.shard_manager.current()
        out = {}
        for gid, c in self.clients.items():
            trusted = c.replicas.get_trusted()
            reachable = [
                n for n in trusted
                if n not in c.breakers or c.breakers[n].allow()
            ]
            out[gid] = {
                "active_replicas": len(trusted),
                "reachable_replicas": len(reachable),
                "quorum_size": c.cfg.quorum_size,
                "degraded": len(reachable) < c.cfg.quorum_size,
                "vnodes": sum(1 for _, g in smap.vnodes if g == gid),
            }
            # Atlas: home-region label (from the signed map) + this
            # client's live lease session, when the group is geo-aware
            region = smap.region_of(gid)
            if region:
                out[gid]["region"] = region
            if c.cfg.lease_enabled:
                out[gid]["lease"] = c.lease_state()
        return out

    def status(self) -> dict:
        """The signed active map + reshard state, for GET /shards."""
        return {
            "state": self.shard_manager.state,
            "map": self.shard_manager.current().to_wire(),
            "groups": {
                gid: sorted(c.replicas.get_all())
                for gid, c in self.clients.items()
            },
        }
