"""Constellation fabric: build S independent BFT-ABD quorum groups.

One group = the full single-shard stack the repo already had — replicas
(+sentinent spares), a supervisor with proactive recovery, per-replica
Merkle anti-entropy, an `AbdClient`, and a Trudy/Nemesis attack surface —
instantiated per group with namespaced endpoints (`s0-replica-3`,
`s1-supervisor`, ...) over ONE shared transport (so ChaosNet schedules,
partitions, and Nemesis attacks apply to any subset of the constellation).
`build_constellation` assembles S groups plus the ShardManager/ShardRouter
pair and a Rebalancer; `build_group` is the per-group factory the live
split uses to bring up a brand-new group mid-flight.

Used by run.launch (config-driven), the shard test suite, and
benchmarks/shard_scaling.py — one topology builder, three consumers.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.shard.rebalance import Rebalancer
from dds_tpu.shard.router import ShardRouter
from dds_tpu.shard.shardmap import ShardManager, ShardMap, ShardState


@dataclass
class ShardGroup:
    """Handle to one quorum group of the constellation."""

    gid: str
    active: list[str]
    sentinent: list[str]
    replicas: dict[str, BFTABDNode]
    supervisor: BFTSupervisor
    client: AbdClient
    state: ShardState
    quorum_size: int
    trudy: object = None

    def all_replicas(self) -> list[str]:
        return self.active + self.sentinent

    def export_from(self, endpoint: str) -> dict:
        """Export one replica's repository (migration seed DATA — every
        receiver re-verifies entries against the manifest quorum)."""
        node = self.replicas.get(endpoint)
        return node.export_state() if node is not None else {}

    def prune_unowned(self) -> int:
        return sum(n.drop_unowned() for n in self.replicas.values())

    async def stop(self) -> None:
        await self.supervisor.stop()
        for n in self.replicas.values():
            await n.antientropy.stop()


@dataclass
class Constellation:
    manager: ShardManager
    router: ShardRouter
    groups: list[ShardGroup]
    rebalancer: Rebalancer
    net: object = None
    secret: bytes = b""
    _build_kwargs: dict = field(default_factory=dict)

    @property
    def gids(self) -> list[str]:
        """Group ids in construction order (the Lodestone resident
        plane's pool registration order; see ShardRouter.group_ids)."""
        return [g.gid for g in self.groups]

    def group(self, gid: str) -> ShardGroup:
        return next(g for g in self.groups if g.gid == gid)

    async def split(self, victim_gid: str) -> ShardGroup:
        """Live split: bring up a fresh group, migrate ~half of the
        victim's keyspace into it (Aegis-verified, epoch-fenced), activate.
        The new group fences everything until activation, so it can be
        built eagerly without receiving traffic."""
        new_gid = f"s{len(self.groups)}"
        old_map = self.manager.current()
        state = ShardState(new_gid, old_map, self.secret)
        group = build_group(self.net, new_gid, state, **self._build_kwargs)
        victim = self.group(victim_gid)
        await self.rebalancer.split(victim, group)
        self.groups.append(group)
        self.router.clients[new_gid] = group.client
        group.client.shard_epoch = lambda m=self.manager: m.current().epoch
        if not group.client.cfg.shard:
            group.client.cfg.shard = new_gid
        return group

    async def stop(self) -> None:
        for g in self.groups:
            await g.stop()


def build_group(
    net,
    gid: str,
    state: ShardState,
    *,
    n_active: int = 4,
    n_sentinent: int = 1,
    quorum: int = 3,
    max_faults: int = 1,
    rcfg: ReplicaConfig | None = None,
    sup_cfg: SupervisorConfig | None = None,
    abd_cfg: AbdClientConfig | None = None,
    chaos: bool = False,
    rng: random.Random | None = None,
    namer=None,
) -> ShardGroup:
    """One namespaced quorum group over `net`, fencing under `state`.

    `namer` maps a bare endpoint name to its transport address — identity
    for the in-memory fabric, `TcpNet.local_addr` for a Meridian group
    process so every endpoint is a routable `host:port/name`."""
    import dataclasses as _dc

    namer = namer or (lambda name: name)
    rcfg = rcfg or ReplicaConfig(quorum_size=quorum)
    endpoints = [
        namer(f"{gid}-replica-{i}") for i in range(n_active + n_sentinent)
    ]
    active, sentinent = endpoints[:n_active], endpoints[n_active:]
    sup_addr = namer(f"{gid}-supervisor")
    replicas = {
        e: BFTABDNode(e, endpoints, sup_addr, net, rcfg, shard=state)
        for e in endpoints
    }
    for e in sentinent:
        replicas[e].behavior = "sentinent"
    supervisor = BFTSupervisor(
        sup_addr, active, sentinent, net,
        sup_cfg or SupervisorConfig(quorum_size=quorum,
                                    proactive_recovery_enabled=False),
        rng=rng,
    )
    if abd_cfg is None:
        abd_cfg = AbdClientConfig(quorum_size=quorum)
    elif not abd_cfg.shard:
        abd_cfg = _dc.replace(abd_cfg)
    abd_cfg.shard = gid
    abd_cfg.supervisor = sup_addr
    client = AbdClient(namer(f"{gid}-proxy"), net, active, abd_cfg)
    if chaos:
        from dds_tpu.malicious.trudy import Nemesis

        trudy = Nemesis(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                        rng=rng)
    else:
        from dds_tpu.malicious.trudy import Trudy

        trudy = Trudy(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                      rng=rng)
    return ShardGroup(gid, active, sentinent, replicas, supervisor, client,
                      state, quorum, trudy)


def build_constellation(
    net,
    *,
    shard_count: int = 2,
    vnodes_per_group: int = 16,
    secret: bytes = b"intranet-abd-secret",
    manifest_timeout: float = 2.0,
    ack_timeout: float = 5.0,
    chunk_keys: int = 256,
    prune: bool = True,
    seed: int | None = None,
    namer=None,
    **group_kwargs,
) -> Constellation:
    """S homogeneous groups + manager/router/rebalancer over one fabric."""
    gids = [f"s{i}" for i in range(shard_count)]
    smap = ShardMap.build(gids, vnodes_per_group).sign(secret)
    manager = ShardManager(smap, secret)
    rng = random.Random(seed) if seed is not None else None
    groups = []
    for gid in gids:
        state = ShardState(gid, smap, secret)
        grp_rng = random.Random(rng.getrandbits(64)) if rng else None
        groups.append(build_group(net, gid, state, rng=grp_rng, namer=namer,
                                  **group_kwargs))
    router = ShardRouter(manager, {g.gid: g.client for g in groups})
    rebalancer = Rebalancer(
        manager, net, secret,
        addr=(namer or (lambda n: n))("rebalancer"),
        manifest_timeout=manifest_timeout,
        ack_timeout=ack_timeout, chunk_keys=chunk_keys, prune=prune,
    )
    return Constellation(manager, router, groups, rebalancer, net=net,
                         secret=secret,
                         _build_kwargs=dict(group_kwargs, namer=namer))
