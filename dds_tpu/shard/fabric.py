"""Constellation fabric: build S independent BFT-ABD quorum groups.

One group = the full single-shard stack the repo already had — replicas
(+sentinent spares), a supervisor with proactive recovery, per-replica
Merkle anti-entropy, an `AbdClient`, and a Trudy/Nemesis attack surface —
instantiated per group with namespaced endpoints (`s0-replica-3`,
`s1-supervisor`, ...) over ONE shared transport (so ChaosNet schedules,
partitions, and Nemesis attacks apply to any subset of the constellation).
`build_constellation` assembles S groups plus the ShardManager/ShardRouter
pair and a Rebalancer; `build_group` is the per-group factory the live
split uses to bring up a brand-new group mid-flight.

Used by run.launch (config-driven), the shard test suite, and
benchmarks/shard_scaling.py — one topology builder, three consumers.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.shard.rebalance import Rebalancer
from dds_tpu.shard.router import ShardRouter
from dds_tpu.shard.shardmap import ShardManager, ShardMap, ShardState


@dataclass
class ShardGroup:
    """Handle to one quorum group of the constellation."""

    gid: str
    active: list[str]
    sentinent: list[str]
    replicas: dict[str, BFTABDNode]
    supervisor: BFTSupervisor
    client: AbdClient
    state: ShardState
    quorum_size: int
    trudy: object = None

    def all_replicas(self) -> list[str]:
        return self.active + self.sentinent

    def export_from(self, endpoint: str) -> dict:
        """Export one replica's repository (migration seed DATA — every
        receiver re-verifies entries against the manifest quorum)."""
        node = self.replicas.get(endpoint)
        return node.export_state() if node is not None else {}

    def prune_unowned(self) -> int:
        return sum(n.drop_unowned() for n in self.replicas.values())

    async def stop(self) -> None:
        await self.supervisor.stop()
        for n in self.replicas.values():
            await n.antientropy.stop()


@dataclass
class Constellation:
    manager: ShardManager
    router: ShardRouter
    groups: list[ShardGroup]
    rebalancer: Rebalancer
    net: object = None
    secret: bytes = b""
    _build_kwargs: dict = field(default_factory=dict)
    # warm standbys: groups a merge retired (still running, pruned empty)
    # — the next split or takeover reuses one instead of building fresh
    standbys: list = field(default_factory=list)

    @property
    def gids(self) -> list[str]:
        """Group ids in construction order (the Lodestone resident
        plane's pool registration order; see ShardRouter.group_ids)."""
        return [g.gid for g in self.groups]

    def group(self, gid: str) -> ShardGroup:
        for g in self.groups:
            if g.gid == gid:
                return g
        raise ValueError(f"unknown group {gid!r}")

    def _fresh_gid(self) -> str:
        used = {g.gid for g in self.groups} | {g.gid for g in self.standbys}
        n = len(used)
        while f"s{n}" in used:
            n += 1
        return f"s{n}"

    def _acquire_standby(self, gid: str | None = None) -> ShardGroup:
        """A serving-capable group outside the active map: a warm standby
        a merge retired, else a freshly built one (fenced until a map
        gives it keys, so it can be brought up eagerly without traffic).
        A caller naming `gid` (an operator's replayable split target)
        gets that standby, or a fresh group under that name."""
        if gid is not None:
            for i, g in enumerate(self.standbys):
                if g.gid == gid:
                    return self.standbys.pop(i)
            if gid in {g.gid for g in self.groups}:
                raise ValueError(f"target group {gid!r} is already active")
        else:
            if self.standbys:
                return self.standbys.pop(0)
            gid = self._fresh_gid()
        state = ShardState(gid, self.manager.current(), self.secret)
        return build_group(self.net, gid, state, **self._build_kwargs)

    def _adopt(self, group: ShardGroup) -> None:
        self.groups.append(group)
        self.router.clients[group.gid] = group.client
        group.client.shard_epoch = lambda m=self.manager: m.current().epoch
        if not group.client.cfg.shard:
            group.client.cfg.shard = group.gid

    async def split(self, victim_gid: str,
                    target_gid: str | None = None) -> ShardGroup:
        """Live split: bring up a group (warm standby preferred; an
        explicit `target_gid` makes the operation replayable by name),
        migrate ~half of the victim's keyspace into it (Aegis-verified,
        epoch-fenced), activate."""
        group = self._acquire_standby(target_gid)
        victim = self.group(victim_gid)
        try:
            await self.rebalancer.split(victim, group)
        except BaseException:
            # an aborted plan rolled the map back: the group is still a
            # serving-capable standby — keep it warm instead of leaking it
            self.standbys.append(group)
            raise
        self._adopt(group)
        return group

    async def merge(self, victim_gid: str) -> list[str]:
        """Live merge: fold `victim_gid`'s keyspace back into its ring
        successors (same freeze/attest/stream/activate machinery as
        split, run in reverse). The retired group keeps running as a
        warm standby for the next split. Returns the receiver gids."""
        old_map = self.manager.current()
        receivers = [self.group(g) for g in old_map.absorbers(victim_gid)]
        victim = self.group(victim_gid)
        await self.rebalancer.merge(victim, receivers)
        self.groups.remove(victim)
        self.router.clients.pop(victim_gid, None)
        self.standbys.append(victim)
        return [r.gid for r in receivers]

    async def promote(self, dead_gid: str) -> ShardGroup:
        """Disaster takeover: `dead_gid`'s process is gone (no replica
        answers), so its slice of the keyspace is relabeled — same ring
        positions, epoch+1 — onto a standby group, which starts serving
        it immediately. Availability over data: a whole-group loss is
        beyond the <= f fault model, so the slice restarts empty and
        refills from client writes (and the Lodestone resident plane,
        where enabled). Announced like any activation (on_activate ->
        gossip), so followers and routers converge on the takeover map."""
        from dds_tpu.obs.flight import flight
        from dds_tpu.shard.rebalance import _maybe_await

        dead = self.group(dead_gid)
        standby = self._acquire_standby()
        new_map = (self.manager.current()
                   .relabel(dead_gid, standby.gid).sign(self.secret))
        self.groups.remove(dead)
        self.router.clients.pop(dead_gid, None)
        for g in self.groups:
            g.state.install(new_map)
        standby.state.install(new_map)
        self.manager.activate(new_map)
        self._adopt(standby)
        if self.rebalancer.on_activate is not None:
            await _maybe_await(self.rebalancer.on_activate(new_map))
        await flight.record_async("takeover", dead=dead_gid,
                                  standby=standby.gid, epoch=new_map.epoch)
        return standby

    async def stop(self) -> None:
        for g in self.groups + self.standbys:
            await g.stop()


def build_group(
    net,
    gid: str,
    state: ShardState,
    *,
    n_active: int = 4,
    n_sentinent: int = 1,
    quorum: int = 3,
    max_faults: int = 1,
    rcfg: ReplicaConfig | None = None,
    sup_cfg: SupervisorConfig | None = None,
    abd_cfg: AbdClientConfig | None = None,
    chaos: bool = False,
    rng: random.Random | None = None,
    namer=None,
) -> ShardGroup:
    """One namespaced quorum group over `net`, fencing under `state`.

    `namer` maps a bare endpoint name to its transport address — identity
    for the in-memory fabric, `TcpNet.local_addr` for a Meridian group
    process so every endpoint is a routable `host:port/name`."""
    import dataclasses as _dc

    namer = namer or (lambda name: name)
    rcfg = rcfg or ReplicaConfig(quorum_size=quorum)
    endpoints = [
        namer(f"{gid}-replica-{i}") for i in range(n_active + n_sentinent)
    ]
    active, sentinent = endpoints[:n_active], endpoints[n_active:]
    sup_addr = namer(f"{gid}-supervisor")
    replicas = {
        e: BFTABDNode(e, endpoints, sup_addr, net, rcfg, shard=state)
        for e in endpoints
    }
    for e in sentinent:
        replicas[e].behavior = "sentinent"
    supervisor = BFTSupervisor(
        sup_addr, active, sentinent, net,
        sup_cfg or SupervisorConfig(quorum_size=quorum,
                                    proactive_recovery_enabled=False),
        rng=rng,
    )
    if abd_cfg is None:
        abd_cfg = AbdClientConfig(quorum_size=quorum)
    elif not abd_cfg.shard:
        abd_cfg = _dc.replace(abd_cfg)
    abd_cfg.shard = gid
    abd_cfg.supervisor = sup_addr
    client = AbdClient(namer(f"{gid}-proxy"), net, active, abd_cfg)
    if chaos:
        from dds_tpu.malicious.trudy import Nemesis

        trudy = Nemesis(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                        rng=rng)
    else:
        from dds_tpu.malicious.trudy import Trudy

        trudy = Trudy(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                      rng=rng)
    return ShardGroup(gid, active, sentinent, replicas, supervisor, client,
                      state, quorum, trudy)


def build_constellation(
    net,
    *,
    shard_count: int = 2,
    vnodes_per_group: int = 16,
    secret: bytes = b"intranet-abd-secret",
    manifest_timeout: float = 2.0,
    ack_timeout: float = 5.0,
    chunk_keys: int = 256,
    prune: bool = True,
    fence_lease: float = 0.0,
    journal_dir: str | None = None,
    seed: int | None = None,
    namer=None,
    **group_kwargs,
) -> Constellation:
    """S homogeneous groups + manager/router/rebalancer over one fabric."""
    gids = [f"s{i}" for i in range(shard_count)]
    smap = ShardMap.build(gids, vnodes_per_group).sign(secret)
    manager = ShardManager(smap, secret)
    rng = random.Random(seed) if seed is not None else None
    groups = []
    for gid in gids:
        state = ShardState(gid, smap, secret)
        grp_rng = random.Random(rng.getrandbits(64)) if rng else None
        groups.append(build_group(net, gid, state, rng=grp_rng, namer=namer,
                                  **group_kwargs))
    router = ShardRouter(manager, {g.gid: g.client for g in groups})
    rebalancer = Rebalancer(
        manager, net, secret,
        addr=(namer or (lambda n: n))("rebalancer"),
        manifest_timeout=manifest_timeout,
        ack_timeout=ack_timeout, chunk_keys=chunk_keys, prune=prune,
        fence_lease=fence_lease, journal_dir=journal_dir,
    )
    return Constellation(manager, router, groups, rebalancer, net=net,
                         secret=secret,
                         _build_kwargs=dict(group_kwargs, namer=namer))
