"""Constellation fabric: build S independent BFT-ABD quorum groups.

One group = the full single-shard stack the repo already had — replicas
(+sentinent spares), a supervisor with proactive recovery, per-replica
Merkle anti-entropy, an `AbdClient`, and a Trudy/Nemesis attack surface —
instantiated per group with namespaced endpoints (`s0-replica-3`,
`s1-supervisor`, ...) over ONE shared transport (so ChaosNet schedules,
partitions, and Nemesis attacks apply to any subset of the constellation).
`build_constellation` assembles S groups plus the ShardManager/ShardRouter
pair and a Rebalancer; `build_group` is the per-group factory the live
split uses to bring up a brand-new group mid-flight.

Used by run.launch (config-driven), the shard test suite, and
benchmarks/shard_scaling.py — one topology builder, three consumers.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.geo.lease import LeaseTable
from dds_tpu.geo.placement import group_regions, spread
from dds_tpu.shard.rebalance import Rebalancer
from dds_tpu.shard.router import ShardRouter
from dds_tpu.shard.shardmap import ShardManager, ShardMap, ShardState


@dataclass
class ShardGroup:
    """Handle to one quorum group of the constellation."""

    gid: str
    active: list[str]
    sentinent: list[str]
    replicas: dict[str, BFTABDNode]
    supervisor: BFTSupervisor
    client: AbdClient
    state: ShardState
    quorum_size: int
    trudy: object = None
    # Atlas: replica endpoint -> region, the group's home region label,
    # and the shared per-group read-lease table (None = leases off)
    replica_regions: dict = field(default_factory=dict)
    home_region: str = ""
    lease_table: object = None

    def all_replicas(self) -> list[str]:
        return self.active + self.sentinent

    def region_census(self) -> dict:
        """region -> replica count, for /health and placement checks."""
        out: dict = {}
        for region in self.replica_regions.values():
            out[region] = out.get(region, 0) + 1
        return dict(sorted(out.items()))

    def export_from(self, endpoint: str) -> dict:
        """Export one replica's repository (migration seed DATA — every
        receiver re-verifies entries against the manifest quorum)."""
        node = self.replicas.get(endpoint)
        return node.export_state() if node is not None else {}

    def prune_unowned(self) -> int:
        return sum(n.drop_unowned() for n in self.replicas.values())

    async def stop(self) -> None:
        await self.supervisor.stop()
        for n in self.replicas.values():
            await n.antientropy.stop()


@dataclass
class Constellation:
    manager: ShardManager
    router: ShardRouter
    groups: list[ShardGroup]
    rebalancer: Rebalancer
    net: object = None
    secret: bytes = b""
    _build_kwargs: dict = field(default_factory=dict)
    # warm standbys: groups a merge retired (still running, pruned empty)
    # — the next split or takeover reuses one instead of building fresh
    standbys: list = field(default_factory=list)
    # Atlas build parameters, kept so standby groups built later place
    # the same way the original fleet did
    geo_regions: list = field(default_factory=list)
    geo_placement: object = "span"
    geo_lease_ttl: float = 0.0
    geo_client_region: str = ""

    @property
    def gids(self) -> list[str]:
        """Group ids in construction order (the Lodestone resident
        plane's pool registration order; see ShardRouter.group_ids)."""
        return [g.gid for g in self.groups]

    def group(self, gid: str) -> ShardGroup:
        for g in self.groups:
            if g.gid == gid:
                return g
        raise ValueError(f"unknown group {gid!r}")

    def _fresh_gid(self) -> str:
        used = {g.gid for g in self.groups} | {g.gid for g in self.standbys}
        n = len(used)
        while f"s{n}" in used:
            n += 1
        return f"s{n}"

    def regions_of_endpoints(self) -> dict:
        """Every fabric endpoint's region label (replicas per their
        placement; supervisor/proxy per the group home / client region) —
        what ChaosNet region matrices and /health key off."""
        out: dict = {}
        for g in self.groups + self.standbys:
            out.update(g.replica_regions)
            if g.home_region:
                out[g.supervisor.addr] = g.home_region
            region = self.geo_client_region or g.home_region
            if region:
                out[g.client.addr] = region
        return out

    def _acquire_standby(self, gid: str | None = None,
                         prefer_region: str = "") -> ShardGroup:
        """A serving-capable group outside the active map: a warm standby
        a merge retired, else a freshly built one (fenced until a map
        gives it keys, so it can be brought up eagerly without traffic).
        A caller naming `gid` (an operator's replayable split target)
        gets that standby, or a fresh group under that name.
        `prefer_region` picks a standby homed there when one exists (the
        Atlas takeover preference); a fresh group is homed there too."""
        if gid is not None:
            for i, g in enumerate(self.standbys):
                if g.gid == gid:
                    return self.standbys.pop(i)
            if gid in {g.gid for g in self.groups}:
                raise ValueError(f"target group {gid!r} is already active")
        else:
            if self.standbys:
                if prefer_region:
                    for i, g in enumerate(self.standbys):
                        if g.home_region == prefer_region:
                            return self.standbys.pop(i)
                return self.standbys.pop(0)
            gid = self._fresh_gid()
        state = ShardState(gid, self.manager.current(), self.secret)
        kwargs = dict(self._build_kwargs)
        if self.geo_regions:
            mode = (self.geo_placement.get(gid, "span")
                    if isinstance(self.geo_placement, dict)
                    else self.geo_placement)
            home = prefer_region or self.geo_regions[0]
            kwargs["regions"] = ([home] if mode == "home"
                                 else list(self.geo_regions))
            kwargs["home_region"] = home
            kwargs["lease_ttl"] = self.geo_lease_ttl
        group = build_group(self.net, gid, state, **kwargs)
        if self.geo_regions and hasattr(self.net, "set_regions"):
            labels = dict(group.replica_regions)
            if group.home_region:
                labels[group.supervisor.addr] = group.home_region
                labels[group.client.addr] = (self.geo_client_region
                                             or group.home_region)
            self.net.set_regions(labels)
        return group

    def _adopt(self, group: ShardGroup) -> None:
        self.groups.append(group)
        self.router.clients[group.gid] = group.client
        group.client.shard_epoch = lambda m=self.manager: m.current().epoch
        if not group.client.cfg.shard:
            group.client.cfg.shard = group.gid

    async def split(self, victim_gid: str,
                    target_gid: str | None = None) -> ShardGroup:
        """Live split: bring up a group (warm standby preferred; an
        explicit `target_gid` makes the operation replayable by name),
        migrate ~half of the victim's keyspace into it (Aegis-verified,
        epoch-fenced), activate."""
        group = self._acquire_standby(target_gid)
        victim = self.group(victim_gid)
        try:
            await self.rebalancer.split(victim, group)
        except BaseException:
            # an aborted plan rolled the map back: the group is still a
            # serving-capable standby — keep it warm instead of leaking it
            self.standbys.append(group)
            raise
        self._adopt(group)
        return group

    async def merge(self, victim_gid: str) -> list[str]:
        """Live merge: fold `victim_gid`'s keyspace back into its ring
        successors (same freeze/attest/stream/activate machinery as
        split, run in reverse). The retired group keeps running as a
        warm standby for the next split. Returns the receiver gids."""
        old_map = self.manager.current()
        receivers = [self.group(g) for g in old_map.absorbers(victim_gid)]
        victim = self.group(victim_gid)
        await self.rebalancer.merge(victim, receivers)
        self.groups.remove(victim)
        self.router.clients.pop(victim_gid, None)
        self.standbys.append(victim)
        return [r.gid for r in receivers]

    async def promote(self, dead_gid: str) -> ShardGroup:
        """Disaster takeover: `dead_gid`'s process is gone (no replica
        answers), so its slice of the keyspace is relabeled — same ring
        positions, epoch+1 — onto a standby group, which starts serving
        it immediately. Availability over data: a whole-group loss is
        beyond the <= f fault model, so the slice restarts empty and
        refills from client writes (and the Lodestone resident plane,
        where enabled). Announced like any activation (on_activate ->
        gossip), so followers and routers converge on the takeover map."""
        from dds_tpu.obs.flight import flight
        from dds_tpu.shard.rebalance import _maybe_await

        dead = self.group(dead_gid)
        # prefer a standby homed where the dead group lived — the
        # relabeled slice keeps its geography (and its WAN profile)
        standby = self._acquire_standby(prefer_region=dead.home_region)
        new_map = (self.manager.current()
                   .relabel(dead_gid, standby.gid).sign(self.secret))
        self.groups.remove(dead)
        self.router.clients.pop(dead_gid, None)
        for g in self.groups:
            g.state.install(new_map)
        standby.state.install(new_map)
        self.manager.activate(new_map)
        self._adopt(standby)
        if self.rebalancer.on_activate is not None:
            await _maybe_await(self.rebalancer.on_activate(new_map))
        await flight.record_async("takeover", dead=dead_gid,
                                  standby=standby.gid, epoch=new_map.epoch)
        return standby

    async def stop(self) -> None:
        for g in self.groups + self.standbys:
            await g.stop()


def build_group(
    net,
    gid: str,
    state: ShardState,
    *,
    n_active: int = 4,
    n_sentinent: int = 1,
    quorum: int = 3,
    max_faults: int = 1,
    rcfg: ReplicaConfig | None = None,
    sup_cfg: SupervisorConfig | None = None,
    abd_cfg: AbdClientConfig | None = None,
    chaos: bool = False,
    rng: random.Random | None = None,
    namer=None,
    regions: list[str] | None = None,
    home_region: str = "",
    lease_ttl: float = 0.0,
) -> ShardGroup:
    """One namespaced quorum group over `net`, fencing under `state`.

    `namer` maps a bare endpoint name to its transport address — identity
    for the in-memory fabric, `TcpNet.local_addr` for a Meridian group
    process so every endpoint is a routable `host:port/name`.

    Atlas: `regions` spreads the group's replicas round-robin across the
    listed regions (the span-group shape read-local leases need);
    `home_region` labels the group (and places the supervisor — defaults
    to the first region). `lease_ttl > 0` installs the group's shared
    read-lease table on every replica, switching its coordinators to the
    holder-pinned quorum geometry (dds_tpu/geo)."""
    import dataclasses as _dc

    namer = namer or (lambda name: name)
    rcfg = rcfg or ReplicaConfig(quorum_size=quorum)
    endpoints = [
        namer(f"{gid}-replica-{i}") for i in range(n_active + n_sentinent)
    ]
    active, sentinent = endpoints[:n_active], endpoints[n_active:]
    sup_addr = namer(f"{gid}-supervisor")
    replica_regions = spread(endpoints, regions or [])
    if regions and not home_region:
        home_region = regions[0]
    replicas = {
        e: BFTABDNode(e, endpoints, sup_addr, net, rcfg, shard=state)
        for e in endpoints
    }
    lease_table = None
    if lease_ttl > 0 and regions:
        # one table per group, shared by its replicas — the same
        # in-process config-push idiom as ShardState
        lease_table = LeaseTable(gid, state.secret)
        for node in replicas.values():
            node.lease_table = lease_table
    for e in sentinent:
        replicas[e].behavior = "sentinent"
    supervisor = BFTSupervisor(
        sup_addr, active, sentinent, net,
        sup_cfg or SupervisorConfig(quorum_size=quorum,
                                    proactive_recovery_enabled=False),
        rng=rng,
    )
    if abd_cfg is None:
        abd_cfg = AbdClientConfig(quorum_size=quorum)
    elif not abd_cfg.shard:
        abd_cfg = _dc.replace(abd_cfg)
    abd_cfg.shard = gid
    abd_cfg.supervisor = sup_addr
    if replica_regions:
        abd_cfg.replica_regions = dict(replica_regions)
        if lease_ttl > 0:
            abd_cfg.lease_ttl = lease_ttl
            if abd_cfg.region:
                # a client without a home region stays on the quorum path
                abd_cfg.lease_enabled = True
    client = AbdClient(namer(f"{gid}-proxy"), net, active, abd_cfg)
    if chaos:
        from dds_tpu.malicious.trudy import Nemesis

        trudy = Nemesis(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                        rng=rng)
    else:
        from dds_tpu.malicious.trudy import Trudy

        trudy = Trudy(net, active, max_faults, addr=namer(f"{gid}-trudy"),
                      rng=rng)
    return ShardGroup(gid, active, sentinent, replicas, supervisor, client,
                      state, quorum, trudy,
                      replica_regions=replica_regions,
                      home_region=home_region, lease_table=lease_table)


def build_constellation(
    net,
    *,
    shard_count: int = 2,
    vnodes_per_group: int = 16,
    secret: bytes = b"intranet-abd-secret",
    manifest_timeout: float = 2.0,
    ack_timeout: float = 5.0,
    chunk_keys: int = 256,
    prune: bool = True,
    fence_lease: float = 0.0,
    journal_dir: str | None = None,
    seed: int | None = None,
    namer=None,
    regions: list[str] | None = None,
    placement="span",
    lease_ttl: float = 0.0,
    client_region: str = "",
    **group_kwargs,
) -> Constellation:
    """S homogeneous groups + manager/router/rebalancer over one fabric.

    Atlas: `regions` switches the constellation geo-aware — group homes
    are assigned round-robin and carried (signed) on the ShardMap, and
    each group's replicas are placed per `placement`: `"span"` spreads
    every group across all regions (the read-local lease shape), `"home"`
    packs each group into its home region (the shape whose heartbeats die
    with the region), or a dict gid -> mode mixes both. `lease_ttl > 0`
    installs per-group read-lease tables; `client_region` homes every
    group's proxy client (enabling its lease fast path) in one region.
    When `net` is a ChaosNet, every endpoint is registered with its
    region so `[chaos.profiles]` WAN matrices apply unchanged."""
    gids = [f"s{i}" for i in range(shard_count)]
    homes = group_regions(gids, regions or [])
    smap = ShardMap.build(gids, vnodes_per_group,
                          regions=homes or None).sign(secret)
    manager = ShardManager(smap, secret)
    rng = random.Random(seed) if seed is not None else None
    groups = []
    for gid in gids:
        state = ShardState(gid, smap, secret)
        grp_rng = random.Random(rng.getrandbits(64)) if rng else None
        groups.append(build_group(
            net, gid, state, rng=grp_rng, namer=namer,
            **_geo_group_kwargs(group_kwargs, gid, regions, homes,
                                placement, lease_ttl, client_region),
        ))
    router = ShardRouter(manager, {g.gid: g.client for g in groups})
    rebalancer = Rebalancer(
        manager, net, secret,
        addr=(namer or (lambda n: n))("rebalancer"),
        manifest_timeout=manifest_timeout,
        ack_timeout=ack_timeout, chunk_keys=chunk_keys, prune=prune,
        fence_lease=fence_lease, journal_dir=journal_dir,
    )
    constellation = Constellation(
        manager, router, groups, rebalancer, net=net, secret=secret,
        _build_kwargs=dict(group_kwargs, namer=namer),
        geo_regions=list(regions or []), geo_placement=placement,
        geo_lease_ttl=lease_ttl, geo_client_region=client_region,
    )
    if regions:
        _register_net_regions(net, constellation)
    return constellation


def _geo_group_kwargs(group_kwargs: dict, gid: str, regions, homes: dict,
                      placement, lease_ttl: float,
                      client_region: str) -> dict:
    """Per-group build kwargs with the Atlas placement resolved."""
    kwargs = dict(group_kwargs)
    if not regions:
        return kwargs
    mode = placement.get(gid, "span") if isinstance(placement, dict) \
        else placement
    home = homes.get(gid, regions[0])
    kwargs["regions"] = [home] if mode == "home" else list(regions)
    kwargs["home_region"] = home
    kwargs["lease_ttl"] = lease_ttl
    if client_region and lease_ttl > 0:
        import dataclasses as _dc

        abd_cfg = kwargs.get("abd_cfg")
        abd_cfg = _dc.replace(abd_cfg) if abd_cfg is not None \
            else AbdClientConfig(quorum_size=kwargs.get("quorum", 3))
        abd_cfg.region = client_region
        kwargs["abd_cfg"] = abd_cfg
    return kwargs


def _register_net_regions(net, constellation: Constellation) -> None:
    """Label every fabric endpoint with its region on a ChaosNet, so
    `[chaos.profiles]` region-pair matrices and `region_partition` apply
    to the constellation without per-test bookkeeping."""
    if not hasattr(net, "set_regions"):
        return
    net.set_regions(constellation.regions_of_endpoints())



