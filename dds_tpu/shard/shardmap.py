"""Constellation shard maps: epoch-versioned, HMAC-signed keyspace partitions.

The ROADMAP's first scale lever. A `ShardMap` deterministically partitions
the key->set keyspace across S independent BFT-ABD quorum groups with a
consistent-hash ring of virtual nodes: every group contributes
`vnodes_per_group` ring positions derived from sha256(group_id # index),
and a key belongs to the group owning the first vnode clockwise of
sha256(key). Properties the rest of the plane leans on:

- **deterministic**: any party holding the map resolves the same owner for
  the same key — routers, replicas, and the rebalancer never negotiate.
- **epoch-versioned**: maps only ever move forward; every client->replica
  message carries the sender's epoch and replicas fence requests for keys
  their group no longer owns (core/replica), so a stale map can stall a
  request (retry under its Deadline budget) but never misroute it.
- **HMAC-signed**: the map is operator state distributed to every fencing
  party and served at GET /shards; the signature (intranet secret) stops a
  credentialed-but-keyless peer from installing a forged map that silently
  re-homes the keyspace.
- **split-local**: `split()` places the new group's vnodes at the ring
  midpoint of each victim vnode's arc, so a split moves (about half of)
  the VICTIM's keys and nothing else — every other group's ownership is
  bit-identical across the epoch bump, which is what keeps a live reshard
  a single-group migration instead of a cluster-wide reshuffle.

All groups share one Paillier modulus (the clients' key pair): sharding
partitions *storage and quorum fan-out*, not the ciphertext algebra, so
scatter-gathered aggregate partials combine with a plain modular-product
tail reduction (parallel/mesh.combine_partials).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import logging
from dataclasses import dataclass

from dds_tpu.utils import sigs

log = logging.getLogger("dds.shard.map")

_RING = 1 << 64  # ring positions are the first 8 bytes of sha256


def _position(label: str) -> int:
    return int.from_bytes(hashlib.sha256(label.encode()).digest()[:8], "big")


def _region_labels(mapping: dict | None) -> tuple:
    """Canonical (gid, region) tuple for a labels dict (sorted, so equal
    assignments sign identically)."""
    if not mapping:
        return ()
    return tuple(sorted((str(g), str(r)) for g, r in mapping.items()))


@dataclass(frozen=True)
class ShardMap:
    epoch: int
    # sorted (ring position, group id) pairs; positions are unique
    vnodes: tuple
    groups: tuple
    signature: bytes = b""
    # Atlas: sorted (gid, home region) labels. () = geo-unaware. Covered
    # by the signature WHEN PRESENT (a forged region label would steer
    # lease grants and Helmsman promotion), and omitted from the payload
    # when empty so pre-Atlas signed maps keep verifying byte-identically.
    regions: tuple = ()

    # ------------------------------------------------------------ building

    @staticmethod
    def build(groups: list[str], vnodes_per_group: int = 16,
              epoch: int = 1, regions: dict | None = None) -> "ShardMap":
        """Fresh map over `groups`; deterministic for a given group list.
        `regions` (gid -> home region) attaches the Atlas labels."""
        if not groups:
            raise ValueError("a shard map needs at least one group")
        vnodes = []
        seen = set()
        for gid in sorted(groups):
            for i in range(vnodes_per_group):
                pos = _position(f"{gid}#{i}")
                while pos in seen:  # astronomically rare; keep positions unique
                    pos = (pos + 1) % _RING
                seen.add(pos)
                vnodes.append((pos, gid))
        vnodes.sort()
        return ShardMap(epoch, tuple(vnodes), tuple(sorted(groups)),
                        regions=_region_labels(regions))

    def split(self, victim: str, new_gid: str) -> "ShardMap":
        """Epoch+1 map where `new_gid` takes ~half of `victim`'s keyspace:
        one new vnode at the ring midpoint of each victim vnode's arc.
        Ownership outside the victim's arcs is untouched (unsigned —
        callers sign the result before distributing it)."""
        if victim not in self.groups:
            raise ValueError(f"unknown victim group {victim!r}")
        if new_gid in self.groups:
            raise ValueError(f"group {new_gid!r} already in the map")
        positions = [p for p, _ in self.vnodes]
        added = []
        taken = set(positions)
        for i, (pos, gid) in enumerate(self.vnodes):
            if gid != victim:
                continue
            pred = self.vnodes[i - 1][0]  # ring predecessor (wraps at i=0)
            arc = (pos - pred) % _RING
            if arc < 2:
                continue
            mid = (pred + arc // 2) % _RING
            if mid in taken:
                continue
            taken.add(mid)
            added.append((mid, new_gid))
        if not added:
            raise ValueError(f"victim {victim!r} has no splittable arc")
        vnodes = tuple(sorted(self.vnodes + tuple(added)))
        # the carved-off group inherits the victim's home region: a split
        # is a local capacity move, never a geography change
        regions = self.regions
        if regions:
            regions = _region_labels(
                dict(regions) | {new_gid: self.region_of(victim)})
        return ShardMap(self.epoch + 1, vnodes,
                        tuple(sorted(self.groups + (new_gid,))),
                        regions=regions)

    def merge(self, victim: str) -> "ShardMap":
        """Epoch+1 map with `victim`'s vnodes RETIRED: every key the
        victim owned falls to the first surviving vnode clockwise of its
        position. The exact inverse of `split` — `m.split(v, g).merge(g)`
        owns every key identically to `m` (epoch aside) — and merge-local
        the same way split is split-local: only keys the victim owned
        move; every other group's ownership is bit-identical across the
        epoch bump. Unsigned — callers sign before distributing."""
        if victim not in self.groups:
            raise ValueError(f"unknown victim group {victim!r}")
        if len(self.groups) < 2:
            raise ValueError("cannot merge the last group away")
        vnodes = tuple((p, g) for p, g in self.vnodes if g != victim)
        groups = tuple(g for g in self.groups if g != victim)
        regions = tuple((g, r) for g, r in self.regions if g != victim)
        return ShardMap(self.epoch + 1, vnodes, groups, regions=regions)

    def relabel(self, old_gid: str, new_gid: str) -> "ShardMap":
        """Epoch+1 map where `new_gid` takes over `old_gid`'s ring
        positions VERBATIM — the disaster-takeover move when a whole
        group process dies: ownership arcs are bit-identical, only the
        serving group changes, so no key moves between surviving groups.
        Unsigned — callers sign before distributing."""
        if old_gid not in self.groups:
            raise ValueError(f"unknown group {old_gid!r}")
        if new_gid in self.groups:
            raise ValueError(f"group {new_gid!r} already in the map")
        vnodes = tuple(
            (p, new_gid if g == old_gid else g) for p, g in self.vnodes
        )
        groups = tuple(sorted(
            new_gid if g == old_gid else g for g in self.groups
        ))
        regions = _region_labels({
            (new_gid if g == old_gid else g): r for g, r in self.regions
        })
        return ShardMap(self.epoch + 1, vnodes, groups, regions=regions)

    def absorbers(self, victim: str) -> list[str]:
        """Groups that would receive keys if `victim` merged away: for
        each victim vnode, the owner of the first surviving vnode
        clockwise (the group absorbing that arc). Construction order is
        ring order, deduplicated — deterministic for a given map, so the
        rebalancer and any observer derive the same receiver set."""
        if victim not in self.groups:
            raise ValueError(f"unknown victim group {victim!r}")
        out: list[str] = []
        n = len(self.vnodes)
        for i, (_, gid) in enumerate(self.vnodes):
            if gid != victim:
                continue
            for j in range(1, n):
                succ = self.vnodes[(i + j) % n][1]
                if succ != victim:
                    if succ not in out:
                        out.append(succ)
                    break
        return out

    # ------------------------------------------------------------- routing

    @staticmethod
    def key_position(key: str) -> int:
        return _position(key)

    def owner(self, key: str) -> str:
        """Group owning `key`: first vnode clockwise of the key's position."""
        positions = [p for p, _ in self.vnodes]
        idx = bisect.bisect_left(positions, self.key_position(key))
        return self.vnodes[idx % len(self.vnodes)][1]

    def region_of(self, gid: str) -> str:
        """Home region label of `gid` ("" = unlabelled / geo-unaware)."""
        for g, r in self.regions:
            if g == gid:
                return r
        return ""

    def with_regions(self, mapping: dict) -> "ShardMap":
        """Same map with the Atlas region labels replaced (unsigned —
        callers sign the result before distributing it)."""
        return dataclasses.replace(
            self, regions=_region_labels(mapping), signature=b"")

    # ---------------------------------------------------------- signatures

    def _payload(self) -> dict:
        payload = {"epoch": self.epoch,
                   "vnodes": [[p, g] for p, g in self.vnodes]}
        if self.regions:
            payload["regions"] = [[g, r] for g, r in self.regions]
        return payload

    def sign(self, secret: bytes) -> "ShardMap":
        sig = sigs.manifest_signature(secret, "shard-map", self._payload(),
                                      self.epoch)
        return dataclasses.replace(self, signature=sig)

    def verify(self, secret: bytes) -> bool:
        return sigs.validate_manifest_signature(
            secret, "shard-map", self._payload(), self.epoch, self.signature
        )

    # ---------------------------------------------------------------- wire

    def to_wire(self) -> dict:
        wire = {
            "epoch": self.epoch,
            "groups": list(self.groups),
            "vnodes": [[p, g] for p, g in self.vnodes],
            "signature": self.signature.hex(),
        }
        if self.regions:
            wire["regions"] = [[g, r] for g, r in self.regions]
        return wire

    @staticmethod
    def from_wire(d: dict) -> "ShardMap":
        return ShardMap(
            int(d["epoch"]),
            tuple((int(p), str(g)) for p, g in d["vnodes"]),
            tuple(str(g) for g in d["groups"]),
            bytes.fromhex(d.get("signature", "")),
            regions=tuple(
                (str(g), str(r)) for g, r in d.get("regions", [])
            ),
        )


def moved_keys(old: ShardMap, new: ShardMap, keys) -> list[str]:
    """Keys in `keys` whose owner changes between the two maps."""
    return [k for k in keys if old.owner(k) != new.owner(k)]


class ShardState:
    """One replica group's live fencing state: the group id plus the
    newest verified map the group has been handed. Every replica of a
    group shares ONE instance (installed in a single step per group —
    the in-process analogue of a config push), so `owns()` answers the
    fence question consistently across the group.

    **Fence lease**: a reshard's freeze step installs the new map with a
    TTL (`lease` seconds). If the plan's driver dies before committing
    (activation or rollback), the lease expires and the state reverts to
    the last COMMITTED map on its own — a crashed controller can stall a
    group for one TTL, never fence it forever. The rebalancer renews the
    lease while it streams and commits it (re-install, no lease) right
    after activation or abort."""

    def __init__(self, group_id: str, smap: ShardMap, secret: bytes,
                 clock=None):
        import time as _time

        self.group_id = group_id
        self.secret = secret
        self._clock = clock or _time.monotonic
        self._map = None
        self._lease_at = 0.0        # monotonic expiry; 0 = committed
        self._fallback = None       # last committed map, restored on expiry
        self.install(smap)

    def _lease_check(self) -> None:
        if self._fallback is not None and self._clock() >= self._lease_at:
            # the driver never came back: heal to the committed map
            expired, self._map = self._map, self._fallback
            self._fallback, self._lease_at = None, 0.0
            from dds_tpu.obs.metrics import metrics

            metrics.inc("dds_shard_lease_expired_total",
                        shard=self.group_id,
                        help="fence leases that expired back to the "
                             "committed map (crashed reshard driver)")
            log.warning(
                "group %s fence lease expired: epoch %d reverts to "
                "committed epoch %d", self.group_id, expired.epoch,
                self._map.epoch,
            )

    @property
    def map(self) -> ShardMap:
        self._lease_check()
        return self._map

    @property
    def epoch(self) -> int:
        self._lease_check()
        return self._map.epoch

    @property
    def leased(self) -> bool:
        self._lease_check()
        return self._fallback is not None

    def lease_remaining(self) -> float:
        """Seconds until the current fence lease heals back (0 when the
        installed map is committed)."""
        self._lease_check()
        if self._fallback is None:
            return 0.0
        return max(0.0, self._lease_at - self._clock())

    def owns(self, key: str) -> bool:
        self._lease_check()
        return self._map.owner(key) == self.group_id

    def install(self, smap: ShardMap, force: bool = False,
                lease: float = 0.0) -> None:
        """Adopt a newer signed map. `force` permits an epoch rollback —
        reserved for the rebalancer's abort path, which restores the
        previous map after a failed migration. `lease > 0` installs the
        map PROVISIONALLY for that many seconds (see class docstring);
        re-installing the same epoch with a lease renews it, and
        installing with `lease=0` commits. A committed map never reverts."""
        if not smap.verify(self.secret):
            raise ValueError("shard map signature invalid")
        self._lease_check()
        if self._map is not None and smap.epoch < self._map.epoch and not force:
            raise ValueError(
                f"shard map epoch moved backwards "
                f"({self._map.epoch} -> {smap.epoch})"
            )
        if lease > 0:
            if self._fallback is None:
                # the map in force BEFORE the provisional install is the
                # committed state the lease heals back to
                self._fallback = self._map
            self._lease_at = self._clock() + lease
        else:
            self._fallback, self._lease_at = None, 0.0
        self._map = smap


class ShardManager:
    """The routing authority: holds the ACTIVE map (what routers resolve
    against) and the reshard state flag. During a live split the source
    and target groups fence under the NEW map while the manager still
    serves the old one; `activate()` is the final cut-over."""

    def __init__(self, smap: ShardMap, secret: bytes):
        if not smap.verify(secret):
            raise ValueError("shard map signature invalid")
        self.secret = secret
        self._map = smap
        self.state = "stable"  # stable | resharding

    def current(self) -> ShardMap:
        return self._map

    @property
    def epoch(self) -> int:
        return self._map.epoch

    def begin_reshard(self) -> None:
        self.state = "resharding"

    def end_reshard(self) -> None:
        self.state = "stable"

    def activate(self, smap: ShardMap) -> None:
        if not smap.verify(self.secret):
            raise ValueError("shard map signature invalid")
        if smap.epoch <= self._map.epoch:
            raise ValueError(
                f"activation requires a newer epoch "
                f"({smap.epoch} <= {self._map.epoch})"
            )
        self._map = smap
