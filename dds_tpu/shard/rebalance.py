"""Live resharding: epoch-fenced, Byzantine-verified key migration.

Splitting a shard group is a five-step protocol built from pieces the
stack already trusts — epoch fencing (shard/shardmap + core/replica) and
Aegis verified state transfer (StateDigest manifests, chunked streaming,
>= f+1 distinct-signer attestation):

1. **plan**   — derive the epoch+1 map (`ShardMap.split`) and sign it.
2. **freeze** — install the new map on the SOURCE and TARGET groups'
   fencing state. From this instant every write to a moving key is
   fenced (coordinator Envelope check + storage-layer Write check), so
   the moving slice of the keyspace is immutable while it is copied;
   clients retry under their Deadline budgets and land on the new group
   after activation. The router still serves the OLD map — unmoved keys
   see zero disruption.
3. **attest** — collect a quorum of HMAC-signed state manifests from the
   source group (the same frames recovery uses). Fewer than `support`
   (= f+1) attestations aborts: an unverifiable migration never ships.
4. **stream** — export the moving keys from the best-attested source
   replica (data, not truth) and stream ShardMigrateBegin + bounded
   StateChunk(kind="migrate") frames to EVERY target replica, which
   installs only entries attested by >= f+1 distinct signers and owned
   under ITS map, store-if-newer. A quorum of acks each accepting the
   full verified set is required — a Byzantine source replica that
   withholds or corrupts entries fails the ack bar and aborts.
5. **activate** — the router's ShardManager adopts the new map (clients
   route to the new group), the source group prunes its moved keys, and
   the target group's own Merkle anti-entropy loop repairs any replica
   that missed chunks (e.g. partitioned mid-migration).

Any failure rolls the fencing state back to the old map (force install),
records a `reshard_abort` flight incident + metric, and raises
`ReshardAborted` — the keyspace is exactly as before, minus the brief
write stall on the moving slice.
"""

from __future__ import annotations

import asyncio
import inspect
import logging

from dds_tpu.core import messages as M
from dds_tpu.core.replica import verified_manifest
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.shard.rebalance")


class ReshardAborted(RuntimeError):
    """A live split failed safely: the old map is back in force."""


async def _maybe_await(value):
    """Group handles are duck-typed: the in-process `ShardGroup` answers
    state installs / exports / prunes synchronously, the Meridian
    `RemoteShardGroup` returns awaitables that resolve on the remote
    agent's ack. The rebalancer awaits whichever it gets."""
    if inspect.isawaitable(value):
        return await value
    return value


class Rebalancer:
    def __init__(self, manager, net, abd_mac_secret: bytes,
                 addr: str = "rebalancer", manifest_timeout: float = 2.0,
                 ack_timeout: float = 5.0, chunk_keys: int = 256,
                 prune: bool = True, on_activate=None):
        self.manager = manager
        self.net = net
        self.secret = abd_mac_secret
        self.addr = addr
        self.manifest_timeout = manifest_timeout
        self.ack_timeout = ack_timeout
        self.chunk_keys = chunk_keys
        # Meridian hook: fires (sync or async) with the activated map
        # right after cut-over, BEFORE the prune — the multi-host
        # controller broadcasts ShardMapActivate to every group agent
        # here so remote /shards views and long-pollers see the bump
        self.on_activate = on_activate
        # pruning the source group's moved keys after activation is the
        # production default; tests keep the pre-split state around to
        # assert zero stale-epoch writes ever landed there
        self.prune = prune
        # nonce -> (future, sender -> StateDigest, target count)
        self._manifest_collects: dict[int, tuple] = {}
        # session -> (future, sender -> ShardMigrateAck, needed)
        self._ack_collects: dict[int, tuple] = {}
        net.register(addr, self._handle)

    async def _handle(self, sender: str, msg) -> None:
        if isinstance(msg, M.StateDigest):
            coll = self._manifest_collects.get(msg.nonce)
            if coll is None:
                return
            fut, votes, target = coll
            if sender in votes:
                return
            if not sigs.validate_manifest_signature(
                self.secret, sender, msg.manifest, msg.nonce, msg.signature
            ):
                log.warning("dropping StateDigest with bad HMAC from %s",
                            sender)
                return
            votes[sender] = msg
            if len(votes) >= target and not fut.done():
                fut.set_result(None)
        elif isinstance(msg, M.ShardMigrateAck):
            coll = self._ack_collects.get(msg.session)
            if coll is None:
                return
            fut, acks, needed = coll
            acks[sender] = msg
            if len(acks) >= needed and not fut.done():
                fut.set_result(None)

    # ------------------------------------------------------------- manifest

    async def _collect_manifests(self, replicas: list[str],
                                 quorum: int) -> dict:
        nonce = sigs.generate_nonce()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        votes: dict[str, M.StateDigest] = {}
        self._manifest_collects[nonce] = (fut, votes,
                                          min(len(replicas), quorum))
        for r in replicas:
            self.net.send(self.addr, r, M.StateDigestRequest(nonce))
        try:
            await asyncio.wait_for(fut, self.manifest_timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._manifest_collects.pop(nonce, None)
        return votes

    # ---------------------------------------------------------------- split

    async def split(self, source, target) -> "object":
        """Split `source`'s keyspace, moving ~half to `target` (both are
        shard.fabric.ShardGroup handles). Returns the activated ShardMap;
        raises ReshardAborted with the old map restored on any failure."""
        old_map = self.manager.current()
        new_map = old_map.split(source.gid, target.gid).sign(self.secret)
        support = max(1, 2 * source.quorum_size - len(source.active))

        self.manager.begin_reshard()
        metrics.set("dds_shard_reshard_state", 1,
                    help="0=stable 1=resharding")
        with tracer.span("shard.split", source=source.gid, target=target.gid,
                         epoch=new_map.epoch) as span:
            try:
                # freeze: both groups fence under the NEW map from here on
                # (remote groups ack the install before anything streams —
                # streaming into an unfenced group would break the
                # immutable-while-copied guarantee)
                await _maybe_await(source.state.install(new_map))
                await _maybe_await(target.state.install(new_map))
                smap = await self._migrate(source, target, new_map, support)
                span["moved"] = smap
            except ReshardAborted:
                raise
            except Exception as e:  # any unplanned failure aborts safely
                await self._abort(source, target, old_map,
                                  f"unexpected: {e!r}")
            finally:
                self.manager.end_reshard()
                metrics.set("dds_shard_reshard_state", 0,
                            help="0=stable 1=resharding")
        return self.manager.current()

    async def _migrate(self, source, target, new_map, support: int) -> int:
        old_map = self.manager.current()
        votes = await self._collect_manifests(source.active,
                                              source.quorum_size)
        if len(votes) < support:
            await self._abort(
                source, target, old_map,
                f"manifest quorum failed: {len(votes)}/{len(source.active)} "
                f"attested (need >= {support})",
            )
        digests = [
            [sender, d.manifest, d.nonce, d.signature.hex()]
            for sender, d in votes.items()
        ]
        verified = verified_manifest(digests, support, self.secret)
        moving = {
            k: v for k, v in verified.items()
            if new_map.owner(k) == target.gid
        }

        # seed source: the attesting replica whose manifest covers the most
        # verified moving entries — its export is still just DATA (receivers
        # re-verify every entry against the digest quorum)
        def coverage(sender: str) -> int:
            m = votes[sender].manifest
            return sum(
                1 for k, want in moving.items()
                if k in m and (int(m[k][0]), str(m[k][1]), str(m[k][2]))
                == want
            )

        seeder = max(votes, key=coverage) if votes else None
        exported = (
            await _maybe_await(source.export_from(seeder)) if seeder else {}
        )
        entries = {k: e for k, e in exported.items() if k in moving}

        session = sigs.generate_nonce()
        items = sorted(entries.items())
        k = max(1, self.chunk_keys)
        chunks = [dict(items[i:i + k]) for i in range(0, len(items), k)] or [{}]
        targets = target.all_replicas()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        acks: dict[str, M.ShardMigrateAck] = {}
        self._ack_collects[session] = (fut, acks, target.quorum_size)
        begin = M.ShardMigrateBegin(digests, session, len(chunks), support,
                                    new_map.epoch)
        for t in targets:
            self.net.send(self.addr, t, begin)
            for seq, chunk in enumerate(chunks):
                self.net.send(self.addr, t,
                              M.StateChunk(session, seq, chunk, kind="migrate"))
        tracer.event("shard.migrate", source=source.gid, target=target.gid,
                     keys=len(entries), chunks=len(chunks), seeder=seeder)
        try:
            await asyncio.wait_for(fut, self.ack_timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._ack_collects.pop(session, None)

        want = len(moving)
        good = [a for a in acks.values() if a.accepted >= want]
        if len(good) < target.quorum_size:
            await self._abort(
                source, target, old_map,
                f"migration ack quorum failed: {len(good)}/{len(targets)} "
                f"replicas accepted all {want} verified keys "
                f"(need >= {target.quorum_size})",
            )

        # cut-over: routers resolve the new map from the next attempt on
        self.manager.activate(new_map)
        metrics.set("dds_shard_epoch", new_map.epoch,
                    help="active shard-map epoch")
        if self.on_activate is not None:
            await _maybe_await(self.on_activate(new_map))
        if self.prune:
            dropped = await _maybe_await(source.prune_unowned())
            tracer.event("shard.pruned", source=source.gid, dropped=dropped)
        log.info(
            "reshard complete: %s -> %s, epoch %d, %d keys moved",
            source.gid, target.gid, new_map.epoch, want,
        )
        return want

    async def _abort(self, source, target, old_map, reason: str) -> None:
        # roll fencing back to the old map (force: epoch goes backwards);
        # the router never saw the new map, so routing is untouched. A
        # REMOTE rollback can itself fail (agent unreachable) — the group
        # then stays fenced under the orphaned epoch, which is safe
        # (fencing rejects, never misroutes) and self-heals on the next
        # install; it must not mask the abort itself.
        for grp in (source, target):
            try:
                await _maybe_await(grp.state.install(old_map, force=True))
            except Exception:
                log.exception(
                    "reshard abort could not roll %s back to epoch %d "
                    "(group stays fenced until the next map install)",
                    grp.gid, old_map.epoch,
                )
        metrics.inc("dds_reshard_aborts_total",
                    help="live resharding attempts aborted safely")
        tracer.event("shard.reshard_abort", source=source.gid,
                     target=target.gid, reason=reason)
        await flight.record_async("reshard_abort", source=source.gid,
                                  target=target.gid, reason=reason,
                                  epoch=old_map.epoch)
        log.warning("reshard %s -> %s aborted: %s", source.gid, target.gid,
                    reason)
        raise ReshardAborted(reason)
