"""Live resharding: epoch-fenced, Byzantine-verified key migration.

Reshaping a shard group — splitting a hot one onto a standby, or merging
a cold one back into its ring neighbors — is a five-step protocol built
from pieces the stack already trusts: epoch fencing (shard/shardmap +
core/replica) and Aegis verified state transfer (StateDigest manifests,
chunked streaming, >= f+1 distinct-signer attestation):

1. **plan**   — derive the epoch+1 map (`ShardMap.split` / `.merge`) and
   sign it. The plan is journaled (`PlanJournal`) before any state moves
   so a crashed driver is resolved deterministically on restart.
2. **freeze** — install the new map on every PARTICIPANT group's fencing
   state, under a fence LEASE (TTL): from this instant every write to a
   moving key is fenced (coordinator Envelope check + storage-layer
   Write check), so the moving slice of the keyspace is immutable while
   it is copied; clients retry under their Deadline budgets and land on
   the new owner after activation. The router still serves the OLD map —
   unmoved keys see zero disruption. If the driver dies here, the lease
   expires and every participant heals back to the committed map on its
   own — no group is ever fenced forever.
3. **attest** — collect a quorum of HMAC-signed state manifests from the
   source group (the same frames recovery uses). Fewer than `support`
   (= f+1) attestations aborts: an unverifiable migration never ships.
4. **stream** — export the moving keys from the best-attested source
   replica (data, not truth) and stream ShardMigrateBegin + bounded
   StateChunk(kind="migrate") frames to EVERY receiving replica, which
   installs only entries attested by >= f+1 distinct signers and owned
   under ITS map, store-if-newer. A quorum of acks each accepting the
   full verified slice is required per receiving group — a Byzantine
   source replica that withholds or corrupts entries fails the ack bar
   and aborts. (A split streams to one target; a merge partitions the
   victim's keys by their NEW ring owner and streams one session per
   absorbing group.)
5. **commit + activate** — every participant re-installs the new map
   WITHOUT a lease (the fencing point of no return, acked; failure still
   aborts safely), then the router's ShardManager adopts the new map,
   the source group prunes its moved keys, and the receivers' own Merkle
   anti-entropy loops repair any replica that missed chunks.

Any failure before commit rolls the fencing state back to the old map
(force install — and any participant the rollback cannot reach heals
itself when its fence lease expires), records a `reshard_abort` flight
incident + metric, and raises `ReshardAborted` — the keyspace is exactly
as before, minus the brief write stall on the moving slice.

Crash safety: the journal names the plan's phase. `recover()` resolves
an interrupted plan deterministically — phases before "commit" roll
BACK (the router never activated; the old map is the truth), "commit"
and later roll FORWARD (participants hold committed new-map fencing;
re-activate, re-broadcast, re-prune).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import logging
import os
import pathlib
import time

from dds_tpu.core import messages as M
from dds_tpu.core.replica import verified_manifest
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.shard.shardmap import ShardMap
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.shard.rebalance")

# phase -> worst-case seconds the plan can still spend there, for the
# 409 Retry-After a concurrent /_reshard answer derives (manifest and
# ack timeouts are added by retry_after(); this covers the fixed tail)
_PHASES = ("plan", "freeze", "attest", "stream", "commit", "activate")


class ReshardAborted(RuntimeError):
    """A live reshard failed safely: the old map is back in force."""


async def _maybe_await(value):
    """Group handles are duck-typed: the in-process `ShardGroup` answers
    state installs / exports / prunes synchronously, the Meridian
    `RemoteShardGroup` returns awaitables that resolve on the remote
    agent's ack. The rebalancer awaits whichever it gets."""
    if inspect.isawaitable(value):
        return await value
    return value


def _entries_bytes(entries: dict) -> int:
    """Approximate migrated payload size — the BTS-style cost every plan
    is priced in (migrated ciphertext bytes, not group count)."""
    try:
        return len(json.dumps(entries, default=repr, separators=(",", ":")))
    except (TypeError, ValueError):
        return sum(len(k) + len(repr(v)) for k, v in entries.items())


class PlanJournal:
    """Crash-safe reshard plan state: one JSON file, written atomically
    (tmp + rename) at every phase transition and cleared when the plan
    resolves. A driver that restarts reads the file and knows exactly
    how far the interrupted plan got — the basis of `Rebalancer.recover`.
    Directory empty/None = in-memory only (tests, ephemeral fleets)."""

    def __init__(self, directory: str | None = None,
                 name: str = "reshard_plan.json"):
        self._dir = pathlib.Path(directory) if directory else None
        self._name = name
        self._mem: dict | None = None

    @property
    def path(self) -> pathlib.Path | None:
        return self._dir / self._name if self._dir else None

    def write(self, plan: dict) -> None:
        self._mem = dict(plan)
        if self._dir is None:
            return
        self._dir.mkdir(parents=True, exist_ok=True)
        tmp = self._dir / (self._name + ".tmp")
        tmp.write_text(json.dumps(plan, separators=(",", ":")))
        os.replace(tmp, self._dir / self._name)

    def load(self) -> dict | None:
        if self._dir is not None:
            p = self._dir / self._name
            try:
                return json.loads(p.read_text())
            except FileNotFoundError:
                return None
            except (ValueError, OSError) as e:
                log.warning("unreadable reshard journal %s: %s", p, e)
                return None
        return dict(self._mem) if self._mem else None

    def clear(self) -> None:
        self._mem = None
        if self._dir is not None:
            try:
                (self._dir / self._name).unlink()
            except FileNotFoundError:
                pass


class Rebalancer:
    def __init__(self, manager, net, abd_mac_secret: bytes,
                 addr: str = "rebalancer", manifest_timeout: float = 2.0,
                 ack_timeout: float = 5.0, chunk_keys: int = 256,
                 prune: bool = True, on_activate=None,
                 fence_lease: float = 0.0, journal_dir: str | None = None,
                 clock=time.monotonic):
        self.manager = manager
        self.net = net
        self.secret = abd_mac_secret
        self.addr = addr
        self.manifest_timeout = manifest_timeout
        self.ack_timeout = ack_timeout
        self.chunk_keys = chunk_keys
        # Meridian hook: fires (sync or async) with the activated map
        # right after cut-over, BEFORE the prune — the multi-host
        # controller broadcasts ShardMapActivate to every group agent
        # here so remote /shards views and long-pollers see the bump
        self.on_activate = on_activate
        # pruning the source group's moved keys after activation is the
        # production default; tests keep the pre-split state around to
        # assert zero stale-epoch writes ever landed there
        self.prune = prune
        # fence-lease TTL handed to every freeze install (0 = legacy
        # no-lease installs, kept for old handles/spies); sized so a live
        # plan always commits or aborts well inside one TTL
        self.fence_lease = fence_lease
        self.journal = PlanJournal(journal_dir)
        self._clock = clock
        # one plan at a time: the controller-owned serialization point
        # every reshard entrypoint (Helmsman, POST /_reshard, tests)
        # funnels through
        self.lock = asyncio.Lock()
        self.phase: str | None = None
        self._phase_at = 0.0
        self.plan_info: dict | None = None
        self.last_moved_keys = 0
        self.last_moved_bytes = 0
        self.moved_bytes_total = 0
        # nonce -> (future, sender -> StateDigest, target count)
        self._manifest_collects: dict[int, tuple] = {}
        # session -> (future, sender -> ShardMigrateAck, needed)
        self._ack_collects: dict[int, tuple] = {}
        net.register(addr, self._handle)

    async def _handle(self, sender: str, msg) -> None:
        if isinstance(msg, M.StateDigest):
            coll = self._manifest_collects.get(msg.nonce)
            if coll is None:
                return
            fut, votes, target = coll
            if sender in votes:
                return
            if not sigs.validate_manifest_signature(
                self.secret, sender, msg.manifest, msg.nonce, msg.signature
            ):
                log.warning("dropping StateDigest with bad HMAC from %s",
                            sender)
                return
            votes[sender] = msg
            if len(votes) >= target and not fut.done():
                fut.set_result(None)
        elif isinstance(msg, M.ShardMigrateAck):
            coll = self._ack_collects.get(msg.session)
            if coll is None:
                return
            fut, acks, needed = coll
            acks[sender] = msg
            if len(acks) >= needed and not fut.done():
                fut.set_result(None)

    # -------------------------------------------------------------- phases

    def _enter(self, phase: str, **info) -> None:
        self.phase = phase
        self._phase_at = self._clock()
        if self.plan_info is not None:
            self.plan_info["phase"] = phase
            self.journal.write(self.plan_info)
        if info:
            tracer.event("shard.phase", phase=phase, **info)

    def _resolve(self) -> None:
        self.phase = None
        self.plan_info = None
        self.journal.clear()

    def retry_after(self) -> float:
        """Honest Retry-After for a caller refused because a plan is in
        flight: the worst-case seconds the CURRENT phase (and the fixed
        tail after it) can still take before the lock frees."""
        if self.phase is None:
            return 1.0
        elapsed = max(0.0, self._clock() - self._phase_at)
        budget = {
            "plan": self.manifest_timeout + self.ack_timeout + 2.0,
            "freeze": self.manifest_timeout + self.ack_timeout + 2.0,
            "attest": self.manifest_timeout + self.ack_timeout + 1.0,
            "stream": self.ack_timeout + 1.0,
            "commit": 2.0,
            "activate": 1.0,
        }.get(self.phase, self.ack_timeout)
        return max(0.5, round(budget - elapsed, 2))

    # ------------------------------------------------------------- manifest

    async def _collect_manifests(self, replicas: list[str],
                                 quorum: int) -> dict:
        nonce = sigs.generate_nonce()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        votes: dict[str, M.StateDigest] = {}
        self._manifest_collects[nonce] = (fut, votes,
                                          min(len(replicas), quorum))
        for r in replicas:
            self.net.send(self.addr, r, M.StateDigestRequest(nonce))
        try:
            await asyncio.wait_for(fut, self.manifest_timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._manifest_collects.pop(nonce, None)
        return votes

    # ---------------------------------------------------------- install ops

    async def _install(self, grp, smap: ShardMap, *, force: bool = False,
                       lease: float = 0.0):
        """Fencing install on one participant. The lease kwarg is only
        passed when armed, so legacy handles (and test spies) with the
        old two-argument surface keep working."""
        if lease > 0:
            return await _maybe_await(
                grp.state.install(smap, force=force, lease=lease)
            )
        return await _maybe_await(grp.state.install(smap, force=force))

    async def _freeze(self, participants, new_map: ShardMap) -> None:
        # every participant fences under the NEW map from here on (remote
        # groups ack the install before anything streams — streaming into
        # an unfenced group would break the immutable-while-copied
        # guarantee). Provisional: the fence lease heals a participant
        # whose driver dies before commit/abort.
        for grp in participants:
            await self._install(grp, new_map, lease=self.fence_lease)

    async def _renew(self, participants, new_map: ShardMap) -> None:
        """Best-effort lease renewal before the stream phase — a slow
        attest must not leave the stream racing the freeze TTL."""
        if self.fence_lease <= 0:
            return
        for grp in participants:
            try:
                await self._install(grp, new_map, lease=self.fence_lease)
            except Exception as e:
                log.warning("lease renewal on %s failed: %s", grp.gid, e)

    async def _commit(self, participants, new_map: ShardMap) -> None:
        # the fencing point of no return: re-install WITHOUT a lease so
        # the new map is the committed state every participant heals TO,
        # not from. Acked — a participant that cannot commit aborts the
        # plan while rollback is still the safe resolution.
        for grp in participants:
            await self._install(grp, new_map)

    # ---------------------------------------------------------------- split

    async def split(self, source, target) -> "object":
        """Split `source`'s keyspace, moving ~half to `target` (both are
        shard.fabric.ShardGroup handles). Returns the activated ShardMap;
        raises ReshardAborted with the old map restored on any failure."""
        async with self.lock:
            old_map = self.manager.current()
            new_map = old_map.split(source.gid, target.gid).sign(self.secret)
            return await self._run_plan("split", source, [target],
                                        old_map, new_map)

    async def merge(self, victim, receivers) -> "object":
        """Merge `victim` away: its vnodes retire and every key it owned
        streams to its ring successor group(s) (`receivers`, the handles
        for `old_map.absorbers(victim.gid)` in that order). Same freeze/
        attest/stream/commit/activate machinery and >= f+1 attestation
        discipline as `split`; the victim ends the plan owning nothing
        (and pruned, when pruning is on) — a warm standby again."""
        async with self.lock:
            old_map = self.manager.current()
            new_map = old_map.merge(victim.gid).sign(self.secret)
            want = old_map.absorbers(victim.gid)
            got = [r.gid for r in receivers]
            if sorted(got) != sorted(want):
                raise ValueError(
                    f"merge receivers {got} != ring absorbers {want}"
                )
            return await self._run_plan("merge", victim, receivers,
                                        old_map, new_map)

    async def _run_plan(self, kind: str, source, targets,
                        old_map: ShardMap, new_map: ShardMap):
        support = max(1, 2 * source.quorum_size - len(source.active))
        self.plan_info = {
            "kind": kind, "source": source.gid,
            "targets": [t.gid for t in targets],
            "old": old_map.to_wire(), "new": new_map.to_wire(),
            "phase": "plan",
        }
        self._enter("plan", kind=kind, source=source.gid)
        self.manager.begin_reshard()
        metrics.set("dds_shard_reshard_state", 1,
                    help="0=stable 1=resharding")
        participants = [source] + list(targets)
        with tracer.span(f"shard.{kind}", source=source.gid,
                         targets=",".join(t.gid for t in targets),
                         epoch=new_map.epoch) as span:
            try:
                self._enter("freeze")
                await self._freeze(participants, new_map)
                moved = await self._migrate(kind, source, targets,
                                            old_map, new_map, support)
                span["moved"] = moved
            except ReshardAborted:
                raise
            except Exception as e:  # any unplanned failure aborts safely
                await self._abort(kind, source, targets, old_map,
                                  f"unexpected: {e!r}")
            finally:
                self.manager.end_reshard()
                metrics.set("dds_shard_reshard_state", 0,
                            help="0=stable 1=resharding")
                self._resolve()
        return self.manager.current()

    async def _migrate(self, kind: str, source, targets, old_map,
                       new_map, support: int) -> int:
        self._enter("attest")
        votes = await self._collect_manifests(source.active,
                                              source.quorum_size)
        if len(votes) < support:
            await self._abort(
                kind, source, targets, old_map,
                f"manifest quorum failed: {len(votes)}/{len(source.active)} "
                f"attested (need >= {support})",
            )
        digests = [
            [sender, d.manifest, d.nonce, d.signature.hex()]
            for sender, d in votes.items()
        ]
        verified = verified_manifest(digests, support, self.secret)
        # moving = verified keys whose owner changes source -> target(s):
        # for a split, the slice the new group takes; for a merge, every
        # key the victim owned, partitioned by its NEW ring owner
        receiver_gids = {t.gid for t in targets}
        moving = {
            k: v for k, v in verified.items()
            if old_map.owner(k) == source.gid
            and new_map.owner(k) in receiver_gids
        }

        # seed source: the attesting replica whose manifest covers the most
        # verified moving entries — its export is still just DATA (receivers
        # re-verify every entry against the digest quorum)
        def coverage(sender: str) -> int:
            m = votes[sender].manifest
            return sum(
                1 for k, want in moving.items()
                if k in m and (int(m[k][0]), str(m[k][1]), str(m[k][2]))
                == want
            )

        seeder = max(votes, key=coverage) if votes else None
        exported = (
            await _maybe_await(source.export_from(seeder)) if seeder else {}
        )
        entries = {k: e for k, e in exported.items() if k in moving}

        await self._renew([source] + list(targets), new_map)
        self._enter("stream")
        moved_bytes = 0
        sessions = []
        for target in targets:
            slice_keys = {
                k for k in moving if new_map.owner(k) == target.gid
            }
            slice_entries = {k: e for k, e in entries.items()
                             if k in slice_keys}
            moved_bytes += _entries_bytes(slice_entries)
            sessions.append((target, len(slice_keys), slice_entries))

        async def stream_one(target, want: int, slice_entries: dict) -> bool:
            session = sigs.generate_nonce()
            items = sorted(slice_entries.items())
            k = max(1, self.chunk_keys)
            chunks = ([dict(items[i:i + k])
                       for i in range(0, len(items), k)] or [{}])
            replicas = target.all_replicas()
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            acks: dict[str, M.ShardMigrateAck] = {}
            self._ack_collects[session] = (fut, acks, target.quorum_size)
            begin = M.ShardMigrateBegin(digests, session, len(chunks),
                                        support, new_map.epoch)
            for t in replicas:
                self.net.send(self.addr, t, begin)
                for seq, chunk in enumerate(chunks):
                    self.net.send(
                        self.addr, t,
                        M.StateChunk(session, seq, chunk, kind="migrate"),
                    )
            tracer.event("shard.migrate", source=source.gid,
                         target=target.gid, keys=len(slice_entries),
                         chunks=len(chunks), seeder=seeder)
            try:
                await asyncio.wait_for(fut, self.ack_timeout)
            except asyncio.TimeoutError:
                pass
            finally:
                self._ack_collects.pop(session, None)
            good = [a for a in acks.values() if a.accepted >= want]
            return len(good) >= target.quorum_size

        results = await asyncio.gather(
            *(stream_one(t, w, s) for t, w, s in sessions)
        )
        failed = [t.gid for (t, _, _), ok in zip(sessions, results)
                  if not ok]
        if failed:
            await self._abort(
                kind, source, targets, old_map,
                f"migration ack quorum failed for group(s) "
                f"{', '.join(failed)} (need >= quorum replicas accepting "
                f"every verified key of their slice)",
            )

        # fencing point of no return: every participant commits the new
        # map (no lease) BEFORE the router cut-over, so an unreachable
        # participant aborts here — after this line the plan only ever
        # rolls forward
        self._enter("commit")
        try:
            await self._commit([source] + list(targets), new_map)
        except Exception as e:
            await self._abort(kind, source, targets, old_map,
                              f"fence commit failed: {e!r}")

        # cut-over: routers resolve the new map from the next attempt on
        self._enter("activate")
        self.manager.activate(new_map)
        metrics.set("dds_shard_epoch", new_map.epoch,
                    help="active shard-map epoch")
        want = len(moving)
        self.last_moved_keys = want
        self.last_moved_bytes = moved_bytes
        self.moved_bytes_total += moved_bytes
        metrics.inc("dds_reshard_moved_bytes_total", moved_bytes,
                    help="approximate ciphertext bytes migrated by live "
                         "resharding (the BTS cost model's currency)")
        if self.on_activate is not None:
            await _maybe_await(self.on_activate(new_map))
        if self.prune:
            dropped = await _maybe_await(source.prune_unowned())
            tracer.event("shard.pruned", source=source.gid, dropped=dropped)
        log.info(
            "%s complete: %s -> %s, epoch %d, %d keys (%d bytes) moved",
            kind, source.gid, ",".join(t.gid for t in targets),
            new_map.epoch, want, moved_bytes,
        )
        return want

    async def _abort(self, kind: str, source, targets, old_map,
                     reason: str) -> None:
        # roll fencing back to the old map (force: epoch goes backwards;
        # no lease: the old map is the committed state again); the router
        # never saw the new map, so routing is untouched. A REMOTE
        # rollback can itself fail (agent unreachable) — the group then
        # stays fenced under the orphaned epoch, which is safe (fencing
        # rejects, never misroutes) and heals ITSELF when its fence
        # lease expires (or at the next install, whichever is sooner);
        # it must not mask the abort itself.
        for grp in [source] + list(targets):
            try:
                await self._install(grp, old_map, force=True)
            except Exception:
                log.exception(
                    "reshard abort could not roll %s back to epoch %d "
                    "(group heals when its fence lease expires)",
                    grp.gid, old_map.epoch,
                )
        metrics.inc("dds_reshard_aborts_total",
                    help="live resharding attempts aborted safely")
        tracer.event("shard.reshard_abort", kind=kind, source=source.gid,
                     targets=",".join(t.gid for t in targets),
                     reason=reason)
        await flight.record_async("reshard_abort", plan=kind,
                                  source=source.gid,
                                  target=",".join(t.gid for t in targets),
                                  reason=reason, epoch=old_map.epoch)
        log.warning("%s %s -> %s aborted: %s", kind, source.gid,
                    ",".join(t.gid for t in targets), reason)
        raise ReshardAborted(reason)

    # ------------------------------------------------------------- recovery

    async def recover(self, handle_for) -> str | None:
        """Resolve a plan an earlier (crashed) driver left in the journal.
        `handle_for(gid)` returns a group handle. Deterministic rule:

        - phase before "commit": roll BACK — the router never activated,
          so the old map is the truth; force-install it on every
          participant (best effort: a participant the rollback cannot
          reach heals itself when its fence lease expires).
        - phase "commit"/"activate": roll FORWARD — participants hold
          (or were told to hold) committed new-map fencing; finish the
          cut-over: commit installs, activate the manager, broadcast,
          prune the source.

        Returns "rollback", "rollforward", or None (no interrupted plan).
        """
        plan = self.journal.load()
        if not plan:
            return None
        kind = plan.get("kind", "split")
        phase = plan.get("phase", "plan")
        old_map = ShardMap.from_wire(plan["old"])
        new_map = ShardMap.from_wire(plan["new"])
        gids = [plan["source"]] + list(plan.get("targets", []))
        handles = []
        for gid in gids:
            try:
                handles.append(handle_for(gid))
            except Exception as e:
                log.warning("recovery has no handle for %s: %s", gid, e)
        forward = phase in ("commit", "activate")
        action = "rollforward" if forward else "rollback"
        target_map = new_map if forward else old_map
        for grp in handles:
            try:
                await self._install(grp, target_map, force=not forward)
            except Exception as e:
                log.warning(
                    "recovery %s install on %s failed (%s); its fence "
                    "lease heals it", action, grp.gid, e,
                )
        if forward:
            if new_map.epoch > self.manager.epoch:
                self.manager.activate(new_map)
                metrics.set("dds_shard_epoch", new_map.epoch,
                            help="active shard-map epoch")
            if self.on_activate is not None:
                try:
                    await _maybe_await(self.on_activate(new_map))
                except Exception as e:
                    log.warning("recovery activation broadcast failed: %s", e)
            if self.prune and handles:
                try:
                    await _maybe_await(handles[0].prune_unowned())
                except Exception as e:
                    log.warning("recovery prune of %s failed: %s",
                                gids[0], e)
        self.journal.clear()
        metrics.inc("dds_reshard_recoveries_total", action=action,
                    help="interrupted reshard plans resolved at restart")
        await flight.record_async("reshard_recovered", plan=kind,
                                  phase=phase, action=action,
                                  source=plan["source"],
                                  targets=",".join(plan.get("targets", [])),
                                  old_epoch=old_map.epoch,
                                  new_epoch=new_map.epoch)
        log.warning("recovered interrupted %s (%s phase) by %s",
                    kind, phase, action)
        return action
