"""Trudy: crash / Byzantine fault injector.

Counterpart of `malicious/MaliciousAttack.scala` + `malicious/Trudy.scala`:
the attack enum and parser, and an injector that either crashes up to
`max_faults` random replicas (the reference's `PoisonPill` — here the
replica endpoint is torn off the transport so it goes silent) or flips them
to the `byzantine` behavior via the `Compromise` backdoor
(`BFTABDNode.scala:380-381`).
"""

from __future__ import annotations

import enum
import logging
import random

from dds_tpu.core import messages as M
from dds_tpu.core.transport import Transport

log = logging.getLogger("dds.trudy")


class AttackType(enum.Enum):
    CRASH = "crash"
    BYZANTINE = "byzantine"


def parse_attack(name: str) -> AttackType:
    """`MaliciousAttack.parse` equivalent; raises on unknown attack names."""
    try:
        return AttackType(name.strip().lower())
    except ValueError:
        raise ValueError(f"unknown attack type {name!r} (crash|byzantine)")


class Trudy:
    def __init__(self, net: Transport, replicas: list[str], max_faults: int = 2,
                 rng: random.Random | None = None):
        self.net = net
        self.replicas = list(replicas)
        self.max_faults = max_faults
        self._rng = rng or random.Random()

    def trigger(self, attack: AttackType | str) -> list[str]:
        """Attack up to max_faults random replicas; returns the victims."""
        if isinstance(attack, str):
            attack = parse_attack(attack)
        victims = self._rng.sample(self.replicas, min(self.max_faults, len(self.replicas)))
        for v in victims:
            if attack is AttackType.CRASH:
                log.info("Trudy crashes %s", v)
                self.net.unregister(v)  # node goes silent (PoisonPill analogue)
            else:
                log.info("Trudy compromises %s", v)
                self.net.send("trudy", v, M.Compromise())
        return victims
