"""Trudy: crash / Byzantine fault injector.

Counterpart of `malicious/MaliciousAttack.scala` + `malicious/Trudy.scala`:
the attack enum and parser, and an injector that either crashes up to
`max_faults` random replicas (the reference's `PoisonPill` — here the
replica endpoint is torn off the transport so it goes silent) or flips them
to the `byzantine` behavior via the `Compromise` backdoor
(`BFTABDNode.scala:380-381`).
"""

from __future__ import annotations

import enum
import logging
import random

from dds_tpu.core import messages as M
from dds_tpu.core.transport import Transport

log = logging.getLogger("dds.trudy")


class AttackType(enum.Enum):
    CRASH = "crash"
    BYZANTINE = "byzantine"


def parse_attack(name: str) -> AttackType:
    """`MaliciousAttack.parse` equivalent; raises on unknown attack names."""
    try:
        return AttackType(name.strip().lower())
    except ValueError:
        raise ValueError(f"unknown attack type {name!r} (crash|byzantine)")


class Trudy:
    def __init__(self, net: Transport, replicas: list[str], max_faults: int = 2,
                 rng: random.Random | None = None, addr: str = "trudy"):
        self.net = net
        self.replicas = list(replicas)
        self.max_faults = max_faults
        self.addr = addr  # routable src so attacks also ride a TCP fabric
        self._rng = rng or random.Random()

    def trigger(self, attack: AttackType | str) -> list[str]:
        """Attack up to max_faults random replicas; returns the victims.

        Both attacks travel as transport messages (`Crash` / `Compromise`),
        so they work identically on InMemoryNet and across a TcpNet
        deployment — the reference's Trudy does the same through Akka
        remoting ActorRefs (`Trudy.scala:14-32`)."""
        if isinstance(attack, str):
            attack = parse_attack(attack)
        victims = self._rng.sample(self.replicas, min(self.max_faults, len(self.replicas)))
        for v in victims:
            if attack is AttackType.CRASH:
                log.info("Trudy crashes %s", v)
                self.net.send(self.addr, v, M.Crash())
            else:
                log.info("Trudy compromises %s", v)
                self.net.send(self.addr, v, M.Compromise())
        return victims
