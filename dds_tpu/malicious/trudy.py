"""Trudy & Nemesis: process- and network-level fault injectors.

Trudy is the counterpart of `malicious/MaliciousAttack.scala` +
`malicious/Trudy.scala`: the attack enum and parser, and an injector that
either crashes up to `max_faults` random replicas (the reference's
`PoisonPill` — here the replica endpoint is torn off the transport so it
goes silent) or flips them to the `byzantine` behavior via the
`Compromise` backdoor (`BFTABDNode.scala:380-381`).

Nemesis extends Trudy with the NETWORK faults the reference never had —
`partition`, `delay`, `flood`, and `heal` — driven through the same
`trigger()` injection path as crash/byzantine so harnesses schedule any
fault mix uniformly. Partition/delay/heal require the fabric to be a
`ChaosNet` (core/chaos.py); flood works on any transport (it is just
unauthenticated junk traffic the replicas must shed via their MAC layer).
"""

from __future__ import annotations

import enum
import logging
import random

from dds_tpu.core import messages as M
from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.replica import BFTABDNode
from dds_tpu.core.transport import Transport
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.trudy")


class AttackType(enum.Enum):
    CRASH = "crash"
    BYZANTINE = "byzantine"
    # network-level attacks (Nemesis; partition/delay/heal need a ChaosNet)
    PARTITION = "partition"
    DELAY = "delay"
    FLOOD = "flood"
    HEAL = "heal"


def parse_attack(name: str) -> AttackType:
    """`MaliciousAttack.parse` equivalent; raises on unknown attack names."""
    try:
        return AttackType(name.strip().lower())
    except ValueError:
        raise ValueError(
            f"unknown attack type {name!r} "
            "(crash|byzantine|partition|delay|flood|heal)"
        )


class StaleTagForger(BFTABDNode):
    """A compromised coordinator that answers reads with a properly
    proxy-MAC'd FORGED stale (tag, value) pair. The client's cryptographic
    checks all pass — the forger holds the real secret — so the attack is
    invisible in-band; only auditing the committed tag sequence across the
    whole trace catches it. This is the cross-host audit regression
    schedule: `attacks.type = "stale_tag"` in a Meridian group process
    arms its replicas with this class (fabric/deploy), and the collector-
    fed Watchtower on the proxy must emit `tag_monotonicity` +
    `quorum_intersection` verdicts for the offending trace.

    Writes (and everything else) stay honest, so the committed history the
    forgery contradicts is real."""

    forged_tag = (1, "forged")
    forged_value = ["stale"]
    forging = True

    async def _healthy(self, sender, msg):
        match msg:
            case M.Envelope(M.IRead(key), nonce, _sig) if self.forging:
                tag = M.ABDTag(*self.forged_tag)
                challenge = nonce + self.cfg.nonce_increment
                sig = sigs.proxy_signature(
                    self.cfg.proxy_mac_secret, key, challenge,
                    [self.forged_value, sigs.tag_payload(tag)],
                )
                self._send(sender, M.Envelope(
                    M.IReadReply(key, self.forged_value, tag=tag),
                    challenge, sig,
                ))
            case _:
                await super()._healthy(sender, msg)


def arm_stale_tag_forgers(replicas: dict) -> list[str]:
    """Flip a group's live BFTABDNode instances to StaleTagForger in place
    (`__class__` swap — build_group has no class hook, and the swap keeps
    every bit of already-wired state: transport registration, merkle
    index, anti-entropy agent). Arms every replica because a fleet
    harness cannot steer coordinator choice through the HTTP edge; reads
    forge, writes stay honest either way. Returns the armed names."""
    armed = []
    for addr, node in replicas.items():
        if isinstance(node, BFTABDNode) and type(node) is BFTABDNode:
            node.__class__ = StaleTagForger
            armed.append(node.name)
    if armed:
        log.warning("stale-tag forgers armed: %s", armed)
        tracer.event("attack.stale_tag", victims=armed)
        metrics.inc("dds_attacks_total", type="stale_tag",
                    help="Trudy/Nemesis attacks triggered by type")
    return armed


class Trudy:
    def __init__(self, net: Transport, replicas: list[str], max_faults: int = 2,
                 rng: random.Random | None = None, addr: str = "trudy"):
        self.net = net
        self.replicas = list(replicas)
        self.max_faults = max_faults
        self.addr = addr  # routable src so attacks also ride a TCP fabric
        self._rng = rng or random.Random()

    def _victims(self) -> list[str]:
        return self._rng.sample(
            self.replicas, min(self.max_faults, len(self.replicas))
        )

    @staticmethod
    def _note_attack(attack: AttackType, victims: list[str]) -> None:
        """Telemetry for every injected attack: trace event + counter +
        flight-recorder incident, so a chaos-suite failure records which
        fault fired and at whom (self-describing post-mortems)."""
        names = [v.rsplit("/", 1)[-1] for v in victims]
        tracer.event("attack." + attack.value, victims=names)
        metrics.inc("dds_attacks_total", type=attack.value,
                    help="Trudy/Nemesis attacks triggered by type")
        flight.record("attack_" + attack.value, victims=names)

    def trigger(self, attack: AttackType | str) -> list[str]:
        """Attack up to max_faults random replicas; returns the victims.

        Both attacks travel as transport messages (`Crash` / `Compromise`),
        so they work identically on InMemoryNet and across a TcpNet
        deployment — the reference's Trudy does the same through Akka
        remoting ActorRefs (`Trudy.scala:14-32`)."""
        if isinstance(attack, str):
            attack = parse_attack(attack)
        victims = self._victims()
        for v in victims:
            if attack is AttackType.CRASH:
                log.info("Trudy crashes %s", v)
                self.net.send(self.addr, v, M.Crash())
            elif attack is AttackType.BYZANTINE:
                log.info("Trudy compromises %s", v)
                self.net.send(self.addr, v, M.Compromise())
            else:
                raise ValueError(
                    f"{attack.value!r} is a Nemesis attack — use Nemesis"
                )
        self._note_attack(attack, victims)
        return victims


class Nemesis(Trudy):
    """Trudy plus network-level attacks on a ChaosNet fabric.

    `partition` isolates the victims from the rest of the cluster
    (symmetric, with timed heal when `partition_duration` is set);
    `delay` injects fixed+jittered latency into every link toward the
    victims; `flood` bursts junk Envelopes at the victims (shed by their
    proxy-MAC validation — a load fault, not a correctness one); `heal`
    lifts every partition and link fault Nemesis (or anyone) installed."""

    def __init__(
        self,
        net: Transport,
        replicas: list[str],
        max_faults: int = 2,
        rng: random.Random | None = None,
        addr: str = "trudy",
        delay: float = 0.02,
        jitter: float = 0.02,
        flood_messages: int = 25,
        partition_duration: float | None = None,
    ):
        super().__init__(net, replicas, max_faults, rng, addr)
        self.delay = delay
        self.jitter = jitter
        self.flood_messages = flood_messages
        self.partition_duration = partition_duration
        self.active_partitions = []

    def _chaos(self) -> ChaosNet:
        if not isinstance(self.net, ChaosNet):
            raise TypeError(
                "partition/delay/heal attacks need a ChaosNet fabric; "
                f"got {type(self.net).__name__}"
            )
        return self.net

    def trigger(self, attack: AttackType | str) -> list[str]:
        if isinstance(attack, str):
            attack = parse_attack(attack)
        if attack in (AttackType.CRASH, AttackType.BYZANTINE):
            return super().trigger(attack)
        if attack is AttackType.HEAL:
            log.info("Nemesis heals the network")
            self._chaos().heal_all()
            self.active_partitions.clear()
            self._note_attack(attack, [])
            return []
        victims = self._victims()
        if attack is AttackType.PARTITION:
            log.info("Nemesis partitions %s", victims)
            self.active_partitions.append(
                self._chaos().partition(
                    victims, duration=self.partition_duration
                )
            )
        elif attack is AttackType.DELAY:
            log.info("Nemesis delays links to %s", victims)
            chaos = self._chaos()
            for v in victims:
                chaos.set_dest(
                    v.rsplit("/", 1)[-1],
                    LinkFaults(delay=self.delay, jitter=self.jitter),
                )
        elif attack is AttackType.FLOOD:
            log.info("Nemesis floods %s", victims)
            for v in victims:
                for _ in range(self.flood_messages):
                    # junk under a garbage signature: replicas burn a MAC
                    # check and drop it — pure load, no protocol effect
                    self.net.send(
                        self.addr, v,
                        M.Envelope(
                            M.IRead(f"flood-{self._rng.getrandbits(32):08x}"),
                            self._rng.getrandbits(63),
                            b"nemesis-junk",
                        ),
                    )
        self._note_attack(attack, victims)
        return victims
