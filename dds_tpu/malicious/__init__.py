"""Fault injection: the built-in attacker ("Trudy")."""

from dds_tpu.malicious.trudy import Trudy, AttackType, parse_attack  # noqa: F401
