"""Stratum: the three-tier ciphertext hierarchy and its fold planner.

`Stratum` wraps a Lodestone `ResidentPlane` and grows it downward:

    hot   — the content-addressed ResidentPool rows in HBM (unchanged
            math; one fused gather+fold dispatch per aggregate),
    warm  — host-pinned numpy limb rows (`warm.WarmCache`), fed by pool
            eviction instead of the old capacity RESET: past `max_rows`
            the pool now spills its coldest rows here and keeps serving
            the fused fast path for the rows that stay,
    cold  — the append-only HMAC'd segment log (`segment.SegmentStore`),
            fed by warm-budget overflow; logical-delete + compaction.

A `TierDirectory` tracks per-entry residency and an exponentially
decayed touch count fed from the fold, search, and write-ingest paths;
under the Zipf workloads the load plane models (`clt/distribution.py`)
the decayed counts rank-order like the popularity weights, so eviction
takes the tail and promotion takes the head.

`fold_groups` is the tier planner: each group's operand multiset splits
into a *resident leg* (hot + never-seen operands, folded by the plane's
single fused dispatch exactly as before) and *streamed legs* (warm rows
stacked from host memory, cold rows read + re-verified from segments in
`chunk_rows` slices, each slice folded on-device via `ModCtx.reduce_mul`
while the next stages on the host). The legs merge through
`parallel/mesh.combine_partials` — an exact modular product — so the
answer is bit-for-bit the all-resident answer; capacity is simply no
longer bounded by HBM. Chronoscope attributes the movement under the new
`tier-demote` / `tier-promote` / `tier-cold-read` stages, and
`pressure()` feeds Helmsman's pool-pressure signal so the autoscaler
reshapes on real tier occupancy.

Threading: every byte-moving method (fold_groups, demote, promote) runs
on worker threads — the server reaches them via `asyncio.to_thread`, the
pool's spill fires inside fold/ingest calls that are already off-loop.
Only the pure-dict popularity touches (`note_write`, `touch`) are
loop-safe.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from dds_tpu.obs.metrics import metrics
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx
from dds_tpu.storage.directory import COLD, HOT, WARM, TierDirectory
from dds_tpu.storage.segment import SegmentStore
from dds_tpu.storage.warm import WarmCache
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.stratum")

Stripe = tuple  # (gid, tenant, modulus)


class Stratum:
    """Tiered ciphertext storage over one ResidentPlane (module docstring)."""

    def __init__(self, plane, directory, *, warm_bytes: int = 64 << 20,
                 chunk_rows: int = 256, promote_score: float = 2.0,
                 max_promote: int = 256, half_life: float = 60.0,
                 keep: int = 3, compact_segments: int = 8,
                 secret: bytes | None = None, name: str = "stratum"):
        self.plane = plane
        self.warm = WarmCache(warm_bytes)
        self.cold = SegmentStore(directory, name=name, secret=secret,
                                 keep=keep, compact_segments=compact_segments)
        self.dir = TierDirectory(half_life=half_life)
        self.chunk_rows = max(2, int(chunk_rows))
        self.promote_score = float(promote_score)
        self.max_promote = max(1, int(max_promote))
        self._lock = threading.Lock()
        self._hits = {HOT: 0, WARM: 0, COLD: 0}
        self._evictions = {HOT: 0, WARM: 0}
        self._cold_reads = 0
        self._promotions = 0
        self._demotions = 0
        # bounded write-time (tenant, key) -> (gid, ciphers) map: the
        # Spyglass selection path speaks keys, the directory speaks
        # ciphertexts — this is the translation that lets a search hit
        # warm its row's fold operands. Loop-thread only (note_write /
        # touch_keys both run on the event loop), insertion-ordered so
        # overflow drops the oldest mapping.
        self._keymap: dict[tuple[str, str], tuple[str, tuple[int, ...]]] = {}
        self._keymap_max = 65536
        # boot: verify + index every durable segment (crash-mid-demotion
        # orphans included) and seed the directory's cold residency
        loaded = self.cold.load()
        for stripe, ciphers in self.cold.entries().items():
            for c in ciphers:
                self.dir.set_tier(stripe, c, COLD)
        if loaded:
            log.info("stratum cold tier loaded: %d entries, %d segments",
                     loaded, self.cold.stats()["segments"])
        self.attach(plane)

    # ------------------------------------------------------------- plumbing

    def attach(self, plane) -> None:
        """Become the plane's tier sink: new pools wire at creation
        (`ResidentPlane.pool`), existing ones retrofit here — after this,
        capacity overflow demotes instead of resetting."""
        plane.tier_sink = self
        with plane._lock:
            pools = list(plane._pools.items())
        for key, pool in pools:
            self.wire_pool(key, pool)

    def wire_pool(self, key: Stripe, pool) -> None:
        pool.spill = lambda rows, _s=key: self.demote(_s, rows)
        pool.evict_rank = lambda cs, _s=key: self.rank(_s, cs)

    def rank(self, stripe: Stripe, ciphers: list[int]) -> list[int]:
        """Coldest-first eviction order for a pool's victim pick."""
        return [c for _, c in self.dir.coldest(
            [(stripe, c) for c in ciphers]
        )]

    # ------------------------------------------------------------- demotion

    def demote(self, stripe: Stripe, rows: list[tuple[int, np.ndarray]]) -> None:
        """Pool spill sink (hot -> warm), cascading warm -> cold when the
        host budget overflows. Runs inside fold/ingest worker threads."""
        if not rows:
            return
        t0 = time.perf_counter()
        moved = 0
        for cipher, row in rows:
            self.warm.put(stripe, cipher, row)
            self.dir.set_tier(stripe, cipher, WARM)
            moved += row.nbytes
        gid = stripe[0] or "-"
        with self._lock:
            self._evictions[HOT] += len(rows)
            self._demotions += len(rows)
        metrics.inc("dds_tier_evictions_total", len(rows), tier="hot",
                    shard=gid, help="entries demoted out of a tier")
        self._rebalance_warm()
        tracer.record(
            "tier.demote", (time.perf_counter() - t0) * 1e3,
            rows=len(rows), bytes=moved, shard=gid,
        )

    def _rebalance_warm(self) -> None:
        """Push the coldest warm rows into the segment log until the host
        byte budget holds. One durable append per wave (fsync'd before
        return), so a row acked out of warm memory is on disk first."""
        over = self.warm.over_budget()
        if not over:
            return
        items = self.warm.items()
        order = self.dir.coldest([(stripe, c) for stripe, c, _ in items])
        batch: dict[Stripe, list[int]] = {}
        freed = 0
        for stripe, cipher in order:
            if freed >= over:
                break
            row = self.warm.pop(stripe, cipher)
            if row is None:
                continue
            freed += row.nbytes
            batch.setdefault(stripe, []).append(cipher)
        if not batch:
            return
        self.cold.append(batch)
        n = 0
        for stripe, ciphers in batch.items():
            for c in ciphers:
                self.dir.set_tier(stripe, c, COLD)
            n += len(ciphers)
            metrics.inc("dds_tier_evictions_total", len(ciphers), tier="warm",
                        shard=stripe[0] or "-",
                        help="entries demoted out of a tier")
        with self._lock:
            self._evictions[WARM] += n

    # ------------------------------------------------------------ promotion

    def _promote(self, stripe: Stripe, candidates: list[int]) -> int:
        """Warm/cold -> hot for entries whose decayed score cleared the
        promotion bar (the Zipf head re-enters the fused fast path)."""
        if not candidates:
            return 0
        gid, tenant, modulus = stripe
        cands = candidates[: self.max_promote]
        t0 = time.perf_counter()
        pool = self.plane.pool(gid, modulus, tenant)
        grew = pool.ingest(cands)
        for c in cands:
            self.warm.pop(stripe, c)
            self.dir.set_tier(stripe, c, HOT)
        self.cold.discard(stripe, cands)
        with self._lock:
            self._promotions += len(cands)
        metrics.inc("dds_tier_promotions_total", len(cands),
                    shard=gid or "-",
                    help="entries promoted back into the hot (HBM) tier")
        tracer.record(
            "tier.promote", (time.perf_counter() - t0) * 1e3,
            rows=len(cands), ingested=grew, shard=gid or "-",
        )
        return len(cands)

    # ---------------------------------------------------- popularity inputs

    def note_write(self, gid: str, ciphers: list[int], tenant: str = "",
                   modulus: int | None = None, key: str | None = None) -> None:
        """Write-ingest popularity: committed ciphertexts count toward the
        EWMA under every modulus stripe this group has established (pure
        dict math — loop-safe, mirrors `ResidentPlane.note_write`). With
        `key`, also records the key -> ciphers mapping the search-path
        feed (`touch_keys`) translates through."""
        if not ciphers:
            return
        if key is not None:
            km, kk = self._keymap, (tenant, key)
            km.pop(kk, None)
            km[kk] = (gid, tuple(ciphers))
            while len(km) > self._keymap_max:
                km.pop(next(iter(km)))
        with self.plane._lock:
            moduli = [m for g, t, m in self.plane._pools
                      if g == gid and t == tenant]
        for m in moduli or ([modulus] if modulus else []):
            stripe = (gid, tenant, m)
            for c in ciphers:
                self.dir.touch(stripe, c, weight=0.5)

    def touch(self, gid: str, modulus: int, ciphers, tenant: str = "",
              weight: float = 1.0) -> None:
        """Search/analytics-path popularity (Spyglass hits keep their
        matched values' fold rows warm). Loop-safe."""
        stripe = (gid, tenant, modulus)
        for c in ciphers:
            self.dir.touch(stripe, c, weight=weight)

    def touch_keys(self, keys, tenant: str = "",
                   weight: float = 1.0) -> None:
        """Search-path popularity: a Spyglass selection names KEYS, and
        every selected key this stripe has seen committed (bounded
        write-time key->cipher map) touches its fold ciphertexts — rows
        users keep finding stay in the fused hot leg. Loop-safe."""
        moduli_by_gid: dict[str, list[int]] = {}
        for k in keys:
            ent = self._keymap.get((tenant, k))
            if ent is None:
                continue
            gid, ciphers = ent
            moduli = moduli_by_gid.get(gid)
            if moduli is None:
                with self.plane._lock:
                    moduli = [m for g, t, m in self.plane._pools
                              if g == gid and t == tenant]
                moduli_by_gid[gid] = moduli
            for m in moduli:
                stripe = (gid, tenant, m)
                for c in ciphers:
                    self.dir.touch(stripe, c, weight=weight)

    # ------------------------------------------------------------ the planner

    def fold_groups(self, parts: list[tuple[str, list[int]]], modulus: int,
                    tenant: str = "") -> int | None:
        """prod over every group's operands mod `modulus`, split per group
        into a resident-fused leg and streamed warm/cold legs, merged via
        the exact `combine_partials` product — bit-for-bit the plane's
        all-resident answer. Returns None only when the plane itself
        cannot serve a resident leg (operand set wider than `max_rows`
        even after eviction), matching the plane's fallback contract."""
        from dds_tpu.parallel.mesh import combine_partials

        parts = [(gid, ops) for gid, ops in parts if ops]
        if not parts:
            return 1 % modulus
        ctx = ModCtx.make(modulus)
        resident_parts: list[tuple[str, list[int]]] = []
        streamed: list[tuple[Stripe, list[int], list[int]]] = []
        promote_cands: dict[Stripe, list[int]] = {}
        for gid, ops in parts:
            stripe = (gid, tenant, modulus)
            pool = self.plane.pool(gid, modulus, tenant)
            member = pool.membership(ops)
            hot_ops: list[int] = []
            warm_ops: list[int] = []
            cold_ops: list[int] = []
            direct_ops: list[int] = []
            seen_scored: set[int] = set()
            # the resident leg must keep its distinct operand set within
            # the pool (ensure() answers None past max_rows, losing the
            # whole fused leg): hot members are already rows, so fresh
            # never-seen operands only admit while room remains — the
            # rest stream directly and adopt into the warm tier below
            fresh_budget = pool.max_rows - len(
                {c for c, m in zip(ops, member) if m}
            )
            fresh_admitted: set[int] = set()
            for c, is_hot in zip(ops, member):
                score = self.dir.touch(stripe, c)
                if is_hot:
                    hot_ops.append(c)
                    continue
                if self.warm.contains(stripe, c):
                    warm_ops.append(c)
                elif self.cold.contains(stripe, c):
                    cold_ops.append(c)
                elif (c in fresh_admitted
                        or len(fresh_admitted) < fresh_budget):
                    # never-seen operand (fresh from the quorum read):
                    # enters through the hot path like before Stratum
                    fresh_admitted.add(c)
                    hot_ops.append(c)
                    continue
                else:
                    direct_ops.append(c)
                    continue
                if score >= self.promote_score and c not in seen_scored:
                    seen_scored.add(c)
                    promote_cands.setdefault(stripe, []).append(c)
            gidl = gid or "-"
            if hot_ops:
                metrics.inc("dds_tier_hits_total", len(hot_ops), tier="hot",
                            shard=gidl,
                            help="fold operands served per tier")
            if warm_ops:
                metrics.inc("dds_tier_hits_total", len(warm_ops), tier="warm",
                            shard=gidl,
                            help="fold operands served per tier")
            if cold_ops:
                metrics.inc("dds_tier_hits_total", len(cold_ops), tier="cold",
                            shard=gidl,
                            help="fold operands served per tier")
            with self._lock:
                self._hits[HOT] += len(hot_ops)
                self._hits[WARM] += len(warm_ops)
                self._hits[COLD] += len(cold_ops)
            if hot_ops:
                resident_parts.append((gid, hot_ops))
            if warm_ops or cold_ops or direct_ops:
                streamed.append((stripe, warm_ops, cold_ops, direct_ops))
        partials: list[int] = []
        if resident_parts:
            r = self.plane.fold_groups(resident_parts, modulus, tenant)
            if r is None:
                return None  # wider than the pool: caller's legacy fallback
            partials.append(r)
        adopted = False
        for stripe, warm_ops, cold_ops, direct_ops in streamed:
            partials.append(
                self._stream_fold(stripe, ctx, warm_ops, cold_ops,
                                  direct_ops)
            )
            adopted = adopted or bool(direct_ops)
        if adopted:
            # direct-overflow rows adopted into warm above: enforce the
            # byte budget once per fold, not once per group
            self._rebalance_warm()
        if not partials:
            return 1 % modulus
        result = (combine_partials(partials, modulus)
                  if len(partials) > 1 else partials[0] % modulus)
        for stripe, cands in promote_cands.items():
            self._promote(stripe, cands)
        return result

    def _stream_fold(self, stripe: Stripe, ctx: ModCtx,
                     warm_ops: list[int], cold_ops: list[int],
                     direct_ops: list[int] = ()) -> int:
        """Fold the streamed legs of one group: warm rows stack straight
        from host memory, cold rows read + re-verify from segments under
        the `tier.cold_read` stage, and `direct_ops` (never-seen overflow
        past the pool's admission budget) convert from the operand ints —
        then adopt into warm so the next fold serves them from a tier.
        `chunk_rows` slices dispatch through `ModCtx.reduce_mul` so
        device compute overlaps the next slice's host staging, and the
        chunk partials combine exactly."""
        from dds_tpu.parallel.mesh import combine_partials

        gid = stripe[0] or "-"
        rows: list[np.ndarray] = []
        for c in dict.fromkeys(direct_ops):
            if self.warm.contains(stripe, c):
                continue  # adopted by an earlier duplicate this fold
            row = np.asarray(bn.int_to_limbs(c % ctx.n, ctx.L),
                             dtype=np.uint32)
            self.warm.put(stripe, c, row)
            self.dir.set_tier(stripe, c, WARM)
        for c in direct_ops:
            row = self.warm.get(stripe, c)
            if row is None:  # raced out by a concurrent rebalance
                row = bn.int_to_limbs(c % ctx.n, ctx.L)
            rows.append(np.asarray(row, dtype=np.uint32))
        for c in warm_ops:
            row = self.warm.get(stripe, c)
            if row is None:  # raced away (demoted mid-plan): reconvert
                row = bn.int_to_limbs(c % ctx.n, ctx.L)
            rows.append(np.asarray(row, dtype=np.uint32))
        if cold_ops:
            t0 = time.perf_counter()
            try:
                cold_rows = self.cold.read_rows(stripe, cold_ops, ctx.L)
            except (KeyError, ValueError) as e:
                # compacted away / quarantined between plan and read: the
                # operand ints are in hand, convert directly
                log.debug("cold read fell back to conversion: %s", e)
                cold_rows = bn.ints_to_batch(
                    [c % ctx.n for c in cold_ops], ctx.L
                )
            rows.extend(np.asarray(r, dtype=np.uint32) for r in cold_rows)
            with self._lock:
                self._cold_reads += len(cold_ops)
            metrics.inc("dds_tier_cold_reads_total", len(cold_ops),
                        shard=gid,
                        help="fold operands streamed from the segment log")
            tracer.record(
                "tier.cold_read", (time.perf_counter() - t0) * 1e3,
                rows=len(cold_ops), shard=gid,
            )
        if not rows:
            return 1 % ctx.n
        chunk_partials: list[int] = []
        for i in range(0, len(rows), self.chunk_rows):
            stack = np.stack(rows[i: i + self.chunk_rows])
            out = ctx.reduce_mul(stack)
            chunk_partials.append(bn.limbs_to_int(np.asarray(out)[0]))
        return (combine_partials(chunk_partials, ctx.n)
                if len(chunk_partials) > 1 else chunk_partials[0])

    # -------------------------------------------------------------- surface

    def pressure(self) -> float:
        """0..1 capacity signal for Helmsman's `pool_pressure` input: the
        fullest pool's hot occupancy, or the warm budget's fill when that
        is higher — either tier saturating means this group set is living
        past its memory and the autoscaler should reshape."""
        with self.plane._lock:
            pools = list(self.plane._pools.values())
        hot = max(
            (p.resident / p.max_rows for p in pools if p.max_rows), default=0.0
        )
        warm = (self.warm.bytes / self.warm.max_bytes
                if self.warm.max_bytes else 0.0)
        return round(min(1.0, max(hot, warm)), 4)

    def stats(self) -> dict:
        """The /health "storage" section."""
        with self.plane._lock:
            pools = list(self.plane._pools.values())
        hot_rows = sum(p.resident for p in pools)
        hot_bytes = sum(p.nbytes() for p in pools)
        with self._lock:
            hits = dict(self._hits)
            evictions = dict(self._evictions)
            cold_reads = self._cold_reads
            promotions = self._promotions
            demotions = self._demotions
        return {
            "tiers": {
                "hot": {"rows": hot_rows, "bytes": hot_bytes},
                "warm": self.warm.stats(),
                "cold": self.cold.stats(),
            },
            "directory": self.dir.counts(),
            "hits": hits,
            "evictions": evictions,
            "cold_reads": cold_reads,
            "promotions": promotions,
            "demotions": demotions,
            "pressure": self.pressure(),
        }

    def export_gauges(self, registry=metrics) -> None:
        """Scrape-time dds_tier_{rows,bytes}{tier,shard} gauges (the
        counters — hits/evictions/cold_reads — increment at event time)."""
        with self.plane._lock:
            pools = list(self.plane._pools.items())
        per_gid: dict[str, list] = {}
        for (gid, _tenant, _mod), pool in pools:
            agg = per_gid.setdefault(gid or "-", [0, 0])
            agg[0] += pool.resident
            agg[1] += pool.nbytes()
        for gid, (rows, nbytes) in per_gid.items():
            registry.set("dds_tier_rows", rows, tier="hot", shard=gid,
                         help="entries resident per storage tier")
            registry.set("dds_tier_bytes", nbytes, tier="hot", shard=gid,
                         help="bytes held per storage tier")
        warm_gid: dict[str, list] = {}
        for stripe, _c, nbytes in self.warm.items():
            agg = warm_gid.setdefault(stripe[0] or "-", [0, 0])
            agg[0] += 1
            agg[1] += nbytes
        for gid, (rows, nbytes) in warm_gid.items():
            registry.set("dds_tier_rows", rows, tier="warm", shard=gid,
                         help="entries resident per storage tier")
            registry.set("dds_tier_bytes", nbytes, tier="warm", shard=gid,
                         help="bytes held per storage tier")
        cold_rows_by_gid: dict[str, int] = {}
        for stripe, ciphers in self.cold.entries().items():
            gid = stripe[0] or "-"
            cold_rows_by_gid[gid] = cold_rows_by_gid.get(gid, 0) + len(ciphers)
        for gid, rows in cold_rows_by_gid.items():
            registry.set("dds_tier_rows", rows, tier="cold", shard=gid,
                         help="entries resident per storage tier")
        # segment files are shared across stripes: bytes report unsharded
        registry.set("dds_tier_bytes", self.cold.stats()["bytes"],
                     tier="cold", shard="-",
                     help="bytes held per storage tier")
