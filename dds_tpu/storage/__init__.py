"""Stratum: tiered ciphertext storage — HBM -> host-pinned -> segment log.

Three explicit tiers per (shard group, tenant, modulus) stripe:

- hot: the Lodestone `ResidentPool` HBM buffer (resident/pool.py),
- warm: `WarmCache` host numpy limb rows under a byte budget,
- cold: `SegmentStore`, an append-only log of HMAC'd segment files with
  keep-N manifest rotation (snapshot v2's crash-safety discipline).

`Stratum` orchestrates: a `TierDirectory` drives Zipf-aware (decayed
touch count) promotion/eviction, pool overflow demotes instead of
resetting, and `fold_groups` splits every aggregate into a resident-
fused leg plus streamed-from-tier legs merged bit-for-bit exactly.
"""

from dds_tpu.storage.directory import COLD, HOT, TIERS, WARM, TierDirectory
from dds_tpu.storage.segment import SegmentStore, derive_segment_secret
from dds_tpu.storage.stratum import Stratum
from dds_tpu.storage.warm import WarmCache

__all__ = [
    "Stratum", "SegmentStore", "WarmCache", "TierDirectory",
    "derive_segment_secret", "TIERS", "HOT", "WARM", "COLD",
]
