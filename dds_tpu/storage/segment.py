"""Stratum cold tier: append-only log-structured ciphertext segments.

One `SegmentStore` per node persists demoted ciphertexts as a sequence
of immutable segment files plus a rotating manifest, reusing snapshot
v2's on-disk discipline (`core/snapshot.py`) byte-for-byte in spirit:

    {name}.segment.{seq:08d}.log
        <canonical JSON body>\n<hmac-sha256 hex footer>\n
        body = {"v": 1, "seq": s, "saved_at": ts,
                "records": [{"gid": g, "tenant": t, "modulus": hex,
                             "ciphers": [hex, ...]}, ...]}

    {name}.manifest.{gen:08d}.json
        same framing; body = {"v": 1, "generation": g, "saved_at": ts,
                              "segments": [segment file names]}

Properties the tier planner leans on:

- **Append-only**: a demotion wave writes ONE new segment (fsync before
  rename, directory fd fsync'd after — `snapshot.write_authenticated`),
  then a new manifest generation referencing it. A crash between the
  two leaves an *orphan* segment: `load()` scans the directory, verifies
  every footer, and ADOPTS verified orphans into a fresh manifest — a
  crash mid-demotion never loses a durably-written row.
- **Logical deletes**: promotion back to warmer tiers only drops the
  in-memory index entry; the bytes stay until `compact()` rewrites the
  live set into one segment. Content addressing makes re-appends of the
  same value harmless (set union at load).
- **Keep-N manifests, never-strand segments**: manifest generations
  rotate keep-N like snapshots, and segment pruning deletes ONLY files
  absent from the NEWEST manifest — a file any retained generation still
  names but the newest dropped is compaction garbage by definition,
  while everything the newest names is load-bearing and untouchable.
- **Verify-on-read**: `read_rows` re-verifies the segment footer at
  every cold read (bit-rot between boot and read is caught, not folded);
  corrupt files quarantine to `*.corrupt` exactly like snapshots.

The store is synchronous and blocking by design — every caller reaches
it from a worker thread (`asyncio.to_thread`), never the event loop; the
Argus `async` pass enforces that for the fsync/open family.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import re
import threading
import time

import numpy as np

from dds_tpu.core.snapshot import (
    DEFAULT_BASE,
    derive_secret,
    read_authenticated,
    write_authenticated,
)
from dds_tpu.obs.metrics import metrics
from dds_tpu.ops import bignum as bn

log = logging.getLogger("dds.stratum")

_SEG_RE = re.compile(r"\.segment\.(\d{8})\.log$")
_MAN_RE = re.compile(r"\.manifest\.(\d{8})\.json$")

# (gid, tenant, modulus) — the same pool address Lodestone stripes by
Stripe = tuple


def derive_segment_secret(base: bytes = DEFAULT_BASE,
                          node_key_path=None) -> bytes:
    """Segment MAC key: the snapshot derivation with Stratum's own label,
    so a snapshot footer can never verify as a segment footer."""
    return derive_secret(base, node_key_path, label=b"dds-stratum-mac-v1")


def _stripe_to_wire(stripe: Stripe) -> dict:
    gid, tenant, modulus = stripe
    return {"gid": gid, "tenant": tenant, "modulus": f"{modulus:x}"}


def _stripe_from_wire(rec: dict) -> Stripe:
    return (str(rec["gid"]), str(rec["tenant"]), int(str(rec["modulus"]), 16))


class SegmentStore:
    """Append-only HMAC'd segment log + rotating manifest (see module
    docstring). Thread-safe; all disk work happens under one lock (the
    callers are worker threads, so serializing demotion waves is the
    point, not a hazard)."""

    def __init__(self, directory, name: str = "stratum",
                 secret: bytes | None = None, keep: int = 3,
                 compact_segments: int = 8):
        self.dir = pathlib.Path(directory)
        self.name = name
        self.keep = max(1, int(keep))
        self.compact_segments = max(2, int(compact_segments))
        self._secret = secret or derive_segment_secret()
        self._lock = threading.Lock()
        # seq -> path of every live (manifest-referenced or adopted) segment
        self._live: dict[int, pathlib.Path] = {}
        # stripe -> {cipher int -> seq holding it}
        self._index: dict[Stripe, dict[int, int]] = {}
        self._generation = 0
        self.quarantined = 0
        self.compactions = 0

    # -------------------------------------------------------------- framing

    def _seg_path(self, seq: int) -> pathlib.Path:
        return self.dir / f"{self.name}.segment.{seq:08d}.log"

    def _scan(self, pattern: re.Pattern, glob: str):
        out = []
        for p in self.dir.glob(glob):
            m = pattern.search(p.name)
            if m:
                out.append((int(m.group(1)), p))
        return sorted(out)

    def _quarantine(self, path: pathlib.Path, reason: str) -> None:
        target = path.with_name(path.name + ".corrupt")
        log.warning("quarantining segment file %s -> %s (%s)",
                    path, target.name, reason)
        self.quarantined += 1
        metrics.inc(
            "dds_segment_verify_failures_total",
            help="segment/manifest files quarantined (corrupt/truncated/"
                 "forged)",
        )
        try:
            os.replace(path, target)
        except OSError as e:  # pragma: no cover - fs-dependent
            log.warning("could not quarantine %s: %s", path, e)

    def _read_segment(self, path: pathlib.Path) -> dict:
        body = json.loads(read_authenticated(path, self._secret))
        if body.get("v") != 1 or not isinstance(body.get("records"), list):
            raise ValueError(f"unsupported segment body v={body.get('v')!r}")
        return body

    def _write_segment(self, seq: int,
                       entries: dict[Stripe, list[int]]) -> pathlib.Path:
        records = [
            {**_stripe_to_wire(stripe),
             "ciphers": [f"{c:x}" for c in ciphers]}
            for stripe, ciphers in entries.items() if ciphers
        ]
        body = json.dumps(
            {"v": 1, "seq": seq, "saved_at": time.time(), "records": records},
            sort_keys=True, separators=(",", ":"),
        ).encode()
        path = self._seg_path(seq)
        write_authenticated(path, body, self._secret)
        return path

    def _write_manifest(self) -> None:
        """New manifest generation naming every live segment, then keep-N
        rotation of OLDER manifest generations only (caller holds lock)."""
        self._generation += 1
        body = json.dumps(
            {"v": 1, "generation": self._generation, "saved_at": time.time(),
             "segments": [p.name for _, p in sorted(self._live.items())]},
            sort_keys=True, separators=(",", ":"),
        ).encode()
        path = self.dir / f"{self.name}.manifest.{self._generation:08d}.json"
        write_authenticated(path, body, self._secret)
        for gen, old in self._scan(_MAN_RE, f"{self.name}.manifest.*.json"):
            if gen <= self._generation - self.keep:
                try:
                    old.unlink()
                except OSError:  # pragma: no cover - fs-dependent
                    pass

    # ----------------------------------------------------------------- boot

    def load(self) -> int:
        """Scan + verify every segment on disk, quarantining corrupt or
        truncated files; adopt verified orphans (crash-mid-demotion) into
        a fresh manifest. Returns distinct entries indexed. Never raises
        for bad files — one flipped byte cannot abort boot."""
        self.dir.mkdir(parents=True, exist_ok=True)
        with self._lock:
            # newest verified manifest seeds the generation counter (and
            # is itself quarantined when unverifiable — the segment scan
            # below is the source of truth for contents either way)
            manifested: set[str] = set()
            for gen, path in reversed(
                self._scan(_MAN_RE, f"{self.name}.manifest.*.json")
            ):
                try:
                    body = json.loads(read_authenticated(path, self._secret))
                    if body.get("v") != 1:
                        raise ValueError("unsupported manifest version")
                except (OSError, ValueError, json.JSONDecodeError) as e:
                    self._quarantine(path, str(e))
                    continue
                self._generation = max(self._generation, gen)
                manifested = set(body.get("segments") or [])
                break
            adopted = 0
            for seq, path in self._scan(
                _SEG_RE, f"{self.name}.segment.*.log"
            ):
                try:
                    body = self._read_segment(path)
                except (OSError, ValueError, json.JSONDecodeError) as e:
                    self._quarantine(path, str(e))
                    continue
                self._live[seq] = path
                if path.name not in manifested:
                    adopted += 1
                for rec in body["records"]:
                    stripe = _stripe_from_wire(rec)
                    dest = self._index.setdefault(stripe, {})
                    for hexc in rec.get("ciphers", ()):
                        dest[int(hexc, 16)] = seq
            if adopted:
                # crash-mid-demotion: the segment made it, the manifest
                # didn't — re-manifest so the next compaction sees it live
                log.info("adopting %d orphan segment(s) into manifest",
                         adopted)
                self._write_manifest()
            return sum(len(v) for v in self._index.values())

    # --------------------------------------------------------------- writes

    def append(self, entries: dict[Stripe, list[int]]) -> int | None:
        """Persist one demotion wave as a new segment + manifest
        generation; returns the new seq (None when `entries` is empty).
        Durable (fsync'd) before return — a row acked into the cold tier
        survives any crash after this call."""
        entries = {s: [c for c in cs] for s, cs in entries.items() if cs}
        if not entries:
            return None
        with self._lock:
            self.dir.mkdir(parents=True, exist_ok=True)
            seq = (max(self._live) + 1) if self._live else 1
            path = self._write_segment(seq, entries)
            self._live[seq] = path
            for stripe, ciphers in entries.items():
                dest = self._index.setdefault(stripe, {})
                for c in ciphers:
                    dest[c] = seq
            self._write_manifest()
            if len(self._live) > self.compact_segments:
                self._compact_locked()
            return seq

    def _compact_locked(self) -> None:
        """Rewrite the live entry set into ONE fresh segment, manifest it,
        then delete every segment file the NEWEST manifest no longer
        names. Pruning is driven off the newest manifest alone — a
        segment any retained generation references is only deleted once
        the newest generation has stopped naming it, and everything the
        newest names survives (the co-rotation invariant the tests pin)."""
        live_entries: dict[Stripe, list[int]] = {
            stripe: sorted(m) for stripe, m in self._index.items() if m
        }
        seq = (max(self._live) + 1) if self._live else 1
        path = self._write_segment(seq, live_entries)
        old = dict(self._live)
        self._live = {seq: path}
        for stripe in list(self._index):
            self._index[stripe] = {
                c: seq for c in self._index[stripe]
            }
        self._write_manifest()
        for oseq, opath in old.items():
            if oseq == seq:
                continue
            try:
                opath.unlink()
            except OSError:  # pragma: no cover - fs-dependent
                pass
        self.compactions += 1
        metrics.inc(
            "dds_segment_compactions_total",
            help="cold-tier segment compactions (live set rewritten)",
        )

    def compact(self) -> None:
        with self._lock:
            if self._live:
                self._compact_locked()

    def discard(self, stripe: Stripe, ciphers) -> int:
        """Logical delete (promotion to a warmer tier): drop the index
        entries; bytes reclaim at the next compaction."""
        with self._lock:
            dest = self._index.get(stripe)
            if not dest:
                return 0
            dropped = 0
            for c in ciphers:
                if dest.pop(c, None) is not None:
                    dropped += 1
            return dropped

    # ---------------------------------------------------------------- reads

    def contains(self, stripe: Stripe, cipher: int) -> bool:
        with self._lock:
            dest = self._index.get(stripe)
            return bool(dest) and cipher in dest

    def entries(self) -> dict[Stripe, list[int]]:
        """Stripe -> live ciphers (boot-time directory seeding)."""
        with self._lock:
            return {s: list(m) for s, m in self._index.items() if m}

    def read_rows(self, stripe: Stripe, ciphers: list[int],
                  L: int) -> np.ndarray:
        """(K, L) uint32 limb rows for `ciphers` (duplicates allowed, order
        preserved) read from disk with footer re-verification per touched
        segment. Raises KeyError when a cipher is not in the cold index,
        ValueError when a touched segment fails verification (the caller
        falls back to converting from the operand it already holds)."""
        modulus = stripe[2]
        with self._lock:
            dest = self._index.get(stripe) or {}
            need: dict[int, int] = {}
            for c in ciphers:
                seq = dest.get(c)
                if seq is None:
                    raise KeyError(c)
                need[c] = seq
            paths = {seq: self._live[seq] for seq in set(need.values())}
        present: set[int] = set()
        nbytes = 0
        for seq, path in paths.items():
            body = self._read_segment(path)  # re-verify at read time
            nbytes += path.stat().st_size
            for rec in body["records"]:
                if _stripe_from_wire(rec) != stripe:
                    continue
                for hexc in rec.get("ciphers", ()):
                    present.add(int(hexc, 16))
        missing = [c for c in need if c not in present]
        if missing:
            raise KeyError(missing[0])
        metrics.inc(
            "dds_tier_cold_read_bytes_total", nbytes, shard=stripe[0] or "-",
            help="segment bytes read + re-verified by cold-tier streams",
        )
        ctxL = L
        return bn.ints_to_batch([c % modulus for c in ciphers], ctxL)

    # -------------------------------------------------------------- surface

    def stats(self) -> dict:
        with self._lock:
            rows = sum(len(m) for m in self._index.values())
            nbytes = 0
            for p in self._live.values():
                try:
                    nbytes += p.stat().st_size
                except OSError:  # pragma: no cover - fs-dependent
                    pass
            return {
                "rows": rows,
                "bytes": nbytes,
                "segments": len(self._live),
                "generation": self._generation,
                "quarantined": self.quarantined,
                "compactions": self.compactions,
            }
