"""Stratum tier directory: residency + decayed popularity per entry.

One record per (stripe, cipher): which tier holds the entry (hot = HBM
pool, warm = host cache, cold = segment store) and an exponentially
decayed touch count — `score` halves every `half_life` seconds, and each
touch from a fold/search/ingest path adds its weight. Under a Zipf
workload (the load plane's `clt/distribution.ZipfKeys` popularity model,
which doubles as the test harness) the decayed counts rank-order exactly
like the underlying popularity weights, so:

- eviction picks the tail (`coldest` — lowest score first),
- promotion picks entries whose score clears `promote_score` (touched
  repeatedly within recent half-lives, i.e. the Zipf head),
- the split planner routes each fold operand to the leg its current
  tier can serve without moving bytes first.

Pure in-memory dict math — safe to call from the event loop (the write
path's `note_write` touches go through here) and from worker threads.
"""

from __future__ import annotations

import threading
import time

HOT, WARM, COLD = "hot", "warm", "cold"
TIERS = (HOT, WARM, COLD)

Stripe = tuple  # (gid, tenant, modulus)


class _Entry:
    __slots__ = ("tier", "score", "stamp")

    def __init__(self, tier: str, now: float):
        self.tier = tier
        self.score = 0.0
        self.stamp = now


class TierDirectory:
    """Residency + EWMA popularity per (stripe, cipher)."""

    def __init__(self, half_life: float = 60.0):
        self.half_life = max(1e-3, float(half_life))
        self._lock = threading.Lock()
        self._entries: dict[Stripe, dict[int, _Entry]] = {}

    # ------------------------------------------------------------- scoring

    def _decayed(self, e: _Entry, now: float) -> float:
        dt = max(0.0, now - e.stamp)
        return e.score * (0.5 ** (dt / self.half_life))

    def touch(self, stripe: Stripe, cipher: int, weight: float = 1.0,
              tier: str | None = None) -> float:
        """Decay-then-add one touch; returns the new score. `tier` seeds
        residency for entries the directory has not met yet (a fresh
        quorum-read operand enters as hot — the pool ingests it)."""
        now = time.monotonic()
        with self._lock:
            dest = self._entries.setdefault(stripe, {})
            e = dest.get(cipher)
            if e is None:
                e = dest[cipher] = _Entry(tier or HOT, now)
            e.score = self._decayed(e, now) + weight
            e.stamp = now
            return e.score

    def score(self, stripe: Stripe, cipher: int) -> float:
        now = time.monotonic()
        with self._lock:
            dest = self._entries.get(stripe)
            e = dest.get(cipher) if dest else None
            return 0.0 if e is None else self._decayed(e, now)

    # ----------------------------------------------------------- residency

    def set_tier(self, stripe: Stripe, cipher: int, tier: str) -> None:
        assert tier in TIERS, tier
        now = time.monotonic()
        with self._lock:
            dest = self._entries.setdefault(stripe, {})
            e = dest.get(cipher)
            if e is None:
                dest[cipher] = _Entry(tier, now)
            else:
                e.tier = tier

    def tier_of(self, stripe: Stripe, cipher: int) -> str | None:
        with self._lock:
            dest = self._entries.get(stripe)
            e = dest.get(cipher) if dest else None
            return None if e is None else e.tier

    def drop(self, stripe: Stripe, cipher: int) -> None:
        with self._lock:
            dest = self._entries.get(stripe)
            if dest:
                dest.pop(cipher, None)

    def drop_stripe(self, stripe: Stripe) -> int:
        with self._lock:
            dest = self._entries.pop(stripe, None)
            return len(dest) if dest else 0

    # ------------------------------------------------------------ planning

    def coldest(self, candidates: list[tuple[Stripe, int]],
                k: int | None = None) -> list[tuple[Stripe, int]]:
        """`candidates` ordered coldest-first by decayed score (the Zipf
        tail leads); `k` truncates. Victim selection for both the pool's
        eviction rank and the warm cache's demotion sweep."""
        now = time.monotonic()
        with self._lock:
            def key(sc):
                stripe, c = sc
                dest = self._entries.get(stripe)
                e = dest.get(c) if dest else None
                return self._decayed(e, now) if e is not None else 0.0

            out = sorted(candidates, key=key)
        return out if k is None else out[:k]

    def counts(self) -> dict:
        with self._lock:
            out = {t: 0 for t in TIERS}
            for dest in self._entries.values():
                for e in dest.values():
                    out[e.tier] = out.get(e.tier, 0) + 1
            return out
