"""Stratum warm tier: host-pinned numpy limb rows under a byte budget.

The middle rung of the hierarchy: rows evicted from a ResidentPool's HBM
buffer land here as plain `(L,)` uint32 numpy arrays — already
limb-converted, so promotion back to HBM is a pure H2D transfer and a
streamed warm fold skips the CPU-heavy `ints_to_batch` conversion that
makes cold/direct folds expensive. The cache itself is policy-free: it
tracks bytes and answers membership; the `TierDirectory`'s Zipf/EWMA
scores decide WHICH entries `Stratum` pushes down to the segment store
when the budget is exceeded (`over_budget` + `items` are the hooks).
"""

from __future__ import annotations

import threading

import numpy as np

Stripe = tuple  # (gid, tenant, modulus)


class WarmCache:
    """Byte-budgeted host cache of limb rows keyed (stripe, cipher)."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._rows: dict[Stripe, dict[int, np.ndarray]] = {}
        self._bytes = 0

    def put(self, stripe: Stripe, cipher: int, row: np.ndarray) -> None:
        row = np.ascontiguousarray(row, dtype=np.uint32)
        with self._lock:
            dest = self._rows.setdefault(stripe, {})
            old = dest.get(cipher)
            if old is not None:
                self._bytes -= old.nbytes
            dest[cipher] = row
            self._bytes += row.nbytes

    def get(self, stripe: Stripe, cipher: int) -> np.ndarray | None:
        with self._lock:
            dest = self._rows.get(stripe)
            return None if dest is None else dest.get(cipher)

    def pop(self, stripe: Stripe, cipher: int) -> np.ndarray | None:
        with self._lock:
            dest = self._rows.get(stripe)
            if dest is None:
                return None
            row = dest.pop(cipher, None)
            if row is not None:
                self._bytes -= row.nbytes
            return row

    def contains(self, stripe: Stripe, cipher: int) -> bool:
        with self._lock:
            dest = self._rows.get(stripe)
            return bool(dest) and cipher in dest

    # ------------------------------------------------------------- pressure

    @property
    def bytes(self) -> int:
        return self._bytes

    def over_budget(self) -> int:
        """Bytes above the budget (0 when within) — the demotion trigger."""
        with self._lock:
            return max(0, self._bytes - self.max_bytes)

    def items(self) -> list[tuple[Stripe, int, int]]:
        """(stripe, cipher, nbytes) of every cached row — the victim-
        selection sweep (Stratum scores these against the directory)."""
        with self._lock:
            return [
                (stripe, c, row.nbytes)
                for stripe, dest in self._rows.items()
                for c, row in dest.items()
            ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "rows": sum(len(d) for d in self._rows.values()),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
            }
