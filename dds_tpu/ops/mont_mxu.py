"""Hybrid VPU+MXU Montgomery multiply (kernel v2).

The CIOS kernel in `pallas_mont` interleaves the schoolbook product with
the Montgomery reduction, so both halves of the work (2 L^2 limb products
per multiply) run as uint32 VPU multiplies — the measured bottleneck
(~60% of kernel time; u32 multiply throughput is ~8x below add/logic
throughput on TPU VPUs). v2 separates the two halves and exploits that
the *modulus is shared across the batch*:

- the a*b schoolbook product keeps the only varying*varying math on the
  VPU as a Pallas kernel (L^2 u32 multiplies — half of CIOS), producing a
  redundant 2L-digit accumulator without CIOS's per-step m/shift
  bookkeeping;
- the Montgomery reduction `m = T*N' mod R; t = (T + m*N)/R` is LINEAR in
  the varying operand with batch-constant coefficients (N' = -n^-1 mod R,
  N = n), so both products become matmuls against precomputed Toeplitz
  band matrices of the modulus digits in base 2^8 — int8 MXU work that is
  ~free next to the VPU product;
- carry normalization between stages is Kogge-Stone carry-lookahead in
  plain XLA: O(log L) full-width vector passes instead of the O(L)
  sequential scans of the v1 finalize.

int8 matmuls need inputs in [-128, 127]; digit vectors/matrices live in
[0, 255], so both are split as x = x' + 128*mask (x' signed, mask the 0/1
support): M @ d = M'@d' + 128*(mask_M@d') + (128*M'@1 + 2^14*mask_M@1),
i.e. two int8 matmuls plus a precomputed per-row constant.

Replaces the same reference semantics as `pallas_mont` (the
`HomoAdd.sum` / `HomoMult.multiply` folds of
`dds/http/DDSRestServer.scala:385,423,479,518`); exactness is validated
against python int arithmetic in tests/test_mxu.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx

LIMB_BITS = bn.LIMB_BITS          # 16
MASK16 = np.uint32(0xFFFF)
MASK8 = np.int32(0xFF)

# lane tile for the product kernel: swept on a real v5e chip at L=256 —
# 128 lanes beat 256/512/1024 by ~3-10% (smaller tiles keep the (2L, TB)
# accumulator and operand blocks comfortably in VMEM)
PROD_TB = 128
GROUP = 8                         # a-limbs per aligned accumulator update


def _tb_for(L: int) -> int:
    """Lane tile per limb count. Small-limb moduli (RSA-1024: L=64)
    under-fill a 128-lane tile's fixed costs — wider tiles amortize them
    while the (2L, TB) accumulator still fits VMEM easily (L=64, TB=512:
    ~0.3 MB). L=256 (128 lanes) is the r3-measured winner; the small-L
    values are VMEM-fit picks pending the on-chip DDS_PROD_TB sweep
    (e.g. `DDS_PROD_TB=512 python -m benchmarks.product --sizes 1024`).
    CAUTION: DDS_PROD_TB is read at TRACE time and the callers' jit/lru
    caches key on shapes only — sweep with ONE PROCESS PER VALUE, never
    by mutating the env mid-process (stale traces would be re-timed)."""
    from dds_tpu.ops.flags import prod_tb

    env_tb = prod_tb()  # validated: int, > 0, multiple of 128 — loud errors
    if env_tb is not None:
        return env_tb
    if L <= 64:
        return 512
    if L <= 128:
        return 256
    return PROD_TB


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Pallas schoolbook product: (L, TB) x (L, TB) canonical -> (2L, TB) redundant
# ---------------------------------------------------------------------------


def _accumulate_prod(a_read, b, acc_ref, rows: int, TB: int) -> None:
    """Schoolbook-accumulate a*b into acc_ref ((2*rows + GROUP, TB),
    pre-zeroed). `a_read(i)` yields a's digit row i as (1, TB) (a closure
    over a ref — lets callers aim at a half of a larger operand); `b` is
    the whole (rows, TB) canonical digit value.

    GROUP shifted partial products per loop step keep the dynamic
    accumulator update sublane-aligned; the pad offsets (j / GROUP-j for
    the lo halves, j+1 / GROUP-j-1 for the hi halves) encode the digit
    alignment. Digit bound: each position sums <= rows lo-halves + rows
    hi-halves, each < 2^16, so digits < 2*rows*2^16 = 2^26 for rows = 512
    (Paillier-4096) — comfortably below u32 and carry_norm's < 2^31 input
    bound; no carries inside the loop."""

    def body(g, _):
        base = g * GROUP
        w = jnp.zeros((rows + GROUP, TB), jnp.uint32)
        for j in range(GROUP):
            p = a_read(base + j) * b                      # (rows, TB)
            lo = jnp.pad(p & MASK16, ((j, GROUP - j), (0, 0)))
            hi = jnp.pad(p >> LIMB_BITS, ((j + 1, GROUP - j - 1), (0, 0)))
            w = w + lo + hi
        cur = acc_ref[pl.ds(base, rows + GROUP), :]
        acc_ref[pl.ds(base, rows + GROUP), :] = cur + w
        return 0

    jax.lax.fori_loop(0, rows // GROUP, body, 0)


def _make_prod_kernel(L: int, TB: int):
    """T = a*b as redundant base-2^16 digits, limbs-major (see
    _accumulate_prod for the scheme + digit bounds)."""
    Lacc = 2 * L + GROUP  # top pad so every (L+GROUP)-row update fits

    def kernel(a_ref, b_ref, out_ref, acc_ref):
        acc_ref[:, :] = jnp.zeros((Lacc, TB), jnp.uint32)
        _accumulate_prod(
            lambda i: a_ref[pl.ds(i, 1), :], b_ref[:, :], acc_ref, L, TB
        )
        out_ref[:, :] = acc_ref[0 : 2 * L, :]

    return kernel


@functools.lru_cache(maxsize=None)
def _prod_call(L: int, B: int, TB: int, interpret: bool):
    kernel = _make_prod_kernel(L, TB)
    return pl.pallas_call(
        kernel,
        grid=(B // TB,),
        in_specs=[
            pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * L, B), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((2 * L + GROUP, TB), jnp.uint32)],
        interpret=interpret,
    )


def _make_prod3_kernel(h: int, TB: int):
    """Three independent (h, TB) x (h, TB) schoolbook products in ONE
    kernel dispatch, outputs stacked as (6h, TB): the fused Karatsuba
    product (z0 | z2 | z1-of-half-sums) without the per-product dispatch
    + HBM round-trips that sank the composed variant. Same digit bounds
    as _make_prod_kernel at half the row count."""

    def kernel(a0_ref, b0_ref, a1_ref, b1_ref, sa_ref, sb_ref, out_ref, acc_ref):
        for idx, (a_ref, b_ref) in enumerate(
            ((a0_ref, b0_ref), (a1_ref, b1_ref), (sa_ref, sb_ref))
        ):
            acc_ref[:, :] = jnp.zeros((2 * h + GROUP, TB), jnp.uint32)
            _accumulate_prod(
                lambda i, r=a_ref: r[pl.ds(i, 1), :], b_ref[:, :], acc_ref, h, TB
            )
            out_ref[pl.ds(idx * 2 * h, 2 * h), :] = acc_ref[0 : 2 * h, :]

    return kernel


@functools.lru_cache(maxsize=None)
def _prod3_call(h: int, B: int, TB: int, interpret: bool):
    kernel = _make_prod3_kernel(h, TB)
    spec = pl.BlockSpec((h, TB), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(B // TB,),
        in_specs=[spec] * 6,
        out_specs=pl.BlockSpec(
            (6 * h, TB), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((6 * h, B), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((2 * h + GROUP, TB), jnp.uint32)],
        interpret=interpret,
    )


def _karatsuba_combine(z0c, z2c, z1, sa, ca, sb, cb, h: int, L: int):
    """The proof-carrying Karatsuba recombination, shared by the composed
    (prod_lm_k1, XLA values) and fused (_make_kfused_kernel, in-kernel
    values) variants — ONE copy of the borrow-free complement-add math.

    Inputs: canonical half products z0c/z2c (2h, B); redundant middle
    product z1 (2h, B) of the normalized half sums sa/sb (h, B) with
    overflow bits ca/cb (1, B) in {0,1}. Returns the (2L, B) redundant
    accumulator T = z0 + [z1_full - z0 - z2]*X + z2*X^2 (see prod_lm_k1's
    docstring for the digit bounds and the exactly-2 carry-out proof)."""
    rows = 2 * h + 1
    # z1_full over `rows` digits: cross terms of the overflow bits
    z1f = jnp.pad(z1, ((0, 1), (0, 0)))
    z1f = z1f.at[h : 2 * h].add(sb * ca)
    z1f = z1f.at[h : 2 * h].add(sa * cb)
    z1f = z1f.at[2 * h].add((ca * cb)[0])
    # borrow-free middle term: complement-add the canonicalized z0/z2
    comp0 = jnp.pad(MASK16 - z0c, ((0, 1), (0, 0)), constant_values=0xFFFF)
    comp2 = jnp.pad(MASK16 - z2c, ((0, 1), (0, 0)), constant_values=0xFFFF)
    t = z1f + comp0 + comp2
    t = t.at[0:1].add(2)
    mid, _ = carry_norm(t)   # carry-out is exactly 2; digits carry the value
    B = z1.shape[1]
    T = jnp.zeros((2 * L, B), jnp.uint32)
    T = T.at[0 : 2 * h].add(z0c)
    T = T.at[h : h + rows].add(mid)
    T = T.at[2 * h :].add(z2c)
    return T


def _make_kfused_kernel(L: int, TB: int):
    """FULLY fused Karatsuba product: the three half-size schoolbook
    products AND the recombination (carry normalizations, complement-add
    middle term, shifted assembly) all inside ONE kernel, VMEM-resident.

    This is the lever the measured prod_lm_k1 verdict names: the composed
    variant's 25% multiply saving was eaten by the combine's XLA-side HBM
    passes; here the combine's carry_norm/assembly arithmetic runs on
    in-register values, so only (a, b) in and T out touch HBM — the same
    traffic as the plain schoolbook kernel. Math and digit bounds are
    identical to prod_lm_k1 (see its docstring); `carry_norm` is pure
    jnp shifts/masks and traces inside Pallas unchanged."""
    h = L // 2

    def kernel(a_ref, b_ref, out_ref, acc_ref, sa_ref):
        # normalized half sums + their 0/1 overflow bits. Only the a-side
        # operand of a product needs a ref (dynamic per-row reads inside
        # the accumulate loop); b-sides are consumed whole as values, so
        # sb never round-trips VMEM.
        sa, ca = carry_norm(a_ref[0:h, :] + a_ref[h:L, :])
        sb, cb = carry_norm(b_ref[0:h, :] + b_ref[h:L, :])
        sa_ref[:, :] = sa

        def prod(a_read, b):
            acc_ref[:, :] = jnp.zeros((2 * h + GROUP, TB), jnp.uint32)
            _accumulate_prod(a_read, b, acc_ref, h, TB)
            return acc_ref[0 : 2 * h, :]

        z0 = prod(lambda i: a_ref[pl.ds(i, 1), :], b_ref[0:h, :])
        z0c, _ = carry_norm(z0)
        z2 = prod(lambda i: a_ref[pl.ds(h + i, 1), :], b_ref[h:L, :])
        z2c, _ = carry_norm(z2)
        z1 = prod(lambda i: sa_ref[pl.ds(i, 1), :], sb)

        out_ref[:, :] = _karatsuba_combine(z0c, z2c, z1, sa, ca, sb, cb, h, L)

    return kernel


@functools.lru_cache(maxsize=None)
def _kfused_call(L: int, B: int, TB: int, interpret: bool):
    h = L // 2
    kernel = _make_kfused_kernel(L, TB)
    spec = pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(B // TB,),
        in_specs=[spec, spec],
        out_specs=pl.BlockSpec((2 * L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * L, B), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2 * h + GROUP, TB), jnp.uint32),
            pltpu.VMEM((h, TB), jnp.uint32),
        ],
        interpret=interpret,
    )


def prod_lm_kf(a, b, TB: int | None = None, interpret: bool | None = None):
    """Fused-Karatsuba full product, limbs-major (L,B)x(L,B)->(2L,B).
    Same contract as prod_lm/prod_lm_k1; requires L even with L/2 a
    multiple of GROUP (falls back to prod_lm otherwise)."""
    if interpret is None:
        interpret = _interpret_default()
    L = a.shape[0]
    if TB is None:
        TB = _tb_for(L)
    if L % 2 or (L // 2) % GROUP:
        return prod_lm(a, b, TB, interpret)
    a, B = _pad_lanes(a, TB)
    b, _ = _pad_lanes(b, TB)
    return _kfused_call(L, a.shape[1], TB, interpret)(a, b)[:, :B]


def _pad_lanes(x, TB: int):
    B = x.shape[1]
    Bp = max(TB, ((B + TB - 1) // TB) * TB)
    if Bp != B:
        x = jnp.pad(x, ((0, 0), (0, Bp - B)))
    return x, B


def prod_lm(a, b, TB: int | None = None, interpret: bool | None = None):
    """Full product of canonical limbs-major operands: (L,B)x(L,B)->(2L,B).

    Handles any L: operands are zero-padded on the limb axis to a multiple
    of GROUP for the kernel (zero top limbs don't change the value) and the
    output is sliced back to 2L rows (the padded product's top rows are
    provably zero). TB=None picks the measured per-L lane tile (_tb_for)."""
    if interpret is None:
        interpret = _interpret_default()
    L = a.shape[0]
    if TB is None:
        TB = _tb_for(L)
    Lp = ((L + GROUP - 1) // GROUP) * GROUP
    if Lp != L:
        a = jnp.pad(a, ((0, Lp - L), (0, 0)))
        b = jnp.pad(b, ((0, Lp - L), (0, 0)))
    a, B = _pad_lanes(a, TB)
    b, _ = _pad_lanes(b, TB)
    return _prod_call(Lp, a.shape[1], TB, interpret)(a, b)[: 2 * L, :B]


def prod_lm_k1(a, b, TB: int | None = None, interpret: bool | None = None):
    """One Karatsuba level over prod_lm: 3 half-size schoolbook products
    instead of 1 full-size one — 25% fewer VPU u32 multiplies, the v2
    kernel's dominant cost. Composed entirely from existing primitives:

        a = a0 + a1*X, b = b0 + b1*X  with X = 2^(16h), h = L/2
        T = z0 + [z1 - z0 - z2]*X + z2*X^2,  z1 = (a0+a1)(b0+b1)

    The half sums are carry-normalized into canonical h-limb digits plus a
    0/1 overflow bit each (the bit's cross terms are cheap masked adds), so
    the half-size products stay within prod_lm's 16-bit-digit contract.
    The middle-term subtraction runs borrow-free as a complement add: with
    rows = 2h+1 and canonical z0c/z2c,
        t = z1_full + comp(z0c) + comp(z2c) + 2
          = mid + 2*2^(16*rows)
    so after carry_norm the carry-out is exactly 2 and the canonical
    digits ARE the middle term. Digit bounds: every accumulated vector
    stays < 2^27, far under carry_norm's 2^31 input bound.

    Returns the same (2L, B) redundant accumulator shape as prod_lm; only
    the digit decomposition differs, which _redc's carry normalization
    absorbs. Requires L even with L/2 a multiple of GROUP (all supported
    key sizes; falls back to prod_lm otherwise).

    MEASURED VERDICT (v5e, sustained fold): the 25% multiply saving does
    not survive the XLA-side combine — ~4% SLOWER at L=256 (17.0 vs
    16.4 ms @ K=32768) and only ~3.5% faster at L=512 (14.0 vs 14.5 ms
    @ K=8192), and fusing all three half-products into ONE dispatch
    (_prod3_call, used here) moved those numbers by <1% vs the composed
    three-dispatch form — so the cost is the combine's HBM passes
    (2 carry_norms + complement adds + assembly over (2h..2L, B) arrays),
    not dispatch overhead. Kept flag-gated (DDS_KARATSUBA=1) as a
    correctness-tested experiment and as the record of why the default
    stays plain schoolbook; the VMEM-combine variant this verdict calls
    for exists as DDS_KARATSUBA=2 (`prod_lm_kf`, fully in-kernel)."""
    if interpret is None:
        interpret = _interpret_default()
    L = a.shape[0]
    if TB is None:
        TB = _tb_for(L)
    if L % 2 or (L // 2) % GROUP:
        return prod_lm(a, b, TB, interpret)
    h = L // 2
    a0, a1 = a[:h], a[h:]
    b0, b1 = b[:h], b[h:]
    sa, ca = carry_norm(a0 + a1)                           # (h,B), (1,B) in {0,1}
    sb, cb = carry_norm(b0 + b1)
    ap0, B0 = _pad_lanes(a0, TB)
    bp0, _ = _pad_lanes(b0, TB)
    ap1, _ = _pad_lanes(a1, TB)
    bp1, _ = _pad_lanes(b1, TB)
    sap, _ = _pad_lanes(sa, TB)
    sbp, _ = _pad_lanes(sb, TB)
    out = _prod3_call(h, ap0.shape[1], TB, interpret)(
        ap0, bp0, ap1, bp1, sap, sbp
    )
    z0 = out[0 : 2 * h, :B0]                               # (2h, B)
    z2 = out[2 * h : 4 * h, :B0]
    z1 = out[4 * h :, :B0]
    # products < 2^(32h): the carries past 2h rows are provably zero
    z0c, _ = carry_norm(z0)
    z2c, _ = carry_norm(z2)
    return _karatsuba_combine(z0c, z2c, z1, sa, ca, sb, cb, h, L)


def _use_karatsuba() -> str | bool:
    """DDS_KARATSUBA mode (see ops/flags.karatsuba_mode — jax-free so
    validators need not import this module): False = plain schoolbook
    (the measured default), "k1" = composed variant, "fused" = the fully
    in-kernel variant (_make_kfused_kernel)."""
    from dds_tpu.ops.flags import karatsuba_mode

    return karatsuba_mode()


# ---------------------------------------------------------------------------
# XLA carry normalization (Kogge-Stone) in base 2^16 or 2^8
# ---------------------------------------------------------------------------


def _shift_up(x, k: int):
    """Digit k -> k+1 on the row axis; top rows drop off."""
    return jnp.pad(x, ((k, 0), (0, 0)))[: x.shape[0]]


def carry_norm(x, bits: int = 16):
    """Redundant digits (u32, < 2^31) -> (canonical digits, carry_out).

    x: (rows, B) base-2^bits digits, row 0 least significant. Returns
    canonical digits (< 2^bits) and the (1, B) u32 value carried out past
    the top row. Three local extract passes bound the pending carries to
    one bit; a Kogge-Stone generate/propagate prefix scan resolves the
    remaining ripple in log2(rows) passes.
    """
    mask = jnp.uint32((1 << bits) - 1)
    x = x.astype(jnp.uint32)
    rows = x.shape[0]
    carry_out = jnp.zeros((1, x.shape[1]), jnp.uint32)
    for _ in range(3):
        c = x >> bits
        x = (x & mask) + _shift_up(c, 1)
        carry_out = carry_out + c[-1:]
    # x <= mask + 1 now; resolve the single-bit ripple with carry-lookahead
    c = x >> bits
    s = x & mask
    carry_out = carry_out + c[-1:]
    a = _shift_up(c, 1)                       # pending +1s
    s = s + a                                 # <= mask + 1
    g = s > mask
    p = s == mask
    k = 1
    while k < rows:
        g = g | (p & _shift_up(g, k))
        p = p & _shift_up(p, k)
        k *= 2
    cin = _shift_up(g.astype(jnp.uint32), 1)
    carry_out = carry_out + g[-1:].astype(jnp.uint32)
    return (s + cin) & mask, carry_out


# ---------------------------------------------------------------------------
# Montgomery reduction constants: Toeplitz band matrices in base 2^8
# ---------------------------------------------------------------------------


def _digits8(v: int, count: int) -> np.ndarray:
    return np.array([(v >> (8 * i)) & 0xFF for i in range(count)], np.int32)


def _toeplitz8(digits: np.ndarray, out_rows: int, in_cols: int):
    """M[k, i] = digits[k - i] (0 <= k - i < len), as the int8 pair
    (signed_part, support_mask) with M = signed + 128 * mask."""
    d = np.zeros((out_rows, in_cols), np.int32)
    msk = np.zeros((out_rows, in_cols), np.int8)
    n = len(digits)
    for i in range(in_cols):
        lo, hi = i, min(i + n, out_rows)
        d[lo:hi, i] = digits[: hi - lo]
        msk[lo:hi, i] = 1
    signed = (d - 128 * msk.astype(np.int32)).astype(np.int8)
    return signed, msk


@dataclass(frozen=True, eq=False)
class MxuCtx:
    """Per-modulus constants for the v2 multiply."""

    ctx: ModCtx
    L8: int
    m_signed: np.ndarray = field(repr=False)   # (L8, L8) int8: N' band, mod R
    m_mask: np.ndarray = field(repr=False)
    q_signed: np.ndarray = field(repr=False)   # (2*L8, L8) int8: N band
    q_mask: np.ndarray = field(repr=False)

    @staticmethod
    @functools.lru_cache(maxsize=64)
    def make(ctx: ModCtx) -> "MxuCtx":
        L8 = 2 * ctx.L
        R = 1 << (LIMB_BITS * ctx.L)
        nprime = (-pow(ctx.n, -1, R)) % R
        m_signed, m_mask = _toeplitz8(_digits8(nprime, L8), L8, L8)
        q_signed, q_mask = _toeplitz8(_digits8(ctx.n, L8), 2 * L8, L8)
        return MxuCtx(ctx=ctx, L8=L8, m_signed=m_signed, m_mask=m_mask,
                      q_signed=q_signed, q_mask=q_mask)


def _band_dot(signed, mask, d8):
    """M @ d for digit vectors d8 in [0, 255], via two int8 matmuls.

    M = signed + 128*mask, d = d' + 128*support (support = all-ones over
    the L8 input rows). The constant pieces fold into per-row sums that
    depend only on the matrices, but computing them against the actual
    all-ones support costs nothing extra because XLA folds them — so for
    clarity: M@d = signed@d' + 128*(mask@d') + 128*(signed@ones) +
    2^14*(mask@ones), with the last two terms precomputed at trace time.
    """
    dprime = (d8 - 128).astype(jnp.int8)
    s = jax.lax.dot(signed.astype(jnp.int8), dprime,
                    preferred_element_type=jnp.int32)
    m = jax.lax.dot(mask.astype(jnp.int8), dprime,
                    preferred_element_type=jnp.int32)
    ones = jnp.ones((signed.shape[1], 1), jnp.int8)
    srow = jax.lax.dot(signed.astype(jnp.int8), ones,
                       preferred_element_type=jnp.int32)
    mrow = jax.lax.dot(mask.astype(jnp.int8), ones,
                       preferred_element_type=jnp.int32)
    return s + 128 * m + 128 * srow + (1 << 14) * mrow


def _split8(x16):
    """(L, B) canonical 16-bit digits -> (2L, B) base-2^8 digits (i32)."""
    L, B = x16.shape
    x16 = x16.astype(jnp.int32)
    lo = x16 & MASK8
    hi = x16 >> 8
    return jnp.stack([lo, hi], axis=1).reshape(2 * L, B)


def _merge8(q8):
    """(rows8, B) base-2^8 digits (< 2^11 after pre-pass) -> base-2^16."""
    rows8, B = q8.shape
    pair = q8.reshape(rows8 // 2, 2, B)
    return (pair[:, 0, :] + (pair[:, 1, :] << 8)).astype(jnp.uint32)


def _prenorm8(q, passes: int = 2):
    """Two local base-2^8 extract passes: digits < 2^25 -> < 2^11
    (pass 1: < 2^8 + 2^17, pass 2: < 2^8 + 2^10), so the 8->16 merge
    stays < 2^11*2^8 + 2^11 < 2^20, far from u32 overflow. Carries out of
    the top row cannot occur: all digits are nonnegative and the value
    fits the row span, so the top digit is always below the base."""
    q = q.astype(jnp.uint32)
    for _ in range(passes):
        q = (q & 0xFF) + _shift_up(q >> 8, 1)
    return q


# ---------------------------------------------------------------------------
# the v2 multiply and fold
# ---------------------------------------------------------------------------


def _redc(mctx: MxuCtx, T):
    """Montgomery reduction of the redundant product T (2L, B) -> (L, B)
    canonical, = value(T) * R^-1 mod n, for value(T) < n*R."""
    ctx = mctx.ctx
    L = ctx.L

    Tlo, cL = carry_norm(T[:L])
    Thi = T[L:].at[0:1].add(cL)

    d8 = _split8(Tlo)
    m_red = _band_dot(mctx.m_signed, mctx.m_mask, d8)      # (L8, B) >= 0
    m8, _ = carry_norm(m_red, bits=8)                      # mod R: drop carry

    q_red = _band_dot(mctx.q_signed, mctx.q_mask, m8.astype(jnp.int32))
    q16 = _merge8(_prenorm8(q_red))                        # (2L, B) < 2^19

    s_lo = Tlo + q16[:L]                                   # (T + q) mod R...
    zeros, u = carry_norm(s_lo)                            # ...== 0: digits
    del zeros                                              # provably zero
    t_red = (Thi + q16[L:]).at[0:1].add(u)                 # (T + q) / R
    t, c_top = carry_norm(t_red)                           # t + c_top*R < 2n

    # conditional subtract via complement add: t - N + R
    comp = jnp.asarray((MASK16 - ctx.N).astype(np.uint32))[:, None]
    w = t + comp
    w = w.at[0:1].add(1)
    diff, borrow = carry_norm(w)
    take_diff = (borrow + c_top) >= 1                      # t >= N
    return jnp.where(take_diff, diff, t)


def mul2_lm(mctx: MxuCtx, a, b, interpret: bool | None = None,
            karatsuba: bool | str | None = None):
    """Montgomery product a*b*R^-1 mod n, limbs-major (L, B) canonical.

    `karatsuba` must be passed EXPLICITLY by traced callers (their jit
    caches key on it); None reads the DDS_KARATSUBA env flag. Modes:
    False = schoolbook, "k1"/True = composed Karatsuba, "fused" =
    in-kernel Karatsuba (see _use_karatsuba)."""
    mode = _use_karatsuba() if karatsuba is None else karatsuba
    if mode == "fused":
        T = prod_lm_kf(a, b, interpret=interpret)
    elif mode:  # "k1" or legacy True
        T = prod_lm_k1(a, b, interpret=interpret)
    else:
        T = prod_lm(a, b, interpret=interpret)
    return _redc(mctx, T)


# ---------------------------------------------------------------------------
# v2 modexp: 4-bit windowed ladder over mul2_lm (lax.scan over the digits)
# ---------------------------------------------------------------------------


def _pow2_body(mctx: MxuCtx, E: int, interpret: bool, karatsuba: bool):
    """The traced ladder body (un-jitted): callers that already run under a
    transform (jit in _pow2_fn, shard_map in parallel/mesh.py) close over
    this directly."""
    ctx = mctx.ctx
    mul = functools.partial(mul2_lm, karatsuba=karatsuba)

    def run(bases, digits):
        x = bases.T                                           # (L, B)
        shape = x.shape
        r2 = jnp.broadcast_to(jnp.asarray(ctx.R2)[:, None], shape)
        xm = mul(mctx, x, r2, interpret)                  # to mont
        onem = jnp.broadcast_to(
            jnp.asarray(ctx.one_mont)[:, None], shape
        ).astype(jnp.uint32)
        # windowed table x^0..x^15 in the Montgomery domain (15 multiplies,
        # amortized over E digits; digit 0 multiplies by the identity so the
        # scan body stays branch-free)
        tab = [onem, xm]
        for _ in range(2, 16):
            tab.append(mul(mctx, tab[-1], xm, interpret))
        table = jnp.stack(tab, axis=0)                        # (16, L, B)
        acc = jnp.take(table, digits[0], axis=0)

        def step(acc, d):
            for _ in range(4):                                # window bits
                acc = mul(mctx, acc, acc, interpret)
            acc = mul(mctx, acc, jnp.take(table, d, axis=0), interpret)
            return acc, None

        if E > 1:
            acc, _ = jax.lax.scan(step, acc, digits[1:])
        one = jnp.asarray(bn.ones_batch(1, ctx.L)).T          # (L, 1)
        out = mul2_lm(
            mctx, acc, jnp.broadcast_to(one, shape), interpret
        )                                                     # from mont
        return out.T

    return run


@functools.lru_cache(maxsize=None)
def _pow2_fn(mctx: MxuCtx, E: int, interpret: bool, karatsuba: bool):
    return jax.jit(_pow2_body(mctx, E, interpret, karatsuba))


def pow_mod2(mctx: MxuCtx, bases, exp: int, interpret: bool | None = None):
    """Plain-domain bases^exp mod n via the v2 multiply; (B, L) in/out.
    Contract identical to pallas_mont.pow_mod / ModCtx.pow_mod.

    vs the v1 fused ladder (back-to-back on a v5e @ B=256, L=256, 64-bit
    exp, benchmarks/kernel_compare): ~1.7x faster sustained (7.5 vs
    12.7 ms/batch) and ~1.75x lower single-dispatch latency (48 vs 84 ms)
    — the MXU REDC removes most of the VPU multiply work, which outweighs
    the per-multiply HBM round-trips v1 avoids by keeping its chain
    VMEM-resident. The serving backend uses this variant whenever folds
    use v2 (the TPU default)."""
    from dds_tpu.ops.montgomery import _exp_to_digits

    if interpret is None:
        interpret = _interpret_default()
    if exp == 0:
        return jnp.asarray(bn.ones_batch(bases.shape[0], mctx.ctx.L))
    digits = jnp.asarray(_exp_to_digits(exp).astype(np.int32))
    from dds_tpu.obs import kprof

    fn = kprof.counted(
        "mont_mxu.pow2", _pow2_fn,
        mctx, int(digits.shape[0]), interpret, _use_karatsuba(),
    )
    return fn(jnp.asarray(bases), digits)


@functools.lru_cache(maxsize=None)
def _reduce2_fn(mctx: MxuCtx, P2: int, interpret: bool, karatsuba: bool):
    def run(cs, fix):
        x = cs.T
        w = P2
        while w > 1:
            h = w // 2
            x = mul2_lm(mctx, x[:, :h], x[:, h : 2 * h], interpret, karatsuba)
            w = h
        x = mul2_lm(mctx, x[:, :1], fix[:, None], interpret, karatsuba)
        return x[:, :1].T

    return jax.jit(run)


def reduce_mul2(mctx: MxuCtx, cs, interpret: bool | None = None):
    """v2 modular product of all K rows of cs ((K, L) plain domain).

    Contract identical to pallas_mont.reduce_mul / ModCtx.reduce_mul."""
    from dds_tpu.ops.pallas_mont import _fold_fix

    if interpret is None:
        interpret = _interpret_default()
    ctx = mctx.ctx
    cs = jnp.asarray(cs)
    K = cs.shape[0]
    P2 = 1 << max(1, (K - 1).bit_length())
    if P2 != K:
        pad = jnp.broadcast_to(jnp.asarray(ctx.one_mont), (P2 - K, ctx.L))
        cs = jnp.concatenate([cs, pad], axis=0)
    from dds_tpu.obs import kprof

    fn = kprof.counted(
        "mont_mxu.reduce2", _reduce2_fn, mctx, P2, interpret, _use_karatsuba()
    )
    return fn(cs, _fold_fix(ctx, K))
