"""Spyglass predicate kernels: batched device predicate evaluation.

The `Search*`/`Order*`/`Range` routes are selection problems — the 0/1-row
cousin of Prism's selector-matrix `GroupBySum` (PC-MM, arxiv 2504.14497):
given every stored record's column ciphertext, produce a selection mask
(or a sort permutation) in ONE device dispatch instead of a host Python
loop over N records. GME (arxiv 2309.11001) makes the complementary
point: the win comes from comparing against material that is already
device-resident, not re-moved per query — the SearchPlane
(dds_tpu/search) keeps the packed columns pinned and calls down here.

Operand encodings (device side is x64-OFF JAX, so nothing is wider than
uint32):

- OPE ciphertexts (models/ope: `enc(x) = (x + 2^31) * 2^20 + prf`, ≤ 52
  bits, strictly order-preserving) split into two 26-bit lanes
  ``hi = c >> 26, lo = c & (2^26 - 1)``; lexicographic (hi, lo) compare
  IS integer compare, and a two-key `jax.lax.sort` over the lanes IS
  integer ordering. Descending order reuses the same stable sort over the
  complemented lanes (an order-reversing bijection on 26-bit values), so
  ties keep the ascending row order exactly like Python's stable
  `sorted(..., reverse=True)`.
- DET/CHE and LSE-tag equality operands are blake2b-64 digests of the
  ciphertext STRING, split into two uint32 lanes. Digest equality is a
  candidate filter only — 64-bit collisions are possible, so callers must
  confirm candidates against the exact strings host-side (the SearchPlane
  does, via hmac.compare_digest) to keep results bit-for-bit equal to the
  legacy scan.

Dispatch discipline matches ops/foldmany: one module-level `_FN_CACHE`
keyed by op family (shapes retrace under a single entry), lookups
accounted via `kprof.cache_event("predicate", ...)`, every dispatch
timed through `kprof.profiled("predicate", ...)` so `kernel.predicate.*`
spans and histograms line up with the fold kernels'.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

from dds_tpu.obs import kprof

_FN_CACHE: dict = {}
_FN_CACHE_MAX = 64
_FN_CACHE_LOCK = threading.Lock()

# 52-bit OPE ciphertexts split into two 26-bit lanes (see module docstring)
LANE_BITS = 26
LANE_MASK = (1 << LANE_BITS) - 1
# largest integer the two-lane packing can represent; values outside
# [0, PACK_MAX] (foreign plaintext ints, negative thresholds) make the
# caller fall back to its host evaluation path
PACK_MAX = (1 << (2 * LANE_BITS)) - 1


def packable(v: int) -> bool:
    return 0 <= v <= PACK_MAX


def pack_ints(values) -> tuple[np.ndarray, np.ndarray]:
    """(hi, lo) uint32 lane arrays for a column of packable ints."""
    n = len(values)
    hi = np.fromiter((v >> LANE_BITS for v in values), np.uint32, n)
    lo = np.fromiter((v & LANE_MASK for v in values), np.uint32, n)
    return hi, lo


def digest_lanes(s: str) -> tuple[int, int]:
    """blake2b-64 of a ciphertext string as two uint32 lanes."""
    d = hashlib.blake2b(s.encode(), digest_size=8).digest()
    return int.from_bytes(d[:4], "big"), int.from_bytes(d[4:], "big")


def pack_digests(values) -> tuple[np.ndarray, np.ndarray]:
    """(hi, lo) uint32 digest-lane arrays for a column of strings."""
    n = len(values)
    pairs = [digest_lanes(s) for s in values]
    hi = np.fromiter((p[0] for p in pairs), np.uint32, n)
    lo = np.fromiter((p[1] for p in pairs), np.uint32, n)
    return hi, lo


def _fn_cache_put(key, fn) -> None:
    """foldmany's eviction discipline: FIFO-capped insert under the lock.
    Shapes are NOT in the key — jit retraces per input shape under one
    entry per op family."""
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn


def _lex_gt(hi, lo, thi, tlo):
    return (hi > thi) | ((hi == thi) & (lo > tlo))


def _lex_ge(hi, lo, thi, tlo):
    return (hi > thi) | ((hi == thi) & (lo >= tlo))


def compare_mask(hi: np.ndarray, lo: np.ndarray, op: str,
                 threshold: int) -> np.ndarray:
    """Boolean mask of rows whose packed value satisfies `op threshold`.

    op in {"gt", "ge", "lt", "le"}; threshold must be packable (the
    caller clamps or falls back otherwise).
    """
    import jax
    import jax.numpy as jnp

    key = ("cmp", op)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("predicate", hit=fn is not None)
    if fn is None:
        def run(hi, lo, thi, tlo):
            ge = _lex_ge(hi, lo, thi, tlo)
            gt = _lex_gt(hi, lo, thi, tlo)
            return {"gt": gt, "ge": ge, "lt": ~ge, "le": ~gt}[op]

        fn = jax.jit(run)
        _fn_cache_put(key, fn)
    thi = np.uint32(threshold >> LANE_BITS)
    tlo = np.uint32(threshold & LANE_MASK)
    out = kprof.profiled(
        "predicate",
        lambda: fn(jnp.asarray(hi), jnp.asarray(lo), thi, tlo),
        op=op, n=int(hi.shape[0]),
    )
    return np.asarray(out)


def range_mask(hi: np.ndarray, lo: np.ndarray, lo_bound: int,
               hi_bound: int) -> np.ndarray:
    """Boolean mask of rows with lo_bound <= value <= hi_bound (both
    bounds packable)."""
    import jax
    import jax.numpy as jnp

    key = ("cmp", "range")
    fn = _FN_CACHE.get(key)
    kprof.cache_event("predicate", hit=fn is not None)
    if fn is None:
        def run(hi, lo, ahi, alo, bhi, blo):
            return _lex_ge(hi, lo, ahi, alo) & ~_lex_gt(hi, lo, bhi, blo)

        fn = jax.jit(run)
        _fn_cache_put(key, fn)
    out = kprof.profiled(
        "predicate",
        lambda: fn(
            jnp.asarray(hi), jnp.asarray(lo),
            np.uint32(lo_bound >> LANE_BITS), np.uint32(lo_bound & LANE_MASK),
            np.uint32(hi_bound >> LANE_BITS), np.uint32(hi_bound & LANE_MASK),
        ),
        op="range", n=int(hi.shape[0]),
    )
    return np.asarray(out)


def eq_mask(dhi: np.ndarray, dlo: np.ndarray, query: str) -> np.ndarray:
    """Candidate mask of rows whose digest lanes equal the query's.
    Collisions are possible — confirm candidates host-side."""
    import jax
    import jax.numpy as jnp

    key = ("digest", "eq")
    fn = _FN_CACHE.get(key)
    kprof.cache_event("predicate", hit=fn is not None)
    if fn is None:
        fn = jax.jit(lambda dhi, dlo, qhi, qlo: (dhi == qhi) & (dlo == qlo))
        _fn_cache_put(key, fn)
    qhi, qlo = digest_lanes(query)
    out = kprof.profiled(
        "predicate",
        lambda: fn(jnp.asarray(dhi), jnp.asarray(dlo),
                   np.uint32(qhi), np.uint32(qlo)),
        op="eq", n=int(dhi.shape[0]),
    )
    return np.asarray(out)


def entry_mask(dhi: np.ndarray, dlo: np.ndarray, valid: np.ndarray,
               queries: list[str], mode: str) -> np.ndarray:
    """Candidate mask over an (N, C) element-digest matrix.

    mode "any": rows where ANY valid element matches ANY query
    (SearchEntry with one query, SearchEntryOR with three).
    mode "all": rows where EVERY query matches some valid element
    (SearchEntryAND). Candidates only — confirm host-side.
    """
    import jax
    import jax.numpy as jnp

    key = ("entry", mode)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("predicate", hit=fn is not None)
    if fn is None:
        def run(dhi, dlo, valid, qhi, qlo):
            # (N, C, Q) element-vs-query digest equality, masked to real
            # (non-padding) elements
            m = (
                (dhi[:, :, None] == qhi[None, None, :])
                & (dlo[:, :, None] == qlo[None, None, :])
                & valid[:, :, None]
            )
            per_query = m.any(axis=1)  # (N, Q): query matched in row
            if mode == "all":
                return per_query.all(axis=1)
            return per_query.any(axis=1)

        fn = jax.jit(run)
        _fn_cache_put(key, fn)
    pairs = [digest_lanes(q) for q in queries]
    qhi = np.asarray([p[0] for p in pairs], np.uint32)
    qlo = np.asarray([p[1] for p in pairs], np.uint32)
    out = kprof.profiled(
        "predicate",
        lambda: fn(jnp.asarray(dhi), jnp.asarray(dlo), jnp.asarray(valid),
                   jnp.asarray(qhi), jnp.asarray(qlo)),
        op=f"entry_{mode}", n=int(dhi.shape[0]),
    )
    return np.asarray(out)


def sort_perm(hi: np.ndarray, lo: np.ndarray, descending: bool) -> np.ndarray:
    """Stable sort permutation over the packed column: row indices in
    ascending (or descending) value order, ties keeping row order — the
    device twin of Python's stable `sorted` by value."""
    import jax
    import jax.numpy as jnp

    key = ("sort", descending)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("predicate", hit=fn is not None)
    if fn is None:
        def run(hi, lo):
            if descending:
                # complementing both 26-bit lanes reverses the
                # lexicographic order while the stable sort keeps ties in
                # ascending row order — exactly sorted(reverse=True)
                hi = LANE_MASK - hi
                lo = LANE_MASK - lo
            idx = jnp.arange(hi.shape[0], dtype=jnp.int32)
            _, _, perm = jax.lax.sort((hi, lo, idx), num_keys=2,
                                      is_stable=True)
            return perm

        fn = jax.jit(run)
        _fn_cache_put(key, fn)
    out = kprof.profiled(
        "predicate",
        lambda: fn(jnp.asarray(hi), jnp.asarray(lo)),
        op="sort_desc" if descending else "sort_asc", n=int(hi.shape[0]),
    )
    return np.asarray(out)
