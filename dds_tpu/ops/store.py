"""Content-addressed device-resident ciphertext store.

The proxy's aggregates (`SumAll`/`MultAll`, `dds/http/DDSRestServer.scala:
397-446,491-540`) fold the same stored ciphertexts on every request; the
reference re-runs a JVM BigInteger loop over them each time. Here the limb
decompositions live in TPU HBM between requests: each distinct ciphertext
*value* is ingested once (int -> 16-bit limbs -> device row) and every
subsequent aggregate gathers resident rows on-device and tree-reduces.

Content addressing (ciphertext int -> row) is what keeps the dependability
story intact: the proxy still performs full ABD quorum reads per aggregate
— the store only memoizes the transfer/limb-conversion of bytes the device
has already seen, so a stale cache entry cannot exist by construction.

Capacity grows by doubling up to `max_rows`; beyond that the store resets
(entries re-ingest on demand) — simple, and an aggregate after a reset
pays exactly the one-time ingest cost again, never wrong results.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

import numpy as np

from dds_tpu.obs import kprof
from dds_tpu.obs.metrics import metrics
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.store")


@dataclass
class DeviceCipherStore:
    """Resident (rows, L) uint32 limb buffer for one modulus.

    `reduce` is the device-level fold callable ((K, L) array -> (1, L));
    backends inject theirs (TpuBackend.reduce_mul_device) so kernel
    dispatch lives in exactly one place. Default: the jnp reference path.
    """

    modulus: int
    reduce: object = None
    initial_rows: int = 256
    max_rows: int = 1 << 20  # ~1 GiB of HBM at L=256
    _ctx: ModCtx = field(init=False, repr=False)
    _buf: object = field(init=False, repr=False)   # jnp (cap, L) uint32
    _index: dict[int, int] = field(init=False, repr=False)
    _count: int = field(init=False, default=0, repr=False)

    def __post_init__(self):
        import jax.numpy as jnp

        self._ctx = ModCtx.make(self.modulus)
        if self.reduce is None:
            self.reduce = self._ctx.reduce_mul
        self._buf = jnp.zeros((self.initial_rows, self._ctx.L), jnp.uint32)
        self._index = {}
        # (cs-list identity, epoch, idx array): aggregates pass the same
        # operand list object while the proxy's caches validate unchanged,
        # so the O(K) big-int index lookups run once per distinct list.
        # The strong ref keeps the keyed list alive (identity stays unique);
        # epoch invalidates across capacity resets.
        self._idx_memo: tuple | None = None
        self._epoch = 0
        # folds may run on proxy worker threads; ingest (index+buffer
        # mutation) must be serialized. Reads gather from an immutable
        # buffer snapshot, so only `ensure` needs the lock.
        self._lock = threading.Lock()

    @property
    def resident(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        return int(self._buf.shape[0])

    def _grow(self, need: int) -> None:
        import jax.numpy as jnp

        cap = self.capacity
        while cap < need:
            cap *= 2
        if cap > self.max_rows:
            log.warning(
                "cipher store over max_rows (%d > %d): resetting", need, self.max_rows
            )
            self._index.clear()
            self._count = 0
            self._epoch += 1  # row indices changed: invalidate idx memos
            cap = max(self.initial_rows, min(cap, self.max_rows))
            self._buf = jnp.zeros((cap, self._ctx.L), jnp.uint32)
            return
        pad = jnp.zeros((cap - self.capacity, self._ctx.L), jnp.uint32)
        self._buf = jnp.concatenate([self._buf, pad], axis=0)

    def ensure(self, cs: list[int], pre: dict | None = None) -> np.ndarray | None:
        """Ingest any unseen ciphertexts; return row indices for all of cs.
        Caller must hold `_lock`. `pre` optionally maps ciphertext -> already
        limb-converted row (fold() precomputes these OUTSIDE the lock so the
        CPU-heavy conversion never serializes concurrent folds).

        Returns None when the distinct operands cannot fit even after a
        reset (aggregate wider than max_rows) — callers fall back to a
        direct, non-resident fold."""
        import jax
        import jax.numpy as jnp

        missing = sorted({c for c in cs if c not in self._index})
        if missing:
            if self._count + len(missing) > self.capacity:
                self._grow(self._count + len(missing))
                missing = sorted({c for c in cs if c not in self._index})
            if self._count + len(missing) > self.capacity:
                return None  # wider than max_rows even when empty
            if pre is not None and all(c in pre for c in missing):
                rows = np.stack([pre[c] for c in missing])
            else:
                rows = bn.ints_to_batch(
                    [c % self.modulus for c in missing], self._ctx.L
                )
            start = self._count
            self._buf = jax.lax.dynamic_update_slice(
                self._buf, jnp.asarray(rows), (start, 0)
            )
            for i, c in enumerate(missing):
                self._index[c] = start + i
            self._count += len(missing)
        return np.asarray([self._index[c] for c in cs], dtype=np.int32)

    def fold(self, cs: list[int]) -> int:
        """prod(cs) mod modulus, gathering resident rows on-device."""
        import jax.numpy as jnp

        if not cs:
            return 1 % self.modulus
        # fast path: everything resident — only a brief lock for the lookup
        with self._lock:
            m = self._idx_memo
            if m is not None and m[0] is cs and m[1] == self._epoch:
                idx = m[2]
                buf = self._buf
                missing = ()
            else:
                missing = sorted({c for c in cs if c not in self._index})
                if not missing:
                    idx = np.asarray(
                        [self._index[c] for c in cs], dtype=np.int32
                    )
                    self._idx_memo = (cs, self._epoch, idx)
                    buf = self._buf  # immutable jax array: safe outside
                else:
                    idx = buf = None
        if buf is None:
            # limb-convert the unseen operands OUTSIDE the lock (the
            # CPU-heavy part); placement/index update stays serialized.
            # Entries are only ever added, so `missing` can only shrink in
            # between; ensure() recomputes it under the lock (and converts
            # inline in the rare capacity-reset case where `pre` is short).
            converted = bn.ints_to_batch(
                [c % self.modulus for c in missing], self._ctx.L
            )
            pre = {c: converted[i] for i, c in enumerate(missing)}
            with self._lock:
                idx = self.ensure(cs, pre)
                if idx is not None:
                    self._idx_memo = (cs, self._epoch, idx)
                buf = self._buf
        if idx is None:  # aggregate wider than the store: direct fold
            rows = jnp.asarray(
                bn.ints_to_batch([c % self.modulus for c in cs], self._ctx.L)
            )
        else:
            rows = jnp.take(buf, jnp.asarray(idx), axis=0)
        metrics.inc(
            "dds_cipher_store_total", len(cs) - len(missing), outcome="resident",
            help="fold operands served from device-resident rows vs ingested",
        )
        metrics.inc("dds_cipher_store_total", len(missing), outcome="ingested",
                    help="fold operands served from device-resident rows vs ingested")
        with tracer.span("kernel.fold", k=len(cs), resident=idx is not None):
            # dispatch (trace/compile) timed apart from block_until_ready
            # device execution (obs/kprof) — the split the flat span hid
            out = kprof.profiled(
                "store.reduce", lambda: self.reduce(rows), k=len(cs),
            )
            return bn.limbs_to_int(np.asarray(out)[0])
