"""Content-addressed device-resident ciphertext store (compat surface).

The single-store `DeviceCipherStore` of the pre-Lodestone tree is now a
thin alias of `dds_tpu.resident.pool.ResidentPool` — the per-shard-group
pool family the Constellation's fused aggregates gather from (see
dds_tpu/resident/). The class keeps its name, constructor signature and
`fold`/`ensure`/`resident`/`capacity` surface here so existing backends
and tests are untouched; new code should import `ResidentPool` (and the
`ResidentPlane` that owns one per group) from `dds_tpu.resident`.

Semantics preserved from the original store: each distinct ciphertext
*value* ingests once (int -> 16-bit limbs -> device row); aggregates
gather resident rows on-device; content addressing means a stale entry
cannot exist by construction (the proxy's full quorum reads still decide
WHICH ciphertexts fold); capacity doubles up to `max_rows`, beyond which
the store resets and re-ingests on demand. One accounting fix rides the
move: the wider-than-`max_rows` direct-fold fallback now reports its
operands as `outcome="direct"` in `dds_cipher_store_total` instead of
misreporting them as resident (every limb was host-marshaled).
"""

from __future__ import annotations

from dds_tpu.resident.pool import ResidentPool


class DeviceCipherStore(ResidentPool):
    """Resident (rows, L) uint32 limb buffer for one modulus — the
    unsharded (single-pool) alias of `ResidentPool`."""


__all__ = ["DeviceCipherStore"]
