"""Pallas TPU kernels for batched Montgomery modular arithmetic.

The compiled hot path behind `ops.montgomery.ModCtx`: the pure-jnp CIOS in
that module is the portable reference; these kernels implement the same
math as single fused Pallas programs so the limb accumulator lives in
VMEM/vregs for the whole multiply instead of round-tripping HBM on every
one of the L scan steps. This is the TPU-native replacement for the
reference system's JVM ``BigInteger`` hot loop (``hlib.hj.mlib`` consumed
via ``utils/SJHomoLibProvider.scala:53-71``; proxy-side folds at
``dds/http/DDSRestServer.scala:385,423,479,518``).

Layout: **limbs-major** ``(L, B)`` uint32 — limbs on the sublane axis,
batch on the lane axis. Both CIOS operands are then in the *same* layout:
the per-step limb broadcast ``a[i, :]`` is a cheap dynamic sublane slice,
and ``b`` is consumed whole; no transposed operand copies anywhere, so
multiply chains (modexp ladders, reduction trees) stay in one layout.

CIOS step (base 2^16, uint32 lanes), accumulator t kept *redundant*
(limbs < 2^26, no carry chains inside the hot loop):

    p   = a_i * b                      (full 32-bit products)
    m   = (t[0] + lo(p)[0]) * n0' mod 2^16
    q   = m * N
    v   = t + lo(p) + lo(q)            (v[0] = 0 mod 2^16 by m's choice)
    t'  = (v >> one limb) + hi(p) + hi(q) + (v[0] >> 16 at limb 0)

Growth audit: t' <= t_shift + 2*(2^16-1) + carry0, carry0 < 2^10+2,
so after L=256 steps limbs stay < 2^26 << 2^32; products a_i*b and m*N
are exact in uint32 because a, b, N are canonical (< 2^16). The final
normalize (one O(L) carry scan) and conditional subtract run in-kernel so
outputs are canonical and chainable.

Reference-parity note: replaces the semantics of `HomoAdd.sum` /
`HomoMult.multiply` aggregate folds; exact math validated against python
`pow`/`*`//`%` in tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import WINDOW, ModCtx, _exp_to_digits

LIMB_BITS = bn.LIMB_BITS
MASK = np.uint32(bn.LIMB_MASK)

MUL_TB = 512  # lane-tile (batch columns) per grid step for the mul kernel
EXP_TB = 256  # smaller for modexp: the 16-entry window table lives in VMEM


def _pad_rows(L: int) -> int:
    """Accumulator sublane count: L plus one overflow limb, 8-aligned."""
    return ((L + 1 + 7) // 8) * 8


# ---------------------------------------------------------------------------
# kernel bodies
# ---------------------------------------------------------------------------


def _cios_loop(a_read, b, nb, n0, t0, L):
    """The shared CIOS main loop. `a_read(i)` yields limb row i as (1, TB).

    t0: (Lt, TB) initial accumulator. Returns redundant t (limbs < 2^26).
    """
    Lt, TB = t0.shape
    pad = ((0, Lt - L), (0, 0))

    def body(i, t):
        p = a_read(i) * b                      # (L, TB) sublane-broadcast mul
        lo = p & MASK
        hi = p >> LIMB_BITS
        u0 = t[0:1, :] + lo[0:1, :]
        m = (u0 * n0) & MASK                   # (1, TB)
        q = m * nb                             # (L, TB)
        v = t + jnp.pad(lo + (q & MASK), pad)
        c0 = v[0:1, :] >> LIMB_BITS
        t2 = jnp.concatenate(
            [v[1:, :], jnp.zeros((1, TB), jnp.uint32)], axis=0
        )
        add = jnp.concatenate([c0 + hi[0:1, :], hi[1:, :]], axis=0)
        return t2 + jnp.pad(add + (q >> LIMB_BITS), pad)

    return jax.lax.fori_loop(0, L, body, t0)


def _finalize(t, t_ref, nbx_ref, out_write, L):
    """Normalize redundant t to canonical limbs and conditionally subtract N.

    t: (Lt, TB) redundant value < 2n. t_ref: scratch ref, same shape.
    nbx_ref: (Lt, TB) modulus limbs broadcast (zero rows above L).
    out_write(rows) stores the final (L, TB) canonical result.
    """
    Lt, TB = t.shape
    t_ref[:, :] = t

    def norm(i, carry):
        s = t_ref[pl.ds(i, 1), :] + carry
        t_ref[pl.ds(i, 1), :] = s & MASK
        return s >> LIMB_BITS

    jax.lax.fori_loop(0, Lt, norm, jnp.zeros((1, TB), jnp.uint32))

    # borrow scan for t - N; diff rows < L land in the output buffer
    def sub_step(i, borrow):
        ti = t_ref[pl.ds(i, 1), :].astype(jnp.int32)
        ni = nbx_ref[pl.ds(i, 1), :].astype(jnp.int32)
        d = ti - ni - borrow
        neg = d < 0
        dd = jnp.where(neg, d + (1 << LIMB_BITS), d).astype(jnp.uint32)

        @pl.when(i < L)
        def _():
            out_write(pl.ds(i, 1), dd)

        return neg.astype(jnp.int32)

    borrow = jax.lax.fori_loop(
        0, Lt, sub_step, jnp.zeros((1, TB), jnp.int32)
    )
    return borrow == 1  # (1, TB): True where t < N (keep t, not diff)


def _make_mul_kernel(L: int, Lt: int, TB: int):
    def kernel(n0_ref, a_ref, b_ref, nbx_ref, out_ref, t_ref):
        n0 = n0_ref[0, 0]
        b = b_ref[:, :]
        nb = nbx_ref[0:L, :]
        t = _cios_loop(
            lambda i: a_ref[pl.ds(i, 1), :],
            b,
            nb,
            n0,
            jnp.zeros((Lt, TB), jnp.uint32),
            L,
        )
        lt = _finalize(
            t, t_ref, nbx_ref, lambda ds, v: out_ref.__setitem__((ds, slice(None)), v), L
        )
        out_ref[:, :] = jnp.where(lt, t_ref[0:L, :], out_ref[:, :])

    return kernel


def _make_exp_kernel(L: int, Lt: int, TB: int, E: int):
    """base^exp, all in Montgomery domain: 4-bit-window ladder, shared exp.

    Inputs: base (L, TB) canonical Montgomery-domain; digits (E,) int32
    MSB-first 4-bit digits in SMEM; one_mont (L, TB) broadcast R mod n.
    """

    def kernel(n0_ref, digits_ref, base_ref, nbx_ref, onem_ref, out_ref,
               tab_ref, t_ref, d_ref, a_ref):
        n0 = n0_ref[0, 0]
        nb = nbx_ref[0:L, :]

        def mul(a_val, b_val):
            # stage `a` in VMEM so its limb rows are dynamically sliceable
            a_ref[:, :] = a_val
            t = _cios_loop(
                lambda i: a_ref[pl.ds(i, 1), :],
                b_val,
                nb,
                n0,
                jnp.zeros((Lt, TB), jnp.uint32),
                L,
            )
            lt = _finalize(
                t, t_ref, nbx_ref,
                lambda ds, v: d_ref.__setitem__((ds, slice(None)), v), L
            )
            return jnp.where(lt, t_ref[0:L, :], d_ref[0:L, :])

        base = base_ref[:, :]
        onem = onem_ref[:, :]
        tab_ref[0] = onem
        tab_ref[1] = base
        acc = base
        for d in range(2, 1 << WINDOW):
            acc = mul(acc, base)
            tab_ref[d] = acc

        def digit_step(e, r):
            for _ in range(WINDOW):
                r = mul(r, r)
            digit = digits_ref[e]
            tv = tab_ref[pl.ds(digit, 1), :, :][0]
            return mul(r, tv)

        out_ref[:, :] = jax.lax.fori_loop(0, E, digit_step, onem)

    return kernel


# ---------------------------------------------------------------------------
# pallas_call wrappers (cached per shape)
# ---------------------------------------------------------------------------


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _mul_call(L: int, B: int, TB: int, interpret: bool):
    Lt = _pad_rows(L)
    grid = B // TB
    kernel = _make_mul_kernel(L, Lt, TB)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((Lt, TB), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((Lt, TB), jnp.uint32)],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _exp_call(L: int, B: int, TB: int, E: int, interpret: bool):
    Lt = _pad_rows(L)
    grid = B // TB
    kernel = _make_exp_kernel(L, Lt, TB, E)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((E,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((Lt, TB), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((L, TB), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((1 << WINDOW, L, TB), jnp.uint32),
            pltpu.VMEM((Lt, TB), jnp.uint32),
            pltpu.VMEM((Lt, TB), jnp.uint32),
            pltpu.VMEM((L, TB), jnp.uint32),
        ],
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# device-level helpers (operate on limbs-major (L, B) jnp values)
# ---------------------------------------------------------------------------


def _nbx(ctx: ModCtx, TB: int) -> np.ndarray:
    """Modulus limbs broadcast to (Lt, TB), zero rows above L."""
    Lt = _pad_rows(ctx.L)
    out = np.zeros((Lt, TB), np.uint32)
    out[: ctx.L, :] = ctx.N[:, None]
    return out

def _n0(ctx: ModCtx) -> np.ndarray:
    return np.full((1, 1), ctx.n0inv, np.uint32)


def _pad_lanes(x, TB: int):
    """Pad (L, B) on the lane axis to a multiple of TB (zeros: harmless,
    pad columns compute garbage that callers slice off)."""
    B = x.shape[1]
    Bp = max(TB, ((B + TB - 1) // TB) * TB)
    if Bp != B:
        x = jnp.pad(x, ((0, 0), (0, Bp - B)))
    return x, B


def mul_lm(ctx: ModCtx, a, b, TB: int = MUL_TB, interpret: bool | None = None):
    """Montgomery product a*b*R^-1 mod n, limbs-major (L, B) canonical."""
    if interpret is None:
        interpret = _interpret_default()
    a, B = _pad_lanes(a, TB)
    b, _ = _pad_lanes(b, TB)
    out = _mul_call(ctx.L, a.shape[1], TB, interpret)(
        _n0(ctx), a, b, _nbx(ctx, TB)
    )
    return out[:, :B]


def exp_lm(ctx: ModCtx, base_mont, digits, TB: int = EXP_TB,
           interpret: bool | None = None):
    """base^exp in Montgomery domain, limbs-major; digits (E,) int32."""
    if interpret is None:
        interpret = _interpret_default()
    base_mont, B = _pad_lanes(base_mont, TB)
    onem = jnp.broadcast_to(jnp.asarray(ctx.one_mont)[:, None], (ctx.L, TB))
    out = _exp_call(ctx.L, base_mont.shape[1], TB, int(digits.shape[0]), interpret)(
        _n0(ctx), digits.astype(jnp.int32), base_mont, _nbx(ctx, TB), onem
    )
    return out[:, :B]


# ---------------------------------------------------------------------------
# public API: batch-major (B, L) in/out, mirroring ModCtx semantics
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _reduce_fn(ctx: ModCtx, P2: int, interpret: bool):
    """Jitted tree-reduction over (P2, L) batch-major input (P2 a power of
    two). The K-dependent R^K domain fixup enters as a runtime argument so
    one compiled executable serves every fold length with the same P2."""
    TB = MUL_TB

    def run(cs, fix):
        x = cs.T                                   # (L, P2)
        w = P2
        while w > 1:
            h = w // 2
            x = mul_lm(ctx, x[:, :h], x[:, h : 2 * h], TB, interpret)
            w = h
        x = mul_lm(ctx, x[:, :1], fix[:, None], TB, interpret)
        return x[:, :1].T                          # (1, L)

    return jax.jit(run)


@functools.lru_cache(maxsize=512)
def _fold_fix(ctx: ModCtx, K: int):
    """Device-resident R^K mod n fixup for a K-term fold (cached: the proxy
    folds the same store size repeatedly, and the host modexp + transfer
    otherwise costs milliseconds per aggregate on tunneled platforms)."""
    R = 1 << (LIMB_BITS * ctx.L)
    return jax.device_put(bn.int_to_limbs(pow(R % ctx.n, K, ctx.n), ctx.L))


def reduce_mul(ctx: ModCtx, cs, interpret: bool | None = None):
    """Modular product of all K rows of cs ((K, L) plain domain, K >= 1).

    Same contract as ModCtx.reduce_mul: pads K to a power of two with
    R mod n (the Montgomery identity), tree-reduces with in-VMEM CIOS
    kernels, and folds the accumulated R^-(K-1) fixup (times the pads'
    R factors) into one final multiply. Returns (1, L).
    """
    if interpret is None:
        interpret = _interpret_default()
    cs = jnp.asarray(cs)
    K = cs.shape[0]
    P2 = 1 << max(1, (K - 1).bit_length())
    if P2 != K:
        pad = jnp.broadcast_to(jnp.asarray(ctx.one_mont), (P2 - K, ctx.L))
        cs = jnp.concatenate([cs, pad], axis=0)
    return _reduce_fn(ctx, P2, interpret)(cs, _fold_fix(ctx, K))


@functools.lru_cache(maxsize=None)
def _pow_fn(ctx: ModCtx, E: int, interpret: bool):
    TB = EXP_TB

    def run(bases, digits):
        x = bases.T                                # (L, B)
        r2 = jnp.asarray(ctx.R2)[:, None]
        xm = mul_lm(ctx, x, jnp.broadcast_to(r2, x.shape), TB, interpret)
        r = exp_lm(ctx, xm, digits, TB, interpret)
        one = np.zeros((ctx.L, 1), np.uint32)
        one[0, 0] = 1
        out = mul_lm(ctx, r, jnp.broadcast_to(jnp.asarray(one), r.shape), TB, interpret)
        return out.T

    return jax.jit(run)


def pow_mod(ctx: ModCtx, bases, exp: int, interpret: bool | None = None):
    """Plain-domain bases^exp mod n, shared host-int exponent; (B, L) in/out."""
    if interpret is None:
        interpret = _interpret_default()
    if exp == 0:
        return jnp.asarray(bn.ones_batch(bases.shape[0], ctx.L))
    digits = jnp.asarray(_exp_to_digits(exp).astype(np.int32))
    return _pow_fn(ctx, int(digits.shape[0]), interpret)(jnp.asarray(bases), digits)
