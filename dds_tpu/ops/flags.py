"""Environment-flag parsing for the kernel layer — deliberately jax-free
so config validation (e.g. at backend construction) never pays the
pallas import for ten lines of os.environ parsing."""

from __future__ import annotations

import os


def karatsuba_mode() -> str | bool:
    """DDS_KARATSUBA: "" / 0 -> off (plain schoolbook, the measured
    default), 1 -> the composed k1 variant (XLA-side combine; kept as the
    negative-result record), 2 / "fused" -> the fully in-kernel variant.
    Returns a mode usable as a jit cache key; unknown values fail loudly
    (a typo silently running the recorded-negative k1 variant would
    mislead every number downstream)."""
    flag = os.environ.get("DDS_KARATSUBA", "").strip().lower()
    if not flag or flag in ("0", "false", "off", "no"):
        return False
    if flag in ("2", "fused"):
        return "fused"
    if flag in ("1", "true", "on", "yes", "k1"):
        return "k1"
    raise ValueError(
        f"unknown DDS_KARATSUBA value {flag!r} (use 0, 1/k1, or 2/fused)"
    )


def analytics_max_rows(default: int = 256) -> int:
    """Per-request weight-row cap for the Prism analytics routes (MatVec
    rows / GroupBySum groups): DDS_ANALYTICS_MAX_ROWS when set, else
    `default` (the `[analytics] max-rows` config value flows in here).
    Whatever wins is validated the same loud way DDS_PROD_TB is — int,
    within [1, 65536] — so a typo fails at server construction with an
    actionable message instead of surfacing as a per-request 500. The
    ceiling bounds the weight-matrix kernel work one request can demand:
    rows x columns x exponent-width modmuls all scale with it."""
    env = os.environ.get("DDS_ANALYTICS_MAX_ROWS", "").strip()
    source = "DDS_ANALYTICS_MAX_ROWS" if env else "[analytics] max-rows"
    raw = env if env else default
    try:
        rows = int(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{source} must be an integer row count, got {raw!r}"
        ) from None
    if not 1 <= rows <= 65536:
        raise ValueError(
            f"{source} must be in [1, 65536] (per-request analytics row "
            f"cap), got {rows}"
        )
    return rows


def secret_device(default: bool = False) -> bool:
    """Sanctum device opt-in: run the secret-material CRT decrypt legs
    as a fused batched device dispatch instead of the host-only default
    (DEPLOY.md "Secret-material trust boundary (Sanctum)").
    DDS_SECRET_DEVICE when set, else `default` (the `[crypto]
    secret-device` config value flows in here). Validated the same loud
    way DDS_PROD_TB is — a typo fails at provider construction with an
    actionable message, because an operator who believes they opted
    IN (or OUT) of device residency for key material must never be
    silently wrong about it."""
    env = os.environ.get("DDS_SECRET_DEVICE", "").strip().lower()
    if not env:
        if not isinstance(default, bool):
            raise ValueError(
                "[crypto] secret-device must be a boolean, got "
                f"{default!r}"
            )
        return default
    if env in ("1", "true", "on", "yes"):
        return True
    if env in ("0", "false", "off", "no"):
        return False
    raise ValueError(
        f"unknown DDS_SECRET_DEVICE value {env!r} (use 1/true/on/yes or "
        "0/false/off/no)"
    )


def prod_tb() -> int | None:
    """DDS_PROD_TB: lane-tile override for the MXU product kernel, or None
    when unset. Validated HERE — int, positive, multiple of the 128-lane
    width — so a typo fails loudly at flag-read time with an actionable
    message instead of an opaque ValueError (or a mis-shaped kernel) deep
    inside a trace (mirrors karatsuba_mode's loud-validation policy)."""
    env = os.environ.get("DDS_PROD_TB", "").strip()
    if not env:
        return None
    try:
        tb = int(env)
    except ValueError:
        raise ValueError(
            f"DDS_PROD_TB must be an integer number of lanes, got {env!r}"
        ) from None
    if tb <= 0 or tb % 128:
        raise ValueError(
            f"DDS_PROD_TB must be a positive multiple of 128 (the TPU lane "
            f"width), got {tb}"
        )
    return tb
