"""Fixed-shape big-integer limb arithmetic for TPU.

Big integers are represented as ``(batch, L)`` arrays of ``uint32`` holding
16-bit limbs, **little-endian** (limb 0 is the least significant 16 bits).
16-bit limbs are the TPU-friendly digit size: a full 16x16 product fits a
single native ``uint32`` multiply (no 64-bit widening, which the TPU vector
unit does not have), and carry chains can be kept *redundant* (limbs are
allowed to exceed 16 bits between normalization passes) so everything
vectorizes over both the batch and limb axes.

This replaces the JVM ``BigInteger`` arithmetic that is the compute hot spot
of the reference system (Paillier/RSA modmul + modexp inside
``hlib.hj.mlib``, consumed via ``utils/SJHomoLibProvider.scala:53-71`` and the
proxy aggregate folds at ``dds/http/DDSRestServer.scala:385,423,479,518``).
Nothing here mirrors JVM code: the representation and algorithms are chosen
for the TPU's 8x128 VPU (vectorized multiply/mask/shift) and XLA's static
shapes (one compiled kernel per key size).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1  # 0xFFFF


# ---------------------------------------------------------------------------
# Host-side conversions (python int <-> limb arrays)
# ---------------------------------------------------------------------------

def n_limbs_for_bits(bits: int) -> int:
    """Number of 16-bit limbs needed for `bits`-bit integers."""
    return -(-bits // LIMB_BITS)


def int_to_limbs(x: int, L: int) -> np.ndarray:
    """Python int -> little-endian uint32 array of L 16-bit limbs."""
    if x < 0:
        raise ValueError("negative ints not representable")
    if x >> (LIMB_BITS * L):
        raise ValueError(f"{x.bit_length()}-bit int does not fit {L} limbs")
    b = x.to_bytes(2 * L, "little")
    return np.frombuffer(b, dtype="<u2").astype(np.uint32)


def limbs_to_int(arr) -> int:
    """Little-endian limb array (canonical, limbs < 2^16) -> python int.

    Canonical arrays convert via one bytes round-trip (~20x faster than a
    per-limb loop — this sits on every decrypt/extract path); arrays with
    redundant limbs >= 2^16 fall back to the exact per-limb fold."""
    a = np.asarray(arr, dtype=np.uint64)
    if not (a >> LIMB_BITS).any():
        return int.from_bytes(a.astype("<u2").tobytes(), "little")
    out = 0
    for i in range(a.shape[-1] - 1, -1, -1):
        out = (out << LIMB_BITS) + int(a[i])  # + not |: digits may carry
    return out


def ones_batch(B: int, L: int) -> np.ndarray:
    """(B, L) limb batch of the integer 1 — the shared identity used by
    the modexp shells (exp == 0 results, from-Montgomery epilogues)."""
    out = np.zeros((B, L), np.uint32)
    out[:, 0] = 1
    return out


def ints_to_batch(xs, L: int) -> np.ndarray:
    """List of python ints -> (B, L) uint32 limb batch.

    One joined bytes buffer + a single frombuffer/reshape instead of
    per-int arrays and np.stack — the cipher store's ingest path converts
    tens of thousands of ints per aggregate warm-up. to_bytes raises for
    negatives and for ints over 2*L bytes, preserving int_to_limbs's
    range checks."""
    xs = list(xs)
    if not xs:
        return np.zeros((0, L), np.uint32)
    nbytes = 2 * L
    try:
        buf = b"".join(x.to_bytes(nbytes, "little") for x in xs)
    except OverflowError as e:  # keep int_to_limbs's error contract
        raise ValueError(f"operand out of range for {L} limbs: {e}") from None
    return (
        np.frombuffer(buf, dtype="<u2")
        .astype(np.uint32)
        .reshape(len(xs), L)
    )


def batch_to_ints(batch) -> list[int]:
    b = np.asarray(batch)
    return [limbs_to_int(b[i]) for i in range(b.shape[0])]


# ---------------------------------------------------------------------------
# Device-side primitives (all pure jnp, vectorized over batch & limb axes)
# ---------------------------------------------------------------------------

def normalize(t: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fully propagate carries -> canonical limbs (< 2^16).

    ``t``: (B, K) uint32 with limbs < 2^32 - 2^16 (so limb + carry cannot
    overflow uint32). Returns (canonical (B, K), carry_out (B,)).

    Sequential over the K limb axis (a `lax.scan`) but vectorized over batch;
    this is O(K) next to the O(K^2) multiply work, so it costs ~1/K.
    """

    def step(carry, col):
        s = col + carry
        return s >> LIMB_BITS, s & LIMB_MASK

    carry, cols = jax.lax.scan(step, jnp.zeros(t.shape[0], jnp.uint32), t.T)
    return cols.T, carry


def add(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Canonical + canonical -> (canonical sum, carry_out). Shapes equal."""
    return normalize(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """a - b with borrow propagation (canonical inputs).

    Returns (diff (B, K) canonical, borrow_out (B,) — 1 where a < b, in which
    case diff is the 2^(16K)-complement value).
    """

    def step(borrow, cols):
        ai, bi = cols
        d = ai.astype(jnp.int32) - bi.astype(jnp.int32) - borrow.astype(jnp.int32)
        new_borrow = (d < 0).astype(jnp.uint32)
        d = jnp.where(d < 0, d + (1 << LIMB_BITS), d).astype(jnp.uint32)
        return new_borrow, d

    borrow, cols = jax.lax.scan(
        step, jnp.zeros(a.shape[0], jnp.uint32), (a.T, b.T)
    )
    return cols.T, borrow


def cond_sub(t: jnp.ndarray, mod: jnp.ndarray) -> jnp.ndarray:
    """Return t - mod where t >= mod else t (canonical t, (B,K); mod (K,))."""
    diff, borrow = sub(t, jnp.broadcast_to(mod, t.shape))
    return jnp.where((borrow == 1)[:, None], t, diff)


def geq(a: jnp.ndarray, mod: jnp.ndarray) -> jnp.ndarray:
    """(B,) bool: a >= mod (canonical limbs; mod (K,))."""
    _, borrow = sub(a, jnp.broadcast_to(mod, a.shape))
    return borrow == 0


def scalar_mul_small(x: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Multiply canonical (B, K) limbs by per-row 16-bit scalars s (B,).

    Returns canonical (B, K+1). Used for Paillier's (1 + m*n) fast path where
    m has been limb-decomposed already; see models/paillier.py.
    """
    p = x * s[:, None]                       # each product < 2^32
    lo = p & LIMB_MASK
    hi = p >> LIMB_BITS
    t = jnp.pad(lo, ((0, 0), (0, 1)))
    t = t.at[:, 1:].add(hi)
    out, carry = normalize(t)
    # carry out of the top limb is impossible: value < 2^16 * 2^(16K)
    del carry
    return out
