"""Segmented (multi-request) modular-product folds in ONE device dispatch.

The small-aggregate regime problem (BASELINE.md config 5): a single
SumAll over K < ~1k sets loses to a host fold because flat dispatch
latency dominates. But a proxy serving CONCURRENT small aggregates can
coalesce them — R requests' folds become one (P2*R, L) elem-major batch
that tree-reduces in one dispatch, amortizing the latency R ways (the
"consensus batch" idea of SURVEY.md §7 applied to the query plane;
the reference folds each aggregate separately and sequentially,
`dds/http/DDSRestServer.scala:397-446`).

Layout: row elem*R + req, so level halving `x[:h*R] * x[h*R:2h*R]`
multiplies elem i with elem i+h within every request at once. Each
request pads to the shared P2 with the Montgomery identity; the per-
request R^-(K_r-1) power is fixed with one final multiply by R^K_r
(same accounting as ModCtx.reduce_mul). All requests share one modulus —
the coalescer groups by modulus.

Compiled executables retrace per (P2, R); both axes are bucketed to
powers of two by the caller so the shape set stays tiny.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from dds_tpu.obs import kprof
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.flags import karatsuba_mode
from dds_tpu.ops.montgomery import ModCtx, _mont_mul_raw

_FN_CACHE: dict = {}
_FN_CACHE_MAX = 64
_FN_CACHE_LOCK = threading.Lock()


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _mul_bm(ctx: ModCtx, kernel: str, interpret: bool):
    """Batch-major (B, L) Montgomery multiply for the kernel family
    (mirrors parallel/mesh._local_fold_fn's selection)."""
    if kernel == "v2":
        from dds_tpu.ops import mont_mxu

        mctx = mont_mxu.MxuCtx.make(ctx)
        karatsuba = mont_mxu._use_karatsuba()
        return lambda a, b: mont_mxu.mul2_lm(mctx, a.T, b.T, interpret, karatsuba).T
    if kernel == "v1":
        from dds_tpu.ops import pallas_mont

        return lambda a, b: pallas_mont.mul_lm(ctx, a.T, b.T, interpret=interpret).T
    N = jnp.asarray(ctx.N)
    n0inv = jnp.uint32(ctx.n0inv)
    return lambda a, b: _mont_mul_raw(a, b, N, n0inv)


def _fold_many_fn(ctx: ModCtx, kernel: str, R: int):
    # the karatsuba mode and interpret flag are captured at build time by
    # _mul_bm, so they MUST be in the cache key (mirroring mont_mxu's
    # per-call karatsuba keying) — otherwise flipping DDS_KARATSUBA or the
    # backend mid-process would silently serve a stale compiled function
    interpret = _interpret_default()
    kmode = karatsuba_mode() if kernel == "v2" else None
    key = (ctx.n, kernel, R, interpret, kmode)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("foldmany", hit=fn is not None)
    if fn is not None:
        return fn
    mul = _mul_bm(ctx, kernel, interpret)

    def run(arr, fixes):
        # arr: (P2*R, L) elem-major plain-domain; fixes: (R, L) = R^K_r
        w = arr.shape[0] // R
        x = arr
        while w > 1:
            h = w // 2
            x = mul(x[: h * R], x[h * R : 2 * h * R])
            w = h
        return mul(x, fixes)                       # (R, L) plain domain

    fn = jax.jit(run)
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn
    return fn


def fold_many(folds: list[list[int]], modulus: int, kernel: str = "jnp") -> list[int]:
    """Modular product of each request's operand list, one device dispatch.

    Pads every fold to the shared power-of-two width and the request axis
    to a power of two (dummy folds of [1]) so compiled shapes stay few.
    """
    ctx = ModCtx.make(modulus)
    R_real = len(folds)
    Rp = 1 << max(0, (R_real - 1).bit_length())
    Kmax = max(len(f) for f in folds)
    P2 = 1 << max(0, (Kmax - 1).bit_length())

    arr = np.empty((P2, Rp, ctx.L), np.uint32)
    arr[:] = ctx.one_mont  # identity pads (elem pads + dummy requests)
    for r, f in enumerate(folds):
        arr[: len(f), r, :] = bn.ints_to_batch(f, ctx.L)
    R_ = 1 << (bn.LIMB_BITS * ctx.L)
    fixes = np.stack(
        [
            bn.int_to_limbs(pow(R_ % ctx.n, len(f), ctx.n), ctx.L)
            for f in folds
        ]
        + [bn.int_to_limbs(R_ % ctx.n, ctx.L)] * (Rp - R_real)  # dummies: K=1
    )
    fn = _fold_many_fn(ctx, kernel, Rp)
    # dispatch (trace+compile on a cold cache) vs device execute, timed
    # separately (obs/kprof): the compile-vs-execute accounting GPU/TPU HE
    # work sizes kernels by
    out = kprof.profiled(
        "foldmany",
        lambda: fn(jnp.asarray(arr.reshape(P2 * Rp, ctx.L)), jnp.asarray(fixes)),
        R=R_real, P2=P2,
    )
    return [bn.limbs_to_int(row) for row in np.asarray(out)[:R_real]]
