"""Segmented (multi-request) modular-product folds in ONE device dispatch.

The small-aggregate regime problem (BASELINE.md config 5): a single
SumAll over K < ~1k sets loses to a host fold because flat dispatch
latency dominates. But a proxy serving CONCURRENT small aggregates can
coalesce them — R requests' folds become one (P2*R, L) elem-major batch
that tree-reduces in one dispatch, amortizing the latency R ways (the
"consensus batch" idea of SURVEY.md §7 applied to the query plane;
the reference folds each aggregate separately and sequentially,
`dds/http/DDSRestServer.scala:397-446`).

Layout: row elem*R + req, so level halving `x[:h*R] * x[h*R:2h*R]`
multiplies elem i with elem i+h within every request at once. Each
request pads to the shared P2 with the Montgomery identity; the per-
request R^-(K_r-1) power is fixed with one final multiply by R^K_r
(same accounting as ModCtx.reduce_mul). All requests share one modulus —
the coalescer groups by modulus.

Compiled executables retrace per (P2, R); both axes are bucketed to
powers of two by the caller so the shape set stays tiny.

`fold_weighted` extends the same machinery to weighted folds — per-row
products of operands raised to per-(row, operand) plaintext exponents —
the plaintext-ciphertext matrix-multiplication kernel of the Prism
analytics plane (dds_tpu/analytics). It shares the compiled-fn cache,
kernel-family selection, and Montgomery contexts with fold_many.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from dds_tpu.obs import kprof
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.flags import karatsuba_mode
from dds_tpu.ops.montgomery import ModCtx, _mont_mul_raw

_FN_CACHE: dict = {}
_FN_CACHE_MAX = 64
_FN_CACHE_LOCK = threading.Lock()


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _mul_bm(ctx: ModCtx, kernel: str, interpret: bool):
    """Batch-major (B, L) Montgomery multiply for the kernel family
    (mirrors parallel/mesh._local_fold_fn's selection)."""
    if kernel == "v2":
        from dds_tpu.ops import mont_mxu

        mctx = mont_mxu.MxuCtx.make(ctx)
        karatsuba = mont_mxu._use_karatsuba()
        return lambda a, b: mont_mxu.mul2_lm(mctx, a.T, b.T, interpret, karatsuba).T
    if kernel == "v1":
        from dds_tpu.ops import pallas_mont

        return lambda a, b: pallas_mont.mul_lm(ctx, a.T, b.T, interpret=interpret).T
    N = jnp.asarray(ctx.N)
    n0inv = jnp.uint32(ctx.n0inv)
    return lambda a, b: _mont_mul_raw(a, b, N, n0inv)


def _fold_many_fn(ctx: ModCtx, kernel: str, R: int):
    # the karatsuba mode and interpret flag are captured at build time by
    # _mul_bm, so they MUST be in the cache key (mirroring mont_mxu's
    # per-call karatsuba keying) — otherwise flipping DDS_KARATSUBA or the
    # backend mid-process would silently serve a stale compiled function
    interpret = _interpret_default()
    kmode = karatsuba_mode() if kernel == "v2" else None
    key = (ctx.n, kernel, R, interpret, kmode)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("foldmany", hit=fn is not None)
    if fn is not None:
        return fn
    mul = _mul_bm(ctx, kernel, interpret)

    def run(arr, fixes):
        # arr: (P2*R, L) elem-major plain-domain; fixes: (R, L) = R^K_r
        w = arr.shape[0] // R
        x = arr
        while w > 1:
            h = w // 2
            x = mul(x[: h * R], x[h * R : 2 * h * R])
            w = h
        return mul(x, fixes)                       # (R, L) plain domain

    fn = jax.jit(run)
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn
    return fn


_WINDOW = 4  # digit width of the weighted fold's ladder (16-entry tables)


def _fold_weighted_fn(ctx: ModCtx, kernel: str):
    """Compiled weighted-fold kernel for (ctx, kernel family): shapes are
    NOT in the cache key — jit retraces per (P2, Rp, D) input shape under
    one entry, like mesh's "reduce" keys — but the karatsuba/interpret
    flags are, for the same stale-executable reason as _fold_many_fn."""
    interpret = _interpret_default()
    kmode = karatsuba_mode() if kernel == "v2" else None
    key = ("weighted", ctx.n, kernel, interpret, kmode)
    fn = _FN_CACHE.get(key)
    kprof.cache_event("fold_weighted", hit=fn is not None)
    if fn is not None:
        return fn
    mul = _mul_bm(ctx, kernel, interpret)
    one_mont = jnp.asarray(ctx.one_mont)
    R2 = jnp.asarray(ctx.R2)
    one_plain = np.zeros((ctx.L,), np.uint32)
    one_plain[0] = 1
    one_plain = jnp.asarray(one_plain)
    L = ctx.L

    def run(cs, digits):
        # cs: (P2, L) plain-domain operands; digits: (D, Rp, P2) int32
        # MSB-first 4-bit windows of each (row, operand) weight. Everything
        # runs in the Montgomery domain (entry via R2, exit via 1), so no
        # R-power bookkeeping is needed: mont_mul is closed over x~ = xR.
        P2 = cs.shape[0]
        Rp = digits.shape[1]
        cs_m = mul(cs, jnp.broadcast_to(R2, cs.shape))
        # table[d, k] = cs[k]^d for d in [0, 16): row-independent, so the
        # per-digit gather below serves every output row from one table
        tab = [jnp.broadcast_to(one_mont, cs.shape), cs_m]
        for _ in range(2, 1 << _WINDOW):
            tab.append(mul(tab[-1], cs_m))
        table = jnp.stack(tab, axis=0)             # (16, P2, L)
        kidx = jnp.arange(P2)[None, :]

        def step(acc, dig):                        # acc (Rp, L); dig (Rp, P2)
            for _ in range(_WINDOW):
                acc = mul(acc, acc)
            sel = table[dig, kidx]                 # (Rp, P2, L)
            w = P2
            x = sel
            while w > 1:                           # tree fold over operands
                h = w // 2
                x = mul(
                    x[:, :h].reshape(-1, L), x[:, h : 2 * h].reshape(-1, L)
                ).reshape(Rp, h, L)
                w = h
            return mul(acc, x[:, 0]), None

        acc0 = jnp.broadcast_to(one_mont, (Rp, L))
        acc, _ = jax.lax.scan(step, acc0, digits)
        return mul(acc, jnp.broadcast_to(one_plain, acc.shape))

    fn = jax.jit(run)
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn
    return fn


def fold_weighted(
    cs: list[int], weights: list[list[int]], modulus: int, kernel: str = "jnp",
    rows=None,
) -> list[int]:
    """Per-row weighted modular products, one device dispatch:

        out[r] = prod_j cs[j] ** weights[r][j]  mod modulus

    The PC-MM kernel behind the Prism analytics plane (arxiv 2504.14497):
    a plaintext-matrix x ciphertext-vector product over Paillier is exactly
    this shape with modulus = n^2 and negative weights pre-encoded as
    n - |w| by the caller (models/paillier.matvec_encode). Weights must be
    non-negative ints below the modulus; rows must all span len(cs).

    Structure: a shared 4-bit-window ladder over the longest weight's
    digits — per digit, 4 batched squarings of the (R, L) accumulator,
    one 16-entry table gather per (row, operand), and a halving tree fold
    over the operand axis — so the work is R*K-wide batched Montgomery
    multiplies end to end, the batch shape the MXU/VPU kernel families
    were built for. Operands pad to a power of two with 1 (weight 0),
    rows pad with all-zero weight vectors; both pads gather the identity
    table entry, so padding never perturbs results.

    Public parameters only (ciphertexts, plaintext weights, a public
    modulus): nothing here touches secret key material, so ModCtx's global
    cache and the persistent compile cache are safe — ADVICE.md's
    secret-CRT-parameter concern does not apply to this path.

    `rows` optionally supplies the operands as an already-device-resident
    (K, L) plain-domain limb array (a Lodestone pool gather,
    dds_tpu/resident): the int -> limb marshaling of `cs` is skipped and
    only the pad rows are host-built. `cs` is still required — it carries
    the operand count and the host-side weight validation.
    """
    ctx = ModCtx.make(modulus)
    K, R_real = len(cs), len(weights)
    if K == 0 or R_real == 0:
        raise ValueError("fold_weighted needs >= 1 operand and >= 1 row")
    for row in weights:
        if len(row) != K:
            raise ValueError(
                f"weight row spans {len(row)} operands, expected {K}"
            )
        for w in row:
            if w < 0 or w >= modulus:
                raise ValueError(
                    "weights must be encoded to [0, modulus) before the "
                    "kernel (negative weights: models/paillier.matvec_encode)"
                )
    P2 = 1 << max(0, (K - 1).bit_length())
    Rp = 1 << max(0, (R_real - 1).bit_length())
    if rows is not None and getattr(rows, "shape", None) == (K, ctx.L):
        arr = jnp.asarray(rows)
        if P2 != K:
            pad = jnp.asarray(bn.ints_to_batch([1] * (P2 - K), ctx.L))
            arr = jnp.concatenate([arr, pad], axis=0)
    else:
        arr = bn.ints_to_batch(list(cs) + [1] * (P2 - K), ctx.L)
    E = max((w.bit_length() for row in weights for w in row), default=0)
    D = max(1, -(-E // _WINDOW))
    digits = np.zeros((D, Rp, P2), np.int32)
    for r, row in enumerate(weights):
        for k, w in enumerate(row):
            for d in range(-(-w.bit_length() // _WINDOW)):
                digits[D - 1 - d, r, k] = (w >> (_WINDOW * d)) & 0xF
    fn = _fold_weighted_fn(ctx, kernel)
    out = kprof.profiled(
        "fold_weighted",
        lambda: fn(jnp.asarray(arr), jnp.asarray(digits)),
        R=R_real, K=K, D=D,
    )
    return [bn.limbs_to_int(row) for row in np.asarray(out)[:R_real]]


def fold_many(folds: list[list[int]], modulus: int, kernel: str = "jnp") -> list[int]:
    """Modular product of each request's operand list, one device dispatch.

    Pads every fold to the shared power-of-two width and the request axis
    to a power of two (dummy folds of [1]) so compiled shapes stay few.
    """
    ctx = ModCtx.make(modulus)
    R_real = len(folds)
    Rp = 1 << max(0, (R_real - 1).bit_length())
    Kmax = max(len(f) for f in folds)
    P2 = 1 << max(0, (Kmax - 1).bit_length())

    arr = np.empty((P2, Rp, ctx.L), np.uint32)
    arr[:] = ctx.one_mont  # identity pads (elem pads + dummy requests)
    for r, f in enumerate(folds):
        arr[: len(f), r, :] = bn.ints_to_batch(f, ctx.L)
    R_ = 1 << (bn.LIMB_BITS * ctx.L)
    fixes = np.stack(
        [
            bn.int_to_limbs(pow(R_ % ctx.n, len(f), ctx.n), ctx.L)
            for f in folds
        ]
        + [bn.int_to_limbs(R_ % ctx.n, ctx.L)] * (Rp - R_real)  # dummies: K=1
    )
    fn = _fold_many_fn(ctx, kernel, Rp)
    # dispatch (trace+compile on a cold cache) vs device execute, timed
    # separately (obs/kprof): the compile-vs-execute accounting GPU/TPU HE
    # work sizes kernels by
    out = kprof.profiled(
        "foldmany",
        lambda: fn(jnp.asarray(arr.reshape(P2 * Rp, ctx.L)), jnp.asarray(fixes)),
        R=R_real, P2=P2,
    )
    return [bn.limbs_to_int(row) for row in np.asarray(out)[:R_real]]
