"""Batched Montgomery modular arithmetic (CIOS) on 16-bit limbs.

The core kernel of the whole framework: every Paillier / RSA-multiplicative
homomorphic operation (encrypt, decrypt, homomorphic add = modmul mod n^2,
homomorphic mult = modmul mod n) reduces to batched Montgomery multiplies.
This is the TPU-native replacement for the reference's per-ciphertext JVM
``BigInteger`` folds (``dds/http/DDSRestServer.scala:412-430, 505-524``).

Design (see SURVEY.md §7):

- Numbers live as ``(B, L)`` uint32 arrays of 16-bit limbs (``ops.bignum``).
- ``mont_mul`` is CIOS: a ``lax.scan`` over the L limbs of the first operand;
  each step is fully vectorized over (batch, limbs) with *redundant* carries
  (one vectorized carry pass per step keeps limbs < 2^17, no sequential
  ripple inside the hot loop).
- ``mont_exp`` is a fixed 4-bit-window ladder over a *shared* exponent (all
  batch rows use the same exponent — true for every scheme here: Paillier
  encrypt r^n, decrypt c^lambda, RSA e/d), as a scan over exponent digits.
- ``reduce_mul`` folds K ciphertexts into their modular product with a
  binary tree of mont_muls on plain-domain inputs; the accumulated
  R^-(K-1) factor is fixed up with one extra multiply by a host-computed
  R^K mod n. This makes a K-term homomorphic SUM cost ~1 modmul per term,
  with no domain conversion of the inputs.

Carry-bound argument for the CIOS step (base b = 2^16, uint32 lanes):
limbs enter each step < 2^17 (invariant); adding the lo/hi halves of
``a_i * B`` and ``m * N`` adds < 3 * 2^16; the single vectorized carry pass
at the end of the step restores limbs to < 2^16 + 2^3 < 2^17. All
intermediate values stay < 2^19 << 2^32. The final result is normalized with
one O(L) scan and conditionally reduced below n.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from dds_tpu.ops.bignum import (
    LIMB_BITS,
    LIMB_MASK,
    int_to_limbs,
    n_limbs_for_bits,
    normalize,
    cond_sub,
)

WINDOW = 4  # modexp window size (16-entry table)


def _mont_mul_raw(a, b, N, n0inv):
    """CIOS Montgomery multiply. a, b: (B, L) canonical; N: (L,); n0inv scalar.

    Returns (B, L) canonical, < n:  a * b * R^-1 mod n, R = 2^(16 L).
    """
    B, L = a.shape

    def step(t, ai):
        # t: (B, L+1) uint32, limbs < 2^17
        p = ai[:, None] * b                       # (B, L) < 2^32
        t = t.at[:, :-1].add(p & LIMB_MASK)
        t = t.at[:, 1:].add(p >> LIMB_BITS)
        m = (t[:, 0] * n0inv) & LIMB_MASK         # (B,)
        q = m[:, None] * N[None, :]
        t = t.at[:, :-1].add(q & LIMB_MASK)
        t = t.at[:, 1:].add(q >> LIMB_BITS)
        carry0 = t[:, 0] >> LIMB_BITS             # t[:,0] = 0 mod 2^16 by construction
        t = jnp.concatenate([t[:, 1:], jnp.zeros((B, 1), jnp.uint32)], axis=1)
        t = t.at[:, 0].add(carry0)
        c = t[:, :-1] >> LIMB_BITS                # one redundant-carry pass
        t = t.at[:, :-1].set(t[:, :-1] & LIMB_MASK)
        t = t.at[:, 1:].add(c)
        return t, None

    t0 = jnp.zeros((B, L + 1), jnp.uint32)
    t, _ = jax.lax.scan(step, t0, a.T)            # scan over a's limbs
    t, carry = normalize(t)
    del carry                                     # result < 2n < 2^(16L+1): top limb holds it
    N_ext = jnp.concatenate([N, jnp.zeros((1,), jnp.uint32)])
    t = cond_sub(t, N_ext)
    return t[:, :-1]


def _mont_exp_raw(base, exp_digits, one_mont, N, n0inv):
    """Shared-exponent 4-bit-window ladder.

    base: (B, L) in Montgomery domain. exp_digits: (E,) uint32, MSB-first
    4-bit digits. Returns base^exp * R^-(...) correction-free: result is in
    Montgomery domain (base^exp in domain).
    """
    mul = lambda x, y: _mont_mul_raw(x, y, N, n0inv)

    # table[d] = base^d (Montgomery domain), d in [0, 16)
    one_b = jnp.broadcast_to(one_mont, base.shape)
    tab = [one_b, base]
    for _ in range(2, 1 << WINDOW):
        tab.append(mul(tab[-1], base))
    table = jnp.stack(tab, axis=0)                # (16, B, L)

    def step(r, digit):
        for _ in range(WINDOW):
            r = mul(r, r)
        r = mul(r, jnp.take(table, digit, axis=0))
        return r, None

    r, _ = jax.lax.scan(step, one_b, exp_digits)
    return r


def _mont_mul_rowmod_raw(a, b, N, n0inv):
    """CIOS Montgomery multiply with PER-ROW moduli.

    a, b: (B, L) canonical; N: (B, L) — each row's own modulus limbs;
    n0inv: (B,) per-row Montgomery constants. Returns (B, L) canonical,
    row i being a[i] * b[i] * R^-1 mod N[i]. The per-row twin of
    `_mont_mul_raw`: every step is already elementwise over the batch
    axis, so a per-row modulus costs nothing extra — it exists so the
    Sanctum secret-material plane (dds_tpu/sanctum) can run both CRT
    decrypt legs (moduli p^2 and q^2) as ONE stacked dispatch. The
    carry-bound argument at the top of this module holds per row
    unchanged.
    """
    B, L = a.shape

    def step(t, ai):
        p = ai[:, None] * b                       # (B, L) < 2^32
        t = t.at[:, :-1].add(p & LIMB_MASK)
        t = t.at[:, 1:].add(p >> LIMB_BITS)
        m = (t[:, 0] * n0inv) & LIMB_MASK         # (B,)
        q = m[:, None] * N
        t = t.at[:, :-1].add(q & LIMB_MASK)
        t = t.at[:, 1:].add(q >> LIMB_BITS)
        carry0 = t[:, 0] >> LIMB_BITS
        t = jnp.concatenate([t[:, 1:], jnp.zeros((B, 1), jnp.uint32)], axis=1)
        t = t.at[:, 0].add(carry0)
        c = t[:, :-1] >> LIMB_BITS
        t = t.at[:, :-1].set(t[:, :-1] & LIMB_MASK)
        t = t.at[:, 1:].add(c)
        return t, None

    t0 = jnp.zeros((B, L + 1), jnp.uint32)
    t, _ = jax.lax.scan(step, t0, a.T)
    t, carry = normalize(t)
    del carry
    N_ext = jnp.concatenate([N, jnp.zeros((B, 1), jnp.uint32)], axis=1)
    t = cond_sub(t, N_ext)
    return t[:, :-1]


def _mont_exp_rowdigits_raw(base, exp_digits, one_mont, N, n0inv):
    """Per-row-exponent 4-bit-window ladder over per-row moduli.

    base: (B, L) Montgomery domain; exp_digits: (E, B) uint32 MSB-first
    4-bit digits — row b's exponent in column b (pad shorter exponents
    with LEADING zero digits: a zero digit squares the running identity
    and multiplies by table[0] = 1, a no-op); one_mont/N: (B, L);
    n0inv: (B,). Result stays in the Montgomery domain, like
    `_mont_exp_raw`.
    """
    mul = lambda x, y: _mont_mul_rowmod_raw(x, y, N, n0inv)

    tab = [one_mont, base]
    for _ in range(2, 1 << WINDOW):
        tab.append(mul(tab[-1], base))
    table = jnp.stack(tab, axis=0)                # (16, B, L)

    def step(r, digit):                           # digit: (B,)
        for _ in range(WINDOW):
            r = mul(r, r)
        sel = jnp.take_along_axis(
            table, digit.astype(jnp.int32)[None, :, None], axis=0
        )[0]                                      # (B, L): table[digit[b], b]
        return mul(r, sel), None

    r, _ = jax.lax.scan(step, one_mont, exp_digits)
    return r


def _tree_reduce_raw(cs, N, n0inv):
    """Binary-tree modular product of cs (K, L), K a power of two.

    Inputs in *plain* domain; output = prod(cs) * R^-(K-1) mod n — the caller
    multiplies by R^K mod n via one mont_mul to fix the domain.
    """
    t = cs
    while t.shape[0] > 1:
        t = _mont_mul_raw(t[0::2], t[1::2], N, n0inv)
    return t


def _exp_to_digits(exp: int) -> np.ndarray:
    """Python int -> MSB-first 4-bit digit array (at least one digit)."""
    if exp < 0:
        raise ValueError("negative exponent")
    ndig = max(1, -(-exp.bit_length() // WINDOW))
    return np.array(
        [(exp >> (WINDOW * i)) & ((1 << WINDOW) - 1) for i in range(ndig - 1, -1, -1)],
        dtype=np.uint32,
    )


# ModCtx.make's shared cache: an explicit bounded LRU rather than a
# functools.lru_cache so its CONTENTS are inspectable — the Sanctum
# key-hygiene regression test (tests/test_sanctum.py) asserts no
# secret-derived modulus ever lands here, and tools/secret_lint.py
# treats flows into this cache as violations. Secret CRT moduli must
# use dds_tpu.sanctum's per-key SecretModCtx instead: entries here
# outlive every key object.
_CTX_CACHE: "OrderedDict[tuple[int, int | None], ModCtx]" = OrderedDict()
_CTX_CACHE_MAX = 64
_CTX_CACHE_LOCK = threading.Lock()


def cached_moduli() -> list[int]:
    """The moduli currently held by ModCtx.make's shared cache (hygiene
    introspection; see _CTX_CACHE above)."""
    with _CTX_CACHE_LOCK:
        return [k[0] for k in _CTX_CACHE]


@dataclass(frozen=True, eq=False)
class ModCtx:
    """Precomputed Montgomery context for one odd modulus n.

    Holds the device constants for n: limb decomposition N, the Montgomery
    constant n0' = -n^-1 mod 2^16, R^2 mod n (for domain entry) and
    R mod n (the domain's multiplicative identity).
    """

    n: int
    L: int
    N: np.ndarray = field(repr=False)
    n0inv: np.uint32 = field(repr=False)
    R2: np.ndarray = field(repr=False)
    one_mont: np.ndarray = field(repr=False)

    @staticmethod
    def build(n: int, L: int | None = None) -> "ModCtx":
        """An UNCACHED context. Public-parameter callers want `make`;
        this exists for contexts whose lifetime a caller manages itself
        (the Sanctum secret plane builds its per-key twins from the same
        constants without touching the shared cache)."""
        if n % 2 == 0:
            raise ValueError("Montgomery modulus must be odd")
        if L is None:
            L = n_limbs_for_bits(n.bit_length())
        R = 1 << (LIMB_BITS * L)
        if n >= R:
            raise ValueError("modulus does not fit limb count")
        n0inv = np.uint32((-pow(n % (1 << LIMB_BITS), -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        return ModCtx(
            n=n,
            L=L,
            N=int_to_limbs(n, L),
            n0inv=n0inv,
            R2=int_to_limbs((R * R) % n, L),
            one_mont=int_to_limbs(R % n, L),
        )

    @staticmethod
    def make(n: int, L: int | None = None) -> "ModCtx":
        """The cached entry point for PUBLIC moduli (n, n^2, RSA n): one
        shared context (and one set of compiled kernels hanging off it)
        per modulus, process-wide. Never call with secret-derived moduli
        — entries outlive keys; dds_tpu.sanctum owns that case."""
        key = (n, L)
        with _CTX_CACHE_LOCK:
            ctx = _CTX_CACHE.get(key)
            if ctx is not None:
                _CTX_CACHE.move_to_end(key)
                return ctx
        ctx = ModCtx.build(n, L)
        with _CTX_CACHE_LOCK:
            cached = _CTX_CACHE.get(key)
            if cached is not None:  # lost a benign build race: keep the first
                _CTX_CACHE.move_to_end(key)
                return cached
            while len(_CTX_CACHE) >= _CTX_CACHE_MAX:
                _CTX_CACHE.popitem(last=False)
            _CTX_CACHE[key] = ctx
        return ctx

    # -- jitted entry points (cached per context) ---------------------------

    @functools.cached_property
    def _jit_mont_mul(self):
        N, n0inv = jnp.asarray(self.N), jnp.uint32(self.n0inv)
        return jax.jit(lambda a, b: _mont_mul_raw(a, b, N, n0inv))

    @functools.cached_property
    def _jit_mont_exp(self):
        N, n0inv = jnp.asarray(self.N), jnp.uint32(self.n0inv)
        one = jnp.asarray(self.one_mont)
        return jax.jit(
            lambda base, digits: _mont_exp_raw(base, digits, one, N, n0inv)
        )

    @functools.cached_property
    def _jit_tree_reduce(self):
        N, n0inv = jnp.asarray(self.N), jnp.uint32(self.n0inv)
        return jax.jit(lambda cs: _tree_reduce_raw(cs, N, n0inv))

    @functools.cached_property
    def _jit_to_mont(self):
        """Device-resident R^2 closed over; broadcast happens inside jit."""
        N, n0inv = jnp.asarray(self.N), jnp.uint32(self.n0inv)
        R2 = jnp.asarray(self.R2)
        return jax.jit(
            lambda x: _mont_mul_raw(x, jnp.broadcast_to(R2, x.shape), N, n0inv)
        )

    @functools.cached_property
    def _jit_from_mont(self):
        N, n0inv = jnp.asarray(self.N), jnp.uint32(self.n0inv)
        one = np.zeros((self.L,), np.uint32)
        one[0] = 1
        one = jnp.asarray(one)
        return jax.jit(
            lambda x: _mont_mul_raw(x, jnp.broadcast_to(one, x.shape), N, n0inv)
        )

    # -- public API ---------------------------------------------------------

    def mont_mul(self, a, b):
        """(B,L) x (B,L) -> a*b*R^-1 mod n."""
        return self._jit_mont_mul(a, b)

    def to_mont(self, x):
        return self._jit_to_mont(x)

    def from_mont(self, x):
        return self._jit_from_mont(x)

    def mul_mod(self, a, b):
        """Plain-domain a*b mod n: one domain entry + one multiply."""
        return self._jit_mont_mul(self.to_mont(a), b)

    def pow_mod(self, base, exp: int):
        """Plain-domain base^exp mod n with a shared (host-int) exponent."""
        if exp == 0:
            one = np.zeros((base.shape[0], self.L), np.uint32)
            one[:, 0] = 1
            return jnp.asarray(one)
        r = self._jit_mont_exp(self.to_mont(base), jnp.asarray(_exp_to_digits(exp)))
        return self.from_mont(r)

    def reduce_mul(self, cs):
        """Modular product of all K rows of cs (plain domain, K >= 1).

        The homomorphic-SUM / PRODUCT aggregate kernel: pads K to a power of
        two with R mod n (mont_mul's identity), tree-reduces, then fixes the
        accumulated R^-(K-1) with one multiply by R^K mod n.
        """
        K = cs.shape[0]
        P2 = 1 << max(0, (K - 1).bit_length())
        if P2 != K:
            pad = jnp.broadcast_to(jnp.asarray(self.one_mont), (P2 - K, self.L))
            cs = jnp.concatenate([jnp.asarray(cs), pad], axis=0)
        prod = self._jit_tree_reduce(cs)          # prod * R^-(K-1), (1, L)
        R = 1 << (LIMB_BITS * self.L)
        fix = int_to_limbs(pow(R % self.n, K, self.n), self.L)
        return self._jit_mont_mul(prod, jnp.asarray(fix)[None, :])
