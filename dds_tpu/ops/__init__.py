"""Tier-0 compute kernels: batched big-integer / modular arithmetic on TPU."""

from dds_tpu.ops.bignum import (  # noqa: F401
    LIMB_BITS,
    LIMB_MASK,
    int_to_limbs,
    limbs_to_int,
    ints_to_batch,
    batch_to_ints,
)
from dds_tpu.ops.montgomery import ModCtx  # noqa: F401
