"""Helmsman: the fleet's self-steering loop — SLO burn in, shape out.

The reproduction's dependability story is proactive at the replica level
(supervisor swaps in sentinent spares) and reactive at the proxy
(Bulwark sheds, breakers fast-fail), but the FLEET SHAPE — how many
quorum groups serve the keyspace — was hand-steered: a human watched SLO
burn and POSTed /_reshard, and nothing ever merged capacity back.
Helmsman closes that loop. One instance per fleet, resident next to the
router it observes, flight-recorded like every other controller:

- **signals** (injected callables, the AdmissionController pattern — the
  controller owns no collection machinery and tests drive it with plain
  lambdas + a fake clock): multiwindow SLO burn (`SloEngine.alerts`),
  Bulwark shed level, breaker census, per-group routed-op share
  (`ShardRouter.load_census` deltas), resident-pool pressure, and — for
  dead-group detection — the Panopticon collector's per-source heartbeat
  ages (the span shipper beats ~1/s even when idle, so a silent group
  process is a LOUD signal).
- **actions**: `split(hot_gid)` onto a warm standby when the fleet is in
  distress and one group carries the load; `merge(cold_gid)` to fold a
  cold group back into its ring neighbors when the fleet is calm;
  `promote(dead_gid)` to relabel a dead group's keyspace onto a standby.
- **restraint** (the BTS lesson — throughput tracks how little
  ciphertext you re-move): hot/cold streak hysteresis, a cooldown after
  every action, and a sliding-window **migrated-bytes budget** charged
  with the rebalancer's actual moved bytes, so the controller prices
  every reshape in data moved and can never thrash the fleet into
  permanent migration.
- **override**: `pin()` freezes the shape (autoscaling halts, liveness
  promotion keeps running); `unpin()` resumes. The runbook knob for
  planned maintenance and incident triage.

`step()` is one synchronous-decision tick (async only because actions
are); `start()` runs it on a supervised task every `interval` seconds.
"""

from __future__ import annotations

import collections
import logging
import time

from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.tasks import supervised_task

log = logging.getLogger("dds.fleet.helmsman")


class Helmsman:
    def __init__(
        self,
        *,
        # ---- signals (callables; None disables that signal) ----
        load_census,                 # () -> {gid: cumulative routed ops}
        slo_alerts=None,             # () -> [route, ...] currently burning
        shed_level=None,             # () -> int (Bulwark shed level)
        breaker_census=None,         # () -> (trusted_total, [open ETAs])
        pool_pressure=None,          # () -> 0..1 resident-pool occupancy
        source_ages=None,            # () -> {gid: seconds since heartbeat}
        regions=None,                # () -> {gid: home region} (Atlas)
        tenant_burns=None,           # () -> {tenant: burn} (Bastion)
        canary_unreachable=None,     # () -> {region, ...} (Heliograph)
        # ---- actions (async callables) ----
        split=None,                  # async (gid) -> None
        merge=None,                  # async (gid) -> None
        promote=None,                # async (gid) -> None
        moved_bytes=None,            # () -> cumulative migrated bytes
        reshard_busy=None,           # () -> bool (a plan holds the lock)
        # ---- knobs (mirrored by utils/config.HelmsmanConfig) ----
        interval: float = 5.0,
        hot_streak: int = 3,
        cold_streak: int = 6,
        hot_share: float = 0.5,
        cold_share: float = 0.1,
        min_ops: int = 20,
        min_groups: int = 1,
        max_groups: int = 8,
        cooldown: float = 30.0,
        budget_bytes: int = 64 * 1024 * 1024,
        budget_window: float = 600.0,
        heartbeat_timeout: float = 15.0,
        clock=time.monotonic,
    ):
        self._load_census = load_census
        self._slo_alerts = slo_alerts or (lambda: [])
        self._shed_level = shed_level or (lambda: 0)
        self._breaker_census = breaker_census or (lambda: (0, []))
        self._pool_pressure = pool_pressure
        self._source_ages = source_ages
        self._regions = regions
        self._tenant_burns = tenant_burns
        self._canary_unreachable = canary_unreachable
        self._regions_down: set = set()  # regions currently declared dead
        self._split = split
        self._merge = merge
        self._promote = promote
        self._moved_bytes = moved_bytes or (lambda: 0)
        self._reshard_busy = reshard_busy or (lambda: False)
        self.interval = interval
        self.hot_streak = hot_streak
        self.cold_streak = cold_streak
        self.hot_share = hot_share
        self.cold_share = cold_share
        self.min_ops = min_ops
        self.min_groups = min_groups
        self.max_groups = max_groups
        self.cooldown = cooldown
        self.budget_bytes = budget_bytes
        self.budget_window = budget_window
        self.heartbeat_timeout = heartbeat_timeout
        self._clock = clock
        self.pinned = False
        self._last_counts: dict[str, int] = dict(load_census())
        self._hot_streaks: dict[str, int] = {}
        self._cold_streaks: dict[str, int] = {}
        self._cooldown_until = 0.0
        self._promoted: dict[str, float] = {}   # gid -> last promote time
        self._spend = collections.deque()       # (t, bytes) in the window
        self._last_admission: dict | None = None
        self.history = collections.deque(maxlen=64)
        self._task = None
        self.ticks = 0

    @classmethod
    def from_config(cls, hm_cfg, **signals) -> "Helmsman":
        """Build from a HelmsmanConfig-shaped object (duck-typed), with
        the signal/action callables passed through. `pin = true` starts
        the controller with autoscaling frozen."""
        hm = cls(
            interval=float(getattr(hm_cfg, "interval", 5.0)),
            hot_streak=int(getattr(hm_cfg, "hot_streak", 3)),
            cold_streak=int(getattr(hm_cfg, "cold_streak", 6)),
            hot_share=float(getattr(hm_cfg, "hot_share", 0.5)),
            cold_share=float(getattr(hm_cfg, "cold_share", 0.1)),
            min_ops=int(getattr(hm_cfg, "min_ops", 20)),
            min_groups=int(getattr(hm_cfg, "min_groups", 1)),
            max_groups=int(getattr(hm_cfg, "max_groups", 8)),
            cooldown=float(getattr(hm_cfg, "cooldown", 30.0)),
            budget_bytes=int(getattr(hm_cfg, "budget_bytes", 1 << 26)),
            budget_window=float(getattr(hm_cfg, "budget_window", 600.0)),
            heartbeat_timeout=float(
                getattr(hm_cfg, "heartbeat_timeout", 15.0)
            ),
            **signals,
        )
        hm.pinned = bool(getattr(hm_cfg, "pin", False))
        return hm

    # ------------------------------------------------------------- signals

    def on_admission(self, record: dict) -> None:
        """`AdmissionController.subscribe` target: shed transitions reach
        the controller push-style (no polling race on short sheds)."""
        self._last_admission = dict(record)

    # ------------------------------------------------------------ override

    def pin(self) -> None:
        """Freeze the fleet shape: no split/merge until `unpin()` —
        liveness promotion of a DEAD group keeps running (a pin must
        never turn a process crash into an unserved keyspace)."""
        self.pinned = True
        self._note("pin")

    def unpin(self) -> None:
        self.pinned = False
        # fresh hysteresis: pre-pin streaks must not trigger instantly
        self._hot_streaks.clear()
        self._cold_streaks.clear()
        self._note("unpin")

    # -------------------------------------------------------------- budget

    def _budget_spent(self) -> int:
        now = self._clock()
        while self._spend and now - self._spend[0][0] > self.budget_window:
            self._spend.popleft()
        return sum(b for _, b in self._spend)

    def budget_remaining(self) -> int:
        return max(0, self.budget_bytes - self._budget_spent())

    def _charge(self, before: int) -> int:
        moved = max(0, self._moved_bytes() - before)
        if moved:
            self._spend.append((self._clock(), moved))
        return moved

    # ------------------------------------------------------------- records

    def _note(self, action: str, **detail) -> None:
        rec = {"t": self._clock(), "action": action, **detail}
        self.history.append(rec)
        metrics.inc("dds_helmsman_actions_total", action=action,
                    help="Helmsman decisions by kind")
        flight.record("helmsman", action=action, **detail)
        log.info("helmsman %s %s", action, detail or "")

    # ----------------------------------------------------------------- tick

    def _shares(self) -> tuple[dict[str, float], int]:
        counts = dict(self._load_census())
        delta = {
            g: counts.get(g, 0) - self._last_counts.get(g, 0)
            for g in counts
        }
        self._last_counts = counts
        total = sum(max(0, d) for d in delta.values())
        if total <= 0:
            return {g: 0.0 for g in counts}, 0
        return {g: max(0, d) / total for g, d in delta.items()}, total

    def _distressed(self) -> tuple[bool, dict]:
        alerts = list(self._slo_alerts())
        shed = int(self._shed_level())
        _, etas = self._breaker_census()
        pool = self._pool_pressure() if self._pool_pressure else 0.0
        detail = {"slo_alerts": alerts, "shed_level": shed,
                  "open_breakers": len(etas), "pool_pressure": round(pool, 3)}
        # Bastion attribution: when one tenant dominates the burn, every
        # decision this tick records WHO drove it — a split announced as
        # "tenant X's burn" is the runbook difference between adding
        # capacity and asking why X floods (Bulwark sheds X either way)
        if self._tenant_burns is not None:
            try:
                burns = {t: float(b) for t, b
                         in dict(self._tenant_burns()).items() if b > 0}
            except Exception:  # noqa: BLE001 — a broken signal never blocks
                burns = {}
            if burns:
                top = max(burns, key=burns.get)
                detail["tenant"] = top
                detail["tenant_burn"] = round(burns[top], 3)
        return bool(alerts or shed > 0 or pool >= 0.9), detail

    def _dead_regions(self, ages: dict, known: set) -> dict:
        """Atlas region-death detection: regions whose EVERY homed group's
        heartbeat has aged out at once. Returns {gid: home region} labels
        for promotion detail; declares/clears `region_down` incidents as
        the region dies and heals (a single dead group in a live region
        is a process crash, not a region event)."""
        if self._regions is None:
            return {}
        labels = {g: r for g, r in dict(self._regions()).items() if r}
        stale = {g for g, a in ages.items()
                 if g in known and a >= self.heartbeat_timeout}
        for region in sorted(set(labels.values())):
            homed = {g for g, r in labels.items()
                     if r == region and g in known}
            if homed and homed <= stale:
                if region not in self._regions_down:
                    self._regions_down.add(region)
                    self._note("region_down", region=region,
                               groups=sorted(homed))
                    metrics.inc(
                        "dds_helmsman_region_down_total", region=region,
                        help="whole-region heartbeat losses declared by "
                             "Helmsman",
                    )
            else:
                self._regions_down.discard(region)
        return labels

    async def _check_liveness(self) -> str | None:
        """Dead-group takeover — runs even when pinned. Region-aware
        (Atlas): a whole region aging out is declared `region_down`, and
        each of its groups is promoted like any dead group — the fabric's
        promote prefers a standby homed where the dead group lived, which
        for a dead region means the cross-region takeover the drill
        exercises."""
        if self._promote is None or (
                self._source_ages is None
                and self._canary_unreachable is None):
            return None
        now = self._clock()
        known = set(self._last_counts)
        ages = dict(self._source_ages()) if self._source_ages else {}
        # Heliograph black-box evidence: a region whose canary probes hit
        # the sustained-unreachable streak is treated as aged-out even
        # while its heartbeats still arrive — a process can heartbeat
        # with its SERVING path dead (wedged event loop downstream of the
        # edge, partitioned quorum), and the probes drive the real route.
        # Synthesizing the age (instead of a separate path) feeds the
        # same `_dead_regions` declaration and promotion flow the
        # heartbeat evidence does.
        if self._canary_unreachable is not None and self._regions is not None:
            try:
                down = set(self._canary_unreachable())
            except Exception:  # noqa: BLE001 — a broken signal is silence
                down = set()
            if down:
                for gid, region in dict(self._regions()).items():
                    if region in down:
                        ages[gid] = max(ages.get(gid, 0.0),
                                        self.heartbeat_timeout)
        labels = self._dead_regions(ages, known)
        for gid, age in ages.items():
            if gid not in known or age < self.heartbeat_timeout:
                continue
            if now - self._promoted.get(gid, -1e18) < 2 * self.cooldown:
                continue  # takeover already launched; give it time
            self._promoted[gid] = now
            self._note("promote", dead=gid, heartbeat_age=round(age, 1),
                       region=labels.get(gid, ""))
            try:
                await self._promote(gid)
                self._cooldown_until = now + self.cooldown
                return "promote"
            except Exception as e:
                self._note("promote_failed", dead=gid, error=repr(e))
                return None
        return None

    async def step(self) -> str | None:
        """One decision tick. Returns the action taken ("split", "merge",
        "promote") or None — the unit tests' whole surface."""
        self.ticks += 1
        shares, total = self._shares()
        metrics.set("dds_helmsman_groups", len(shares),
                    help="groups in the active shard map (Helmsman view)")
        acted = await self._check_liveness()
        if acted:
            return acted
        if self.pinned:
            return None
        now = self._clock()
        if now < self._cooldown_until or self._reshard_busy():
            return None
        distressed, detail = self._distressed()
        confident = total >= self.min_ops

        # hot side: distress + one group carrying the load -> split
        for gid, share in shares.items():
            if distressed and confident and share >= self.hot_share:
                self._hot_streaks[gid] = self._hot_streaks.get(gid, 0) + 1
            else:
                self._hot_streaks.pop(gid, None)
        # cold side: calm fleet + a group seeing almost nothing -> merge
        for gid, share in shares.items():
            if (not distressed and confident and shed_ok(self._shed_level)
                    and share <= self.cold_share):
                self._cold_streaks[gid] = self._cold_streaks.get(gid, 0) + 1
            else:
                self._cold_streaks.pop(gid, None)

        budget_left = self.budget_remaining()
        if budget_left <= 0:
            metrics.set("dds_helmsman_budget_exhausted", 1,
                        help="1 while the migrated-bytes window is spent")
            return None
        metrics.set("dds_helmsman_budget_exhausted", 0,
                    help="1 while the migrated-bytes window is spent")

        if self._split is not None and len(shares) < self.max_groups:
            hot = [g for g, s in self._hot_streaks.items()
                   if s >= self.hot_streak]
            if hot:
                gid = max(hot, key=lambda g: shares.get(g, 0.0))
                return await self._act("split", self._split, gid,
                                       share=round(shares.get(gid, 0), 3),
                                       **detail)
        if self._merge is not None and len(shares) > self.min_groups:
            cold = [g for g, s in self._cold_streaks.items()
                    if s >= self.cold_streak]
            if cold:
                gid = min(cold, key=lambda g: shares.get(g, 1.0))
                return await self._act("merge", self._merge, gid,
                                       share=round(shares.get(gid, 0), 3),
                                       **detail)
        return None

    async def _act(self, action: str, fn, gid: str, **detail) -> str | None:
        before = self._moved_bytes()
        self._note(action, group=gid,
                   budget_remaining=self.budget_remaining(), **detail)
        try:
            await fn(gid)
        except Exception as e:
            # an aborted plan left the old map in force — cool down and
            # re-observe rather than hammering the same reshape
            self._note(f"{action}_failed", group=gid, error=repr(e))
            self._cooldown_until = self._clock() + self.cooldown
            return None
        moved = self._charge(before)
        self._cooldown_until = self._clock() + self.cooldown
        self._hot_streaks.clear()
        self._cold_streaks.clear()
        self._note(f"{action}_done", group=gid, moved_bytes=moved)
        return action

    # ----------------------------------------------------------- lifecycle

    async def _loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.interval)
            try:
                await self.step()
            except Exception:  # noqa: BLE001 — the loop must outlive a tick
                log.exception("helmsman tick failed")

    def start(self) -> None:
        if self._task is None:
            self._task = supervised_task(self._loop(), name="helmsman")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -------------------------------------------------------------- health

    def report(self) -> dict:
        return {
            "pinned": self.pinned,
            "ticks": self.ticks,
            "cooldown_remaining": max(
                0.0, round(self._cooldown_until - self._clock(), 2)
            ),
            "budget_remaining_bytes": self.budget_remaining(),
            "hot_streaks": dict(self._hot_streaks),
            "cold_streaks": dict(self._cold_streaks),
            "last_admission": self._last_admission,
            "recent": list(self.history)[-8:],
        }


def shed_ok(shed_level) -> bool:
    """Merging is forbidden while Bulwark sheds ANY class — removing
    capacity under admission pressure is how autoscalers oscillate."""
    try:
        return int(shed_level()) == 0
    except Exception:  # noqa: BLE001 — a broken signal must not block ticks
        return False
