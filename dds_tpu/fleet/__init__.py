"""Fleet-level control loops: the planes that steer the whole
constellation rather than one group — currently the Helmsman autoscaler
(fleet/helmsman.py)."""

from dds_tpu.fleet.helmsman import Helmsman  # noqa: F401
