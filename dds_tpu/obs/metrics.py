"""MetricsRegistry: counters, gauges, fixed-bucket histograms; Prometheus text.

The numeric half of Telescope (the span ring in `utils/trace` is the
temporal half): subsystems increment named series with bounded label sets
(route, method, coordinator, cache, outcome, ...) and `GET /metrics`
serves the whole registry in Prometheus text exposition format 0.0.4 —
stdlib only, no client library.

Design notes:
- one process-wide registry (`metrics`); a `Registry()` can be built for
  tests.
- histograms are FIXED-bucket (chosen at first observe): cumulative
  `_bucket{le=...}` counts plus `_sum`/`_count`, the standard shape
  Prometheus quantile queries expect. No dynamic buckets — re-bucketing
  mid-flight would corrupt rate() queries.
- every mutation takes one short lock; the hot-path cost is a dict lookup
  and a float add, matching the tracer's "one deque append" budget.
- label cardinality is BOUNDED per family (`max_series`, default 1024):
  once a family holds that many distinct label sets, new label sets fold
  into a single `overflow` series (every label value replaced by
  "overflow") and `dds_metrics_label_overflow_total{family=...}` counts
  the fold. Per-tenant gauges can therefore never blow up `/metrics` —
  a wire-supplied label (tenant id, route) is a cardinality attack
  surface, and the registry is the last line of defense.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field

__all__ = [
    "Registry", "metrics",
    "LATENCY_BUCKETS", "SIZE_BUCKETS",
    "OVERFLOW_LABEL", "OVERFLOW_COUNTER",
]

# seconds: 1ms .. 10s, the REST/quorum latency range under chaos schedules
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# element counts: fold widths / batch sizes
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _escape(v: str) -> str:
    # label VALUE escaping per the text-format spec: backslash first (or
    # the escapes we add would themselves be re-escaped), then quote and
    # newline — a raw newline would split the sample line mid-series
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # HELP text escaping per the spec: only backslash and newline (quotes
    # are legal in help text, unlike in label values)
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    # integers render without a trailing .0 — smaller payloads, and exact
    # counter values survive a text round-trip
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


@dataclass
class _Family:
    kind: str                      # counter | gauge | histogram
    help: str = ""
    buckets: tuple = ()
    # label-key -> float (counter/gauge) or [bucket_counts, sum, count]
    samples: dict = field(default_factory=dict)


OVERFLOW_LABEL = "overflow"
OVERFLOW_COUNTER = "dds_metrics_label_overflow_total"


class Registry:
    def __init__(self, max_series: int = 1024):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self.max_series = int(max_series)

    # -------------------------------------------------------------- writes

    def _family(self, name: str, kind: str, help: str, buckets: tuple = ()):
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = _Family(kind, help, buckets)
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, not {kind}"
            )
        elif not fam.help and help:
            # backfill: the first touch may come from a call site that
            # passes no help (scrape-time gauges are set from several
            # places) — a later documented touch must still yield # HELP
            fam.help = help
        return fam

    def _admit(self, fam: _Family, name: str, key: tuple) -> tuple:
        """Cardinality guard (caller holds the lock): an already-known
        label set, any label set while the family is under `max_series`,
        and the overflow counter itself pass through; a NEW label set at
        the cap folds into the family's single `overflow` series and is
        counted in `dds_metrics_label_overflow_total{family=...}`."""
        if (
            not key
            or key in fam.samples
            or len(fam.samples) < self.max_series
            or name == OVERFLOW_COUNTER
        ):
            return key
        oc = self._family(
            OVERFLOW_COUNTER, "counter",
            "label sets folded into the overflow series by the per-family "
            "cardinality cap",
        )
        okey = _label_key({"family": name})
        oc.samples[okey] = oc.samples.get(okey, 0.0) + 1
        return tuple((k, OVERFLOW_LABEL) for k, _ in key)

    def inc(self, name: str, n: float = 1.0, help: str = "", **labels) -> None:
        """Add `n` to a counter series (created on first touch)."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "counter", help)
            key = self._admit(fam, name, key)
            fam.samples[key] = fam.samples.get(key, 0.0) + n

    def set(self, name: str, value: float, help: str = "", **labels) -> None:
        """Set a gauge series to `value`."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "gauge", help)
            key = self._admit(fam, name, key)
            fam.samples[key] = float(value)

    def observe(self, name: str, value: float, buckets: tuple = LATENCY_BUCKETS,
                help: str = "", **labels) -> None:
        """Record one observation into a fixed-bucket histogram series."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "histogram", help, tuple(buckets))
            key = self._admit(fam, name, key)
            s = fam.samples.get(key)
            if s is None:
                s = fam.samples[key] = [[0] * len(fam.buckets), 0.0, 0]
            i = bisect.bisect_left(fam.buckets, value)
            if i < len(fam.buckets):
                s[0][i] += 1
            s[1] += value
            s[2] += 1

    # --------------------------------------------------------------- reads

    def value(self, name: str, **labels) -> float | None:
        """Current counter/gauge value of one series (tests/introspection)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind == "histogram":
                return None
            return fam.samples.get(_label_key(labels))

    def histogram_stats(self, name: str, **labels) -> dict | None:
        """{count, sum} of one histogram series."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram":
                return None
            s = fam.samples.get(_label_key(labels))
            if s is None:
                return None
            return {"count": s[2], "sum": s[1]}

    def overflow_total(self) -> float:
        """Total label sets folded into `overflow` series across every
        family — the registry's dropped-series count. Exported at scrape
        time as the `dds_metrics_dropped_series` gauge so dashboards can
        alarm on cardinality overflow without parsing the per-family
        counter."""
        with self._lock:
            fam = self._families.get(OVERFLOW_COUNTER)
            if fam is None:
                return 0.0
            return float(sum(fam.samples.values()))

    def clear_family(self, name: str) -> None:
        """Drop every series of one family (help/kind registration stays).
        For scrape-time re-exported info gauges whose LABEL VALUES rotate
        (Heliograph's exemplar trace ids): the exporter clears and re-sets
        the current series each sample, so rotation can never accrete
        stale series toward the cardinality cap."""
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                fam.samples.clear()

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    # ---------------------------------------------------------- exposition

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: list[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                if fam.help:
                    out.append(f"# HELP {name} {_escape_help(fam.help)}")
                out.append(f"# TYPE {name} {fam.kind}")
                for key in sorted(fam.samples):
                    labels = dict(key)
                    if fam.kind == "histogram":
                        counts, total, count = fam.samples[key]
                        cum = 0
                        for le, c in zip(fam.buckets, counts):
                            cum += c
                            out.append(
                                f"{name}_bucket{{{self._labels(labels, le=_fmt(le))}}} {cum}"
                            )
                        out.append(
                            f'{name}_bucket{{{self._labels(labels, le="+Inf")}}} {count}'
                        )
                        suffix = self._labels(labels)
                        brace = f"{{{suffix}}}" if suffix else ""
                        out.append(f"{name}_sum{brace} {_fmt(total)}")
                        out.append(f"{name}_count{brace} {count}")
                    else:
                        suffix = self._labels(labels)
                        brace = f"{{{suffix}}}" if suffix else ""
                        out.append(f"{name}{brace} {_fmt(fam.samples[key])}")
        return "\n".join(out) + "\n"

    @staticmethod
    def _labels(labels: dict, **extra) -> str:
        items = {**labels, **extra}
        return ",".join(f'{k}="{_escape(str(v))}"' for k, v in items.items())


# process-wide default registry (subsystems import this)
metrics = Registry()
