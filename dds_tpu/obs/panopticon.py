"""Panopticon: the fleet-wide observability plane.

Telescope sees one process; Meridian runs many. PR 8's multi-host fabric
split the quorum groups across OS processes and `run.launch` rightly
dropped Watchtower quorum audits there — the proxy's tracer never sees a
remote replica's handler spans, so a quorum check would false-positive on
every op. Which means the deployments where a Byzantine coordinator is
MOST plausible were the ones nobody audited. Panopticon closes the loop:

- **SpanShipper** (every non-proxy process): subscribes to the process
  tracer, spools completed span trees (plus flight-incident index entries
  and metric/SLO snapshots) into a bounded buffer, and ships HMAC-signed
  `TelemetryBatch` frames to the proxy's collector over the existing
  TcpNet fabric. Telemetry is strictly best-effort: the spool drops
  (and counts) under pressure, the request path is never blocked.
- **FleetCollector** (the proxy/controller process): verifies batch MACs,
  stitches shipped spans with the proxy's own spans into single trace
  trees keyed by the propagated `tc` context, and replays each stitched
  tree into the Watchtower — children first, root last — after a
  `stitch_window` grace so cross-host straggler spans land before the
  audit fires. Quorum-intersection, tag-monotonicity, and breaker/
  suspicion audits come back to life on Meridian fleets. It also
  federates every source's Prometheus exposition (`GET /fleet/metrics`,
  `host`/`role`/`shard`-labeled, staleness-marked per source), rolls up
  fleet SLO burn (`GET /fleet/slo`: worst-of and sum-of per-host
  windows, per-group resident-pool pressure, admission shed levels), and
  correlates flight incidents fleet-wide by trace id
  (`GET /fleet/incidents`).

Trust model: batches are HMAC-SHA256-signed with the fleet telemetry
secret ON TOP of the frame MAC, so the collector never ingests telemetry
forged by a keyless network attacker. But the signer is the REPORTING
HOST — a Byzantine host can still sign lies about its own stats. What
the audits catch is what lying CANNOT hide: a coordinator that claims a
quorum must show >= q distinct handler spans it does not control (they
ship from OTHER processes), and a forged stale tag is caught by the
committed-tag history regardless of what its host reports. What they
cannot catch: a host under-reporting its own latency/metrics. See
DEPLOY.md "Fleet observability (Panopticon)".
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import hmac as hmac_mod
import json
import logging
import os
import pathlib
import time

from dds_tpu.core import messages as M
from dds_tpu.obs.metrics import metrics as default_metrics
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.utils.trace import SpanRecord, Tracer
from dds_tpu.utils.trace import tracer as default_tracer

log = logging.getLogger("dds.panopticon")

__all__ = [
    "SpanShipper", "FleetCollector", "NullWatchtower",
    "COLLECTOR_ENDPOINT", "SHIPPER_ENDPOINT",
    "batch_mac", "process_info",
]


class NullWatchtower:
    """Audit sink for collectors deployed with `[obs] audit-enabled =
    false`: stitching and federation stay live, but replayed traces are
    discarded instead of being judged against a geometry nobody
    configured (the global Watchtower's defaults would flag every
    stitched commit of a differently-sized fleet)."""

    def on_record(self, rec) -> None:
        pass

    def verdicts(self) -> list:
        return []

# TcpNet endpoint names (full addresses are "host:port/<name>")
COLLECTOR_ENDPOINT = "panopticon"
SHIPPER_ENDPOINT = "panopticon-ship"

# loose (trace-less) events worth shipping: they drive the Watchtower's
# cross-trace breaker/suspicion state machines
_LOOSE_EVENTS = frozenset({
    "breaker.open", "breaker.half_open", "breaker.closed",
    "abd.coordinator_violation",
})

_START_TS = time.time()


def process_info(registry=None, *, role: str, shard: str = "",
                 region: str = "") -> None:
    """Publish the per-process identity gauge every `/metrics` carries:
    `dds_process_info{role,shard,region,pid,start_ts,version} 1`.
    Federated scrapes and incident correlation attribute sources by it."""
    from dds_tpu import __version__

    reg = registry if registry is not None else default_metrics
    reg.set(  # argus: ok[metrics.unbounded-label] one series per process lifetime; start_ts is boot identity, not request-scoped
        "dds_process_info", 1.0,
        role=role, shard=shard or "-", region=region or "-",
        pid=str(os.getpid()),
        start_ts=f"{_START_TS:.3f}", version=__version__,
        help="process identity (value is always 1; the labels carry it)",
    )


def batch_mac(secret: bytes, host: str, role: str, shard: str, seq: int,
              ts: float, spans: list, incidents: list, metrics_text: str,
              slo: dict, dropped: int, region: str = "") -> bytes:
    """HMAC-SHA256 over the canonical JSON of a batch payload. The Atlas
    `region` label is covered too — a forged region would let a
    compromised source masquerade into another region's federated view."""
    body = json.dumps(
        [host, role, shard, seq, ts, spans, incidents, metrics_text, slo,
         dropped, region],
        sort_keys=True, separators=(",", ":"),
    ).encode()
    return hmac_mod.new(secret, body, hashlib.sha256).digest()


def record_from_dict(d: dict) -> SpanRecord | None:
    """Rebuild a SpanRecord from a shipped `Tracer.event_dict` dict.
    Defensive: a collector must survive any shape a (lying) source ships."""
    try:
        return SpanRecord(
            ts=float(d["ts"]),
            name=str(d["name"]),
            dur_ms=float(d.get("dur_ms", 0.0)),
            meta=d.get("meta") if isinstance(d.get("meta"), dict) else {},
            trace_id=d.get("trace_id"),
            span_id=d.get("span_id"),
            parent_id=d.get("parent_id"),
            kind=str(d.get("kind", "span")),
        )
    except (KeyError, TypeError, ValueError):
        return None


# --------------------------------------------------------------------------
# shipper (group / non-proxy processes)
# --------------------------------------------------------------------------


class SpanShipper:
    """Tracer subscriber -> bounded spool -> batched TcpNet shipping.

    The subscriber side (`on_record`) runs on the recording path and does
    one dict append under no lock contention worth naming; everything
    slow (JSON sanitization, incident-index tailing, the actual send)
    lives in the supervised flush task. A trace's locally-recorded spans
    are packaged as one tree once the trace has gone quiet for a flush
    interval — group processes never see the remote root complete, so
    quiescence IS completion from their vantage point."""

    # per-trace local span cap: a runaway trace must not own the spool
    MAX_TREE_SPANS = 512
    # in-flight (not yet quiesced) traces tracked at once
    MAX_ACTIVE = 1024

    def __init__(self, net, *, collector: str, secret: bytes, host: str,
                 role: str, shard: str = "", region: str = "",
                 spool_max: int = 256,
                 batch_max: int = 32, flush_interval: float = 0.25,
                 flight_dir: str = "", slo=None, tracer: Tracer | None = None,
                 registry=None):
        self.net = net
        # collector is "host:port" (the proxy's transport bind)
        self.collector_addr = f"{collector}/{COLLECTOR_ENDPOINT}"
        self.secret = secret
        self.host, self.role, self.shard = host, role, shard
        self.region = region  # Atlas: [fabric] region, MAC-covered
        self.spool_max = max(1, spool_max)
        self.batch_max = max(1, batch_max)
        self.flush_interval = max(0.01, flush_interval)
        self.flight_dir = flight_dir
        self.slo = slo
        self.tracer = tracer if tracer is not None else default_tracer
        self.metrics = registry if registry is not None else default_metrics
        self.src_addr = net.local_addr(SHIPPER_ENDPOINT)
        # trace_id -> {"records": [dict], "last": monotonic}
        self._active: collections.OrderedDict = collections.OrderedDict()
        # quiesced trees awaiting shipment
        self._spool: collections.deque = collections.deque()
        self._loose: collections.deque = collections.deque(maxlen=256)
        self._seq = 0
        self._dropped = 0
        self._index_pos = 0  # byte offset into flight index.jsonl
        self._task: asyncio.Task | None = None
        self._last_ship = 0.0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.net.register(self.src_addr, self.handle)
        self.tracer.subscribe(self.on_record)
        if self._task is None or self._task.done():
            self._task = supervised_task(self._flush_loop(),
                                         name="panopticon.shipper")

    async def stop(self) -> None:
        self.tracer.unsubscribe(self.on_record)
        self.net.unregister(self.src_addr)
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # ----------------------------------------------------------- subscriber

    def on_record(self, rec) -> None:
        """Cheap and non-blocking: convert + append. Never raises (the
        tracer guards too, but telemetry must not break observed paths)."""
        try:
            if rec.trace_id is None:
                if rec.kind == "event" and rec.name in _LOOSE_EVENTS:
                    self._loose.append(Tracer.event_dict(rec))
                return
            buf = self._active.get(rec.trace_id)
            if buf is None:
                buf = self._active[rec.trace_id] = {"records": [], "last": 0.0}
                while len(self._active) > self.MAX_ACTIVE:
                    self._active.popitem(last=False)
                    self._drop("active_overflow")
            if len(buf["records"]) < self.MAX_TREE_SPANS:
                buf["records"].append(Tracer.event_dict(rec))
            else:
                self._drop("tree_overflow")
            buf["last"] = time.monotonic()
        except Exception:  # noqa: BLE001 — observers never break observed paths
            log.exception("shipper on_record failed")

    def _drop(self, reason: str) -> None:
        self._dropped += 1
        self.metrics.inc(
            "dds_fleet_ship_dropped_total", reason=reason,
            help="telemetry units dropped by the span shipper (accounted, "
                 "never blocking)",
        )

    # ------------------------------------------------------------- ack side

    async def handle(self, src: str, msg) -> None:
        if isinstance(msg, M.TelemetryAck):
            if msg.ok:
                self.metrics.inc("dds_fleet_ship_acked_total",
                                 help="telemetry batches the collector "
                                      "acknowledged")
            else:
                self._drop("rejected")
                log.warning("collector rejected telemetry batch %d: %s",
                            msg.seq, msg.error)

    # ------------------------------------------------------------ flush loop

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval)
            try:
                await self._flush_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("panopticon shipper flush failed")

    def _collect_quiesced(self) -> list[list]:
        """Move quiet traces out of the active set into the spool."""
        now = time.monotonic()
        done = [
            tid for tid, buf in self._active.items()
            if now - buf["last"] >= self.flush_interval
        ]
        for tid in done:
            buf = self._active.pop(tid)
            if len(self._spool) >= self.spool_max:
                self._spool.popleft()
                self._drop("spool_overflow")
            self._spool.append(buf["records"])
        trees = []
        while self._spool and len(trees) < self.batch_max:
            trees.append(self._spool.popleft())
        if self._loose:
            trees.append(list(self._loose))
            self._loose.clear()
        return trees

    def _read_new_incidents(self) -> list[dict]:
        """Tail the flight recorder's index.jsonl from the last shipped
        offset (runs on a worker thread — file I/O off the loop)."""
        if not self.flight_dir:
            return []
        idx = pathlib.Path(self.flight_dir) / "index.jsonl"
        try:
            size = idx.stat().st_size
        except OSError:
            return []
        if size < self._index_pos:
            self._index_pos = 0  # pruned/rewritten: re-tail from the top
        if size == self._index_pos:
            return []
        out = []
        try:
            with open(idx) as f:
                f.seek(self._index_pos)
                for line in f:
                    try:
                        entry = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(entry, dict):
                        out.append(entry)
                self._index_pos = f.tell()
        except OSError:
            return []
        return out[-64:]

    async def _flush_once(self) -> None:
        trees = self._collect_quiesced()
        incidents = await asyncio.to_thread(self._read_new_incidents)
        now = time.monotonic()
        # always ship a metrics/SLO heartbeat at least once per second so
        # federation staleness reflects liveness, not workload idleness
        if not trees and not incidents and now - self._last_ship < 1.0:
            return
        self._last_ship = now
        spans = json.loads(json.dumps(trees, default=str))
        self._seq += 1
        ts = time.time()
        metrics_text = self.metrics.render()
        slo = self.slo.report() if self.slo is not None else {}
        mac = batch_mac(self.secret, self.host, self.role, self.shard,
                        self._seq, ts, spans, incidents, metrics_text, slo,
                        self._dropped, self.region)
        batch = M.TelemetryBatch(
            host=self.host, role=self.role, shard=self.shard, seq=self._seq,
            ts=ts, spans=spans, incidents=incidents,
            metrics_text=metrics_text, slo=slo, dropped=self._dropped,
            mac=mac, region=self.region,
        )
        self.net.send(self.src_addr, self.collector_addr, batch)
        self.metrics.inc("dds_fleet_ship_batches_total",
                         help="telemetry batches shipped to the collector")
        n_spans = sum(len(t) for t in trees)
        if n_spans:
            self.metrics.inc("dds_fleet_ship_spans_total", n_spans,
                             help="span records shipped to the collector")

    def stats(self) -> dict:
        return {
            "seq": self._seq,
            "dropped": self._dropped,
            "active_traces": len(self._active),
            "spooled_trees": len(self._spool),
        }


# --------------------------------------------------------------------------
# Prometheus exposition parsing / relabeling (federation)
# --------------------------------------------------------------------------


def _inject_labels(line: str, labels: dict) -> str:
    """Add `labels` to one exposition sample line."""
    extra = ",".join(f'{k}="{v}"' for k, v in labels.items())
    if "{" in line:
        name, rest = line.split("{", 1)
        return f"{name}{{{extra},{rest}"
    name, _, value = line.partition(" ")
    return f"{name}{{{extra}}} {value}"


def merge_expositions(sources: list[dict]) -> str:
    """Merge several Prometheus text expositions into one valid document:
    each family's `# HELP`/`# TYPE` emitted once, every sample line
    relabeled with its source's host/role/shard. `sources` entries are
    {"labels": dict, "text": str}."""
    fams: dict = {}
    order: list[str] = []

    def fam(name: str) -> dict:
        f = fams.get(name)
        if f is None:
            f = fams[name] = {"help": "", "type": "", "samples": []}
            order.append(name)
        return f

    for src in sources:
        labels = src["labels"]
        current = None
        for line in src["text"].splitlines():
            if line.startswith("# HELP "):
                name, _, help_text = line[len("# HELP "):].partition(" ")
                f = fam(name)
                if not f["help"]:
                    f["help"] = help_text
            elif line.startswith("# TYPE "):
                name, _, kind = line[len("# TYPE "):].partition(" ")
                current = name
                f = fam(name)
                if not f["type"]:
                    f["type"] = kind
            elif line and not line.startswith("#"):
                line_name = line.split("{", 1)[0].split(" ", 1)[0]
                target = (
                    current
                    if current is not None and line_name.startswith(current)
                    else line_name
                )
                fam(target)["samples"].append(_inject_labels(line, labels))
    out: list[str] = []
    for name in order:
        f = fams[name]
        if f["help"]:
            out.append(f"# HELP {name} {f['help']}")
        if f["type"]:
            out.append(f"# TYPE {name} {f['type']}")
        out.extend(f["samples"])
    return "\n".join(out) + "\n"


def parse_samples(text: str, name: str) -> list[tuple[dict, float]]:
    """Extract one family's (labels, value) samples from exposition text
    (the collector reads resident-pool/shed gauges out of shipped
    snapshots with this — no second wire format needed)."""
    out = []
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        if "{" in line:
            lname, rest = line.split("{", 1)
            if lname != name:
                continue
            labelstr, _, value = rest.rpartition("} ")
            labels = {}
            # keys are unquoted, so '",' unambiguously ends a label value
            # (our registries never emit escaped quotes in values)
            for part in labelstr.split('",'):
                if "=" not in part:
                    continue
                k, _, v = part.partition("=")
                labels[k.strip(' ,"')] = v.strip('"')
        else:
            lname, _, value = line.partition(" ")
            if lname != name:
                continue
            labels = {}
        try:
            out.append((labels, float(value)))
        except ValueError:
            continue
    return out


# --------------------------------------------------------------------------
# collector (proxy / controller process)
# --------------------------------------------------------------------------


class FleetCollector:
    """Stitch + audit + federate. One per proxy-role process.

    Subscribes to the LOCAL tracer (taking over the Watchtower's seat —
    deploy wires the Watchtower to be fed exclusively through here, so a
    trace is audited exactly once, with the remote spans present) and
    registers the `panopticon` endpoint on the process's TcpNet for
    shipped batches."""

    MAX_TRACES = 1024
    MAX_TRACE_SPANS = 4096
    MAX_INCIDENTS = 1024
    DONE_LRU = 2048

    def __init__(self, net, *, secret: bytes, host: str, role: str = "proxy",
                 region: str = "", stitch_window: float = 1.0,
                 staleness: float = 10.0,
                 watchtower=None, tracer: Tracer | None = None,
                 registry=None, slo=None):
        self.net = net
        self.secret = secret
        self.host, self.role = host, role
        self.region = region  # Atlas: the collector process's own region
        self.stitch_window = max(0.0, stitch_window)
        self.staleness = staleness
        if watchtower is None:
            from dds_tpu.obs.watchtower import watchtower as _wt
            watchtower = _wt
        self.watchtower = watchtower
        self.tracer = tracer if tracer is not None else default_tracer
        self.metrics = registry if registry is not None else default_metrics
        self.slo = slo  # the proxy's own SloEngine (local source)
        # Chronoscope (or None): fed each stitched tree at replay time so
        # the proxy's pipe profile sees remote replica/ingest spans too.
        # Deploy detaches the Chronoscope from the raw tracer and parks it
        # here — a trace is profiled exactly once, stitched.
        self.profiler = None
        self.addr = net.local_addr(COLLECTOR_ENDPOINT)
        # trace_id -> {"records": [SpanRecord], "root": SpanRecord | None,
        #              "due": monotonic | None, "first": monotonic}
        self._traces: collections.OrderedDict = collections.OrderedDict()
        self._done: collections.OrderedDict = collections.OrderedDict()
        # host -> latest snapshot {"role","shard","ts","mono","seq",
        #                          "metrics_text","slo","dropped"}
        self._sources: dict[str, dict] = {}
        self._incidents: collections.deque = collections.deque(
            maxlen=self.MAX_INCIDENTS
        )
        self._task: asyncio.Task | None = None
        self.traces_stitched = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.net.register(self.addr, self.handle)
        self.tracer.subscribe(self.on_record)
        if self._task is None or self._task.done():
            self._task = supervised_task(self._stitch_loop(),
                                         name="panopticon.collector")

    async def stop(self) -> None:
        self.tracer.unsubscribe(self.on_record)
        self.net.unregister(self.addr)
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # ------------------------------------------------- local tracer feed

    def on_record(self, rec) -> None:
        try:
            if rec.trace_id is None:
                # trace-less events (breaker transitions, suspicion
                # strikes) drive cross-trace machines: feed straight
                # through, nothing to stitch
                self.watchtower.on_record(rec)
                return
            self._buffer(rec, local=True)
        except Exception:  # noqa: BLE001
            log.exception("collector local ingest failed")

    def _buffer(self, rec, *, local: bool) -> None:
        tid = rec.trace_id
        if tid in self._done:
            return  # already replayed/audited — a straggler
        buf = self._traces.get(tid)
        if buf is None:
            buf = self._traces[tid] = {
                "records": [], "root": None, "due": None,
                "first": time.monotonic(),
            }
            while len(self._traces) > self.MAX_TRACES:
                old_tid, old = self._traces.popitem(last=False)
                self.metrics.inc(
                    "dds_fleet_collect_evicted_total",
                    help="in-flight stitch buffers evicted unaudited "
                         "(bounded memory)",
                )
        if rec.kind == "span" and rec.parent_id is None:
            # the trace's root: hold the audit open one stitch window so
            # remote handler spans (a socket + flush interval behind)
            # join the tree before the Watchtower sees it complete
            buf["root"] = rec
            buf["due"] = time.monotonic() + self.stitch_window
        elif len(buf["records"]) < self.MAX_TRACE_SPANS:
            buf["records"].append(rec)

    # ------------------------------------------------------ shipped batches

    async def handle(self, src: str, msg) -> None:
        if not isinstance(msg, M.TelemetryBatch):
            return
        expect = batch_mac(self.secret, msg.host, msg.role, msg.shard,
                           msg.seq, msg.ts, msg.spans, msg.incidents,
                           msg.metrics_text, msg.slo, msg.dropped,
                           getattr(msg, "region", ""))
        if not hmac_mod.compare_digest(msg.mac, expect):
            self.metrics.inc(
                "dds_fleet_collect_rejected_total", reason="mac",
                help="telemetry batches the collector refused",
            )
            self.net.send(self.addr, src,
                          M.TelemetryAck(seq=msg.seq, ok=False,
                                         error="bad mac"))
            return
        self._sources[msg.host] = {
            "role": msg.role, "shard": msg.shard, "ts": msg.ts,
            "region": getattr(msg, "region", ""),
            "mono": time.monotonic(), "seq": msg.seq,
            "metrics_text": msg.metrics_text, "slo": msg.slo,
            "dropped": msg.dropped,
        }
        for entry in msg.incidents:
            if isinstance(entry, dict):
                self._incidents.append(
                    {**entry, "host": msg.host, "role": msg.role}
                )
        for tree in msg.spans:
            if not isinstance(tree, list):
                continue
            for d in tree:
                if not isinstance(d, dict):
                    continue
                rec = record_from_dict(d)
                if rec is None:
                    continue
                if rec.trace_id is None:
                    self.watchtower.on_record(rec)
                else:
                    self._buffer(rec, local=False)
        self.metrics.inc("dds_fleet_collect_batches_total", host=msg.host,
                         help="verified telemetry batches ingested")
        self.net.send(self.addr, src, M.TelemetryAck(seq=msg.seq, ok=True))

    # ----------------------------------------------------------- stitching

    async def _stitch_loop(self) -> None:
        tick = max(0.05, min(0.25, self.stitch_window / 4 or 0.25))
        while True:
            await asyncio.sleep(tick)
            try:
                self._replay_due()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("panopticon stitch replay failed")

    def _replay_due(self) -> None:
        now = time.monotonic()
        due = [
            tid for tid, buf in self._traces.items()
            if (buf["due"] is not None and buf["due"] <= now)
            # rootless traces (background work on a remote host whose
            # root never reaches this process) are dropped unaudited
            # after a generous grace
            or (buf["due"] is None
                and now - buf["first"] > max(8 * self.stitch_window, 8.0))
        ]
        for tid in due:
            buf = self._traces.pop(tid, None)
            if buf is None:
                continue
            self._done[tid] = True
            while len(self._done) > self.DONE_LRU:
                self._done.popitem(last=False)
            if buf["root"] is None:
                continue
            # children first, root LAST: the Watchtower audits on root
            # completion, so the stitched tree must be fully buffered
            # before the root record lands
            for rec in buf["records"]:
                self.watchtower.on_record(rec)
            self.watchtower.on_record(buf["root"])
            if self.profiler is not None:
                try:
                    self.profiler.ingest_tree(buf["records"] + [buf["root"]])
                except Exception:  # noqa: BLE001 — profiling never breaks stitching
                    log.exception("chronoscope stitched-tree ingest failed")
            self.traces_stitched += 1
            self.metrics.inc(
                "dds_fleet_traces_stitched_total",
                help="cross-host trace trees stitched and replayed into "
                     "the Watchtower",
            )

    # ----------------------------------------------------------- federation

    def _source_rows(self) -> list[dict]:
        """Every known source, local process first, with staleness."""
        now = time.monotonic()
        rows = [{
            "host": self.host, "role": self.role, "shard": "",
            "region": self.region,
            "age_s": 0.0, "stale": False,
            "metrics_text": self.metrics.render(),
            "slo": self.slo.report() if self.slo is not None else {},
            "dropped": 0,
        }]
        for host, src in sorted(self._sources.items()):
            age = now - src["mono"]
            rows.append({
                "host": host, "role": src["role"], "shard": src["shard"],
                "region": src.get("region", ""),
                "age_s": age,
                "stale": bool(self.staleness and age > self.staleness),
                "metrics_text": src["metrics_text"], "slo": src["slo"],
                "dropped": src["dropped"],
            })
        return rows

    def source_ages(self) -> dict[str, float]:
        """Shard gid -> seconds since that group process's last shipped
        batch. The span shipper beats ~1/s even when idle, so an age of
        tens of seconds means the PROCESS is gone, not merely quiet —
        the Helmsman controller's dead-group takeover signal. Sources
        without a shard label (proxies, observers) are skipped; when two
        sources claim one shard the freshest wins."""
        now = time.monotonic()
        out: dict[str, float] = {}
        for src in self._sources.values():
            gid = src.get("shard") or ""
            if not gid:
                continue
            age = now - src["mono"]
            if gid not in out or age < out[gid]:
                out[gid] = age
        return out

    def source_regions(self) -> dict[str, str]:
        """Shard gid -> home region, from the shipped identity labels.
        Feeds Helmsman's `regions` signal on the Meridian proxy role so
        canary region evidence (Heliograph) and region_down declarations
        can map back to the groups homed there. Freshest source wins a
        contested gid, mirroring `source_ages`."""
        now = time.monotonic()
        best: dict[str, tuple[float, str]] = {}
        for src in self._sources.values():
            gid = src.get("shard") or ""
            region = src.get("region", "") or ""
            if not gid or not region:
                continue
            age = now - src["mono"]
            if gid not in best or age < best[gid][0]:
                best[gid] = (age, region)
        return {gid: region for gid, (_, region) in best.items()}

    def fleet_metrics(self) -> str:
        """The `GET /fleet/metrics` body: every source's exposition merged
        into one valid document, samples labeled by origin, plus
        synthesized per-source freshness series."""
        rows = self._source_rows()
        sources = []
        for r in rows:
            labels = {"host": r["host"], "role": r["role"]}
            if r["shard"]:
                labels["shard"] = r["shard"]
            if r.get("region"):
                labels["region"] = r["region"]
            sources.append({"labels": labels, "text": r["metrics_text"]})
        doc = merge_expositions(sources)
        extra = [
            "# HELP dds_fleet_source_age_seconds seconds since each "
            "source's last telemetry batch (0 for the collector itself)",
            "# TYPE dds_fleet_source_age_seconds gauge",
        ]
        for r in rows:
            extra.append(
                f'dds_fleet_source_age_seconds{{host="{r["host"]}",'
                f'role="{r["role"]}"}} {r["age_s"]:.3f}'
            )
        extra.append("# HELP dds_fleet_source_stale 1 when a source's "
                     "last batch is older than obs.fleet.staleness")
        extra.append("# TYPE dds_fleet_source_stale gauge")
        for r in rows:
            extra.append(
                f'dds_fleet_source_stale{{host="{r["host"]}",'
                f'role="{r["role"]}"}} {1 if r["stale"] else 0}'
            )
        extra.append("# HELP dds_fleet_ship_dropped_by_source telemetry "
                     "units each source reports having dropped")
        extra.append("# TYPE dds_fleet_ship_dropped_by_source gauge")
        for r in rows:
            extra.append(
                f'dds_fleet_ship_dropped_by_source{{host="{r["host"]}"}} '
                f'{r["dropped"]}'
            )
        return doc + "\n".join(extra) + "\n"

    def fleet_slo(self) -> dict:
        """The `GET /fleet/slo` body: per-host SLO reports plus the fleet
        rollup — per route/window, worst-of burn across hosts and the
        sum-of burn over pooled counts — and the autoscaler sensor suite
        (per-group resident-pool pressure, per-host shed level)."""
        rows = self._source_rows()
        hosts: dict = {}
        routes: dict = {}
        resident: dict = {}
        shed: dict = {}
        for r in rows:
            hosts[r["host"]] = {
                "role": r["role"], "shard": r["shard"],
                "region": r.get("region", ""),
                "age_s": round(r["age_s"], 3), "stale": r["stale"],
                "dropped": r["dropped"],
                "slo": r["slo"],
            }
            for labels, v in parse_samples(r["metrics_text"],
                                           "dds_resident_rows"):
                gid = labels.get("shard", r["shard"] or "-")
                resident.setdefault(gid, {})["rows"] = v
                resident[gid]["host"] = r["host"]
            for labels, v in parse_samples(r["metrics_text"],
                                           "dds_resident_bytes"):
                gid = labels.get("shard", r["shard"] or "-")
                resident.setdefault(gid, {})["bytes"] = v
            for _, v in parse_samples(r["metrics_text"],
                                      "dds_admission_shed_level"):
                shed[r["host"]] = v
            slo = r["slo"] if isinstance(r["slo"], dict) else {}
            for route, rep in (slo.get("routes") or {}).items():
                agg = routes.setdefault(route, {
                    "objective": rep.get("objective"),
                    "class": rep.get("class"),
                    "windows": {},
                })
                for wname, w in (rep.get("windows") or {}).items():
                    wa = agg["windows"].setdefault(
                        wname,
                        {"total": 0, "bad": 0, "burn_rate_worst": 0.0},
                    )
                    wa["total"] += int(w.get("total", 0))
                    wa["bad"] += int(w.get("bad", 0))
                    wa["burn_rate_worst"] = max(
                        wa["burn_rate_worst"], float(w.get("burn_rate", 0.0))
                    )
        for route, agg in routes.items():
            budget = max(1e-9, 1.0 - float(agg.get("objective") or 0.99))
            for w in agg["windows"].values():
                frac = (w["bad"] / w["total"]) if w["total"] else 0.0
                w["burn_rate_sum_of"] = round(frac / budget, 3)
        return {
            "hosts": hosts,
            "fleet": {
                "routes": routes,
                "resident": resident,
                "shed_level": shed,
                "shed_level_max": max(shed.values(), default=0.0),
            },
        }

    def fleet_profile(self) -> dict:
        """The `GET /fleet/profile` body: every host's Chronoscope pipe
        profile (carried as `dds_pipe_*` gauges inside the shipped
        metrics_text — zero wire-format changes) rolled up per route.

        Rollup semantics: a stage's fleet p95 is the MAX across hosts —
        stages run on different processes (proxy coalesce vs replica
        apply vs group ingest), so the worst host's self-time is the
        fleet's bottleneck candidate, not an average that would dilute a
        single hot shard. `top` names the single (route, stage) pair with
        the largest p95 self-time fleet-wide."""
        hosts: dict = {}
        routes: dict = {}
        for r in self._source_rows():
            hrow = hosts.setdefault(r["host"], {
                "role": r["role"], "shard": r["shard"],
                "region": r.get("region", ""),
                "age_s": round(r["age_s"], 3), "stale": r["stale"],
                "routes": {},
            })
            text = r["metrics_text"]
            for labels, v in parse_samples(text, "dds_pipe_wall_p95_ms"):
                route = labels.get("route", "-")
                hrow["routes"].setdefault(route, {})["wall_p95_ms"] = v
                agg = routes.setdefault(route, {
                    "wall_p95_ms": 0.0, "coverage_min": None, "stages": {},
                })
                agg["wall_p95_ms"] = max(agg["wall_p95_ms"], v)
            for labels, v in parse_samples(text, "dds_pipe_coverage"):
                route = labels.get("route", "-")
                hrow["routes"].setdefault(route, {})["coverage"] = v
                agg = routes.setdefault(route, {
                    "wall_p95_ms": 0.0, "coverage_min": None, "stages": {},
                })
                cur = agg["coverage_min"]
                agg["coverage_min"] = v if cur is None else min(cur, v)
            for labels, v in parse_samples(text, "dds_pipe_stage_p95_ms"):
                route = labels.get("route", "-")
                stage = labels.get("stage", "other")
                agg = routes.setdefault(route, {
                    "wall_p95_ms": 0.0, "coverage_min": None, "stages": {},
                })
                st = agg["stages"].setdefault(
                    stage, {"p95_ms": 0.0, "host": None})
                if v >= st["p95_ms"]:
                    st["p95_ms"], st["host"] = v, r["host"]
        top = None
        for route, agg in routes.items():
            best = None
            for stage, st in agg["stages"].items():
                if stage == "other":
                    continue  # the unattributed residue is not a bottleneck NAME
                if best is None or st["p95_ms"] > best[1]:
                    best = (stage, st["p95_ms"], st["host"])
            if best is not None:
                agg["top_stage"] = {
                    "stage": best[0], "p95_ms": round(best[1], 3),
                    "host": best[2],
                }
                if top is None or best[1] > top["p95_ms"]:
                    top = {"route": route, "stage": best[0],
                           "p95_ms": round(best[1], 3), "host": best[2]}
        return {"hosts": hosts, "fleet": {"routes": routes, "top": top}}

    _CANARY_VERDICTS = ("ok", "slow", "wrong_answer", "unreachable")

    def fleet_canary(self) -> dict:
        """The `GET /fleet/canary` body: every host's Heliograph ledger
        state (carried as `dds_canary_*` gauges inside the shipped
        metrics_text — zero wire-format changes, like the pipe profile)
        rolled up per probe kind.

        Rollup semantics: a kind's fleet verdict is the WORST across
        hosts (the verdict enum is severity-ordered) — one region's
        prober seeing wrong answers IS the fleet's problem, not a
        minority report to average away. `failures` lists every host's
        current exemplar, newest-first by ledger sequence; each trace id
        resolves via `GET /fleet/incidents?trace_id=...` into the
        stitched Chronoscope span tree for that probe."""
        hosts: dict = {}
        kinds: dict = {}
        failures: list = []
        regions_down: set[str] = set()
        enum = self._CANARY_VERDICTS
        for r in self._source_rows():
            hrow = hosts.setdefault(r["host"], {
                "role": r["role"], "shard": r["shard"],
                "region": r.get("region", ""),
                "age_s": round(r["age_s"], 3), "stale": r["stale"],
                "kinds": {},
            })
            text = r["metrics_text"]
            for labels, v in parse_samples(text, "dds_canary_verdict"):
                kind = labels.get("kind", "-")
                i = int(v) if 0 <= v < len(enum) else len(enum) - 1
                hrow["kinds"].setdefault(kind, {})["verdict"] = enum[i]
                agg = kinds.setdefault(kind, {"worst": 0, "hosts": 0})
                agg["hosts"] += 1
                agg["worst"] = max(agg["worst"], i)
            for labels, v in parse_samples(
                    text, "dds_canary_last_ok_age_seconds"):
                kind = labels.get("kind", "-")
                hrow["kinds"].setdefault(kind, {})["last_ok_age_s"] = (
                    round(v, 3))
            for labels, v in parse_samples(text, "dds_canary_exemplar"):
                failures.append({
                    "host": r["host"], "region": r.get("region", ""),
                    "kind": labels.get("kind", "-"),
                    "verdict": labels.get("verdict", "-"),
                    "trace_id": labels.get("trace_id", ""),
                    "seq": v,
                })
            for labels, v in parse_samples(
                    text, "dds_canary_region_unreachable"):
                if v and labels.get("region"):
                    regions_down.add(labels["region"])
        failures.sort(key=lambda f: -f["seq"])
        for agg in kinds.values():
            agg["worst"] = enum[agg["worst"]]
        return {
            "hosts": hosts,
            "fleet": {
                "kinds": kinds,
                "failures": failures[:32],
                "unreachable_regions": sorted(regions_down),
            },
        }

    def fleet_incidents(self, trace_id: str | None = None) -> dict:
        """The `GET /fleet/incidents` body: shipped incident-index entries
        (newest last) correlated by trace id, plus the collector-side
        audit verdicts — the fleet-wide `why` for any offending trace."""
        entries = [e for e in self._incidents
                   if trace_id is None or e.get("trace_id") == trace_id]
        by_trace: dict = {}
        for e in entries:
            tid = e.get("trace_id")
            if tid:
                by_trace.setdefault(tid, []).append(e)
        verdicts = [
            v.as_dict() for v in self.watchtower.verdicts()
            if trace_id is None or v.trace_id == trace_id
        ]
        return {
            "count": len(entries),
            "incidents": entries,
            "by_trace": by_trace,
            "verdicts": verdicts,
        }

    def sample_gauges(self) -> None:
        """Scrape-time collector gauges (http/server's
        `_sample_state_gauges` hook)."""
        self.metrics.set("dds_fleet_sources", len(self._sources),
                         help="remote telemetry sources the collector "
                              "currently knows")
        self.metrics.set("dds_fleet_pending_traces", len(self._traces),
                         help="trace trees buffered awaiting stitch replay")

    def stats(self) -> dict:
        return {
            "sources": sorted(self._sources),
            "pending_traces": len(self._traces),
            "traces_stitched": self.traces_stitched,
            "incidents": len(self._incidents),
        }
