"""Watchtower: online BFT invariant auditing over the Telescope plane.

Telescope (obs/) records what happened — span trees per request, metrics,
flight incidents — but nothing *consumes* it: a Byzantine coordinator that
answers a write without a quorum, a forged tag that moves a key backwards,
or a breaker that teleports between states all pass silently unless a
human reads traces. Watchtower closes that loop: it subscribes to the
process tracer (`utils/trace.Tracer.subscribe`) and audits every completed
trace online, checking the dependability invariants the paper's claim
rests on:

- `quorum_intersection` — every committed quorum op's phase participant
  sets (replicas that handled the Read/ReadTag phase vs the Write phase,
  scoped to that op's span subtree) must each hold >= quorum_size distinct
  replicas and pairwise intersect in >= max(1, 2q - n) (= f+1 at n=2f+q-n
  ... the bound verified state transfer already uses). A coordinator that
  answered the proxy early — fewer than q replicas ever saw the write —
  is caught here.
- `tag_monotonicity` — per key, across reads AND writes: an op that
  starts after another op on the same key completed must never commit a
  LOWER (seq, id) tag, and a committed write must never re-mint a tag an
  earlier completed op already carried. A coordinator forging a stale
  (properly MAC'd) reply is caught here.
- `read_sees_latest` — within one trace: a read must return a tag >= any
  write to the same key that completed earlier in the same trace.
- `repair_convergence` — anti-entropy `audit.repair` events must install
  a tag >= the tag the peer advertised for that key (a lying peer that
  advertises fresh and serves stale never converges).
- `breaker_legality` — per-target breaker transitions must follow the
  machine: `half_open` is only reachable from `open` (any state may close
  on success or open on failure).
- `suspicion_legality` — a coordinator that accumulated 3 protocol
  violations is permanently excluded; any op committed through it AFTER
  the third strike is a violation.
- `lease_intersection` — Atlas lease reads (spans tagged `lease=True`)
  legally bypass the quorum-intersection bound: their freshness rests on
  the holder-pinned quorum geometry instead (while a lease is active,
  every quorum its group closes includes the holder — dds_tpu/geo). The
  auditable residue is that the serving replica actually HOLDS a lease:
  with a configured `lease_lookup`, a lease-tagged read served by a
  non-holder is a forged local read and a violation.
- `lease_staleness` — the documented weaker bound for lease reads: a
  lease read that returns a tag older than a write known-completed
  before it started is REPORTED under this invariant (the residual
  grant-instant window, bounded by one in-flight round + lease TTL by
  construction), never as `tag_monotonicity`/`read_sees_latest` — so a
  geo drill can assert "only the documented lease-window verdicts, and
  nothing else".

Every violation becomes a structured `Verdict`, increments
`dds_audit_violations_total{invariant=...}`, and files a flight-recorder
incident (`audit_<invariant>`) carrying the offending trace — telemetry
to automated verdicts, never an exception into the audited path.

Scope: the auditor sees THIS process's tracer ring, so quorum checks are
only sound when every replica of the deployment records spans here
(single-process topologies — the default, and every chaos/test harness).
`run.launch` disables `check_quorum` for multi-host splits; the tag,
repair, and state-machine checks audit proxy/agent-side commits and stay
sound everywhere. Late spans that land after a root span completed (a
chaos-delayed straggler delivery) are not re-audited: completed ops
causally precede their root's completion, so the audited tree is always a
superset of what the commit required.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field

from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics

log = logging.getLogger("dds.watchtower")

__all__ = ["Verdict", "Watchtower", "watchtower"]

# phase classification of replica.handle spans by message type
_READ_PHASE_MSGS = {"Read", "ReadTag"}
_WRITE_PHASE_MSGS = {"Write"}
_BREAKER_EVENTS = {"breaker.open", "breaker.half_open", "breaker.closed"}


@dataclass(frozen=True)
class Verdict:
    """One audited invariant violation."""

    invariant: str
    trace_id: str | None
    ts: float
    detail: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "invariant": self.invariant,
            "trace_id": self.trace_id,
            "ts": self.ts,
            "detail": self.detail,
        }


@dataclass
class _Op:
    """A committed quorum op distilled from an abd.* span."""

    op: str                 # "read" | "write"
    key: str
    tag: tuple              # (seq, id)
    start: float
    end: float
    trace_id: str | None
    coordinator: str = ""
    lease: bool = False     # Atlas read-local lease fast path
    replica: str = ""       # the lease holder that served it


class Watchtower:
    """Online trace auditor; attach to a Tracer via `attach()`."""

    def __init__(
        self,
        quorum_size: int = 5,
        n_replicas: int = 7,
        check_quorum: bool = True,
        suspicion_limit: int = 3,
        max_traces: int = 512,
        max_trace_spans: int = 4096,
        max_verdicts: int = 256,
        history_per_key: int = 8,
    ):
        self._lock = threading.Lock()
        self._tracer = None
        self.configure(
            quorum_size=quorum_size,
            n_replicas=n_replicas,
            check_quorum=check_quorum,
        )
        self.suspicion_limit = suspicion_limit
        self.max_traces = max_traces
        self.max_trace_spans = max_trace_spans
        self.history_per_key = history_per_key
        # trace_id -> [SpanRecord] for traces still in flight
        self._traces: collections.OrderedDict = collections.OrderedDict()
        self._verdicts: collections.deque = collections.deque(maxlen=max_verdicts)
        self._violation_counts: collections.Counter = collections.Counter()
        # key -> bounded [_Op] history (max-tag entry always retained)
        self._key_history: dict[str, list] = {}
        self._breaker_state: dict[str, str] = {}
        self._suspicion: collections.Counter = collections.Counter()
        self._excluded_at: dict[str, float] = {}  # node -> ts of 3rd strike
        self.traces_audited = 0
        self.ops_audited = 0

    def configure(
        self,
        quorum_size: int | None = None,
        n_replicas: int | None = None,
        check_quorum: bool | None = None,
        group_geometry: dict | None = None,
        lease_lookup=None,
    ) -> None:
        """Late wiring from a deployment config (run.launch).

        `group_geometry` maps a Constellation group id (the replica-name
        prefix, e.g. "s0" for "s0-replica-3") to that group's (quorum
        size, active replica count): a sharded deployment's ops are
        audited against the geometry of the GROUP whose replicas served
        them, not a global q/n — heterogeneous groups audit correctly.

        `lease_lookup` (Atlas) is a callable `replica_name -> bool`
        answering "does this replica hold an active read lease?" — the
        ground truth the `lease_intersection` invariant audits lease-
        tagged reads against (typically a closure over the fabric's
        per-group LeaseTables). Audit runs at trace completion, so keep
        the lookup tolerant of grants that expired moments ago (renewing
        sessions keep holders stable in practice)."""
        if quorum_size is not None:
            self.quorum_size = quorum_size
        if n_replicas is not None:
            self.n_replicas = n_replicas
        if check_quorum is not None:
            self.check_quorum = check_quorum
        if group_geometry is not None:
            self.group_geometry = dict(group_geometry)
        elif not hasattr(self, "group_geometry"):
            self.group_geometry = {}
        if lease_lookup is not None:
            self.lease_lookup = lease_lookup
        elif not hasattr(self, "lease_lookup"):
            self.lease_lookup = None
        # quorum-intersection bound: any two quorums of size q out of n
        # replicas share >= 2q - n members (>= f+1 for honest quorums)
        self.intersection = max(1, 2 * self.quorum_size - self.n_replicas)

    def _geometry_for(self, participants: set[str]) -> tuple[int, int]:
        """(quorum, intersection bound) for the group that served an op,
        resolved from the participants' name prefixes; falls back to the
        global geometry for unsharded deployments."""
        if self.group_geometry:
            for name in participants:
                for gid, (q, n) in self.group_geometry.items():
                    if name.startswith(gid + "-"):
                        return q, max(1, 2 * q - n)
        return self.quorum_size, self.intersection

    # ------------------------------------------------------------ lifecycle

    def attach(self, tracer) -> None:
        """Subscribe to `tracer`; idempotent (re-attach moves the feed)."""
        if self._tracer is not None:
            self._tracer.unsubscribe(self.on_record)
        self._tracer = tracer
        tracer.subscribe(self.on_record)

    def detach(self) -> None:
        if self._tracer is not None:
            self._tracer.unsubscribe(self.on_record)
            self._tracer = None

    @property
    def attached(self) -> bool:
        return self._tracer is not None

    def reset(self) -> None:
        """Drop all audit state (tests; a fresh deployment in-process)."""
        with self._lock:
            self._traces.clear()
            self._verdicts.clear()
            self._violation_counts.clear()
            self._key_history.clear()
            self._breaker_state.clear()
            self._suspicion.clear()
            self._excluded_at.clear()
            self.traces_audited = 0
            self.ops_audited = 0

    # -------------------------------------------------------------- reports

    def verdicts(self) -> list[Verdict]:
        with self._lock:
            return list(self._verdicts)

    def stats(self) -> dict:
        with self._lock:
            return {
                "attached": self.attached,
                "check_quorum": self.check_quorum,
                "quorum_size": self.quorum_size,
                "n_replicas": self.n_replicas,
                "traces_audited": self.traces_audited,
                "ops_audited": self.ops_audited,
                "pending_traces": len(self._traces),
                "violations": dict(self._violation_counts),
            }

    # ----------------------------------------------------------------- feed

    def on_record(self, rec) -> None:
        """Tracer subscriber: buffer per trace, audit on root completion.
        Called on the recording thread — must stay cheap and never raise
        (the tracer also guards, but a broken auditor silently eating
        telemetry is its own failure mode)."""
        try:
            self._ingest(rec)
        except Exception:  # noqa: BLE001
            log.exception("watchtower ingest failed for %r", rec.name)

    def _ingest(self, rec) -> None:
        # cross-trace state machines update on arrival (their legality is
        # about per-target event ORDER, not trace membership)
        if rec.kind == "event":
            if rec.name in _BREAKER_EVENTS:
                self._on_breaker(rec)
            elif rec.name == "abd.coordinator_violation":
                self._on_suspicion(rec)
        if rec.trace_id is None:
            return
        with self._lock:
            buf = self._traces.get(rec.trace_id)
            if buf is None:
                buf = self._traces[rec.trace_id] = []
                while len(self._traces) > self.max_traces:
                    # oldest in-flight trace is evicted unaudited (bounded
                    # memory beats a complete audit of a leaked trace id)
                    self._traces.popitem(last=False)
            if len(buf) < self.max_trace_spans:
                buf.append(rec)
            complete = rec.kind == "span" and rec.parent_id is None
            if complete:
                self._traces.pop(rec.trace_id, None)
        if complete:
            self._audit_trace(rec.trace_id, buf)

    # ------------------------------------------------- cross-trace machines

    def _on_breaker(self, rec) -> None:
        target = str(rec.meta.get("target", ""))
        state = rec.name.rsplit(".", 1)[-1]
        with self._lock:
            prev = self._breaker_state.get(target, "closed")
            self._breaker_state[target] = state
        # legal: anything -> open (threshold / failed probe), anything ->
        # closed (a success proves health, even from open via an in-flight
        # request begun before the trip); half_open ONLY matures from open.
        if state == "half_open" and prev != "open":
            self._violate(
                "breaker_legality", rec.trace_id,
                target=target, transition=f"{prev}->half_open",
            )

    def _on_suspicion(self, rec) -> None:
        node = str(rec.meta.get("node", ""))
        with self._lock:
            self._suspicion[node] += 1
            if (
                self._suspicion[node] >= self.suspicion_limit
                and node not in self._excluded_at
            ):
                self._excluded_at[node] = rec.ts

    # ------------------------------------------------------------ trace audit

    def _audit_trace(self, trace_id: str, records: list) -> None:
        children: dict[str, list] = collections.defaultdict(list)
        for r in records:
            if r.parent_id is not None:
                children[r.parent_id].append(r)

        ops: list[_Op] = []
        for r in records:
            if r.kind != "span":
                continue
            if r.name in ("abd.write", "abd.fetch") and r.meta.get("ok"):
                op = self._distill_op(r)
                if op is not None:
                    ops.append(op)
                if r.meta.get("lease"):
                    # a lease read is a single hop — no quorum subtree to
                    # intersect; audit the weaker lease invariant instead
                    self._check_lease_intersection(r)
                elif self.check_quorum:
                    self._check_quorum_intersection(r, children)
        for r in records:
            if r.kind == "event" and r.name == "audit.repair":
                self._check_repair(r)

        # completion order within the records list IS commit order (spans
        # record when they exit); audit within-trace read-after-write first,
        # then fold each op into the cross-trace per-key history
        last_write: dict[str, _Op] = {}
        for op in ops:
            flagged = False
            if op.op == "read":
                w = last_write.get(op.key)
                if w is not None and w.end <= op.start and op.tag < w.tag:
                    flagged = True
                    if op.lease:
                        # documented lease-window bound, not a BFT violation
                        self._violate(
                            "lease_staleness", op.trace_id,
                            key=op.key, read_tag=list(op.tag),
                            write_tag=list(w.tag), replica=op.replica,
                            window="intra_trace",
                        )
                    else:
                        self._violate(
                            "read_sees_latest", op.trace_id,
                            key=op.key, read_tag=list(op.tag),
                            write_tag=list(w.tag), coordinator=op.coordinator,
                        )
            self._check_key_history(op, already_flagged=flagged)
            self._check_suspicion_legality(op)
            if op.op == "write":
                cur = last_write.get(op.key)
                if cur is None or op.tag > cur.tag:
                    last_write[op.key] = op
            self.ops_audited += 1
        with self._lock:
            self.traces_audited += 1

    @staticmethod
    def _distill_op(rec) -> _Op | None:
        key = rec.meta.get("key")
        seq = rec.meta.get("seq")
        if not isinstance(key, str) or seq is None:
            return None
        end = rec.ts
        start = end - rec.dur_ms / 1e3
        return _Op(
            op=str(rec.meta.get("op") or
                   ("write" if rec.name == "abd.write" else "read")),
            key=key,
            tag=(int(seq), str(rec.meta.get("tag_id", ""))),
            start=start,
            end=end,
            trace_id=rec.trace_id,
            coordinator=str(rec.meta.get("coordinator", "")),
            lease=bool(rec.meta.get("lease")),
            replica=str(rec.meta.get("replica", "")),
        )

    def _check_lease_intersection(self, op_span) -> None:
        """Audit a lease-tagged read against the lease ground truth: the
        serving replica must hold an active lease (AbdClient only marks
        `lease=True` on the single-hop fast path, whose whole safety case
        is the holder-pinned quorum geometry). Without a configured
        `lease_lookup` there is no ground truth to check — the span is
        merely exempted from the quorum-intersection bound."""
        if self.lease_lookup is None:
            return
        replica = str(op_span.meta.get("replica", ""))
        try:
            holds = bool(self.lease_lookup(replica))
        except Exception:  # noqa: BLE001 — a broken lookup must not drop audits
            log.exception("lease_lookup failed for %r", replica)
            return
        if not holds:
            self._violate(
                "lease_intersection", op_span.trace_id,
                key=op_span.meta.get("key"), replica=replica,
            )

    def _check_quorum_intersection(self, op_span, children) -> None:
        """Phase participant sets over the op span's subtree: committed
        means the coordinator saw a full quorum of phase replies, and each
        reply was sent only AFTER its replica recorded the handler span —
        so an honest commit always shows >= q distinct handlers per phase
        here, and two phases of one op must overlap like any two quorums."""
        read_set: set[str] = set()
        write_set: set[str] = set()
        stack = list(children.get(op_span.span_id, ()))
        seen = 0
        while stack and seen < self.max_trace_spans:
            r = stack.pop()
            seen += 1
            stack.extend(children.get(r.span_id, ()))
            if r.name != "replica.handle":
                continue
            msg = r.meta.get("msg")
            replica = str(r.meta.get("replica", ""))
            if msg in _READ_PHASE_MSGS:
                read_set.add(replica)
            elif msg in _WRITE_PHASE_MSGS:
                write_set.add(replica)
        q, intersection = self._geometry_for(read_set | write_set)
        is_write = op_span.name == "abd.write"
        problems = []
        if len(read_set) < q:
            problems.append(f"read_phase={len(read_set)}<{q}")
        # reads may legally skip the write-back (all-tags-equal fast path):
        # an empty write set is fine, a sub-quorum one never is
        if (is_write or write_set) and len(write_set) < q:
            problems.append(f"write_phase={len(write_set)}<{q}")
        if (
            read_set and write_set
            and len(read_set & write_set) < intersection
        ):
            problems.append(
                f"intersection={len(read_set & write_set)}<{intersection}"
            )
        if problems:
            self._violate(
                "quorum_intersection", op_span.trace_id,
                op=op_span.name, key=op_span.meta.get("key"),
                coordinator=op_span.meta.get("coordinator"),
                read_phase=sorted(read_set), write_phase=sorted(write_set),
                problems=problems,
            )

    def _check_key_history(self, op: _Op, already_flagged: bool) -> None:
        with self._lock:
            hist = self._key_history.setdefault(op.key, [])
            prior = list(hist)
        for h in prior:
            if h.end > op.start:
                continue  # overlapped in real time: no order to enforce
            stale = op.tag < h.tag
            dup_mint = op.op == "write" and op.tag == h.tag
            if (stale or dup_mint) and not already_flagged:
                already_flagged = True
                if op.lease and stale:
                    # the residual grant-instant window (dds_tpu/geo):
                    # file it under the documented lease invariant so a
                    # drill can distinguish it from a real BFT violation
                    self._violate(
                        "lease_staleness", op.trace_id,
                        key=op.key, tag=list(op.tag),
                        prior_tag=list(h.tag), prior_trace=h.trace_id,
                        replica=op.replica, window="cross_trace",
                    )
                    continue
                self._violate(
                    "tag_monotonicity", op.trace_id,
                    key=op.key, op=op.op, tag=list(op.tag),
                    prior_tag=list(h.tag), prior_trace=h.trace_id,
                    coordinator=op.coordinator,
                    violation_kind="duplicate_mint" if dup_mint else "stale",
                )
        with self._lock:
            hist.append(op)
            if len(hist) > self.history_per_key:
                # keep the max-tag entry (the strongest witness) and shed
                # the oldest of the rest
                mx = max(range(len(hist)), key=lambda i: hist[i].tag)
                for i in range(len(hist)):
                    if i != mx:
                        hist.pop(i)
                        break

    def _check_suspicion_legality(self, op: _Op) -> None:
        node = op.coordinator
        if not node:
            return
        with self._lock:
            excluded_ts = self._excluded_at.get(node)
        if excluded_ts is not None and op.start > excluded_ts:
            self._violate(
                "suspicion_legality", op.trace_id,
                coordinator=node, key=op.key, op=op.op,
                strikes=self._suspicion.get(node, 0),
            )

    def _check_repair(self, rec) -> None:
        m = rec.meta
        try:
            src = (int(m["src_seq"]), str(m["src_id"]))
            installed = (int(m["seq"]), str(m["tag_id"]))
        except (KeyError, TypeError, ValueError):
            return
        if installed < src:
            self._violate(
                "repair_convergence", rec.trace_id,
                key=m.get("key"), replica=m.get("replica"),
                peer=m.get("peer"), advertised=list(src),
                installed=list(installed),
            )

    # -------------------------------------------------------------- verdicts

    def report_violation(self, invariant: str, trace_id, **detail) -> "Verdict":
        """External evidence entry point: a plane that PROVED a violation
        by independent means files the verdict here so it lands in the
        same ledger / metrics / flight-incident surface as the passive
        audits. Heliograph's decrypt-and-verify probes use this for
        `canary_wrong_answer` — exactly the forged-tag/corruption class
        the BFT audits exist for, caught by an active check the passive
        tag algebra cannot see (a well-MAC'd wrong ciphertext is
        quorum-consistent)."""
        return self._violate(invariant, trace_id, **detail)

    def _violate(self, invariant: str, trace_id, **detail) -> Verdict:
        v = Verdict(invariant, trace_id, time.time(), detail)
        with self._lock:
            self._verdicts.append(v)
            self._violation_counts[invariant] += 1
        log.warning("audit violation %s (trace %s): %s", invariant, trace_id,
                    detail)
        metrics.inc(
            "dds_audit_violations_total", invariant=invariant,
            help="BFT invariant violations detected by the Watchtower auditor",
        )
        # the offending trace, frozen for post-mortem (no-op when the
        # flight recorder has no directory); per-invariant kind so one
        # noisy invariant cannot rate-limit another's first incident.
        # Detail keys that would shadow record()'s own parameters are
        # namespaced out of the way.
        safe = {
            (k if k not in ("kind", "trace_id") else f"detail_{k}"): val
            for k, val in detail.items()
        }
        flight.record(f"audit_{invariant}", trace_id=trace_id, **safe)
        return v


# process-wide auditor; run.launch() configures + attaches it
watchtower = Watchtower()
