"""Trace-context propagation: the causal spine of Telescope.

A `SpanContext` names one node of a distributed trace: `(trace_id,
span_id, parent_id)`. The REST edge mints a root context per request
(`http/server.py handle`); every `tracer.span(...)` below it derives a
child and installs it in a `contextvars.ContextVar`, so nested spans link
parent->child without threading a parameter through 23 routes, the quorum
client, and the replica protocol handlers.

Cross-task propagation is free in-process: `asyncio.ensure_future` copies
the caller's contextvars at task-creation time, so a replica handler
scheduled by `InMemoryNet.send` (or a ChaosNet-deferred delivery) runs
under the quorum round's span context and its spans slot into the same
tree. Across a `TcpNet` hop the context travels as a tiny `tc` frame
field (`to_wire`/`from_wire`) — observability metadata only, deliberately
OUTSIDE the frame MAC/signature: a forged trace id can mislabel telemetry,
never affect protocol decisions.

Ids are 64-bit random hex (8 bytes), the W3C traceparent sizing halved —
collision-safe for a per-process ring of 64k spans.
"""

from __future__ import annotations

import contextvars
import secrets
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "SpanContext", "current", "root", "child", "attach", "detach",
    "new_id", "to_wire", "from_wire", "from_header", "to_header",
]


@dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None


_current: contextvars.ContextVar[Optional[SpanContext]] = contextvars.ContextVar(
    "dds_span_context", default=None
)


def new_id() -> str:
    return secrets.token_hex(8)


def current() -> Optional[SpanContext]:
    """The active span context of this task, or None outside any trace."""
    return _current.get()


def root() -> SpanContext:
    """Mint a fresh trace root (the REST edge, or a background job)."""
    return SpanContext(new_id(), new_id(), None)


def child(parent: Optional[SpanContext] = None) -> SpanContext:
    """A child of `parent` (default: the current context). With no parent
    anywhere, starts a fresh root — spans recorded outside a request still
    get ids, they just form single-span traces."""
    p = parent if parent is not None else _current.get()
    if p is None:
        return root()
    return SpanContext(p.trace_id, new_id(), p.span_id)


def attach(ctx: Optional[SpanContext]) -> contextvars.Token:
    return _current.set(ctx)


def detach(token: contextvars.Token) -> None:
    _current.reset(token)


# ------------------------------------------------------------------- wire

def to_wire(ctx: Optional[SpanContext] = None) -> Optional[dict]:
    """Compact dict for a transport frame (None = nothing to propagate).
    Carries (trace, span) of the SENDER's active span; the receiver's
    spans become its children."""
    ctx = ctx if ctx is not None else _current.get()
    if ctx is None:
        return None
    return {"t": ctx.trace_id, "s": ctx.span_id}


def _valid_id(v) -> bool:
    """Ids we mint are 16 lowercase-hex chars; accept up to 32 (the W3C
    traceparent width) so foreign tracers can interop, but ONLY hex — the
    `tc` field is unauthenticated, and these strings end up as collector
    dict keys, metric labels, and flight-incident headers."""
    return (isinstance(v, str) and 0 < len(v) <= 32
            and all(c in "0123456789abcdef" for c in v))


def from_wire(d) -> Optional[SpanContext]:
    """Parse a frame's `tc` field; garbage (or absence) degrades to None —
    a malformed trace context must never drop the message it rode on.
    Strict length/charset clamp: a hostile peer's oversized or non-hex
    ids are refused wholesale (the span orphans into a fresh local root)
    instead of truncated into a colliding-but-plausible id that would
    poison cross-host stitching."""
    if d is None:
        return None
    if not isinstance(d, dict):
        return _malformed()
    t, s = d.get("t"), d.get("s")
    if not _valid_id(t) or not _valid_id(s):
        return _malformed()
    return SpanContext(t, s)


def _malformed() -> None:
    """Present garbage (vs. absent context): count it so a peer spraying
    hostile `tc` fields is visible on /metrics."""
    from dds_tpu.obs.metrics import metrics  # lazy: avoid import cycle

    metrics.inc("dds_trace_context_malformed_total",
                help="hostile/garbled tc frame fields dropped at ingest")
    return None


# ----------------------------------------------------------------- header

def to_header(ctx: Optional[SpanContext] = None) -> str:
    """`x-dds-trace` header value ("trace_id-span_id"), "" when none."""
    ctx = ctx if ctx is not None else _current.get()
    return f"{ctx.trace_id}-{ctx.span_id}" if ctx is not None else ""


def from_header(value: str) -> Optional[SpanContext]:
    """Parse an inbound `x-dds-trace` header so an upstream caller (a
    gossiping peer proxy, a load-test harness) can stitch its trace onto
    this process's spans. Malformed values degrade to None (fresh root)."""
    if not value or "-" not in value:
        return None
    t, _, s = value.partition("-")
    t, s = t.strip(), s.strip()
    if not t or not s or len(t) > 32 or len(s) > 32:
        return None
    return SpanContext(t, s)
