"""Flight recorder: fault-triggered incident dumps for post-mortems.

When the stack detects a fault — a suspicion quorum, a circuit breaker
opening, a request budget exhausting (`DeadlineExceededError`), a
Trudy/Nemesis attack firing — the in-memory telemetry that explains it is
about to be overwritten by the span ring. The flight recorder freezes it:
one JSONL incident file per fault with a header record (fault kind, info,
live counters, span summary) followed by the faulting trace's full span
tree and the tail of the span ring. Every chaos-suite failure becomes
self-describing instead of un-reproducible.

Disabled unless given a directory (config `obs.flight_dir` or env
`DDS_OBS_FLIGHT_DIR`) — recording is a disk write on a fault path, so it
must be opt-in and can never raise into the caller. Incidents are
rate-limited per kind (`min_interval`) and pruned to `max_incidents`
files, so a flapping breaker cannot fill a disk. Writes are atomic
(tmp + rename): a crash mid-dump leaves no truncated incident.

Every incident also appends one line to `<dir>/index.jsonl` —
`{"ts", "kind", "trace_id", "path"}` — so operators (and tooling)
enumerate incidents in order without globbing or opening each file;
pruning rewrites the index to drop entries whose file is gone, keeping
it authoritative under the same `max_incidents` retention bound.

Env flags: DDS_OBS_FLIGHT_DIR, DDS_OBS_FLIGHT_MAX (default 32),
DDS_OBS_FLIGHT_INTERVAL (seconds per kind, default 1.0).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import pathlib
import threading
import time

from dds_tpu.obs import context as obs_context
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.flight")

__all__ = ["FlightRecorder", "flight"]


class FlightRecorder:
    # span-ring tail included in every incident alongside the faulting trace
    RING_TAIL = 512

    def __init__(self, dir: str | None = None, max_incidents: int | None = None,
                 min_interval: float | None = None):
        env_dir = os.environ.get("DDS_OBS_FLIGHT_DIR", "")
        self.dir = dir if dir is not None else (env_dir or None)
        self.max_incidents = (
            max_incidents
            if max_incidents is not None
            else int(os.environ.get("DDS_OBS_FLIGHT_MAX", "32") or 32)
        )
        self.min_interval = (
            min_interval
            if min_interval is not None
            else float(os.environ.get("DDS_OBS_FLIGHT_INTERVAL", "1.0") or 1.0)
        )
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}  # kind -> monotonic ts of last dump
        self._seq = 0
        # process identity stamped into every incident header (host/role/
        # shard) so fleet-wide correlation (obs/panopticon) can attribute
        # an incident to its source without parsing file paths
        self.identity: dict = {}

    def configure(self, dir: str | None = None, max_incidents: int | None = None,
                  min_interval: float | None = None,
                  identity: dict | None = None) -> None:
        """Late wiring from a deployment config (run.launch)."""
        if dir is not None:
            self.dir = dir or None
        if max_incidents is not None:
            self.max_incidents = max_incidents
        if min_interval is not None:
            self.min_interval = min_interval
        if identity is not None:
            self.identity = {k: str(v) for k, v in identity.items()}

    @property
    def enabled(self) -> bool:
        return bool(self.dir)

    def record(self, kind: str, trace_id: str | None = None, **info):
        """Dump one incident; returns its path, or None (disabled /
        rate-limited / write failure — never raises). `trace_id` defaults
        to the active trace so the faulting request's tree is captured."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last.get(kind)
            if last is not None and now - last < self.min_interval:
                metrics.inc(
                    "dds_incidents_suppressed_total", kind=kind,
                    help="flight-recorder dumps skipped by rate limiting",
                )
                return None
            self._last[kind] = now
            self._seq += 1
            seq = self._seq
        if trace_id is None:
            cur = obs_context.current()
            trace_id = cur.trace_id if cur is not None else None
        try:
            return self._write(kind, seq, trace_id, info)
        except OSError as e:
            log.warning("flight recorder dump for %r failed: %s", kind, e)
            return None

    async def record_async(self, kind: str, trace_id: str | None = None,
                           **info):
        """`record` for coroutine callers: same semantics, but the lock
        acquisition and disk write happen on a worker thread so an
        incident dump never stalls the event loop (which is busy running
        every other replica in the process). The trace id is resolved
        HERE, on the loop thread, so the faulting request's context is
        captured before the thread hop."""
        if not self.enabled:
            return None
        if trace_id is None:
            cur = obs_context.current()
            trace_id = cur.trace_id if cur is not None else None
        return await asyncio.to_thread(self.record, kind, trace_id, **info)

    # ----------------------------------------------------------- internals

    def _write(self, kind: str, seq: int, trace_id: str | None, info: dict):
        events = tracer.events()
        faulting = (
            [e for e in events if e.trace_id == trace_id] if trace_id else []
        )
        tail = events[-self.RING_TAIL:]
        header = {
            "incident": kind,
            "ts": time.time(),
            "trace_id": trace_id,
            **self.identity,
            "info": info,
            "counters": tracer.counters(),
            "summary": tracer.summary(),
            "trace_spans": len(faulting),
            "ring_tail": len(tail),
        }
        d = pathlib.Path(self.dir)
        d.mkdir(parents=True, exist_ok=True)
        safe_kind = "".join(c if c.isalnum() or c in "-_" else "_" for c in kind)
        name = f"incident-{int(time.time() * 1e3):013d}-{seq:04d}-{safe_kind}.jsonl"
        tmp = d / (name + ".tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for e in faulting:
                f.write(json.dumps(
                    {"section": "trace", **tracer.event_dict(e)}, default=str,
                ) + "\n")
            for e in tail:
                f.write(json.dumps(
                    {"section": "ring", **tracer.event_dict(e)}, default=str,
                ) + "\n")
        path = d / name
        os.replace(tmp, path)
        self._index_append(d, {
            "ts": header["ts"], "kind": kind, "trace_id": trace_id,
            "path": name, **self.identity,
        })
        metrics.inc("dds_incidents_total", kind=kind,
                    help="flight-recorder incident dumps written")
        self._prune(d)
        return str(path)

    INDEX = "index.jsonl"

    def _index_append(self, d: pathlib.Path, entry: dict) -> None:
        try:
            with open(d / self.INDEX, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
        except OSError as e:
            log.warning("flight index append failed: %s", e)

    def _prune(self, d: pathlib.Path) -> None:
        incidents = sorted(d.glob("incident-*.jsonl"))
        pruned = incidents[: max(0, len(incidents) - self.max_incidents)]
        for old in pruned:
            try:
                old.unlink()
            except OSError:
                pass
        if pruned:
            self._rewrite_index(d)

    def _rewrite_index(self, d: pathlib.Path) -> None:
        """Drop index entries whose incident file is gone (atomic rewrite:
        a crash mid-prune leaves the previous index, never a truncated
        one). Unparseable lines are dropped too — the index is derived
        state, the incident files stay authoritative."""
        idx = d / self.INDEX
        try:
            lines = idx.read_text().splitlines()
        except OSError:
            return
        kept = []
        for line in lines:
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if isinstance(entry, dict) and (d / str(entry.get("path"))).exists():
                kept.append(json.dumps(entry, default=str))
        try:
            tmp = idx.with_name(idx.name + ".tmp")
            tmp.write_text("".join(l + "\n" for l in kept))
            os.replace(tmp, idx)
        except OSError as e:
            log.warning("flight index rewrite failed: %s", e)


# process-wide recorder; run.launch() configures it from DDSConfig.obs
flight = FlightRecorder()
