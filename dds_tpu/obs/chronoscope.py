"""Chronoscope: critical-path attribution over stitched span trees.

Telescope records span trees and Panopticon stitches them fleet-wide,
but nothing COMPUTED from them: BENCH_r03/r04 show the fold kernels
sustaining millions of encrypted adds per second while PutSet moves
~1e3 ops/s through the pipe, and the feed-war item cannot be attacked
until someone can say which STAGE of the request pipe eats the time.
GME (arxiv 2309.11001) and BTS (arxiv 2112.15479) both argue HE
throughput is won in the memory/transfer system, not the ALU — which
demands per-stage, bytes-moved measurement, not another end-to-end
latency histogram.

Chronoscope consumes finished traces (as a `Tracer` subscriber, or fed
stitched trees by the Panopticon `FleetCollector`) and, per trace:

1. extracts the CRITICAL PATH — per node, children are clamped to the
   parent's window and claimed back-to-front so overlapping siblings
   (parallel fan-out) contribute only their non-overlapped tail; the
   slowest branch wins, and claimed windows recurse. Every node's
   SELF time (window minus claimed children) lands in exactly one
   stage, so the per-stage waterfall sums to the root duration by
   construction;
2. classifies each span into a closed stage taxonomy (`STAGES`);
   unknown names fall into "other", which counts AGAINST attribution
   coverage — a new span name showing up as "other" is the signal to
   extend the taxonomy;
3. aggregates per route: windowed p50/p95 self-time per stage, EWMA
   stage shares and coverage, cumulative totals (the folded flamegraph
   text), and worst-k slow-trace exemplars per rotating window, pushed
   through the flight recorder (`slow_trace` incidents) when they
   clear the slow floor.

The proxy serves the aggregate at `GET /profile` (JSON waterfall +
folded text) and exports `dds_pipe_*` gauges into the process metrics
registry at analyze time (throttled), so Panopticon's span shipper
carries each host's profile to the collector for the fleet-wide
rollup at `GET /fleet/profile` — zero wire-format changes.

Roots: a parent-less `http.*` span closes its trace (children record
before the root, since spans record on exit). `replica.handle` spans
are ALSO analyzed as subtree roots — on group hosts the proxy's root
never arrives, and this is what decomposes replica-apply time.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import threading
import time
from typing import Iterable, Optional

from dds_tpu.obs import context as obs_context  # noqa: F401  (re-export convenience)
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import SpanRecord, _percentile, tracer

log = logging.getLogger("dds.chronoscope")

# The closed stage taxonomy, in pipe order. Every span name maps to
# exactly one stage; root HTTP self-time (parse/encode/cache work around
# the downstream calls) is the "response" stage.
STAGES = (
    "admission",                # backpressure decision at the front door
    "coalesce-wait",            # sat in the proxy fold coalescer window
    "serialize",                # message -> wire frame (+ MAC/sig)
    "quorum-rtt",               # ABD round: on the wire + remote queueing
    "hmac-verify",              # proxy-side reply signature validation
    "replica-apply",            # replica handler work (storage + sign)
    "ingest-queue-wait",        # sat in a TimedQueue before a drain
    "host-to-device-transfer",  # host limbs -> HBM rows
    "tier-promote",             # Stratum warm/cold rows re-entering HBM
    "tier-demote",              # Stratum eviction: HBM -> warm -> segments
    "tier-cold-read",           # segment read + HMAC re-verify from disk
    "trace-compile",            # one-time jit trace+compile (cold call)
    "dispatch",                 # host-side dispatch orchestration
    "device-execute",           # on-device kernel time
    "response",                 # proxy host work around the calls
    "other",                    # unclassified — counts against coverage
)

_EPS = 1e-9


def classify(name: str, *, root: bool = False) -> str:
    """Map a span name to its pipe stage (see STAGES)."""
    if name == "proxy.admission":
        return "admission"
    if name == "proxy.coalesce_wait":
        return "coalesce-wait"
    if name == "net.serialize":
        return "serialize"
    if name == "abd.verify":
        return "hmac-verify"
    if name.startswith("abd."):
        return "quorum-rtt"
    if name == "ingest.queue_wait":
        return "ingest-queue-wait"
    if name == "ingest.h2d":
        return "host-to-device-transfer"
    if name == "tier.promote":
        return "tier-promote"
    if name == "tier.demote":
        return "tier-demote"
    if name == "tier.cold_read":
        return "tier-cold-read"
    if name.startswith("replica.") or name.startswith("antientropy."):
        return "replica-apply"
    if name.startswith("kernel."):
        if name.endswith(".compile"):
            return "trace-compile"
        if name.endswith(".dispatch"):
            return "dispatch"
        return "device-execute"
    if name in ("proxy.fold", "proxy.resident_fold", "proxy.scatter_fold",
                "proxy.coalesced_fold"):
        # fold orchestration: the kernel children claim their windows,
        # the marshaling remainder is host-side dispatch work
        return "dispatch"
    if name.startswith("http.") or name.startswith("proxy."):
        return "response"
    return "other"


class _Node:
    __slots__ = ("rec", "start", "end", "children", "events")

    def __init__(self, rec: SpanRecord):
        self.rec = rec
        self.end = rec.ts
        self.start = rec.ts - max(0.0, rec.dur_ms) / 1e3
        self.children: list["_Node"] = []
        self.events: list[SpanRecord] = []


def _build_nodes(records: Iterable[SpanRecord]):
    nodes: dict[str, _Node] = {}
    order: list[_Node] = []
    events: list[SpanRecord] = []
    for r in records:
        if r is None or getattr(r, "trace_id", None) is None:
            continue
        if r.kind == "event":
            events.append(r)
            continue
        if r.kind != "span":
            continue
        n = _Node(r)
        order.append(n)
        if r.span_id is not None and r.span_id not in nodes:
            nodes[r.span_id] = n
    return nodes, order, events


def critical_path(records: Iterable[SpanRecord], *,
                  root_span_id: Optional[str] = None,
                  orphans_to_root: bool = True) -> Optional[dict]:
    """Extract the blocking chain and per-stage self-times of one trace.

    Without `root_span_id` the longest parent-less span wins the root.
    With `orphans_to_root`, spans whose parent never arrived (Panopticon
    stragglers, intermediate contexts that never became spans) hang off
    the root and are clamped to its window — a partial tree still
    attributes. Returns None when no root can be found.
    """
    nodes, order, events = _build_nodes(records)
    if not order:
        return None
    if root_span_id is not None:
        root = nodes.get(root_span_id)
    else:
        tops = [n for n in order if n.rec.parent_id is None]
        cands = [n for n in tops if n.rec.name.startswith("http.")] or tops
        root = max(cands, key=lambda n: n.end - n.start, default=None)
    if root is None or root.end - root.start <= _EPS:
        return None
    for n in order:
        if n is root:
            continue
        parent = nodes.get(n.rec.parent_id) if n.rec.parent_id else None
        if parent is n:
            parent = None
        if parent is not None:
            parent.children.append(n)
        elif orphans_to_root:
            root.children.append(n)
    for ev in events:
        holder = nodes.get(ev.parent_id) if ev.parent_id else None
        if holder is not None:
            holder.events.append(ev)

    stages: dict[str, float] = {}
    path: list[dict] = []
    _attribute(root, root.start, root.end, 0, stages, path, root.start)
    wall_ms = (root.end - root.start) * 1e3
    named = sum(v for k, v in stages.items() if k != "other")
    return {
        "route": root.rec.name,
        "trace_id": root.rec.trace_id,
        "wall_ms": round(wall_ms, 3),
        "coverage": round(min(1.0, named / wall_ms), 4) if wall_ms else 1.0,
        "stages": {k: round(v, 3) for k, v in stages.items() if v > 0},
        "path": path,
    }


def _attribute(node: _Node, w_start: float, w_end: float, depth: int,
               stages: dict, path: list, t0: float) -> None:
    """Claim non-overlapping child windows back-to-front inside
    [w_start, w_end]; the unclaimed remainder is this node's self-time.
    Overlapping siblings keep only the tail the later-ending one left
    uncovered, so a parallel fan-out attributes its slowest branch."""
    window = max(0.0, w_end - w_start)
    cursor = w_end
    claimed: list[tuple[_Node, float, float]] = []
    for c in sorted(node.children, key=lambda c: c.end, reverse=True):
        e = min(c.end, cursor)
        s = max(c.start, w_start)
        if e - s <= _EPS:
            continue
        claimed.append((c, s, e))
        cursor = s
    self_s = max(0.0, window - sum(e - s for _, s, e in claimed))
    stage = classify(node.rec.name, root=depth == 0)
    stages[stage] = stages.get(stage, 0.0) + self_s * 1e3
    entry = {
        "name": node.rec.name,
        "stage": stage,
        "depth": depth,
        "start_ms": round((w_start - t0) * 1e3, 3),
        "dur_ms": round(window * 1e3, 3),
        "self_ms": round(self_s * 1e3, 3),
    }
    if node.rec.meta:
        entry["meta"] = dict(node.rec.meta)
    if node.events:
        entry["events"] = [
            {"name": ev.name, **({"meta": ev.meta} if ev.meta else {})}
            for ev in node.events[:8]
        ]
    path.append(entry)
    if depth >= 64:
        return
    for c, s, e in reversed(claimed):  # chronological order
        _attribute(c, s, e, depth + 1, stages, path, t0)


class Chronoscope:
    """Continuous per-route pipe profiler (see module docstring)."""

    MAX_TRACES = 1024        # in-flight trace buffers
    MAX_TRACE_SPANS = 2048   # spans buffered per trace
    DONE_LRU = 2048          # analyzed trace ids (straggler dedup)
    MAX_ROUTES = 64          # gauge-cardinality guard
    MAX_TENANTS = 256        # Bastion usage-ledger cardinality guard
    MAX_TENANT_ROUTES = 16   # per-tenant route breakdown cap

    def __init__(self, registry=metrics, *, window_s: float = 60.0,
                 exemplars: int = 3, slow_ms: float = 50.0,
                 max_samples: int = 512, ewma_alpha: float = 0.2):
        self._registry = registry
        self.window_s = float(window_s)
        self.exemplars = max(1, int(exemplars))
        self.slow_ms = float(slow_ms)
        self.max_samples = max(16, int(max_samples))
        self.ewma_alpha = float(ewma_alpha)
        self.enabled = os.environ.get("DDS_OBS_PIPE", "").strip().lower() \
            not in ("0", "false", "off", "no")
        self._lock = threading.Lock()
        self._traces: collections.OrderedDict = collections.OrderedDict()
        self._done: collections.OrderedDict = collections.OrderedDict()
        self._routes: dict[str, dict] = {}
        self._tenants: dict[str, dict] = {}
        self._attached = None
        self._last_export = 0.0
        self.traces_profiled = 0
        self.traces_evicted = 0

    # ------------------------------------------------------------ lifecycle

    def attach(self, tr=None) -> None:
        """Subscribe to a tracer (detaching any previous one). On hosts
        whose collector stitches fleet traces, leave detached and set
        `collector.profiler = chronoscope` instead — the stitched trees
        include the remote replica handlers."""
        self.detach()
        tr = tr if tr is not None else tracer
        tr.subscribe(self.on_record)
        self._attached = tr

    def detach(self) -> None:
        if self._attached is not None:
            self._attached.unsubscribe(self.on_record)
            self._attached = None

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._done.clear()
            self._routes.clear()
            self._tenants.clear()
            self.traces_profiled = 0
            self.traces_evicted = 0

    # ------------------------------------------- Bastion usage attribution

    def note_usage(self, tenant: str, route: str, dur_s: float) -> None:
        """One served request's wall time attributed to its tenant (fed
        from the REST edge; cheap enough for every request). Cardinality
        is bounded: past MAX_TENANTS live tenants the rest fold into the
        shared "overflow" row, and each tenant's route breakdown caps at
        MAX_TENANT_ROUTES — a tenant flood can never balloon the profile
        (the same argument as the route-gauge guard)."""
        if not self.enabled or not tenant:
            return
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                if len(self._tenants) >= self.MAX_TENANTS:
                    tenant = "overflow"
                    st = self._tenants.get(tenant)
                if st is None:
                    st = self._tenants[tenant] = {
                        "requests": 0, "seconds": 0.0, "routes": {},
                    }
            st["requests"] += 1
            st["seconds"] += dur_s
            rt = st["routes"]
            if route in rt or len(rt) < self.MAX_TENANT_ROUTES:
                rt[route] = rt.get(route, 0) + 1

    def tenant_usage(self) -> dict:
        """Per-tenant cumulative usage for /profile and the fleet rollup:
        request count, attributed wall seconds, top routes."""
        with self._lock:
            return {
                t: {
                    "requests": s["requests"],
                    "seconds": round(s["seconds"], 6),
                    "top_routes": dict(sorted(
                        s["routes"].items(), key=lambda kv: -kv[1]
                    )[:4]),
                }
                for t, s in self._tenants.items()
            }

    # ----------------------------------------------------------- ingestion

    def on_record(self, rec) -> None:
        """Tracer-subscriber feed: buffer per trace, analyze on root."""
        if not self.enabled:
            return
        try:
            tid = getattr(rec, "trace_id", None)
            if tid is None or rec.kind not in ("span", "event"):
                return
            with self._lock:
                if tid in self._done:
                    return
                buf = self._traces.get(tid)
                if buf is None:
                    buf = self._traces[tid] = {"records": [], "roots": set()}
                    while len(self._traces) > self.MAX_TRACES:
                        self._traces.popitem(last=False)
                        self.traces_evicted += 1
                if len(buf["records"]) < self.MAX_TRACE_SPANS:
                    buf["records"].append(rec)
            if rec.kind != "span":
                return
            if rec.parent_id is None and rec.name.startswith("http."):
                with self._lock:
                    buf = self._traces.pop(tid, None)
                    self._done[tid] = True
                    while len(self._done) > self.DONE_LRU:
                        self._done.popitem(last=False)
                if buf is not None:
                    self._analyze(buf["records"], done_roots=buf["roots"])
            elif rec.name == "replica.handle":
                with self._lock:
                    buf = self._traces.get(tid)
                    if buf is None:
                        return
                    buf["roots"].add(rec.span_id)
                    records = list(buf["records"])
                res = critical_path(records, root_span_id=rec.span_id,
                                    orphans_to_root=False)
                if res is not None:
                    self._absorb(res)
        except Exception:  # noqa: BLE001 — observers never break observed paths
            log.exception("chronoscope ingest failed")

    def ingest_tree(self, records) -> None:
        """Collector feed: one stitched trace (children + root), analyzed
        whole — the http root plus every replica.handle subtree."""
        if not self.enabled:
            return
        try:
            self._analyze(list(records), done_roots=set())
        except Exception:  # noqa: BLE001
            log.exception("chronoscope stitched ingest failed")

    def _analyze(self, records: list, *, done_roots: set) -> None:
        roots = [
            r for r in records
            if r.kind == "span" and r.parent_id is None
            and r.name.startswith("http.")
        ]
        for root in roots:
            res = critical_path(records, root_span_id=root.span_id)
            if res is not None:
                self._absorb(res)
        for r in records:
            if (r.kind == "span" and r.name == "replica.handle"
                    and r.span_id not in done_roots):
                res = critical_path(records, root_span_id=r.span_id,
                                    orphans_to_root=False)
                if res is not None:
                    self._absorb(res)

    # ---------------------------------------------------------- aggregation

    def _absorb(self, res: dict) -> None:
        route, wall = res["route"], res["wall_ms"]
        if wall <= 0:
            return
        now = time.monotonic()
        a = self.ewma_alpha
        admitted = False
        with self._lock:
            st = self._routes.get(route)
            if st is None:
                if len(self._routes) >= self.MAX_ROUTES:
                    return
                st = self._routes[route] = {
                    "count": 0,
                    "wall": collections.deque(maxlen=self.max_samples),
                    "coverage": None,
                    "stages": {},
                    "share": {},
                    "totals": {},
                    "ex_start": now,
                    "ex_cur": [],
                    "ex_prev": [],
                }
            st["count"] += 1
            st["wall"].append(wall)
            cov = st["coverage"]
            st["coverage"] = (
                res["coverage"] if cov is None
                else (1 - a) * cov + a * res["coverage"]
            )
            for k in set(st["stages"]) | set(res["stages"]):
                v = res["stages"].get(k, 0.0)
                dq = st["stages"].get(k)
                if dq is None:
                    dq = st["stages"][k] = collections.deque(
                        maxlen=self.max_samples
                    )
                dq.append(v)
                share = v / wall
                old = st["share"].get(k)
                st["share"][k] = (
                    share if old is None else (1 - a) * old + a * share
                )
                st["totals"][k] = st["totals"].get(k, 0.0) + v
            if now - st["ex_start"] >= self.window_s:
                st["ex_prev"] = st["ex_cur"]
                st["ex_cur"] = []
                st["ex_start"] = now
            cur = st["ex_cur"]
            if len(cur) < self.exemplars or wall > cur[-1][0]:
                cur.append((wall, res))
                cur.sort(key=lambda t: -t[0])
                del cur[self.exemplars:]
                admitted = any(r is res for _, r in cur)
            self.traces_profiled += 1
        try:
            self._registry.inc("dds_pipe_traces_total", route=route,
                               help="traces profiled by Chronoscope")
        except Exception:  # noqa: BLE001
            pass
        if admitted and wall >= self.slow_ms:
            self._capture(res)
        self._maybe_export()

    # ------------------------------------------------------------ exemplars

    def _capture(self, res: dict) -> None:
        """Freeze a slow-trace exemplar through the flight recorder.
        Runs inside a tracer subscriber (possibly ON the event loop
        thread), so the blocking write is dispatched supervised via
        `record_async`; only off-loop callers write synchronously."""
        from dds_tpu.obs.flight import flight

        if not getattr(flight, "enabled", False):
            return
        stages = res.get("stages") or {}
        top = max(stages.items(), key=lambda kv: kv[1])[0] if stages \
            else "other"
        info = {
            "route": res["route"], "wall_ms": res["wall_ms"],
            "coverage": res["coverage"], "top_stage": top,
            "stages": stages,
        }
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            try:
                flight.record("slow_trace", trace_id=res["trace_id"], **info)
            except Exception:  # noqa: BLE001
                log.exception("chronoscope exemplar capture failed")
            return
        from dds_tpu.utils.tasks import supervised_task

        supervised_task(
            flight.record_async("slow_trace", trace_id=res["trace_id"],
                                **info),
            name="chronoscope.exemplar",
        )

    # -------------------------------------------------------------- surface

    def _snapshot(self) -> dict:
        with self._lock:
            out = {}
            for route, st in self._routes.items():
                wall = sorted(st["wall"])
                stages = {}
                for k, dq in st["stages"].items():
                    durs = sorted(dq)
                    if not durs or durs[-1] <= 0:
                        continue
                    stages[k] = {
                        "p50_ms": round(_percentile(durs, 0.50), 3),
                        "p95_ms": round(_percentile(durs, 0.95), 3),
                        "share": round(st["share"].get(k, 0.0), 4),
                    }
                # the bottleneck must be a NAMED stage: unattributed
                # residue ("other") only wins when nothing else exists
                cand = {k: v for k, v in stages.items() if k != "other"} \
                    or stages
                top = max(cand.items(), key=lambda kv: kv[1]["p95_ms"])[0] \
                    if cand else None
                exemplars = sorted(
                    st["ex_cur"] + st["ex_prev"], key=lambda t: -t[0]
                )[: self.exemplars]
                out[route] = {
                    "count": st["count"],
                    "wall_p50_ms": round(_percentile(wall, 0.50), 3),
                    "wall_p95_ms": round(_percentile(wall, 0.95), 3),
                    "coverage": round(st["coverage"] or 0.0, 4),
                    "top_stage": top,
                    "stages": stages,
                    "totals_ms": {
                        k: round(v, 1) for k, v in st["totals"].items()
                    },
                    "exemplars": [r for _, r in exemplars],
                }
            return out

    def profile(self) -> dict:
        """The GET /profile JSON body."""
        out = {
            "enabled": self.enabled,
            "window_s": self.window_s,
            "taxonomy": list(STAGES),
            "traces_profiled": self.traces_profiled,
            "routes": self._snapshot(),
        }
        tenants = self.tenant_usage()
        if tenants:
            out["tenants"] = tenants
        return out

    def folded(self) -> str:
        """Folded flamegraph text (route;stage <self_ms>), one line per
        (route, stage) cumulative self-time — feed to any FlameGraph
        renderer."""
        lines = []
        with self._lock:
            for route, st in sorted(self._routes.items()):
                for stage, total in sorted(st["totals"].items()):
                    if total >= 1.0:
                        lines.append(f"{route};{stage} {int(total)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export_gauges(self, registry=None) -> None:
        """Publish the per-route/per-stage profile as dds_pipe_* gauges.
        Called throttled at analyze time (so the Panopticon shipper's
        metrics_text snapshot always carries a fresh profile) and again
        at scrape time."""
        reg = registry if registry is not None else self._registry
        snap = self._snapshot()
        for route, rs in snap.items():
            reg.set("dds_pipe_wall_p50_ms", rs["wall_p50_ms"], route=route,
                    help="profiled request wall time p50 per route")
            reg.set("dds_pipe_wall_p95_ms", rs["wall_p95_ms"], route=route,
                    help="profiled request wall time p95 per route")
            reg.set("dds_pipe_coverage", rs["coverage"], route=route,
                    help="EWMA fraction of wall time attributed to named "
                         "stages")
            for stage, ss in rs["stages"].items():
                reg.set("dds_pipe_stage_p50_ms", ss["p50_ms"],
                        route=route, stage=stage,
                        help="per-stage critical-path self-time p50")
                reg.set("dds_pipe_stage_p95_ms", ss["p95_ms"],
                        route=route, stage=stage,
                        help="per-stage critical-path self-time p95")
                reg.set("dds_pipe_stage_share", ss["share"],
                        route=route, stage=stage,
                        help="EWMA share of wall time per stage")
        for t, ts in self.tenant_usage().items():
            reg.set("dds_tenant_usage_seconds", ts["seconds"], tenant=t,
                    help="cumulative request wall seconds per tenant")
            reg.set("dds_tenant_usage_requests", ts["requests"], tenant=t,
                    help="cumulative served requests per tenant")

    def _maybe_export(self) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_export < 1.0:
                return
            self._last_export = now
        try:
            self.export_gauges()
        except Exception:  # noqa: BLE001
            log.exception("chronoscope gauge export failed")

    def stats(self) -> dict:
        with self._lock:
            return {
                "attached": self._attached is not None,
                "traces_profiled": self.traces_profiled,
                "traces_evicted": self.traces_evicted,
                "buffered_traces": len(self._traces),
                "routes": len(self._routes),
            }


# process-wide profiler (run/deploy attach it alongside the Watchtower)
chronoscope = Chronoscope()
