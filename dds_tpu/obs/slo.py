"""SLO engine: per-route latency objectives + error-budget burn tracking.

A latency histogram tells you what happened; an SLO says whether it was
GOOD ENOUGH. Each route carries an objective — "`objective` of requests
answer under `latency_ms` without a server error" — and every request is
classified good/bad at the REST edge (`http/server.handle`). Bad requests
burn the route's error budget (`1 - objective`); the burn RATE over two
rolling windows (a fast one that catches a cliff, a slow one that
confirms it is not a blip) is the page signal, the multiwindow multi-
burn-rate shape SRE alerting converged on. `GET /slo` serves the whole
report; `export_gauges` mirrors it as `dds_slo_*` gauges for scrapers.

Classification: good = HTTP status < 500 AND latency <= the route's
threshold. 4xx are the client's fault and do not burn the server's
budget; 503 degradations and deadline exhaustions do — that is exactly
what the budget is for.

Time is bucketed into fixed bins (fast_window/60, floor 1 s) so a window
sum is O(bins), state stays bounded per route, and no per-request
timestamps are retained.

Bastion addendum — per-TENANT attribution: `observe` optionally carries
the requesting tenant, binned into a parallel bounded table (at most
`max_tenants` tracked; beyond that, outcomes fold into the "overflow"
tenant, so a tenant-id cardinality attack coarsens attribution instead
of growing state). `tenant_burns()` is the Helmsman/Bulwark signal that
says WHOSE burn it is; `report()` gains a "tenants" section.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from dataclasses import dataclass

__all__ = ["RouteSlo", "SloEngine", "slo_class"]

# The Spyglass-served encrypted query surface (Search*/Order*/Range):
# classified as its own SLO family so operators can budget the indexed
# query plane separately from the fold aggregates it used to hide behind.
_SEARCH_ROUTES = frozenset({
    "OrderLS", "OrderSL", "Range",
    "SearchEq", "SearchNEq", "SearchGt", "SearchGtEq", "SearchLt",
    "SearchLtEq", "SearchEntry", "SearchEntryOR", "SearchEntryAND",
})
_AGGREGATE_ROUTES = frozenset({"Sum", "Mult", "SumAll", "MultAll"})
_ANALYTICS_ROUTES = frozenset({"MatVec", "WeightedSum", "GroupBySum"})
_POINT_ROUTES = frozenset({
    "GetSet", "PutSet", "RemoveSet", "AddElement", "ReadElement",
    "WriteElement", "IsElement",
})


def slo_class(route: str) -> str:
    """Coarse route family for SLO reporting: search | aggregate |
    analytics | point | other. Distinct from core/admission.route_class
    (priority classes for shedding) — this is the reporting taxonomy the
    /slo body and dashboards group by."""
    if route in _SEARCH_ROUTES:
        return "search"
    if route in _AGGREGATE_ROUTES:
        return "aggregate"
    if route in _ANALYTICS_ROUTES:
        return "analytics"
    if route in _POINT_ROUTES:
        return "point"
    return "other"


@dataclass(frozen=True)
class RouteSlo:
    """One route's objective: `objective` of requests good, where good
    means `status < 500 and latency_ms <= latency`."""

    objective: float = 0.99
    latency_ms: float = 250.0


class SloEngine:
    def __init__(
        self,
        default: RouteSlo | None = None,
        routes: dict[str, RouteSlo] | None = None,
        windows: tuple[float, float] = (300.0, 3600.0),
        burn_alert: float = 14.4,
        clock=time.monotonic,
        max_tenants: int = 256,
    ):
        self.default = default or RouteSlo()
        self.routes = dict(routes or {})
        fast, slow = float(windows[0]), float(windows[1])
        if fast > slow:
            fast, slow = slow, fast
        self.windows = (fast, slow)
        self.burn_alert = float(burn_alert)
        self._clock = clock
        self.bin_s = max(1.0, fast / 60.0)
        maxbins = int(math.ceil(slow / self.bin_s)) + 1
        # route -> deque of [bin_index, good, bad_latency, bad_error]
        self._bins: dict[str, collections.deque] = collections.defaultdict(
            lambda: collections.deque(maxlen=maxbins)
        )
        # tenant -> same bin shape (bounded: max_tenants then "overflow")
        self.max_tenants = int(max_tenants)
        self._tenant_bins: dict[str, collections.deque] = {}
        self._maxbins = maxbins
        self._lock = threading.Lock()

    @classmethod
    def from_obs(cls, obs) -> "SloEngine":
        """Build from an ObsConfig-shaped object (duck-typed so this module
        never imports the config tree). Per-route overrides accept either
        `latency-ms` (TOML idiom) or `latency_ms` keys."""
        default = RouteSlo(
            objective=float(getattr(obs, "slo_objective", 0.99)),
            latency_ms=float(getattr(obs, "slo_latency_ms", 250.0)),
        )
        routes = {}
        for name, spec in (getattr(obs, "slo_routes", None) or {}).items():
            if not isinstance(spec, dict):
                continue
            routes[str(name)] = RouteSlo(
                objective=float(spec.get("objective", default.objective)),
                latency_ms=float(
                    spec.get("latency-ms", spec.get("latency_ms",
                                                    default.latency_ms))
                ),
            )
        return cls(
            default=default,
            routes=routes,
            windows=(
                float(getattr(obs, "slo_fast_window", 300.0)),
                float(getattr(obs, "slo_slow_window", 3600.0)),
            ),
            burn_alert=float(getattr(obs, "slo_burn_alert", 14.4)),
        )

    def slo_for(self, route: str) -> RouteSlo:
        return self.routes.get(route, self.default)

    # --------------------------------------------------------------- intake

    def observe(self, route: str, status: int, dur_s: float,
                tenant: str | None = None) -> None:
        slo = self.slo_for(route)
        err = status >= 500
        slow = dur_s * 1e3 > slo.latency_ms
        idx = int(self._clock() / self.bin_s)
        with self._lock:
            targets = [self._bins[route]]
            if tenant is not None:
                tbins = self._tenant_bins.get(tenant)
                if tbins is None:
                    if len(self._tenant_bins) >= self.max_tenants:
                        tenant = "overflow"
                        tbins = self._tenant_bins.get(tenant)
                    if tbins is None:
                        tbins = self._tenant_bins[tenant] = collections.deque(
                            maxlen=self._maxbins
                        )
                targets.append(tbins)
            for bins in targets:
                if not bins or bins[-1][0] != idx:
                    bins.append([idx, 0, 0, 0])
                cur = bins[-1]
                if err:
                    cur[3] += 1
                elif slow:
                    cur[2] += 1
                else:
                    cur[1] += 1

    # -------------------------------------------------------------- reports

    def _window_counts(self, bins, window: float) -> tuple[int, int, int]:
        """(good, bad_latency, bad_error) over the trailing `window` s."""
        cutoff = int((self._clock() - window) / self.bin_s)
        good = bad_lat = bad_err = 0
        for idx, g, bl, be in bins:
            if idx > cutoff:
                good += g
                bad_lat += bl
                bad_err += be
        return good, bad_lat, bad_err

    def report(self) -> dict:
        """The `GET /slo` body: per observed route, the objective and the
        per-window burn state. Burn rate = bad_fraction / error_budget
        (1.0 = burning exactly at the sustainable rate; `burn_alert`x =
        page). `budget_remaining` is the slow window's unspent fraction."""
        out: dict = {
            "windows_s": list(self.windows),
            "burn_alert": self.burn_alert,
            "routes": {},
        }
        with self._lock:
            items = [(r, list(b)) for r, b in self._bins.items()]
        for route, bins in sorted(items):
            slo = self.slo_for(route)
            budget = max(1e-9, 1.0 - slo.objective)
            wreport = {}
            burns = []
            for w in self.windows:
                good, bad_lat, bad_err = self._window_counts(bins, w)
                total = good + bad_lat + bad_err
                bad = bad_lat + bad_err
                frac = (bad / total) if total else 0.0
                burn = frac / budget
                burns.append((burn, total, bad))
                wreport[f"{int(w)}s"] = {
                    "total": total,
                    "bad": bad,
                    "bad_latency": bad_lat,
                    "bad_error": bad_err,
                    "bad_fraction": round(frac, 6),
                    "burn_rate": round(burn, 3),
                }
            _, slow_total, slow_bad = burns[-1]
            remaining = (
                1.0 - min(1.0, slow_bad / (slow_total * budget))
                if slow_total else 1.0
            )
            out["routes"][route] = {
                "objective": slo.objective,
                "latency_ms": slo.latency_ms,
                "class": slo_class(route),
                "windows": wreport,
                "budget_remaining": round(remaining, 6),
                # page only when BOTH windows burn hot: the fast window
                # catches the cliff, the slow one proves it is sustained
                "alert": all(b[0] >= self.burn_alert for b in burns),
            }
        tenants = self.tenant_report()
        if tenants:
            out["tenants"] = tenants
        return out

    def alerts(self) -> list[str]:
        """Routes whose multiwindow burn alert is CURRENTLY firing (both
        windows burning >= burn_alert). The page signal as a cheap list —
        the Bulwark admission controller polls this every evaluation tick,
        so it skips report()'s full per-window dict construction."""
        with self._lock:
            items = [(r, list(b)) for r, b in self._bins.items()]
        out = []
        for route, bins in items:
            slo = self.slo_for(route)
            budget = max(1e-9, 1.0 - slo.objective)
            firing = True
            for w in self.windows:
                good, bad_lat, bad_err = self._window_counts(bins, w)
                total = good + bad_lat + bad_err
                bad = bad_lat + bad_err
                burn = (bad / total) / budget if total else 0.0
                if burn < self.burn_alert:
                    firing = False
                    break
            if firing:
                out.append(route)
        return out

    def burns(self) -> dict[str, list[float]]:
        """Route -> [burn per window, fast first] — the compact snapshot
        the Helmsman controller flight-records with each decision, so an
        autoscale action is auditable against the burn that drove it
        (alerts() says WHETHER a route pages; this says how hard)."""
        with self._lock:
            items = [(r, list(b)) for r, b in self._bins.items()]
        out: dict[str, list[float]] = {}
        for route, bins in items:
            slo = self.slo_for(route)
            budget = max(1e-9, 1.0 - slo.objective)
            row = []
            for w in self.windows:
                good, bad_lat, bad_err = self._window_counts(bins, w)
                total = good + bad_lat + bad_err
                bad = bad_lat + bad_err
                row.append(round((bad / total) / budget if total else 0.0, 3))
            out[route] = row
        return out

    def tenant_burns(self) -> dict[str, list[float]]:
        """Tenant -> [burn per window, fast first], against the DEFAULT
        objective (tenant attribution spans routes, so the per-route
        thresholds already shaped good/bad at observe time). The signal
        Helmsman and dashboards use to answer WHOSE burn the fleet's
        alert is."""
        budget = max(1e-9, 1.0 - self.default.objective)
        with self._lock:
            items = [(t, list(b)) for t, b in self._tenant_bins.items()]
        out: dict[str, list[float]] = {}
        for tenant, bins in items:
            row = []
            for w in self.windows:
                good, bad_lat, bad_err = self._window_counts(bins, w)
                total = good + bad_lat + bad_err
                bad = bad_lat + bad_err
                row.append(round((bad / total) / budget if total else 0.0, 3))
            out[tenant] = row
        return out

    def tenant_report(self) -> dict:
        """Per-tenant window totals for /slo's "tenants" section."""
        with self._lock:
            items = [(t, list(b)) for t, b in self._tenant_bins.items()]
        out: dict = {}
        for tenant, bins in sorted(items):
            wreport = {}
            for w in self.windows:
                good, bad_lat, bad_err = self._window_counts(bins, w)
                total = good + bad_lat + bad_err
                wreport[f"{int(w)}s"] = {
                    "total": total, "bad": bad_lat + bad_err,
                    "bad_latency": bad_lat, "bad_error": bad_err,
                }
            out[tenant] = wreport
        return out

    def export_gauges(self, registry) -> None:
        """Mirror the report as scrape-time gauges (http/server calls this
        from `_sample_state_gauges`)."""
        rep = self.report()
        for route, r in rep["routes"].items():
            for wname, w in r["windows"].items():
                registry.set(
                    "dds_slo_burn_rate", w["burn_rate"], route=route,
                    window=wname,
                    help="error-budget burn rate (1.0 = sustainable) per window",
                )
            registry.set(
                "dds_slo_error_budget_remaining", r["budget_remaining"],
                route=route,
                help="unspent error-budget fraction over the slow window",
            )
            registry.set(
                "dds_slo_objective", r["objective"], route=route,
                help="configured good-request objective per route",
            )
            registry.set(
                "dds_slo_alert", 1.0 if r["alert"] else 0.0, route=route,
                help="1 when both burn windows exceed the alert threshold",
            )
        for tenant, row in self.tenant_burns().items():
            registry.set(
                "dds_slo_tenant_burn_rate", row[0], tenant=tenant,
                help="fast-window error-budget burn rate attributed per "
                     "tenant (bounded cardinality; overflow folds)",
            )
