"""Perf-regression sentry core: per-kernel timing baselines + comparison.

"Cost-Effective Optimization of CRT-Paillier Decryption" and "HEAAN
Demystified" both make the same methodological point: HE performance
claims need CONTINUOUS per-phase measurement against a baseline, not a
one-off benchmark. This module is the mechanism: it distills the kprof
spans (`kernel.<name>.dispatch` / `kernel.<name>.execute`, see obs/kprof)
into per-kernel-and-shape p50/p95 statistics, persists them as a baseline
file, and compares a fresh run against the stored baseline so CI can gate
on ">20% slower than last time" (`benchmarks/sentry.py` is the CLI).

Baseline file schema (JSON):

    {"version": 1, "updated": <unix ts>, "kernels": {
        "<kernel>[k=...,R=...]": {
            "dispatch": {"p50_ms": ..., "p95_ms": ..., "count": N},
            "execute":  {"p50_ms": ..., "p95_ms": ..., "count": N},
            "compile":  {"p50_ms": ..., "p95_ms": ..., "count": N}}}}

The "compile" phase (cold trace+compile calls, split out of dispatch by
obs/kprof) is OPTIONAL per entry: baselines written before the split
stay valid, and entries missing a phase on either side simply skip that
phase's comparison.

Kernels are keyed by execution platform (`platform()`, e.g. `cpu::` /
`tpu::`) plus name plus the shape-ish span meta (`k`, `R`, `P2`) so a
baseline taken at one fold width — or on one accelerator — is never
compared against another.
`benchmarks/common.emit()` persists new kernels opportunistically on
every benchmark run (existing entries are kept unless
`DDS_KERNEL_BASELINE_UPDATE` is truthy), so the baseline grows with the
benchmark suite instead of needing a separate recording ritual.
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import time

__all__ = [
    "collect", "load_baseline", "save_baseline", "compare",
    "baseline_path", "persist_from_tracer", "platform",
]

PHASES = ("dispatch", "execute", "compile")
# span meta keys that describe the kernel's shape (batch width, request
# fan-in, padded sizes) — part of the baseline key, never averaged across
SHAPE_KEYS = ("k", "K", "R", "P2", "L")

_VERSION = 1
_DEFAULT_BASENAME = "kernel_baseline.json"


def baseline_path(path: str | None = None) -> pathlib.Path:
    """Resolve the baseline file path: explicit arg > DDS_KERNEL_BASELINE
    env > benchmarks/kernel_baseline.json next to this repo's benchmarks."""
    if path:
        return pathlib.Path(path)
    env = os.environ.get("DDS_KERNEL_BASELINE", "")
    if env:
        return pathlib.Path(env)
    repo = pathlib.Path(__file__).resolve().parents[2]
    return repo / "benchmarks" / _DEFAULT_BASENAME


def platform() -> str:
    """The execution-platform namespace prefixed onto every baseline key
    (`cpu::foldmany[...]`, `tpu::foldmany[...]`): a shared baseline file
    can hold rows from several environments without a CPU-fabric run ever
    comparing — or, with DDS_KERNEL_BASELINE_UPDATE, ratcheting — against
    an on-chip row. DDS_SENTRY_PLATFORM overrides; otherwise the jax
    default backend of the process that RAN the kernels (collect() is the
    only caller, and kernel spans imply jax was importable). `--check`
    never calls this, keeping the CI smoke jax-free."""
    env = os.environ.get("DDS_SENTRY_PLATFORM", "").strip()
    if env:
        return env
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # pragma: no cover — jax is baked into the image
        return "host"


def _percentile(sorted_vals: list[float], q: float) -> float:
    k = len(sorted_vals)
    return sorted_vals[max(0, min(k - 1, math.ceil(q * k) - 1))]


def collect(trc=None) -> dict:
    """Per-kernel {phase: {p50_ms, p95_ms, count}} from the tracer ring's
    `kernel.*` spans, keyed by execution platform + kernel name + shape
    meta (`compare` intersects keys, so a row collected on one platform
    can never gate — or ratchet — a row from another)."""
    if trc is None:
        from dds_tpu.utils.trace import tracer as trc  # late: avoid cycles
    plat = platform()
    groups: dict[str, dict[str, list[float]]] = {}
    for e in trc.events():
        if e.kind != "span" or not e.name.startswith("kernel."):
            continue
        base, _, phase = e.name[len("kernel."):].rpartition(".")
        if phase not in PHASES or not base:
            continue
        shape = ",".join(
            f"{k}={e.meta[k]}" for k in SHAPE_KEYS if k in e.meta
        )
        key = f"{plat}::{base}[{shape}]" if shape else f"{plat}::{base}"
        groups.setdefault(key, {}).setdefault(phase, []).append(e.dur_ms)
    out: dict = {}
    for key, phases in sorted(groups.items()):
        entry = {}
        for phase, durs in phases.items():
            durs.sort()
            entry[phase] = {
                "p50_ms": round(_percentile(durs, 0.50), 4),
                "p95_ms": round(_percentile(durs, 0.95), 4),
                "count": len(durs),
            }
        out[key] = entry
    return out


# ---------------------------------------------------------------- baseline


def load_baseline(path: str | None = None) -> dict:
    """Load and validate a baseline file; returns its `kernels` dict.
    Raises ValueError on a malformed file (the sentry CLI maps this to a
    non-zero exit so CI catches a corrupted baseline, not just a slow
    kernel). A missing file returns {}."""
    p = baseline_path(path)
    if not p.exists():
        return {}
    try:
        data = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable baseline {p}: {e}") from e
    if not isinstance(data, dict) or not isinstance(data.get("kernels"), dict):
        raise ValueError(f"malformed baseline {p}: expected {{'kernels': ...}}")
    kernels = {}
    for name, entry in data["kernels"].items():
        if not isinstance(entry, dict):
            raise ValueError(f"malformed baseline entry {name!r} in {p}")
        for phase, stats in entry.items():
            if phase not in PHASES or not isinstance(stats, dict):
                raise ValueError(
                    f"malformed baseline phase {name!r}.{phase!r} in {p}"
                )
            for k in ("p50_ms", "p95_ms"):
                if not isinstance(stats.get(k), (int, float)):
                    raise ValueError(
                        f"baseline {name!r}.{phase}.{k} is not a number in {p}"
                    )
        kernels[str(name)] = entry
    return kernels


def save_baseline(stats: dict, path: str | None = None,
                  overwrite: bool = False) -> dict:
    """Merge `stats` into the baseline file (atomic tmp+rename). Existing
    kernels win unless `overwrite` — a baseline is a COMMITMENT, and a
    routine benchmark run must not silently ratchet it to a slower value.
    Returns the merged kernels dict."""
    p = baseline_path(path)
    try:
        existing = load_baseline(p)
    except ValueError:
        existing = {}  # a corrupt baseline is replaced, not fatal
    merged = dict(existing)
    for name, entry in stats.items():
        if overwrite or name not in merged:
            merged[name] = entry
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + ".tmp")
    tmp.write_text(json.dumps(
        {"version": _VERSION, "updated": time.time(), "kernels": merged},
        indent=1, sort_keys=True,
    ))
    os.replace(tmp, p)
    return merged


def persist_from_tracer(path: str | None = None,
                        overwrite: bool | None = None) -> dict | None:
    """Opportunistic baseline persistence for benchmarks/common.emit():
    collect current kernel stats and merge them into the baseline file.
    Returns the collected stats, or None when no kernel spans exist.
    DDS_KERNEL_BASELINE="" disables; DDS_KERNEL_BASELINE_UPDATE=1 lets a
    run overwrite existing entries (a deliberate re-baselining)."""
    if "DDS_KERNEL_BASELINE" in os.environ and not os.environ["DDS_KERNEL_BASELINE"]:
        return None
    stats = collect()
    if not stats:
        return None
    if overwrite is None:
        overwrite = os.environ.get(
            "DDS_KERNEL_BASELINE_UPDATE", ""
        ).strip().lower() in ("1", "true", "yes", "on")
    save_baseline(stats, path, overwrite=overwrite)
    return stats


# --------------------------------------------------------------- comparison


def compare(baseline: dict, fresh: dict, threshold: float = 0.20,
            floor_ms: float = 0.05) -> list[dict]:
    """Regressions of `fresh` vs `baseline`: every (kernel, phase, stat)
    where fresh > baseline * (1 + threshold) AND the absolute delta
    clears `floor_ms` (sub-floor kernels are timer noise, not
    regressions). Only kernels present in BOTH sides are compared — new
    kernels have no baseline to regress from, vanished kernels are a
    coverage change, not a slowdown. Returns a list of finding dicts,
    empty = clean."""
    findings = []
    for name in sorted(set(baseline) & set(fresh)):
        for phase in PHASES:
            b, f = baseline[name].get(phase), fresh[name].get(phase)
            if not b or not f:
                continue
            for stat in ("p50_ms", "p95_ms"):
                bv, fv = float(b[stat]), float(f[stat])
                if fv > bv * (1.0 + threshold) and fv - bv > floor_ms:
                    findings.append({
                        "kernel": name,
                        "phase": phase,
                        "stat": stat,
                        "baseline_ms": bv,
                        "fresh_ms": fv,
                        "ratio": round(fv / bv, 3) if bv > 0 else None,
                    })
    return findings
