"""Heliograph: the active canary plane — synthetic probes that decrypt.

Every other observability surface here is passive: Chronoscope profiles
traffic that happens to arrive, the SLO engine burns on served-request
ratios, Watchtower audits tag algebra over traces it is shown. A quiesced
region, a shredded-but-routable tenant, or a ciphertext-corrupting fault
that never trips an HMAC check is invisible to all of them until a real
user pays for it. Heliograph closes that gap from the OUTSIDE: a
supervised async prober per proxy (and per Meridian process) owns the
reserved `__heliograph__` tenant and continuously drives golden
transactions through the real client crypto path (clt/canary.py) —
PutSet -> quorum write -> GetSet read-your-write, SumAll/MultAll over a
known plaintext population, one Spyglass search, one Prism MatVec — and
verifies every answer by decrypting it.

Outcomes are typed (ok / slow / wrong-answer / unreachable) and land in
three places:

1. the `CanaryLedger`: bounded-cardinality `/metrics` gauges+histograms,
   the `GET /canary` report (fleet-federated by Panopticon as
   `GET /fleet/canary`), and a `/health` section that degrades to
   "stale" but never blocks; each failure carries an exemplar trace id
   linking into the Chronoscope span tree for that probe;
2. the SLO engine, as synthetic `canary.<kind>` availability streams —
   burn alerts fire on black-box evidence even at zero user load;
3. Watchtower/Helmsman: a wrong-answer verdict files a
   `canary_wrong_answer` Watchtower incident (decrypt-and-verify is the
   only check that catches a well-MAC'd wrong ciphertext), and sustained
   unreachable against one region feeds Helmsman's region_down /
   promotion signal — synthetic detection closing the self-healing loop.

Scheduling is jittered (a fleet of probers must never phase-lock into a
thundering herd), every probe carries a wall deadline, and canary
requests pass a dedicated rate-bounded admission carve-out at the edge
(http/server.py) so a wedged prober can never self-DoS the fleet.

`seed_ciphertext_corruption` is the drill fault: it flips a stored
ciphertext IN PLACE on every replica, past the transport-HMAC boundary —
replicas re-MAC their answers over the corrupted value, every passive
surface stays green, and only a probe that decrypts notices.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from dds_tpu.clt.canary import (CanaryClient, CanaryTarget, PROBE_KINDS,
                                build_provider)
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.tasks import supervised_task

__all__ = [
    "VERDICTS", "ProbeResult", "CanaryLedger", "Heliograph",
    "seed_ciphertext_corruption",
]

# verdict -> gauge enum value (dds_canary_verdict); order is severity
VERDICTS = ("ok", "slow", "wrong_answer", "unreachable")

# verdict -> synthetic SLO status for the canary.<kind> streams
_SLO_STATUS = {"ok": 200, "slow": 200, "wrong_answer": 500,
               "unreachable": 503}


@dataclass
class ProbeResult:
    """One typed probe outcome as the ledger stores it."""

    kind: str
    verdict: str
    latency_s: float
    trace_id: str
    target: str = ""
    region: str = ""
    at: float = 0.0            # ledger clock timestamp
    detail: dict = field(default_factory=dict)


class CanaryLedger:
    """Typed probe results with bounded export cardinality.

    Counters (`dds_canary_probes_total{kind,verdict}`) and the latency
    histogram (`dds_canary_probe_seconds{kind}`) are written at record
    time; point-in-time state (last verdict / last-ok age per kind, the
    rotating failure exemplars) exports at scrape time via
    `export_gauges`. Label sets are bounded by construction: kind is one
    of PROBE_KINDS, verdict one of VERDICTS, and the exemplar family is
    cleared and re-set each sample so rotating trace ids never accrete."""

    def __init__(self, clock=time.monotonic, history: int = 64,
                 unreachable_streak: int = 3, registry=None):
        self._clock = clock
        self._history = int(history)
        self.unreachable_streak = max(1, int(unreachable_streak))
        self._reg = registry if registry is not None else metrics
        self._results: list[ProbeResult] = []
        self._last: dict[str, ProbeResult] = {}
        self._last_ok: dict[str, float] = {}
        self._last_failure: dict[str, ProbeResult] = {}
        self._counts: dict[tuple[str, str], int] = {}
        # region -> consecutive unreachable probes (any kind); reset by
        # any non-unreachable result from that region
        self._region_fail: dict[str, int] = {}
        self._seq = 0

    # -------------------------------------------------------------- record

    def record(self, result: ProbeResult) -> None:
        self._seq += 1
        result.at = self._clock()
        self._results.append(result)
        del self._results[:-self._history]
        self._last[result.kind] = result
        key = (result.kind, result.verdict)
        self._counts[key] = self._counts.get(key, 0) + 1
        if result.verdict in ("ok", "slow"):
            self._last_ok[result.kind] = result.at
        else:
            self._last_failure[result.kind] = result
        region = result.region
        if result.verdict == "unreachable":
            self._region_fail[region] = self._region_fail.get(region, 0) + 1
        else:
            self._region_fail[region] = 0
        self._reg.inc(
            "dds_canary_probes_total", kind=result.kind,
            verdict=result.verdict,
            help="Heliograph golden-transaction probes by typed verdict",
        )
        self._reg.observe(
            "dds_canary_probe_seconds", result.latency_s, kind=result.kind,
            help="Heliograph end-to-end probe latency (encrypt, HTTP, "
                 "quorum, decrypt-and-verify)",
        )

    # --------------------------------------------------------------- reads

    def last(self, kind: str) -> ProbeResult | None:
        return self._last.get(kind)

    def last_age(self) -> float | None:
        """Seconds since the most recent probe of any kind (None = never)."""
        if not self._last:
            return None
        return self._clock() - max(r.at for r in self._last.values())

    def unreachable_regions(self) -> set[str]:
        """Regions with >= unreachable_streak consecutive unreachable
        probes — Helmsman's region_down/promotion evidence. The anonymous
        "" region (untargeted local probes) never feeds the signal."""
        return {
            r for r, n in self._region_fail.items()
            if r and n >= self.unreachable_streak
        }

    def report(self) -> dict:
        """The `GET /canary` body: per-kind state, counts, recent
        failures with exemplar trace ids, region streaks."""
        now = self._clock()
        kinds: dict[str, dict] = {}
        for kind, r in self._last.items():
            ok_at = self._last_ok.get(kind)
            entry = {
                "verdict": r.verdict,
                "age_s": round(now - r.at, 3),
                "latency_ms": round(r.latency_s * 1e3, 3),
                "trace_id": r.trace_id,
                "last_ok_age_s": (
                    round(now - ok_at, 3) if ok_at is not None else None
                ),
            }
            fail = self._last_failure.get(kind)
            if fail is not None:
                entry["last_failure"] = {
                    "verdict": fail.verdict,
                    "trace_id": fail.trace_id,
                    "age_s": round(now - fail.at, 3),
                    "target": fail.target,
                    "region": fail.region,
                    "detail": _safe_detail(fail.detail),
                }
            kinds[kind] = entry
        return {
            "kinds": kinds,
            "counts": {
                f"{k}.{v}": n for (k, v), n in sorted(self._counts.items())
            },
            "unreachable_regions": sorted(self.unreachable_regions()),
            "region_streaks": {
                r: n for r, n in self._region_fail.items() if r and n
            },
            "probes_recorded": self._seq,
        }

    def health_section(self, enabled: bool, stale_after: float) -> dict:
        """The `/health` canary section: pure in-memory state, O(kinds),
        never awaits — a wedged prober degrades this to "stale", it can
        never block the health probe itself."""
        if not enabled:
            return {"status": "disabled"}
        age = self.last_age()
        status = "ok"
        if age is None or age > stale_after:
            status = "stale"
        elif any(r.verdict not in ("ok", "slow")
                 for r in self._last.values()):
            status = "failing"
        out: dict = {"status": status, "last_probe_age_s": (
            round(age, 3) if age is not None else None)}
        out["kinds"] = {
            kind: {"verdict": r.verdict,
                   "age_s": round(self._clock() - r.at, 3)}
            for kind, r in sorted(self._last.items())
        }
        return out

    # -------------------------------------------------------------- export

    def export_gauges(self, reg) -> None:
        """Scrape-time gauges (bounded: kinds x 1, plus one rotating
        exemplar series per kind — the family is cleared first so rotated
        trace ids never accrete toward the cardinality cap)."""
        now = self._clock()
        for kind, r in self._last.items():
            reg.set(
                "dds_canary_verdict", VERDICTS.index(r.verdict), kind=kind,
                help="last canary verdict per probe kind "
                     "(0 ok, 1 slow, 2 wrong_answer, 3 unreachable)",
            )
            ok_at = self._last_ok.get(kind)
            if ok_at is not None:
                reg.set(
                    "dds_canary_last_ok_age_seconds", now - ok_at, kind=kind,
                    help="seconds since the last ok/slow canary probe",
                )
        reg.clear_family("dds_canary_exemplar")
        for kind, fail in self._last_failure.items():
            reg.set(  # argus: ok[metrics.unbounded-label] family cleared each scrape above; bounded at one exemplar series per probe kind
                "dds_canary_exemplar", self._seq, kind=kind,
                trace_id=fail.trace_id, verdict=fail.verdict,
                help="latest canary failure exemplar per kind; the value "
                     "orders exemplars fleet-wide (ledger sequence)",
            )
        for region in self.unreachable_regions():
            reg.set(
                "dds_canary_region_unreachable", 1, region=region,
                help="regions at/over the consecutive-unreachable canary "
                     "streak (Helmsman region_down evidence)",
            )


def _safe_detail(detail: dict) -> dict:
    """Failure detail clamped for reports: short strings only (expected/
    observed rows can carry ciphertext-sized ints — truncate, the trace
    id is the real pointer)."""
    out = {}
    for k, v in list(detail.items())[:8]:
        s = str(v)
        out[str(k)] = s if len(s) <= 120 else s[:117] + "..."
    return out


class Heliograph:
    """The supervised prober: owns the canary crypto domain + population,
    schedules jittered probe cycles with per-probe deadlines, records
    every outcome in the ledger, and feeds the SLO / Watchtower /
    Helmsman planes. Construct with a duck-typed `HeliographConfig`;
    `clock`/`rng`/`sleep` inject for deterministic tests."""

    def __init__(self, cfg, targets: list[CanaryTarget], *,
                 slo=None, watchtower=None, ssl_context=None,
                 clock=time.monotonic, rng: random.Random | None = None,
                 sleep=asyncio.sleep, client: CanaryClient | None = None):
        self.cfg = cfg
        self.targets = list(targets) or [CanaryTarget("127.0.0.1", 0)]
        self.slo = slo
        self.watchtower = watchtower
        self.ssl_context = ssl_context
        self.clock = clock
        self.rng = rng if rng is not None else random.Random()
        self.sleep = sleep
        self.client = client
        self.kinds = [k for k in getattr(cfg, "probes", list(PROBE_KINDS))
                      if k in PROBE_KINDS]
        self.ledger = CanaryLedger(
            clock=clock,
            unreachable_streak=getattr(cfg, "unreachable_streak", 3),
        )
        self.cycles = 0
        self._task = None
        self._populated: set[str] = set()

    # ---------------------------------------------------------- scheduling

    def next_delay(self) -> float:
        """Jittered inter-cycle delay: cadence +/- jitter fraction, never
        below 50 ms. Uniform jitter de-phases a fleet of probers whose
        processes started together (same argument as anti-entropy's
        de-synchronising sleep)."""
        cadence = max(0.05, float(self.cfg.cadence))
        jitter = min(1.0, max(0.0, float(self.cfg.jitter)))
        return max(0.05, cadence * (1.0 + jitter * (2 * self.rng.random() - 1)))

    def classify(self, correct: bool, status: int, latency_s: float) -> str:
        """Typed verdict from one probe's verified outcome."""
        if correct and status == 200:
            slow = latency_s * 1e3 > float(self.cfg.slow_ms)
            return "slow" if slow else "ok"
        if status != 200:
            return "unreachable"
        return "wrong_answer"

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._task is None:
            self._task = supervised_task(self._run(), name="heliograph")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.cfg, "enabled", False))

    def stale_after(self) -> float:
        """A ledger older than ~3 cadences is stale (missed cycles plus
        jitter headroom)."""
        return 3.0 * max(0.05, float(self.cfg.cadence))

    # ----------------------------------------------------------- the loop

    async def _run(self) -> None:
        if self.client is None:
            provider = await build_provider(
                getattr(self.cfg, "paillier_bits", 512),
                getattr(self.cfg, "rsa_bits", 512),
            )
            self.client = CanaryClient(
                provider, population=getattr(self.cfg, "population", 4),
                ssl_context=self.ssl_context,
                timeout=float(self.cfg.deadline),
            )
        while True:
            target = self.targets[self.cycles % len(self.targets)]
            await self.run_cycle(target)
            self.cycles += 1
            await self.sleep(self.next_delay())

    async def run_cycle(self, target: CanaryTarget) -> None:
        """One probe cycle against one target: populate once (lazily, per
        target set — idempotent content-addressed writes), then every
        configured probe kind under its own deadline. Exceptions never
        escape: an unreachable edge is a VERDICT, not a crash."""
        if target.label not in self._populated:
            trace = self.client.mint_trace()
            try:
                await asyncio.wait_for(
                    self.client.populate(target, trace),
                    timeout=float(self.cfg.deadline) * self.client.population,
                )
                self._populated.add(target.label)
            except (Exception, asyncio.TimeoutError) as e:
                self.ledger.record(ProbeResult(
                    "putget", "unreachable", 0.0, trace,
                    target=target.label, region=target.region,
                    detail={"phase": "populate", "error": str(e)},
                ))
                return
        for kind in self.kinds:
            await self.probe_once(kind, target)

    async def probe_once(self, kind: str, target: CanaryTarget) -> ProbeResult:
        trace_id = self.client.mint_trace()
        t0 = self.clock()
        status, correct, detail = 0, False, {}
        try:
            check = await asyncio.wait_for(
                self.client.probe(kind, target, trace_id, self.cycles),
                timeout=float(self.cfg.deadline),
            )
            status, correct, detail = check.status, check.correct, check.detail
        except (asyncio.TimeoutError, TimeoutError, OSError) as e:
            detail = {"error": type(e).__name__}
        except asyncio.CancelledError:
            raise
        except Exception as e:  # garbled body / broken crypto = wrong answer
            status, detail = 200, {"error": f"{type(e).__name__}: {e}"}
        latency = self.clock() - t0
        verdict = self.classify(correct, status, latency)
        result = ProbeResult(
            kind, verdict, latency, trace_id,
            target=target.label, region=target.region, detail=detail,
        )
        self.ledger.record(result)
        self._feed(result)
        return result

    # ------------------------------------------------------------- feeding

    def _feed(self, result: ProbeResult) -> None:
        """Fan one typed result out to the passive planes (never raises:
        a broken feed must not kill the prober)."""
        try:
            if self.slo is not None:
                self.slo.observe(
                    f"canary.{result.kind}", _SLO_STATUS[result.verdict],
                    result.latency_s,
                )
        except Exception:  # noqa: BLE001
            pass
        if result.verdict == "wrong_answer":
            try:
                if self.watchtower is not None:
                    self.watchtower.report_violation(
                        "canary_wrong_answer", result.trace_id,
                        probe=result.kind, target=result.target,
                        region=result.region,
                        **_safe_detail(result.detail),
                    )
                else:
                    flight.record(
                        "canary_wrong_answer", trace_id=result.trace_id,
                        probe=result.kind, **_safe_detail(result.detail),
                    )
            except Exception:  # noqa: BLE001
                pass
        elif result.verdict == "unreachable":
            try:
                flight.record(
                    "canary_unreachable", trace_id=result.trace_id,
                    probe=result.kind, target=result.target,
                    region=result.region,
                )
            except Exception:  # noqa: BLE001
                pass

    # ---------------------------------------------------------- plane taps

    def unreachable_regions(self) -> set[str]:
        """Helmsman's injected canary signal (fleet/helmsman.py)."""
        return self.ledger.unreachable_regions()

    def export_gauges(self, reg) -> None:
        self.ledger.export_gauges(reg)

    def report(self) -> dict:
        out = self.ledger.report()
        out["enabled"] = self.enabled
        out["cadence_s"] = float(self.cfg.cadence)
        out["cycles"] = self.cycles
        out["targets"] = [
            {"target": t.label, "region": t.region} for t in self.targets
        ]
        return out

    def health_section(self) -> dict:
        return self.ledger.health_section(
            self.enabled and self._task is not None, self.stale_after()
        )


# ------------------------------------------------------------------ drill

def seed_ciphertext_corruption(replicas, key: str, position: int = 2) -> int:
    """The ChaosNet corruption drill's seeded fault: mutate `key`'s stored
    ciphertext at `position` IN PLACE on every replica, preserving the
    tag. This lands PAST the transport-HMAC boundary — each replica
    re-MACs its (corrupted) answer, quorums agree, Watchtower's tag
    algebra holds, every passive surface stays green — and models a
    storage-layer bit flip / firmware bug rather than a network forgery
    (ChaosNet's own `corrupt` fault is caught by the frame MAC and can
    never produce a valid-MAC wrong answer). Only decrypt-and-verify
    notices: a Paillier ciphertext c+1 is still a valid ciphertext of a
    DIFFERENT plaintext. Returns the number of replicas mutated."""
    nodes = replicas.values() if isinstance(replicas, dict) else replicas
    mutated = 0
    for node in nodes:
        entry = node.repository.get(key)
        if entry is None:
            continue
        tag, value = entry
        if value is None or position >= len(value):
            continue
        v = list(value)
        cell = v[position]
        s = str(cell)
        v[position] = str(int(s) + 1) if s.isdigit() else s + "\x00"
        node._store(key, tag, v)
        mutated += 1
    return mutated
