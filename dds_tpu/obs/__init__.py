"""Telescope: the observability plane (tracing, metrics, flight recorder).

- `obs.context` — distributed trace-context propagation (contextvar +
  transport wire format); `utils/trace.tracer` records spans against it.
- `obs.metrics` — process-wide MetricsRegistry, Prometheus text at
  `GET /metrics` (http/server.py).
- `obs.flight` — fault-triggered incident dumps (JSONL post-mortems).
- `obs.kprof` — kernel dispatch/compile-vs-execute profiling hooks.
- `obs.watchtower` — online BFT invariant auditor over completed traces.
- `obs.slo` — per-route latency objectives + error-budget burn tracking.
- `obs.sentry` — per-kernel timing baselines + regression comparison.
- `obs.panopticon` — fleet-wide plane: cross-host span shipping, the
  proxy-side collector (stitch + Watchtower replay), federated
  metrics/SLO, and incident correlation.
- `obs.chronoscope` — critical-path attribution + per-route/per-stage
  pipe profiling over finished (local or stitched) trace trees.

`flight` and `kprof` import `utils/trace`, which imports `obs.context` —
so this package eagerly exposes only the leaf modules and lazily resolves
the rest (PEP 562) to keep the import graph acyclic.
"""

from dds_tpu.obs import context  # noqa: F401
from dds_tpu.obs.metrics import Registry, metrics  # noqa: F401

__all__ = [
    "context", "metrics", "Registry", "flight", "kprof",
    "watchtower", "slo", "sentry", "panopticon", "chronoscope",
]


def __getattr__(name):
    if name in ("flight", "kprof", "watchtower", "slo", "sentry",
                "panopticon", "chronoscope"):
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
