"""Kernel profiling hooks: dispatch(trace/compile) vs execute, cache hits.

"HEAAN Demystified" (arxiv 2003.04510) argues HE acceleration must start
from per-phase bottleneck accounting, and GPU HE accelerators (GME, arxiv
2309.11001) report compile-vs-execute splits per kernel. JAX hides the
boundary: calling a jitted fn returns as soon as the work is ENQUEUED
(having traced+compiled first on a cache miss), and only
`block_until_ready` exposes device time. `profiled()` separates the two
into distinct tracer spans and metrics histograms; `cache_event`/`counted`
account compile-cache hits vs misses for the manual dict caches
(ops/foldmany) and `functools.lru_cache`d builders (ops/mont_mxu).

Cold calls split further: a compile-cache MISS (correlated by cache name,
or any miss landing during the dispatch window) marks the next
`profiled()` call for that kernel as a compile, and its host-side phase
records as `kernel.<name>.compile` INSTEAD of `.dispatch` — so dispatch
stats stay warm-only and Chronoscope's dispatch stage is never polluted
by one-time trace+compile time (which gets its own trace-compile stage).


`kernel_summary()` condenses both for benchmark records
(benchmarks/common.emit attaches it to every row in results.json).
"""

from __future__ import annotations

import threading
import time

from dds_tpu.obs import context as obs_context
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

__all__ = ["cache_event", "counted", "profiled", "kernel_summary", "reset"]

_lock = threading.Lock()
_cache_stats: dict[str, list[int]] = {}  # cache name -> [hits, misses]
# cache names that missed since their last profiled() call: builder
# caches fire BEFORE the dispatch (the builder returns the jitted fn),
# so the miss is remembered until the matching kernel dispatches
_pending_compile: set[str] = set()


def cache_event(cache: str, hit: bool) -> None:
    """Record one compile-cache lookup (per kernel-builder cache)."""
    with _lock:
        s = _cache_stats.setdefault(cache, [0, 0])
        s[0 if hit else 1] += 1
        if not hit:
            _pending_compile.add(cache)
    metrics.inc(
        "dds_compile_cache_total", cache=cache,
        outcome="hit" if hit else "miss",
        help="kernel compile-cache lookups by outcome",
    )


def counted(cache: str, lru_fn, *args):
    """Call a `functools.lru_cache`d kernel builder, accounting the lookup
    as a compile-cache hit/miss via its cache_info miss delta."""
    before = lru_fn.cache_info().misses
    out = lru_fn(*args)
    cache_event(cache, hit=lru_fn.cache_info().misses == before)
    return out


def profiled(kernel: str, dispatch, **meta):
    """Run `dispatch()` (enqueue device work, return jax arrays) and time
    its two phases separately: the host-side call and the
    `block_until_ready` device execution. A cold call — its builder cache
    missed (by name) since the last dispatch, or any cache miss landed
    DURING the dispatch window — records its host phase as
    `kernel.<name>.compile`; warm calls record `.dispatch`. Both pair
    with `kernel.<name>.execute` spans plus metrics histograms; returns
    the (ready) dispatch result."""
    import jax

    with _lock:
        compiled = kernel in _pending_compile
        _pending_compile.discard(kernel)
        misses0 = sum(m for _, m in _cache_stats.values())
    t0 = time.perf_counter()
    out = dispatch()
    t1 = time.perf_counter()
    jax.block_until_ready(out)
    t2 = time.perf_counter()
    with _lock:
        compiled = compiled or (
            sum(m for _, m in _cache_stats.values()) > misses0
        )
    # fresh child contexts: each phase record is its own span in the
    # trace tree, not a clone of the enclosing span's identity
    cur = obs_context.current()
    phase = "compile" if compiled else "dispatch"
    tracer.record(
        f"kernel.{kernel}.{phase}", (t1 - t0) * 1e3,
        _ctx=obs_context.child(cur) if cur is not None else None, **meta,
    )
    tracer.record(
        f"kernel.{kernel}.execute", (t2 - t1) * 1e3,
        _ctx=obs_context.child(cur) if cur is not None else None, **meta,
    )
    if compiled:
        metrics.observe(
            "dds_kernel_compile_seconds", t1 - t0, kernel=kernel,
            help="host-side trace+compile time on compile-cache misses",
        )
    else:
        metrics.observe(
            "dds_kernel_dispatch_seconds", t1 - t0, kernel=kernel,
            help="host-side dispatch time (warm calls only; cold calls "
                 "record dds_kernel_compile_seconds)",
        )
    metrics.observe(
        "dds_kernel_execute_seconds", t2 - t1, kernel=kernel,
        help="device execute time (block_until_ready)",
    )
    return out


def kernel_summary() -> dict:
    """{spans, compile_cache, dispatch_ms, execute_ms, compile_ms} over
    kernel.* spans recorded so far — the per-record accounting
    benchmarks attach."""
    spans = {
        name: stats
        for name, stats in tracer.summary().items()
        if name.startswith("kernel.")
    }
    with _lock:
        caches = {
            name: {
                "hits": h,
                "misses": m,
                "hit_rate": round(h / (h + m), 4) if h + m else None,
            }
            for name, (h, m) in sorted(_cache_stats.items())
        }
    dispatch_ms = sum(
        s["total_ms"] for n, s in spans.items() if n.endswith(".dispatch")
    )
    execute_ms = sum(
        s["total_ms"] for n, s in spans.items() if n.endswith(".execute")
    )
    compile_ms = sum(
        s["total_ms"] for n, s in spans.items() if n.endswith(".compile")
    )
    return {
        "spans": spans,
        "compile_cache": caches,
        "dispatch_ms": round(dispatch_ms, 3),
        "execute_ms": round(execute_ms, 3),
        "compile_ms": round(compile_ms, 3),
    }


def reset() -> None:
    with _lock:
        _cache_stats.clear()
        _pending_compile.clear()
