"""Read-lease bookkeeping for the read-local quorum geometry.

One `LeaseTable` is shared by every replica of a quorum group (the same
in-process config-push idiom as `shard.ShardState`: a group's replicas
live in one process, in Meridian one process per host). A grant is
installed by the replica that will serve the region's local reads and is
immediately visible to the group's coordinators, which consult
`holders()` to pin their quorums (see `dds_tpu.geo.__doc__` for the
safety argument).

Tokens are HMAC-derived from the grant fields plus a per-table counter,
so a token proves the grant came from this table instance and a stale
token from a previous grant of the same (region, replica) pair is
rejected after revoke/re-grant.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from dataclasses import dataclass
from typing import Callable, Optional

from dds_tpu.obs.metrics import metrics


@dataclass(frozen=True)
class ReadLease:
    """An active read lease: `replica` may answer `region`-local reads
    for its group until `expires` (table-clock seconds)."""

    gid: str
    region: str
    replica: str
    token: str
    expires: float

    def active(self, now: float) -> bool:
        return now < self.expires


class LeaseTable:
    """Per-group read-lease registry: region -> ReadLease."""

    def __init__(self, gid: str, secret: bytes,
                 clock: Callable[[], float] = time.monotonic):
        self.gid = gid
        self.secret = secret
        self.clock = clock
        self._leases: dict[str, ReadLease] = {}
        self._grants = 0  # monotone: distinguishes re-grants of one pair

    def _token(self, region: str, replica: str, expires: float) -> str:
        blob = f"{self.gid}|{region}|{replica}|{expires}|{self._grants}"
        return hmac.new(self.secret, blob.encode(), hashlib.sha256).hexdigest()

    def grant(self, region: str, replica: str, ttl: float) -> ReadLease:
        """Install (or renew) the region's lease on `replica`."""
        self._grants += 1
        expires = self.clock() + ttl
        lease = ReadLease(self.gid, region, replica,
                          self._token(region, replica, expires), expires)
        self._leases[region] = lease
        metrics.inc("dds_geo_lease_grants_total", shard=self.gid,
                    region=region,
                    help="read-lease grants/renewals installed per group")
        return lease

    def revoke(self, region: str) -> bool:
        """Drop the region's lease; local reads fall back to full quorum
        on their next attempt. Returns whether a lease was present."""
        if self._leases.pop(region, None) is None:
            return False
        metrics.inc("dds_geo_lease_revocations_total", shard=self.gid,
                    region=region,
                    help="read leases explicitly revoked per group")
        return True

    def active(self, region: str) -> Optional[ReadLease]:
        lease = self._leases.get(region)
        if lease is None:
            return None
        if not lease.active(self.clock()):
            # expiry is the availability escape hatch: unblock quorums
            # pinned on a dead holder without any message exchange
            del self._leases[region]
            metrics.inc("dds_geo_lease_expired_total", shard=self.gid,
                        region=lease.region,
                        help="read leases that aged out per group")
            return None
        return lease

    def valid(self, region: str, replica: str, token: str) -> bool:
        """May `replica` answer a region-local read bearing `token` now?"""
        lease = self.active(region)
        return (lease is not None and lease.replica == replica
                and hmac.compare_digest(lease.token, token))

    def holders(self) -> frozenset:
        """Replica names holding ANY active lease — the set every quorum
        this group closes must include while leases are out."""
        return frozenset(
            lease.replica for region in list(self._leases)
            for lease in [self.active(region)] if lease is not None
        )

    def held_by(self, replica: str) -> bool:
        return replica in self.holders()

    def census(self) -> dict:
        """Active leases for /health: region -> {replica, remaining}."""
        now = self.clock()
        out = {}
        for region in sorted(self._leases):
            lease = self.active(region)
            if lease is not None:
                out[region] = {"replica": lease.replica,
                               "remaining": round(lease.expires - now, 3)}
        return out
