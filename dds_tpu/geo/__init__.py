"""Atlas: geo-distributed constellation plane.

Region-aware placement (signed region labels on shard maps, per-replica
region spread inside a group), WAN ChaosNet profiles (named per-region
link matrices with 100-300 ms RTT presets), TTL-leased read-local quorum
geometry layered on BFT-ABD, and cross-region convergence/failover glue.

The lease design follows the quorum-lease construction: while a region
holds a read lease on a group, EVERY quorum the group's coordinators
close (write acks, read value rounds) must additionally include the
lease-holding replicas. A leased replica therefore stores every acked
write before its ack exists, so a local read served under an active
lease can never return a value older than the last acked cross-region
write. The price is availability, not safety: a dead lease holder
stalls quorums for at most one lease TTL, after which expiry restores
plain quorum geometry. The one residual window — a lease granted while
a round that already closed its quorum is still in flight — is bounded
by a single round and is audited explicitly by the Watchtower's
lease-window invariant instead of being silently exempt.
"""

from dds_tpu.geo.lease import LeaseTable, ReadLease
from dds_tpu.geo.placement import group_regions, spread
from dds_tpu.geo.wan import WAN_PRESETS, apply_profiles, faults_from_spec

__all__ = [
    "LeaseTable",
    "ReadLease",
    "WAN_PRESETS",
    "apply_profiles",
    "faults_from_spec",
    "group_regions",
    "spread",
]
