"""Region-aware placement helpers.

Two granularities, matching how the fleet is actually laid out:

- `spread`: one GROUP's replicas distributed round-robin across the
  region list — the span-group shape read-local leases need (every
  region holds a replica of every span group);
- `group_regions`: whole groups homed per region round-robin — the
  shape Helmsman's region-aware promotion reasons about (a region dying
  takes its homed groups' heartbeats with it).

Both are deterministic in input order so a seeded fleet build places
identically every run.
"""

from __future__ import annotations


def spread(endpoints: list, regions: list[str]) -> dict[str, str]:
    """endpoint -> region, round-robin in endpoint order."""
    if not regions:
        return {}
    return {e: regions[i % len(regions)] for i, e in enumerate(endpoints)}


def group_regions(gids: list, regions: list[str]) -> dict[str, str]:
    """gid -> home region, round-robin in gid order."""
    if not regions:
        return {}
    return {g: regions[i % len(regions)] for i, g in enumerate(gids)}


def prefer(candidates: list, region_of: dict, region: str) -> list:
    """Candidates reordered: `region` natives first, then the rest —
    input order preserved within each half (stable, so seeded builds
    pick deterministically). The standby-acquisition ordering."""
    if not region:
        return list(candidates)
    native = [c for c in candidates if region_of.get(c, "") == region]
    other = [c for c in candidates if region_of.get(c, "") != region]
    return native + other
