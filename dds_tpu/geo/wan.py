"""WAN link profiles for ChaosNet: named presets + `[chaos.profiles]`.

A profile names the fault parameters of one DIRECTED region pair
("eu->us"); "eu<->us" installs both directions. Values are either a
preset name (string) or a spec table with explicit parameters:

    [chaos.profiles]
    "eu<->us" = "wan-100"

    [chaos.profiles."us->ap"]
    delay-ms = 120
    jitter-ms = 18
    drop = 0.01

Presets model one-way delay as RTT/2 with ~10% jitter. `scale` shrinks
every delay uniformly — the seeded drill tests run the identical
topology at scale=0.02 so the schedule shape (who waits on whom) is
preserved while the suite stays inside the tier-1 time budget;
benchmarks run at scale=1.0.
"""

from __future__ import annotations

from dds_tpu.core.chaos import LinkFaults

# name -> round-trip seconds for a cross-region pair
WAN_PRESETS: dict[str, float] = {
    "wan-100": 0.100,
    "wan-200": 0.200,
    "wan-300": 0.300,
}


def preset_faults(name: str, scale: float = 1.0) -> LinkFaults:
    rtt = WAN_PRESETS.get(name)
    if rtt is None:
        raise ValueError(f"unknown WAN preset {name!r} "
                         f"(have {sorted(WAN_PRESETS)})")
    one_way = rtt / 2.0 * scale
    return LinkFaults(delay=one_way, jitter=one_way * 0.2)


def faults_from_spec(spec, scale: float = 1.0) -> LinkFaults:
    """A LinkFaults from a preset name or a `[chaos.profiles.*]` table.
    Delay/jitter accept ms keys (TOML-friendly) or plain seconds."""
    if isinstance(spec, str):
        return preset_faults(spec, scale)
    if not isinstance(spec, dict):
        raise ValueError(f"malformed link profile {spec!r}")
    known = {"preset", "delay", "jitter", "delay-ms", "delay_ms",
             "jitter-ms", "jitter_ms", "drop", "duplicate", "reorder",
             "corrupt"}
    unknown = set(spec) - known
    if unknown:
        raise ValueError(f"unknown link-profile keys {sorted(unknown)}")
    if "preset" in spec:
        base = preset_faults(spec["preset"], scale)
    else:
        base = LinkFaults()

    def seconds(key: str, default: float) -> float:
        ms = spec.get(f"{key}-ms", spec.get(f"{key}_ms"))
        if ms is not None:
            return float(ms) / 1e3 * scale
        if key in spec:
            return float(spec[key]) * scale
        return default

    return LinkFaults(
        delay=seconds("delay", base.delay),
        jitter=seconds("jitter", base.jitter),
        drop=float(spec.get("drop", base.drop)),
        duplicate=float(spec.get("duplicate", base.duplicate)),
        reorder=float(spec.get("reorder", base.reorder)),
        corrupt=float(spec.get("corrupt", base.corrupt)),
    )


def parse_profiles(profiles: dict, scale: float = 1.0) -> dict:
    """`[chaos.profiles]` -> {(src_region, dst_region): LinkFaults}."""
    out: dict = {}
    for pair, spec in profiles.items():
        faults = faults_from_spec(spec, scale)
        if "<->" in pair:
            src, dst = (p.strip() for p in pair.split("<->", 1))
            out[(src, dst)] = faults
            out[(dst, src)] = faults
        elif "->" in pair:
            src, dst = (p.strip() for p in pair.split("->", 1))
            out[(src, dst)] = faults
        else:
            raise ValueError(
                f"link-profile key {pair!r} must be 'src->dst' or 'a<->b'")
    return out


def apply_profiles(net, profiles: dict, regions: dict | None = None,
                   scale: float = 1.0) -> None:
    """Install `[chaos.profiles]` onto a ChaosNet (optionally assigning
    `regions`: endpoint name -> region, first). Tests and benchmarks go
    through this one loader so both see the identical seeded WAN."""
    if regions:
        net.set_regions(regions)
    for (src, dst), faults in parse_profiles(profiles, scale).items():
        net.set_region_link(src, dst, faults)


def mesh(regions: list[str], preset: str = "wan-100") -> dict:
    """A full symmetric cross-region mesh profile dict (intra-region
    links stay at the fabric default) — the 3-region test topology."""
    return {f"{a}<->{b}": preset
            for i, a in enumerate(regions) for b in regions[i + 1:]}
