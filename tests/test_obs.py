"""Telescope telemetry tests: tracer core, trace-context propagation,
MetricsRegistry + Prometheus exposition, kernel profiling hooks, flight
recorder, and the end-to-end acceptance paths — a request through the REST
proxy under an active ChaosNet schedule yields ONE trace tree spanning
proxy -> quorum round -> >=2f+1 replica handlers, `GET /metrics` serves
parseable Prometheus text, and a Nemesis-triggered fault freezes the
faulting trace into a JSONL incident file.
"""

import asyncio
import json
import random
import re
import threading

import pytest

from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.malicious.trudy import Nemesis
from dds_tpu.obs import context as obs_context
from dds_tpu.obs import kprof
from dds_tpu.obs.flight import FlightRecorder, flight
from dds_tpu.obs.metrics import Registry, metrics
from dds_tpu.utils.trace import Tracer, tracer

pytestmark = pytest.mark.obs


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- tracer core


def test_ring_buffer_bound_evicts_oldest():
    t = Tracer(max_events=32)
    for i in range(100):
        t.record(f"s{i}", 1.0)
    evs = t.events()
    assert len(evs) == 32
    assert evs[0].name == "s68" and evs[-1].name == "s99"


def test_summary_excludes_counters_and_zero_duration_events():
    t = Tracer()
    for d in (1.0, 2.0, 3.0):
        t.record("op", d)
    t.count("op")  # same NAME as the span family — must not inflate count
    t.count("occurrences", 5)
    t.event("annotation")
    s = t.summary()
    assert s["op"]["count"] == 3 and s["op"]["mean_ms"] == 2.0
    assert "occurrences" not in s and "annotation" not in s
    assert t.counters() == {"op": 1, "occurrences": 5}


def test_percentiles_nearest_rank_small_k():
    t = Tracer()
    for d in range(1, 21):  # 1..20 ms
        t.record("op", float(d))
    s = t.summary()["op"]
    # nearest-rank: p95 of 20 samples is the 19th value, NOT the max
    assert s["p95_ms"] == 19.0
    assert s["p50_ms"] == 10.0

    t2 = Tracer()
    t2.record("one", 7.0)
    assert t2.summary()["one"]["p95_ms"] == 7.0  # k=1 must not index [-1]


def test_thread_safety_under_concurrent_record_and_count():
    t = Tracer(max_events=100_000)
    n_threads, per = 8, 500

    def work():
        for i in range(per):
            t.record("op", float(i))
            t.count("hits")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.counters()["hits"] == n_threads * per
    assert t.summary()["op"]["count"] == n_threads * per


def test_dump_jsonl_namespaces_meta(tmp_path):
    t = Tracer()
    # hostile meta: keys that collide with the record's own fields
    t.record("real-name", 42.0, name="shadow", ts=-1, dur_ms=0.0)
    path = tmp_path / "spans.jsonl"
    assert t.dump_jsonl(str(path)) == 1
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["name"] == "real-name" and rec["dur_ms"] == 42.0
    assert rec["meta"] == {"name": "shadow", "ts": -1, "dur_ms": 0.0}


def test_nested_spans_link_parent_child():
    t = Tracer()
    with t.span("outer"):
        with t.span("inner"):
            pass
    inner, outer = t.events("inner")[0], t.events("outer")[0]
    assert inner.trace_id == outer.trace_id
    assert inner.parent_id == outer.span_id
    assert t.trace_events(outer.trace_id) == [inner, outer]


# ---------------------------------------------------------- trace context


def test_context_wire_and_header_round_trip():
    ctx = obs_context.root()
    back = obs_context.from_wire(obs_context.to_wire(ctx))
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    h = obs_context.from_header(obs_context.to_header(ctx))
    assert (h.trace_id, h.span_id) == (ctx.trace_id, ctx.span_id)


def test_context_malformed_degrades_to_none():
    for garbage in (None, "x", 7, [], {"t": 3, "s": "ok"}, {"t": "", "s": "y"}):
        assert obs_context.from_wire(garbage) is None
    for header in ("", "noseparator", "-", "a" * 40 + "-b"):
        assert obs_context.from_header(header) is None


def test_child_derives_from_parent():
    root = obs_context.root()
    c = obs_context.child(root)
    assert c.trace_id == root.trace_id and c.parent_id == root.span_id
    assert c.span_id != root.span_id


# --------------------------------------------------------- MetricsRegistry


def test_registry_counters_gauges_and_kind_conflict():
    r = Registry()
    r.inc("reqs_total", route="a")
    r.inc("reqs_total", 2, route="a")
    r.set("depth", 7.5)
    assert r.value("reqs_total", route="a") == 3
    assert r.value("depth") == 7.5
    with pytest.raises(ValueError):
        r.set("reqs_total", 1)  # counter re-registered as gauge


_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"            # metric name
    r'(\{[a-zA-Z0-9_]+="(\\.|[^"\\])*"'      # first label
    r'(,[a-zA-Z0-9_]+="(\\.|[^"\\])*")*\})?' # more labels
    r" [0-9.eE+-]+$|^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \+Inf$"
)


def _parse_prom(text: str) -> dict[str, float]:
    """Tiny exposition parser: {name{labels}: value}; asserts line syntax."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _LINE.match(line), f"unparseable exposition line: {line!r}"
        series, value = line.rsplit(" ", 1)
        out[series] = float(value)
    return out


def test_histogram_exposition_round_trip():
    r = Registry()
    for v in (0.0005, 0.003, 0.003, 0.04, 99.0):
        r.observe("lat_seconds", v, buckets=(0.001, 0.01, 0.1), op="w")
    parsed = _parse_prom(r.render())
    assert parsed['lat_seconds_bucket{op="w",le="0.001"}'] == 1
    assert parsed['lat_seconds_bucket{op="w",le="0.01"}'] == 3
    assert parsed['lat_seconds_bucket{op="w",le="0.1"}'] == 4
    assert parsed['lat_seconds_bucket{op="w",le="+Inf"}'] == 5  # overflow obs
    assert parsed['lat_seconds_count{op="w"}'] == 5
    assert abs(parsed['lat_seconds_sum{op="w"}'] - 99.0465) < 1e-9
    assert r.histogram_stats("lat_seconds", op="w") == {
        "count": 5, "sum": 0.0005 + 0.003 + 0.003 + 0.04 + 99.0,
    }


def test_label_values_escaped():
    r = Registry()
    r.inc("c_total", route='we"ird\nkey\\x')
    text = r.render()
    assert '\\"' in text and "\\n" in text and "\\\\" in text
    assert "\n\n" not in text  # the raw newline never splits the line


# ------------------------------------------------------------------- kprof


def test_cache_event_accounting_and_counted():
    import functools

    kprof.reset()
    calls = []

    @functools.lru_cache(maxsize=None)
    def build(n):
        calls.append(n)
        return n * 2

    assert kprof.counted("t.cache", build, 3) == 6  # miss
    assert kprof.counted("t.cache", build, 3) == 6  # hit
    kprof.cache_event("t.cache", hit=True)
    stats = kprof.kernel_summary()["compile_cache"]["t.cache"]
    assert stats == {"hits": 2, "misses": 1, "hit_rate": round(2 / 3, 4)}
    assert calls == [3]


def test_profiled_splits_dispatch_from_execute():
    import jax.numpy as jnp

    tracer.reset()
    out = kprof.profiled("testk", lambda: jnp.arange(8) * 2, k=8)
    assert list(out) == list(range(0, 16, 2))
    s = tracer.summary()
    assert s["kernel.testk.dispatch"]["count"] == 1
    assert s["kernel.testk.execute"]["count"] == 1
    ks = kprof.kernel_summary()
    assert ks["dispatch_ms"] >= 0 and ks["execute_ms"] >= 0


# --------------------------------------------------------- flight recorder


def test_flight_recorder_disabled_without_dir():
    fr = FlightRecorder(dir=None)
    assert not fr.enabled and fr.record("breaker_open") is None


def test_flight_recorder_writes_incident_with_faulting_trace(tmp_path):
    tracer.reset()
    fr = FlightRecorder(dir=str(tmp_path), min_interval=0.0)
    with tracer.span("http.GET.GetSet") as _:
        ctx = obs_context.current()
        with tracer.span("abd.fetch"):
            pass
        path = fr.record("deadline_exceeded", trace_id=ctx.trace_id,
                         route="GetSet")
    assert path is not None
    lines = [json.loads(l) for l in open(path)]
    header, rest = lines[0], lines[1:]
    assert header["incident"] == "deadline_exceeded"
    assert header["trace_id"] == ctx.trace_id
    assert header["info"] == {"route": "GetSet"}
    trace_lines = [l for l in rest if l.get("section") == "trace"]
    assert {l["trace_id"] for l in trace_lines} == {ctx.trace_id}
    assert "abd.fetch" in {l["name"] for l in trace_lines}
    assert not list(tmp_path.glob("*.tmp"))  # atomic: no leftover temp file


def test_flight_recorder_rate_limits_per_kind(tmp_path):
    fr = FlightRecorder(dir=str(tmp_path), min_interval=60.0)
    assert fr.record("breaker_open") is not None
    assert fr.record("breaker_open") is None          # suppressed
    assert fr.record("suspicion_quorum") is not None  # other kinds unaffected


def test_flight_recorder_prunes_old_incidents(tmp_path):
    fr = FlightRecorder(dir=str(tmp_path), max_incidents=2, min_interval=0.0)
    for i in range(5):
        fr.record(f"kind_{i}")
    left = sorted(tmp_path.glob("incident-*.jsonl"))
    assert len(left) == 2
    assert all("kind_3" in p.name or "kind_4" in p.name for p in left)


# --------------------------------------------- end-to-end REST acceptance


async def _obs_rest_stack(seed=21, budget=10.0, timeout=2.0, **proxy_kw):
    """7-replica / q=5 (f=2) cluster behind a mildly-delaying ChaosNet."""
    net = ChaosNet(InMemoryNet(), seed=seed)
    net.default_faults = LinkFaults(delay=0.001, jitter=0.002)
    addrs = [f"replica-{i}" for i in range(7)]
    replicas = {
        a: BFTABDNode(a, addrs, "supervisor", net, ReplicaConfig(quorum_size=5))
        for a in addrs
    }
    abd = AbdClient(
        "proxy-0", net, addrs,
        AbdClientConfig(request_timeout=timeout, quorum_size=5),
    )
    server = DDSRestServer(
        abd,
        ProxyConfig(host="127.0.0.1", port=0, request_budget=budget,
                    trace_route_enabled=True, **proxy_kw),
    )
    await server.start()
    return net, server, replicas


async def _call(server, method, target, obj=None):
    body = json.dumps(obj).encode() if obj is not None else None
    return await http_request(
        "127.0.0.1", server.cfg.port, method, target, body, timeout=10.0
    )


def test_request_under_chaos_yields_single_trace_tree():
    """Acceptance: one REST request under an active ChaosNet schedule
    produces ONE trace tree — proxy route span -> quorum round -> >=2f+1
    replica handler spans with per-replica attribution — plus chaos
    annotations on the same trace."""

    async def go():
        net, server, _ = await _obs_rest_stack()
        try:
            tracer.reset()
            status, _ = await _call(
                server, "POST", "/PutSet", {"contents": ["a", "b"]}
            )
            assert status == 200
            await net.quiesce()
        finally:
            await server.stop()

    run(go())
    roots = tracer.events("http.POST.PutSet")
    assert len(roots) == 1
    root = roots[0]
    assert root.trace_id and root.parent_id is None
    tree = tracer.trace_events(root.trace_id)

    # the quorum round is a direct child of the route span
    writes = [e for e in tree if e.name == "abd.write"]
    assert writes and all(e.parent_id == root.span_id for e in writes)
    assert writes[0].meta.get("coordinator", "").startswith("replica-")

    # >=2f+1 DISTINCT replicas served handler spans inside this one trace
    handlers = [e for e in tree if e.name == "replica.handle"]
    assert all(e.parent_id is not None for e in handlers)
    assert len({e.meta["replica"] for e in handlers}) >= 5

    # the fabric's injections annotate the same trace
    chaos_events = [e for e in tree if e.name.startswith("chaos.")]
    assert chaos_events and all(e.kind == "event" for e in chaos_events)


def test_metrics_route_serves_parseable_prometheus_text():
    """Acceptance: GET /metrics is Prometheus exposition text covering
    route latency histograms, quorum RTT, and compile-cache hit rate."""
    from dds_tpu.ops.foldmany import fold_many

    # drive the instrumented kernel path so compile-cache series exist
    n = 7 * 11
    assert fold_many([[2, 3], [4, 5]], n) == [6, 20 % n]
    fold_many([[2, 3], [4, 5]], n)  # second call: cache hit

    async def go():
        net, server, _ = await _obs_rest_stack()
        try:
            status, _ = await _call(
                server, "POST", "/PutSet", {"contents": ["x"]}
            )
            assert status == 200
            status, body = await _call(server, "GET", "/metrics")
            assert status == 200
            await net.quiesce()
            return body.decode()
        finally:
            await server.stop()

    text = run(go())
    parsed = _parse_prom(text)

    def series(prefix):
        return {k: v for k, v in parsed.items() if k.startswith(prefix)}

    # route latency histogram, labelled by route
    buckets = series("dds_http_request_seconds_bucket")
    assert any('route="PutSet"' in k for k in buckets)
    assert any('le="+Inf"' in k for k in buckets)
    # quorum round-trips observed
    assert sum(series("dds_quorum_rtt_seconds_count").values()) >= 1
    # compile-cache accounting from the kernel path (1 miss, then hits)
    cache = series("dds_compile_cache_total")
    hits = sum(v for k, v in cache.items()
               if 'cache="foldmany"' in k and 'outcome="hit"' in k)
    misses = sum(v for k, v in cache.items()
                 if 'cache="foldmany"' in k and 'outcome="miss"' in k)
    assert misses >= 1 and hits >= 1
    # scrape-time state gauges
    assert series("dds_trusted_replicas")
    assert any(k.startswith("dds_breaker_state") for k in parsed)


def test_trace_route_reports_counters_separately():
    async def go():
        net, server, _ = await _obs_rest_stack()
        try:
            tracer.reset()
            tracer.count("standalone.counter", 3)
            await _call(server, "POST", "/PutSet", {"contents": ["y"]})
            status, body = await _call(server, "GET", "/_trace")
            assert status == 200
            await net.quiesce()
            return json.loads(body)
        finally:
            await server.stop()

    out = run(go())
    assert out["counters"]["standalone.counter"] == 3
    assert "standalone.counter" not in out["spans"]
    assert "http.POST.PutSet" in out["spans"]


def test_nemesis_fault_writes_incident_containing_faulting_trace(tmp_path):
    """Acceptance: a Nemesis partition makes a request degrade, and the
    flight recorder freezes that request's trace into a JSONL incident."""

    async def go():
        net, server, _ = await _obs_rest_stack(
            seed=5, budget=0.5, timeout=0.1,
            retry_backoff=0.02, retry_max_delay=0.05,
        )
        flight.configure(dir=str(tmp_path), min_interval=0.0)
        try:
            nem = Nemesis(net, [f"replica-{i}" for i in range(7)],
                          max_faults=7, rng=random.Random(3))
            assert len(nem.trigger("partition")) == 7  # total partition
            status, _ = await _call(server, "GET", "/GetSet/" + "ab" * 64)
            assert status == 503
            await net.quiesce()
        finally:
            flight.configure(dir="")  # back to disabled for other tests
            await server.stop()

    run(go())
    incidents = sorted(tmp_path.glob("incident-*.jsonl"))
    assert incidents
    kinds = {}
    for p in incidents:
        lines = [json.loads(l) for l in open(p)]
        kinds[lines[0]["incident"]] = lines
    # the attack itself recorded an incident...
    assert "attack_partition" in kinds
    # ...and the degraded request recorded one CONTAINING its trace
    fault = kinds.get("deadline_exceeded") or kinds.get("no_trusted_nodes")
    assert fault is not None
    header, rest = fault[0], fault[1:]
    assert header["trace_id"]
    trace_lines = [l for l in rest if l.get("section") == "trace"]
    assert trace_lines
    assert all(l["trace_id"] == header["trace_id"] for l in trace_lines)
    names = {l["name"] for l in trace_lines}
    assert any(n.startswith("http.GET") for n in names)  # the route span
