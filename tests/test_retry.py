"""Unit tests for the deadline/backoff/breaker layer (utils/retry).

Everything runs on fake clocks and recorded sleeps — no wall-clock
dependence, so bounds are exact rather than flaky."""

import asyncio
import random

import pytest

from dds_tpu.utils.retry import (
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
    retry,
    retry_deadline,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def run(coro):
    return asyncio.run(coro)


# ----------------------------------------------------------------- deadline


def test_deadline_accounting_on_fake_clock():
    clock = FakeClock()
    dl = Deadline(5.0, clock=clock)
    assert dl.remaining() == 5.0 and not dl.expired
    clock.advance(3.0)
    assert dl.remaining() == 2.0 and dl.elapsed() == 3.0
    assert dl.timeout(10.0) == 2.0  # per-attempt clipped to the remainder
    assert dl.timeout(0.5) == 0.5
    clock.advance(3.0)
    assert dl.expired and dl.timeout(1.0) == 0.0


# ------------------------------------------------- exponential backoff bounds


def test_full_jitter_backoff_within_exponential_envelope():
    policy = RetryPolicy(base=0.1, multiplier=2.0, max_delay=1.0)
    rng = random.Random(7)
    for attempt in range(8):
        cap = min(1.0, 0.1 * 2.0 ** attempt)
        for _ in range(50):
            d = policy.backoff(attempt, rng)
            assert 0.0 <= d <= cap, (attempt, d, cap)


def test_backoff_without_jitter_is_deterministic_exponential():
    policy = RetryPolicy(base=0.1, multiplier=2.0, max_delay=0.5, jitter=False)
    rng = random.Random(0)
    assert [policy.backoff(a, rng) for a in range(4)] == [
        0.1, 0.2, 0.4, 0.5  # capped at max_delay
    ]


def test_retry_deadline_sleeps_follow_the_policy():
    clock = FakeClock()
    sleeps = []

    async def fake_sleep(d):
        sleeps.append(d)
        clock.advance(d)

    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise ConnectionError("nope")
        return "ok"

    async def go():
        policy = RetryPolicy(base=0.1, multiplier=2.0, max_delay=10.0,
                             jitter=False)
        out = await retry_deadline(
            flaky, Deadline(60.0, clock=clock), policy, sleep=fake_sleep
        )
        assert out == "ok"
        assert sleeps == [0.1, 0.2, 0.4]  # exact exponential ladder

    run(go())


# --------------------------------------------------------- deadline exhaustion


def test_deadline_exhaustion_raises_typed_error_with_context():
    clock = FakeClock()

    async def fake_sleep(d):
        clock.advance(d)

    async def always_down():
        clock.advance(0.05)  # each attempt costs time too
        raise ConnectionError("partitioned")

    async def go():
        policy = RetryPolicy(base=0.2, multiplier=2.0, max_delay=5.0,
                             jitter=False)
        with pytest.raises(DeadlineExceededError) as ei:
            await retry_deadline(
                always_down, Deadline(1.0, clock=clock), policy,
                sleep=fake_sleep,
            )
        err = ei.value
        assert err.attempts >= 1
        assert isinstance(err.last_error, ConnectionError)
        assert err.elapsed <= 1.0 + 1e-9  # degraded WITHIN budget, no overrun
        assert clock.t <= 1.0 + 1e-9     # never slept past the deadline

    run(go())


def test_retry_deadline_does_not_retry_unlisted_exceptions():
    async def boom():
        raise ValueError("a bug, not a blip")

    async def go():
        with pytest.raises(ValueError):
            await retry_deadline(
                boom, Deadline(10.0), retry_on=(ConnectionError,)
            )

    run(go())


def test_retry_deadline_attempt_cap_propagates_real_error():
    calls = {"n": 0}

    async def always_down():
        calls["n"] += 1
        raise ConnectionError("down")

    async def go():
        policy = RetryPolicy(base=0.0, max_attempts=3, jitter=False)
        with pytest.raises(ConnectionError):
            await retry_deadline(always_down, Deadline(10.0), policy)
        assert calls["n"] == 3

    run(go())


def test_legacy_fixed_backoff_retry_still_works():
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("x")
        return 42

    assert run(retry(flaky, 0.0, 5)) == 42
    assert calls["n"] == 3


# ------------------------------------------------------------ circuit breaker


def test_breaker_opens_after_threshold_and_half_opens_after_reset():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout=2.0, clock=clock)
    assert b.state == CircuitBreaker.CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == CircuitBreaker.CLOSED  # below threshold
    b.record_failure()
    assert b.state == CircuitBreaker.OPEN and not b.allow()
    clock.advance(1.9)
    assert not b.allow()  # still open before reset_timeout
    clock.advance(0.2)
    assert b.allow()      # probe admitted
    assert b.state == CircuitBreaker.HALF_OPEN


def test_breaker_half_open_probe_success_closes():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout=1.0, clock=clock)
    b.record_failure()
    assert b.state == CircuitBreaker.OPEN
    clock.advance(1.0)
    assert b.allow() and b.state == CircuitBreaker.HALF_OPEN
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED and b.allow()


def test_breaker_half_open_probe_failure_reopens_with_fresh_timer():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=2, reset_timeout=1.0, clock=clock)
    b.record_failure()
    b.record_failure()
    clock.advance(1.0)
    assert b.state == CircuitBreaker.HALF_OPEN
    b.record_failure()  # ONE failed probe re-opens (no threshold grace)
    assert b.state == CircuitBreaker.OPEN and not b.allow()
    clock.advance(0.5)
    assert not b.allow()  # the reset timer restarted at the failed probe
    clock.advance(0.5)
    assert b.allow()


def test_breaker_success_resets_consecutive_failure_count():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout=1.0, clock=clock)
    for _ in range(4):
        b.record_failure()
        b.record_success()  # CONSECUTIVE failures trip, interleaved don't
    assert b.state == CircuitBreaker.CLOSED
