"""Constellation sharding-plane tests.

Covers the acceptance surface of the shard plane: deterministic signed
shard maps and split-locality, point-op routing isolation, epoch fencing
(typed WrongShard rejections at coordinator, storage, and tag-batch
layers), scatter-gather aggregate equivalence (bit-for-bit vs a single
shard over IDENTICAL ciphertexts), a live Aegis-verified split under a
seeded ChaosNet schedule with a partition healing mid-reshard (zero
stale-epoch writes accepted, anti-entropy convergence, per-group
linearizability, zero Watchtower quorum-intersection violations per
group), the abort path (old map restored + flight incident), and the
/shards + /health + /metrics operator surface.
"""

import asyncio
import json
import random
import time

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.chaos import ChaosNet
from dds_tpu.core.errors import WrongShardError
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.shard import (
    ReshardAborted,
    ShardMap,
    build_constellation,
    moved_keys,
)
from dds_tpu.utils.retry import Deadline, RetryPolicy, retry_deadline
from tests.test_core import run
from tests.test_linearizability import Recorder, check_atomic_register

pytestmark = pytest.mark.shard

SECRET = b"intranet-abd-secret"
_POLICY = RetryPolicy(base=0.01, multiplier=2.0, max_delay=0.08)


def constellation(S=2, net=None, seed=7, **kw):
    net = net or InMemoryNet()
    kw.setdefault("n_active", 4)
    kw.setdefault("n_sentinent", 1)
    kw.setdefault("quorum", 3)
    return build_constellation(net, shard_count=S, vnodes_per_group=8,
                               seed=seed, **kw), net


# ---------------------------------------------------------------- shard map


def test_shardmap_deterministic_signed_and_tamperproof():
    m1 = ShardMap.build(["s0", "s1", "s2"], 8).sign(SECRET)
    m2 = ShardMap.build(["s2", "s1", "s0"], 8).sign(SECRET)
    assert m1.vnodes == m2.vnodes  # group order never changes the ring
    keys = [f"K{i}" for i in range(256)]
    assert [m1.owner(k) for k in keys] == [m2.owner(k) for k in keys]
    assert m1.verify(SECRET) and not m1.verify(b"forged-secret")
    # wire round-trip preserves the signature
    assert ShardMap.from_wire(m1.to_wire()).verify(SECRET)
    # a tampered map (vnode re-homed) fails verification
    forged = ShardMap(m1.epoch, tuple(
        (p, "s0") for p, _ in m1.vnodes), m1.groups, m1.signature)
    assert not forged.verify(SECRET)
    # epochs only move forward at the manager
    from dds_tpu.shard import ShardManager

    mgr = ShardManager(m1, SECRET)
    with pytest.raises(ValueError):
        mgr.activate(m1)  # same epoch


def test_shardmap_split_moves_only_victim_keys():
    m1 = ShardMap.build(["s0", "s1"], 8).sign(SECRET)
    m2 = m1.split("s1", "s2").sign(SECRET)
    assert m2.epoch == m1.epoch + 1
    keys = [f"K{i}" for i in range(512)]
    moved = moved_keys(m1, m2, keys)
    assert moved  # a split that moves nothing split nothing
    for k in moved:
        assert m1.owner(k) == "s1" and m2.owner(k) == "s2"
    # everything that didn't move kept its exact owner
    for k in keys:
        if k not in moved:
            assert m1.owner(k) == m2.owner(k)


def test_shardmap_merge_inverts_split_on_random_rings():
    """Property: merge(split(m)) == m (epoch aside) for random rings —
    the new group's vnodes are retired and every key it briefly owned
    falls back to its original arc, so ownership is bit-identical."""
    rng = random.Random(0xD5)
    keys = [f"P{i}" for i in range(256)]
    for trial in range(24):
        n_groups = rng.randint(1, 5)
        groups = [f"g{trial}x{i}" for i in range(n_groups)]
        # a one-vnode ring has no splittable arc (its own predecessor)
        vpg = rng.choice([2, 4, 8, 16] if n_groups == 1 else [1, 2, 4, 8])
        m = ShardMap.build(groups, vpg)
        victim = rng.choice(groups)
        m2 = m.split(victim, "sNEW")
        m3 = m2.merge("sNEW")
        assert m3.epoch == m.epoch + 2
        assert m3.vnodes == m.vnodes
        assert m3.groups == m.groups
        assert [m3.owner(k) for k in keys] == [m.owner(k) for k in keys]


def test_shardmap_merge_moves_only_victim_keys():
    """Merge locality: the only keys whose owner changes are those the
    victim owned, and they land exactly on the ring-successor absorbers
    the map itself advertises."""
    rng = random.Random(0xA7)
    keys = [f"M{i}" for i in range(512)]
    for trial in range(16):
        n_groups = rng.randint(2, 6)
        groups = [f"h{trial}x{i}" for i in range(n_groups)]
        m1 = ShardMap.build(groups, rng.choice([2, 4, 8]))
        victim = rng.choice(groups)
        m2 = m1.merge(victim)
        assert m2.epoch == m1.epoch + 1
        assert victim not in m2.groups
        moved = moved_keys(m1, m2, keys)
        absorbers = m1.absorbers(victim)
        for k in moved:
            assert m1.owner(k) == victim
            assert m2.owner(k) in absorbers
        for k in keys:
            if k not in moved:
                assert m1.owner(k) == m2.owner(k)
    # degenerate shapes refuse instead of corrupting the ring
    lone = ShardMap.build(["s0"], 4)
    with pytest.raises(ValueError):
        lone.merge("s0")
    with pytest.raises(ValueError):
        ShardMap.build(["s0", "s1"], 4).merge("sX")


def test_shardmap_merge_signed_manifest_across_epoch_bump():
    """The merge result signs/verifies like any other map, survives a
    wire round-trip, rejects tampering, and activates at the manager
    across the epoch bump — while the unsigned intermediate does not."""
    from dds_tpu.shard import ShardManager

    m1 = ShardMap.build(["s0", "s1", "s2"], 8).sign(SECRET)
    merged = m1.merge("s2")
    assert not merged.verify(SECRET)  # unsigned intermediate
    signed = merged.sign(SECRET)
    assert signed.verify(SECRET) and not signed.verify(b"forged")
    rt = ShardMap.from_wire(signed.to_wire())
    assert rt.verify(SECRET) and rt.epoch == m1.epoch + 1
    mgr = ShardManager(m1, SECRET)
    mgr.activate(rt)
    assert mgr.epoch == m1.epoch + 1
    with pytest.raises(ValueError):
        mgr.activate(rt)  # epochs only move forward


def test_shardmap_relabel_is_arc_identical_takeover():
    m1 = ShardMap.build(["s0", "s1", "s2"], 8).sign(SECRET)
    m2 = m1.relabel("s1", "s9")
    assert m2.epoch == m1.epoch + 1
    assert "s1" not in m2.groups and "s9" in m2.groups
    assert [p for p, _ in m2.vnodes] == [p for p, _ in m1.vnodes]
    keys = [f"T{i}" for i in range(256)]
    for k in keys:
        old, new = m1.owner(k), m2.owner(k)
        assert new == ("s9" if old == "s1" else old)
    with pytest.raises(ValueError):
        m1.relabel("sX", "s9")
    with pytest.raises(ValueError):
        m1.relabel("s1", "s0")


# ------------------------------------------------------------ point routing


def test_point_ops_route_to_exactly_one_group():
    async def go():
        const, net = constellation(S=2)
        r = const.router
        keys = [f"ROUTE-{i}" for i in range(12)]
        for k in keys:
            assert await r.write_set(k, [k]) == k
        for k in keys:
            assert await r.fetch_set(k) == [k]
        await net.quiesce()
        owners = {r.owner(k) for k in keys}
        assert owners == {"s0", "s1"}  # the sample spans both groups
        for k in keys:
            owner = r.owner(k)
            for g in const.groups:
                holders = [
                    n for n in g.replicas.values()
                    if n.repository.get(k, (None, None))[1] == [k]
                ]
                if g.gid == owner:
                    assert len(holders) >= g.quorum_size
                else:
                    assert not holders, (k, g.gid)
        await const.stop()

    run(go())


def test_router_read_tags_scatter_and_unchanged_identity():
    async def go():
        const, net = constellation(S=2)
        r = const.router
        keys = sorted(f"TAGS-{i}" for i in range(8))
        for k in keys:
            await r.write_set(k, [k])
        assert len(r.partition_keys(keys)) == 2
        tags = await r.read_tags(keys)
        # scattered per-group rounds agree with per-key quorum reads
        for k, t in zip(keys, tags):
            _, tag = await r.fetch_set_tagged(k)
            assert t == tag
        # all-fresh cached vector comes back BY IDENTITY even though each
        # group only attested its own slice
        cached = list(tags)
        again = await r.read_tags(keys, cached_tags=cached,
                                  fingerprint=b"ignored-by-router")
        assert again is cached
        await const.stop()

    run(go())


# ------------------------------------------------------------ epoch fencing


def _remap_all_to(smap, gid, epoch=None):
    """A forged-free epoch+1 map assigning every vnode to `gid`."""
    return ShardMap(
        epoch if epoch is not None else smap.epoch + 1,
        tuple((p, gid) for p, _ in smap.vnodes), (gid,),
    ).sign(SECRET)


def test_epoch_fence_rejects_stale_route_then_retry_lands():
    async def go():
        const, net = constellation(S=2, n_sentinent=0)
        r = const.router
        smap = const.manager.current()
        key = next(k for k in (f"F{i}" for i in range(64))
                   if smap.owner(k) == "s1")
        await r.write_set(key, ["v0"])
        m2 = _remap_all_to(smap, "s0")
        const.group("s1").state.install(m2)  # freeze: s1 fences, router stale
        with pytest.raises(WrongShardError):
            await r.write_set(key, ["v1"])
        with pytest.raises(WrongShardError):
            await r.read_tags([key])
        from dds_tpu.obs.metrics import metrics

        assert (metrics.value("dds_wrong_shard_retries_total", shard="s1")
                or 0) >= 2
        # no suspicion accrued: the fencing replicas stay fully trusted
        assert not any(const.group("s1").client.replicas.suspicions().values())
        # activation makes the SAME logical op succeed on the new owner
        const.group("s0").state.install(m2)
        const.manager.activate(m2)
        await r.write_set(key, ["v1"])
        assert await r.fetch_set(key) == ["v1"]
        await net.quiesce()
        for n in const.group("s1").replicas.values():
            assert n.repository.get(key, (None, None))[1] != ["v1"]
        await const.stop()

    run(go())


def test_storage_layer_fence_blocks_raced_write_broadcast():
    """A Write broadcast minted before the freeze must not land after it:
    the storage-layer fence drops it unstored and unacked on every
    replica, so zero stale-epoch writes are ever accepted."""

    async def go():
        const, net = constellation(S=1, n_sentinent=0)
        g = const.group("s0")
        smap = const.manager.current()
        key = "RACED"
        # freeze s0 out of the whole keyspace, then hand-deliver a Write
        # that a pre-freeze coordinator would have broadcast
        g.state.install(_remap_all_to(smap, "sX"))
        from dds_tpu.utils import sigs

        nonce = sigs.generate_nonce()
        tag = M.ABDTag(5, "s0-replica-0")
        sig = sigs.abd_signature(SECRET, ["stale"], tag, nonce)
        victim = g.replicas["s0-replica-1"]
        victim.incoming[nonce] = False  # phase already opened pre-freeze
        await victim.handle("s0-replica-0",
                            M.Write(tag, key, ["stale"], sig, nonce))
        assert key not in victim.repository
        await const.stop()

    run(go())


# ------------------------------------------------- scatter-gather aggregates


def test_scatter_gather_sumall_bit_for_bit_vs_single_shard():
    from dds_tpu.models import HEKeys

    he = HEKeys.generate(paillier_bits=512, rsa_bits=512)
    pk = he.psse.public
    vals = [7, 21, 301, 44, 5, 600]
    rows = [[str(pk.encrypt(v))] for v in vals]  # ONE encryption for both runs

    async def serve(S):
        const, net = constellation(S=S, n_sentinent=0, seed=3)
        server = DDSRestServer(const.router,
                               ProxyConfig(port=0, crypto_backend="cpu"))
        await server.start()
        scatters = {"n": 0}
        orig = server._shard_operands

        def spy(pairs, pos):
            out = orig(pairs, pos)
            if len(out) > 1:
                scatters["n"] += 1
            return out

        server._shard_operands = spy
        for row in rows:
            st, _ = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": row}).encode(), timeout=10.0,
            )
            assert st == 200
        if S > 1:  # the sample must genuinely span shards
            assert len(const.router.partition_keys(
                sorted(server.stored_keys))) > 1
        st, body = await http_request(
            "127.0.0.1", server.cfg.port, "GET",
            f"/SumAll?position=0&nsqr={pk.nsquare}", timeout=30.0,
        )
        assert st == 200
        result = json.loads(body)["result"]
        await server.stop()
        await const.stop()
        return result, scatters["n"]

    async def go():
        single, _ = await serve(1)
        sharded, scattered = await serve(4)
        assert scattered >= 1  # the scatter path really ran
        assert sharded == single  # bit-for-bit: shared modulus, assoc product
        assert he.psse.decrypt(int(sharded)) == sum(vals)

    asyncio.run(go())


# ----------------------------------------------------------- live resharding


async def _retrying_writer(router, rec, key, wid, n, seed, budget=10.0):
    rng = random.Random(seed)
    committed = []
    for i in range(n):
        value = [f"w{wid}-{i}"]
        t0 = time.monotonic()
        dl = Deadline(budget)
        await retry_deadline(
            lambda: router.write_set(key, value, deadline=dl),
            dl, _POLICY, rng=rng, retry_on=(Exception,),
        )
        committed.append((f"w{wid}-{i}", t0))  # value, attempt START time
        rec.record("write", f"w{wid}-{i}", t0, time.monotonic())
        await asyncio.sleep(rng.uniform(0, 0.004))
    return committed


@pytest.mark.chaos
def test_live_split_chaos_partition_heals_mid_reshard():
    """The flagship schedule: a seeded ChaosNet partition cuts one future
    new-group replica while a live split runs, healing mid-reshard; a
    writer hammers a MOVING key throughout. Asserts: the history
    linearizes; zero writes were accepted under the stale epoch (no
    post-freeze value ever appears in the source group, whose pre-split
    state is retained via prune=False); the new group holds the final
    value at quorum; the partitioned straggler converges via Merkle
    anti-entropy; and a Watchtower with per-group geometry reports zero
    quorum-intersection violations."""
    from dds_tpu.obs.watchtower import Watchtower
    from dds_tpu.utils.trace import tracer

    async def go():
        net = ChaosNet(InMemoryNet(), seed=909)
        const, _ = constellation(S=2, net=net, n_sentinent=1, seed=11,
                                 prune=False, ack_timeout=8.0)
        wt = Watchtower(quorum_size=3, n_replicas=4)
        wt.configure(group_geometry={"s0": (3, 4), "s1": (3, 4),
                                     "s2": (3, 4)})
        wt.attach(tracer)
        try:
            r = const.router
            smap = const.manager.current()
            m2 = smap.split("s1", "s2")
            moving = next(k for k in (f"MOVE-{i}" for i in range(128))
                          if smap.owner(k) == "s1" and m2.owner(k) == "s2")
            stable = next(k for k in (f"STAY-{i}" for i in range(128))
                          if smap.owner(k) == "s0")
            await r.write_set(moving, ["w0--1"])
            rec = Recorder()
            split_done = asyncio.Event()
            frozen_at = {"t": None}
            # capture the EXACT fence instant: the moment the source
            # group's state adopts the epoch+1 map
            src_state = const.group("s1").state
            orig_install = src_state.install

            def spy_install(m, force=False):
                orig_install(m, force=force)
                if frozen_at["t"] is None and m.epoch > smap.epoch:
                    frozen_at["t"] = time.monotonic()

            src_state.install = spy_install

            async def do_split():
                await asyncio.sleep(0.03)
                # cut a replica of the FUTURE group s2 so it misses the
                # migration stream; heal mid-reshard on a timer
                net.partition(["s2-replica-2"], duration=0.12)
                await const.split("s1")
                split_done.set()

            writes, _, _ = await asyncio.gather(
                _retrying_writer(r, rec, moving, 0, 10, seed=21),
                _retrying_writer(r, rec, stable, 1, 6, seed=22),
                do_split(),
            )
            assert split_done.is_set()
            assert const.manager.epoch == smap.epoch + 1
            net.heal_all()
            await net.quiesce()
            check_atomic_register(
                [o for o in rec.ops if o["kind"] == "write"]
            )
            final = await r.fetch_set(moving)
            assert final == ["w0-9"]
            # zero stale-epoch writes: a write whose attempt STARTED after
            # the fence installed can only ever commit through the new
            # group (every source-group Write phase fences), so its value
            # must never appear in the (unpruned) source group
            assert frozen_at["t"] is not None
            post_freeze = {v for v, t in writes if t > frozen_at["t"]}
            assert post_freeze  # some writes really landed post-freeze
            src = const.group("s1")
            for n in src.replicas.values():
                held = n.repository.get(moving, (None, None))[1]
                assert held is None or held[0] not in post_freeze, (
                    n.name, held)
            # the new group holds the final value at quorum
            new = const.group("s2")
            await net.quiesce()
            holders = [
                n for n in new.replicas.values()
                if n.repository.get(moving, (None, None))[1] == final
            ]
            assert len(holders) >= new.quorum_size
            # the partitioned straggler converges via anti-entropy pulls
            straggler = new.replicas["s2-replica-2"]
            donors = [e for e in new.active if e != straggler.addr]
            for donor in donors:
                await straggler.antientropy.sync_once(donor)
            assert straggler.repository.get(moving, (None, None))[1] == final
            # per-group audit: no quorum-intersection violations anywhere
            bad = [v for v in wt.verdicts()
                   if v.invariant == "quorum_intersection"]
            assert not bad, bad
        finally:
            wt.detach()
            await const.stop()

    run(go())


def test_reshard_abort_restores_old_map_and_records_incident(tmp_path):
    from dds_tpu.obs.flight import flight

    async def go():
        net = ChaosNet(InMemoryNet(), seed=77)
        const, _ = constellation(S=2, net=net, n_sentinent=0, seed=5,
                                 manifest_timeout=0.3, ack_timeout=0.5)
        flight.configure(dir=str(tmp_path), max_incidents=8,
                         min_interval=0.0)
        try:
            old = const.manager.current()
            key = next(k for k in (f"A{i}" for i in range(64))
                       if old.owner(k) == "s1")
            await const.router.write_set(key, ["pre"])
            # the whole source group is unreachable: no manifest quorum
            net.partition([f"s1-replica-{i}" for i in range(4)])
            with pytest.raises(ReshardAborted):
                await const.split("s1")
            assert const.manager.current() is old
            assert const.manager.state == "stable"
            assert const.group("s1").state.epoch == old.epoch  # rolled back
            incidents = [p for p in tmp_path.iterdir()
                         if "reshard_abort" in p.name]
            assert incidents
            # heal: the old owner serves again, nothing was lost
            net.heal_all()
            assert await const.router.fetch_set(key) == ["pre"]
        finally:
            flight.configure(dir="")
            await const.stop()

    run(go())


# ------------------------------------------------------------ REST surface


def test_shards_health_metrics_routes():
    async def go():
        const, net = constellation(S=2, n_sentinent=0)
        server = DDSRestServer(const.router, ProxyConfig(port=0))
        await server.start()
        try:
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["x"]}).encode(), timeout=5.0)
            assert st == 200
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/shards", timeout=5.0)
            assert st == 200
            d = json.loads(body)
            assert d["state"] == "stable"
            # the served map is the SIGNED map: verifiable by an operator
            assert ShardMap.from_wire(d["map"]).verify(SECRET)
            assert set(d["groups"]) == {"s0", "s1"}
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/health", timeout=5.0)
            h = json.loads(body)
            assert st == 200 and h["status"] == "ok"
            assert set(h["shards"]) == {"s0", "s1"}
            assert h["shard_epoch"] == 1
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/metrics", timeout=5.0)
            text = body.decode()
            for fam in ("dds_shard_epoch", "dds_shard_groups",
                        "dds_shard_keys", "dds_shard_reshard_state"):
                assert fam in text, fam
        finally:
            await server.stop()
            await const.stop()

    run(go())


def test_launch_constellation_end_to_end():
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    async def go():
        cfg = DDSConfig()
        cfg.shard.enabled = True
        cfg.shard.count = 2
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        dep = await launch(cfg)
        try:
            st, key = await http_request(
                "127.0.0.1", dep.server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["a", "b"]}).encode(), timeout=5.0)
            assert st == 200
            st, body = await http_request(
                "127.0.0.1", dep.server.cfg.port, "GET",
                f"/GetSet/{key.decode()}", timeout=5.0)
            assert st == 200 and json.loads(body)["contents"] == ["a", "b"]
            # tcp + shard routes through Meridian, which refuses an
            # unknown fabric role without leaking the bound listener
            bad = DDSConfig()
            bad.shard.enabled = True
            bad.transport.kind = "tcp"
            bad.transport.port = 0
            bad.fabric.role = "bogus"
            with pytest.raises(ValueError):
                await launch(bad)
        finally:
            await dep.stop()

    asyncio.run(go())
