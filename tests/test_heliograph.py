"""Heliograph active-canary-plane tests (dds_tpu/obs/heliograph + clt/canary).

Three layers:

- deterministic unit surface on injected clock/rng/client: jittered
  cadence bounds, verdict classification, the typed ledger (counts,
  report, exemplar rotation under the cardinality discipline, the
  consecutive-unreachable region streak), the /health section semantics
  (disabled / ok / failing / stale, never blocking), and the feed
  fan-out — a wrong-answer probe files a `canary_wrong_answer`
  Watchtower incident carrying the exemplar trace id, sustained
  unreachable feeds Helmsman's region_down/promotion signal;
- the tenant boundary: `__heliograph__` passes the edge clamp (and ONLY
  it — other dunder names still 400), canary rows are invisible to
  user-facing aggregates/search in BOTH tenancy modes while the canary's
  own exact-value checks see exactly its population;
- the flagship drill on a real mini-stack: golden transactions all green
  end to end, then `seed_ciphertext_corruption` flips a stored Paillier
  ciphertext past the HMAC boundary — GetSet stays 200 (passive surfaces
  green) while the next decrypt-and-verify sum probe lands wrong_answer
  within one probe period, raising the Watchtower incident.
"""

import asyncio
import contextlib
import json
import random
import time

import pytest

from dds_tpu.clt.canary import (
    PROBE_KINDS,
    CanaryClient,
    CanaryTarget,
    ProbeCheck,
    parse_canary_targets,
)
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.tenant import CANARY_TENANT, TenantError, validate_tenant
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.fleet import Helmsman
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.models.facade import HomoProvider
from dds_tpu.obs.heliograph import (
    VERDICTS,
    CanaryLedger,
    Heliograph,
    ProbeResult,
    seed_ciphertext_corruption,
)
from dds_tpu.obs.metrics import Registry, metrics
from dds_tpu.obs.slo import SloEngine
from dds_tpu.obs.watchtower import Watchtower
from dds_tpu.utils.config import HeliographConfig, TenancyConfig
from tests.test_core import run

pytestmark = pytest.mark.canary

BITS = 256  # tiny Paillier primes: pipe semantics, not crypto strength


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class ScriptClient:
    """Scriptable stand-in for CanaryClient: `script[kind]` is a
    ProbeCheck to return or an exception to raise; `tick` optionally
    advances an injected clock inside the probe (drives `slow`)."""

    population = 2

    def __init__(self, clock=None):
        self.script: dict = {}
        self.clock = clock
        self.tick = 0.0
        self._n = 0

    def mint_trace(self) -> str:
        self._n += 1
        return f"trace-{self._n:04d}"

    async def populate(self, target, trace_id=None):
        return None

    async def probe(self, kind, target, trace_id, cycle=0):
        if self.clock is not None and self.tick:
            self.clock.advance(self.tick)
        action = self.script.get(kind, ProbeCheck(True, 200))
        if isinstance(action, Exception):
            raise action
        return action


class SloRecorder:
    def __init__(self):
        self.seen = []

    def observe(self, route, status, dur_s, tenant=None):
        self.seen.append((route, status))


def _cfg(**kw) -> HeliographConfig:
    kw.setdefault("enabled", True)
    kw.setdefault("cadence", 5.0)
    kw.setdefault("jitter", 0.5)
    kw.setdefault("deadline", 2.0)
    kw.setdefault("slow_ms", 250.0)
    return HeliographConfig(**kw)


def _helio(clock=None, client=None, seed=7, **kw):
    clock = clock or FakeClock()
    client = client if client is not None else ScriptClient(clock)
    slo = kw.pop("slo", SloRecorder())
    wt = kw.pop("watchtower", Watchtower())
    h = Heliograph(
        _cfg(**kw), [CanaryTarget("127.0.0.1", 1, region="east")],
        slo=slo, watchtower=wt, clock=clock,
        rng=random.Random(seed), client=client,
    )
    return h, clock, client, slo, wt


# --------------------------------------------------- edge clamp + targets


def test_canary_tenant_passes_the_edge_clamp_and_only_it():
    assert validate_tenant(CANARY_TENANT) == CANARY_TENANT
    for impostor in ("__heliograph", "_heliograph__", "__canary__", "__x__"):
        with pytest.raises(TenantError):
            validate_tenant(impostor)


def test_parse_canary_targets_regions_and_malformed():
    targets, bad = parse_canary_targets(
        ["10.0.0.1:9000", "west=10.0.0.2:9001", "nope", "x:notaport"]
    )
    assert [(t.host, t.port, t.region) for t in targets] == [
        ("10.0.0.1", 9000, ""), ("10.0.0.2", 9001, "west"),
    ]
    assert bad == ["nope", "x:notaport"]
    assert targets[1].label == "10.0.0.2:9001"


# ------------------------------------------------------ cadence + verdicts


def test_next_delay_jitter_bounds_and_determinism():
    h, *_ = _helio(cadence=5.0, jitter=0.5, seed=42)
    delays = [h.next_delay() for _ in range(200)]
    assert all(2.5 <= d <= 7.5 for d in delays)
    assert len({round(d, 6) for d in delays}) > 50  # actually jittered
    h2, *_ = _helio(cadence=5.0, jitter=0.5, seed=42)
    assert [h2.next_delay() for _ in range(200)] == delays  # seeded = replay
    h3, *_ = _helio(cadence=0.0, jitter=1.0)
    assert h3.next_delay() >= 0.05  # floor: a zero cadence must not spin


def test_classify_covers_the_verdict_lattice():
    h, *_ = _helio(slow_ms=250.0)
    assert h.classify(True, 200, 0.010) == "ok"
    assert h.classify(True, 200, 0.500) == "slow"
    assert h.classify(False, 200, 0.010) == "wrong_answer"
    assert h.classify(False, 503, 0.010) == "unreachable"
    assert h.classify(False, 0, 2.000) == "unreachable"  # no HTTP at all


# ---------------------------------------------------------------- ledger


def _result(kind="sum", verdict="ok", trace="t-1", region="", **kw):
    return ProbeResult(kind, verdict, 0.01, trace, region=region, **kw)


def test_ledger_report_counts_and_exemplars():
    clk = FakeClock()
    led = CanaryLedger(clock=clk, registry=Registry())
    led.record(_result("sum", "ok", "t-1"))
    clk.advance(5)
    led.record(_result("sum", "wrong_answer", "t-2"))
    clk.advance(5)
    led.record(_result("putget", "ok", "t-3"))
    rep = led.report()
    assert rep["probes_recorded"] == 3
    assert rep["counts"] == {"putget.ok": 1, "sum.ok": 1,
                             "sum.wrong_answer": 1}
    assert rep["kinds"]["sum"]["verdict"] == "wrong_answer"
    assert rep["kinds"]["sum"]["last_failure"]["trace_id"] == "t-2"
    assert rep["kinds"]["sum"]["last_ok_age_s"] == 10.0
    assert led.last_age() == 0.0


def test_ledger_exemplar_rotation_never_accretes_series():
    led = CanaryLedger(registry=Registry())
    reg = Registry()
    led.record(_result("sum", "wrong_answer", "t-old"))
    led.export_gauges(reg)
    assert reg.value("dds_canary_exemplar", kind="sum", trace_id="t-old",
                     verdict="wrong_answer") is not None
    led.record(_result("sum", "wrong_answer", "t-new"))
    led.export_gauges(reg)
    # the rotated trace id replaced the old series instead of joining it
    assert reg.value("dds_canary_exemplar", kind="sum", trace_id="t-old",
                     verdict="wrong_answer") is None
    assert reg.value("dds_canary_exemplar", kind="sum", trace_id="t-new",
                     verdict="wrong_answer") is not None
    assert reg.value("dds_canary_verdict", kind="sum") == float(
        VERDICTS.index("wrong_answer"))


def test_ledger_region_streak_resets_and_ignores_anonymous():
    led = CanaryLedger(registry=Registry(), unreachable_streak=3)
    for _ in range(2):
        led.record(_result(verdict="unreachable", region="west"))
    assert led.unreachable_regions() == set()      # streak not reached
    led.record(_result(verdict="ok", region="west"))
    for _ in range(2):
        led.record(_result(verdict="unreachable", region="west"))
    assert led.unreachable_regions() == set()      # success reset the count
    led.record(_result(verdict="unreachable", region="west"))
    assert led.unreachable_regions() == {"west"}
    for _ in range(5):
        led.record(_result(verdict="unreachable", region=""))
    assert led.unreachable_regions() == {"west"}   # "" never feeds Helmsman


def test_health_section_semantics():
    clk = FakeClock()
    led = CanaryLedger(clock=clk, registry=Registry())
    assert led.health_section(False, 15.0) == {"status": "disabled"}
    assert led.health_section(True, 15.0)["status"] == "stale"  # never probed
    led.record(_result("sum", "ok"))
    assert led.health_section(True, 15.0)["status"] == "ok"
    led.record(_result("putget", "wrong_answer"))
    sec = led.health_section(True, 15.0)
    assert sec["status"] == "failing"
    assert sec["kinds"]["putget"]["verdict"] == "wrong_answer"
    clk.advance(60)
    assert led.health_section(True, 15.0)["status"] == "stale"


# ------------------------------------------------------------- the prober


def test_probe_once_feeds_slo_and_watchtower_with_exemplar_trace():
    async def go():
        h, clock, client, slo, wt = _helio()
        target = h.targets[0]
        ok = await h.probe_once("sum", target)
        assert ok.verdict == "ok"
        client.script["sum"] = ProbeCheck(
            False, 200, {"expected": 46, "observed": 47})
        bad = await h.probe_once("sum", target)
        assert bad.verdict == "wrong_answer"
        # the SLO engine saw both, as the synthetic canary route-class
        assert slo.seen == [("canary.sum", 200), ("canary.sum", 500)]
        # the Watchtower incident carries the SAME exemplar trace id the
        # ledger reports, and the decrypt-and-verify evidence
        v, = [x for x in wt.verdicts() if x.invariant == "canary_wrong_answer"]
        assert v.trace_id == bad.trace_id
        assert v.detail["observed"] == "47"
        assert h.ledger.report()["kinds"]["sum"]["trace_id"] == bad.trace_id

    run(go())


def test_probe_once_maps_failure_modes_to_verdicts():
    async def go():
        h, clock, client, slo, wt = _helio(deadline=0.05)
        target = h.targets[0]
        client.script["sum"] = ConnectionRefusedError("edge down")
        assert (await h.probe_once("sum", target)).verdict == "unreachable"
        client.script["sum"] = ValueError("garbled body")
        assert (await h.probe_once("sum", target)).verdict == "wrong_answer"
        client.script["mult"] = ProbeCheck(True, 200)
        client.tick = 0.5  # latency past slow_ms, still correct
        assert (await h.probe_once("mult", target)).verdict == "slow"

    run(go())


def test_run_cycle_populate_failure_is_an_unreachable_verdict():
    async def go():
        h, clock, client, *_ = _helio()

        async def broken_populate(target, trace_id=None):
            raise ConnectionRefusedError("no edge")

        client.populate = broken_populate
        await h.run_cycle(h.targets[0])
        last = h.ledger.last("putget")
        assert last.verdict == "unreachable"
        assert last.detail["phase"] == "populate"

    run(go())


def test_unreachable_streak_feeds_helmsman_promotion():
    async def go():
        h, clock, client, *_ = _helio(unreachable_streak=3)
        client.script["sum"] = ConnectionRefusedError("region dark")
        for _ in range(3):
            await h.probe_once("sum", h.targets[0])
        assert h.unreachable_regions() == {"east"}

        promoted = []

        async def promote(gid):
            promoted.append(gid)

        hm = Helmsman(
            load_census=lambda: {"g-east": 10, "g-west": 10},
            promote=promote,
            regions=lambda: {"g-east": "east", "g-west": "west"},
            canary_unreachable=h.unreachable_regions,
            clock=clock,
        )
        assert await hm.step() == "promote"
        assert promoted == ["g-east"]          # only the dark region's group
        assert "east" in hm._regions_down      # region_down declared
        # recovery clears the signal and the declaration
        client.script["sum"] = ProbeCheck(True, 200)
        await h.probe_once("sum", h.targets[0])
        assert h.unreachable_regions() == set()
        clock.advance(1000)
        assert await hm.step() is None
        assert "east" not in hm._regions_down

    run(go())


# ------------------------------------------------------------ fleet rollup


def test_fleet_canary_rolls_up_worst_verdict_and_exemplars():
    from dds_tpu.obs.panopticon import FleetCollector
    from tests.test_panopticon import LoopNet

    led_a = CanaryLedger(registry=Registry())
    led_a.record(_result("sum", "ok", "t-a"))
    rega = Registry()
    led_a.export_gauges(rega)
    led_b = CanaryLedger(registry=Registry())
    led_b.record(_result("sum", "wrong_answer", "t-b", region="west"))
    regb = Registry()
    led_b.export_gauges(regb)

    net = LoopNet()
    col = FleetCollector(net, secret=b"s", host="proxy-1",
                         watchtower=Watchtower(), registry=Registry())
    now = time.monotonic()
    for host, reg, region in (("host-a", rega, "east"),
                              ("host-b", regb, "west")):
        col._sources[host] = {
            "mono": now, "role": "group", "shard": f"g-{host[-1]}",
            "region": region, "metrics_text": reg.render(), "slo": {},
            "dropped": 0,
        }
    body = col.fleet_canary()
    assert body["fleet"]["kinds"]["sum"]["worst"] == "wrong_answer"
    assert body["fleet"]["kinds"]["sum"]["hosts"] == 2
    f, = body["fleet"]["failures"]
    assert (f["host"], f["trace_id"], f["verdict"]) == (
        "host-b", "t-b", "wrong_answer")
    assert body["hosts"]["host-a"]["kinds"]["sum"]["verdict"] == "ok"


# ----------------------------------------------- the real-stack mini fleet


@contextlib.asynccontextmanager
async def canary_stack(tenancy=False, **proxy_kw):
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig

    net = InMemoryNet()
    rcfg = ReplicaConfig(quorum_size=3)
    addrs = [f"replica-{i}" for i in range(4)]
    replicas = {a: BFTABDNode(a, addrs, "supervisor", net, rcfg)
                for a in addrs}
    abd = AbdClient("proxy-0", net, addrs,
                    AbdClientConfig(request_timeout=2.0, quorum_size=3))
    if tenancy:
        proxy_kw.setdefault("tenancy", TenancyConfig(enabled=True))
    server = DDSRestServer(
        abd, ProxyConfig(host="127.0.0.1", port=0, **proxy_kw),
        slo=SloEngine(),
    )
    await server.start()
    try:
        yield server, replicas
    finally:
        await server.stop()


def _provider() -> HomoProvider:
    # Paillier/RSA/OPE are pure Python; the AES-backed CHE columns are
    # optional here because CanaryClient degrades them to the "None"
    # scheme when the cryptography package is absent — so the golden
    # path stays testable in AES-less environments
    return HomoProvider.generate(BITS, 512)


async def _req(server, method, target, body=None, tenant=None, trace=None):
    headers = {}
    if tenant:
        headers["x-dds-tenant"] = tenant
    if trace:
        headers["x-dds-trace"] = trace
    return await http_request(
        "127.0.0.1", server.cfg.port, method, target, body,
        headers=headers or None, timeout=10.0,
    )


def test_golden_transactions_all_green_and_canary_scoped():
    async def go():
        async with canary_stack() as (server, _):
            provider = _provider()
            client = CanaryClient(provider, population=2)
            target = CanaryTarget("127.0.0.1", server.cfg.port)
            await client.populate(target, client.mint_trace())
            assert len(client.keys) == 2

            # a user stores rows through the SAME edge, untenanted
            user_rows = [[500, "user-0", 1000, 5, "a", "b", "c", "blob-0"],
                         [501, "user-1", 2000, 7, "a", "b", "c", "blob-1"]]
            for row in user_rows:
                enc = provider.encrypt_row(row, 8, client.schema)
                status, _body = await _req(
                    server, "POST", "/PutSet",
                    json.dumps({"contents": enc}).encode())
                assert status == 200

            # every probe kind verifies against the canary population
            # ALONE — user rows in the same store must not leak in
            for kind in PROBE_KINDS:
                check = await client.probe(
                    kind, target, client.mint_trace(), cycle=0)
                assert check.correct, (kind, check.detail)

            # and the user's aggregate excludes the canary population
            nsqr = provider.keys.psse.public.nsquare
            status, body = await _req(
                server, "GET", f"/SumAll?position=2&nsqr={nsqr}")
            assert status == 200
            observed = provider.decrypt(
                json.loads(body.decode())["result"], "PSSE")
            assert observed == 3000  # user rows only, no canary 10+11

            # user search for a canary CHE value sees nothing (same
            # deterministic scheme the canary stored under, so the
            # ciphertexts match byte-for-byte — only scoping hides them)
            enc = provider.encrypt("canary-0", client.schema[1])
            status, body = await _req(
                server, "POST", "/SearchEq?position=1",
                json.dumps({"value": enc}).encode())
            assert status == 200
            assert json.loads(body.decode())["keyset"] == []

    run(go())


def test_canary_invisible_under_tenancy_and_unattributed():
    async def go():
        async with canary_stack(tenancy=True) as (server, _):
            provider = _provider()
            client = CanaryClient(provider, population=2)
            target = CanaryTarget("127.0.0.1", server.cfg.port)
            await client.populate(target, client.mint_trace())

            row = [7, "acme-row", 300, 3, "a", "b", "c", "acme-blob"]
            enc = provider.encrypt_row(row, 8, client.schema)
            status, _body = await _req(
                server, "POST", "/PutSet",
                json.dumps({"contents": enc}).encode(), tenant="acme")
            assert status == 200

            # the tenant's aggregate is exactly its own row
            nsqr = provider.keys.psse.public.nsquare
            status, body = await _req(
                server, "GET", f"/SumAll?position=2&nsqr={nsqr}",
                tenant="acme")
            assert status == 200
            assert provider.decrypt(
                json.loads(body.decode())["result"], "PSSE") == 300
            # ... and the canary's is exactly its population
            check = await client.probe("sum", target, client.mint_trace())
            assert check.correct, check.detail

            # per-tenant analytics attribution never sees the canary
            server._sample_state_gauges()
            assert metrics.value("dds_tenant_stored_keys",
                                 tenant="acme") == 1
            assert metrics.value("dds_tenant_stored_keys",
                                 tenant=CANARY_TENANT) is None
            # ... nor does per-tenant SLO burn attribution
            assert CANARY_TENANT not in server.slo.tenant_burns()
            # the dropped-series registry gauge is exported first-class
            assert metrics.value("dds_metrics_dropped_series") is not None

    run(go())


def test_health_carries_canary_section_and_stays_fast_when_stopped():
    async def go():
        async with canary_stack() as (server, _):
            # no prober wired: the section degrades to disabled
            status, body = await _req(server, "GET", "/health")
            assert status == 200
            assert json.loads(body.decode())["canary"] == {
                "status": "disabled"}

            # prober wired but STOPPED: /health must answer from memory,
            # never await the prober, and stay fast
            h, *_ = _helio()
            server.heliograph = h
            t0 = time.perf_counter()
            status, body = await _req(server, "GET", "/health")
            elapsed = time.perf_counter() - t0
            assert status == 200
            assert json.loads(body.decode())["canary"]["status"] == "disabled"
            assert elapsed < 0.010, f"/health took {elapsed * 1e3:.1f}ms"

            # GET /canary reports disabled without a prober elsewhere
            server.heliograph = None
            status, body = await _req(server, "GET", "/canary")
            assert status == 200
            assert json.loads(body.decode()) == {"enabled": False}

    run(go())


def test_canary_admission_carveout_is_rate_bounded():
    async def go():
        async with canary_stack() as (server, _):
            # freeze refill: the bucket's remaining tokens are the whole
            # budget, the bound a canary-tenant squatter can never exceed
            server._canary_bucket.rate = 0.0
            server._canary_bucket._tokens = 2.0
            before = metrics.value("dds_canary_throttled_total",
                                   route="GetSet") or 0
            statuses = []
            for _ in range(6):
                status, body = await _req(server, "GET", "/GetSet/nokey",
                                          tenant=CANARY_TENANT)
                statuses.append(status)
            assert statuses.count(429) == 4
            assert (metrics.value("dds_canary_throttled_total",
                                  route="GetSet") or 0) == before + 4
            # exempt routes (health) stay reachable for the canary tenant
            status, _body = await _req(server, "GET", "/health",
                                       tenant=CANARY_TENANT)
            assert status == 200

    run(go())


# --------------------------------------------------------------- the drill


def test_seeded_corruption_detected_by_decrypt_and_verify():
    async def go():
        async with canary_stack() as (server, replicas):
            provider = _provider()
            client = CanaryClient(provider, population=2)
            target = CanaryTarget("127.0.0.1", server.cfg.port)
            wt = Watchtower()
            h = Heliograph(_cfg(), [target], watchtower=wt, client=client)
            await client.populate(target, client.mint_trace())

            green = await h.probe_once("sum", target)
            assert green.verdict == "ok"

            # the seeded fault: flip one stored Paillier ciphertext on
            # every replica, PAST the transport-HMAC boundary
            assert seed_ciphertext_corruption(
                replicas, client.keys[0], position=2) == len(replicas)

            # passive surfaces stay green: the quorum read still serves
            # 200 over the (valid-MAC, wrong) ciphertext
            status, _body = await _req(
                server, "GET", f"/GetSet/{client.keys[0]}")
            assert status == 200

            # ... but the very next decrypt-and-verify probe catches it
            red = await h.probe_once("sum", target)
            assert red.verdict == "wrong_answer"
            assert int(red.detail["observed"]) != int(red.detail["expected"])
            v, = [x for x in wt.verdicts()
                  if x.invariant == "canary_wrong_answer"]
            assert v.trace_id == red.trace_id
            assert h.ledger.report()["kinds"]["sum"]["last_failure"][
                "trace_id"] == red.trace_id

    run(go())
