"""BFT-ABD protocol tests over the in-memory transport.

The property layer the reference never had (SURVEY.md §4): quorum
read/write semantics, replay/signature rejection, Byzantine tolerance up to
f=2 with n=7/q=5, and the supervisor's swap/recovery choreography.
"""

import asyncio
import random

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.errors import ByzantineError
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.utils import sigs


class Cluster:
    """In-process cluster: n replicas (+spares), a supervisor, one client."""

    def __init__(self, n_active=7, n_sentinent=2, quorum=5, proactive=False):
        self.net = InMemoryNet()
        self.rcfg = ReplicaConfig(quorum_size=quorum)
        all_addrs = [f"replica-{i}" for i in range(n_active + n_sentinent)]
        self.active = all_addrs[:n_active]
        self.sentinent = all_addrs[n_active:]
        self.replicas = {
            a: BFTABDNode(a, all_addrs, "supervisor", self.net, self.rcfg)
            for a in all_addrs
        }
        for a in self.sentinent:
            self.replicas[a].behavior = "sentinent"
        self.supervisor = BFTSupervisor(
            "supervisor",
            self.active,
            self.sentinent,
            self.net,
            SupervisorConfig(
                quorum_size=quorum,
                proactive_recovery_enabled=proactive,
                proactive_recovery_warmup=0.05,
                proactive_recovery_interval=0.1,
                sentinent_awake_timeout=0.5,
            ),
            redeploy=self._redeploy,
            rng=random.Random(3),
        )
        self.client = AbdClient(
            "proxy-0",
            self.net,
            self.active,
            AbdClientConfig(request_timeout=1.0),
        )
        self.client.replicas._rng = random.Random(7)

    async def _redeploy(self, endpoint):
        self.replicas[endpoint] = BFTABDNode(
            endpoint, list(self.replicas), "supervisor", self.net, self.rcfg
        )


def run(coro):
    return asyncio.run(coro)


def test_write_then_read_roundtrip():
    async def go():
        c = Cluster()
        value = [41, "enc-blob", "123456789", None]
        key = sigs.key_from_set(value)
        assert await c.client.write_set(key, value) == key
        assert await c.client.fetch_set(key) == value
        await c.net.quiesce()
        # at least a quorum of replicas hold the value
        holders = [
            r for r in c.replicas.values()
            if r.repository.get(key, (None, None))[1] == value
        ]
        assert len(holders) >= 5

    run(go())


def test_read_missing_key_returns_none():
    async def go():
        c = Cluster()
        assert await c.client.fetch_set("DEADBEEF") is None

    run(go())


def test_remove_via_write_none():
    async def go():
        c = Cluster()
        key = "K1"
        await c.client.write_set(key, [1, 2, 3])
        await c.client.write_set(key, None)
        assert await c.client.fetch_set(key) is None

    run(go())


def test_sequential_writes_last_wins():
    async def go():
        c = Cluster()
        key = "K2"
        for i in range(5):
            await c.client.write_set(key, [i])
        assert await c.client.fetch_set(key) == [4]

    run(go())


def test_byzantine_minority_tolerated():
    async def go():
        c = Cluster()
        # compromise f=2 replicas (not the ones the seeded client rng picks)
        victims = ["replica-5", "replica-6"]
        for v in victims:
            c.net.send("trudy", v, M.Compromise())
        await c.net.quiesce()
        c.client.replicas.reset([a for a in c.active if a not in victims])
        value = [7, "x"]
        key = sigs.key_from_set(value)
        await c.client.write_set(key, value)
        assert await c.client.fetch_set(key) == value

    run(go())


def test_byzantine_coordinator_detected():
    async def go():
        c = Cluster()
        c.client.replicas.reset(["replica-0"])  # force coordinator choice
        c.net.send("trudy", "replica-0", M.Compromise())
        await c.net.quiesce()
        with pytest.raises((ByzantineError, asyncio.TimeoutError)):
            await c.client.fetch_set("ANYKEY")
        assert c.client.replicas._strikes["replica-0"] >= 1

    run(go())


def test_replayed_proxy_nonce_ignored():
    async def go():
        c = Cluster()
        key = "K3"
        nonce = sigs.generate_nonce()
        sig = sigs.proxy_signature(c.rcfg.proxy_mac_secret, key, nonce, [1])
        env = M.Envelope(M.IWrite(key, [1]), nonce, sig)
        c.net.send("proxy-0", "replica-0", env)
        await c.net.quiesce()
        before = c.replicas["replica-1"].repository.get(key)
        # replay the same nonce with different contents
        sig2 = sigs.proxy_signature(c.rcfg.proxy_mac_secret, key, nonce, [2])
        c.net.send("proxy-0", "replica-0", M.Envelope(M.IWrite(key, [2]), nonce, sig2))
        await c.net.quiesce()
        after = c.replicas["replica-1"].repository.get(key)
        assert before == after  # second write never executed

    run(go())


def test_bad_proxy_signature_rejected():
    async def go():
        c = Cluster()
        nonce = sigs.generate_nonce()
        env = M.Envelope(M.IWrite("K4", [1]), nonce, b"forged")
        c.net.send("proxy-0", "replica-0", env)
        await c.net.quiesce()
        assert all("K4" not in r.repository for r in c.replicas.values())

    run(go())


def test_suspicion_quorum_triggers_recovery():
    async def go():
        c = Cluster()
        # 5 distinct replicas vote against replica-6
        for i in range(5):
            c.net.send(
                f"replica-{i}", "supervisor", M.Suspect("replica-6", sigs.generate_nonce())
            )
        await c.net.quiesce()
        await asyncio.sleep(0.1)
        await c.net.quiesce()
        # replica-6 was demoted to sentinent; one spare was promoted
        assert "replica-6" in c.supervisor.sentinent
        active_names = [a for a, _ in c.supervisor.active]
        assert "replica-6" not in active_names
        assert len(active_names) == 7
        assert c.replicas["replica-6"].behavior == "sentinent"

    run(go())


def test_recovery_preserves_data():
    async def go():
        c = Cluster()
        value = [9, "persist"]
        key = sigs.key_from_set(value)
        await c.client.write_set(key, value)
        await c.net.quiesce()
        # recover replica-0 explicitly (as the proactive timer would)
        await c.supervisor.recover("replica-0")
        await c.net.quiesce()
        # the promoted spare holds the data (it observed quorum writes while
        # sentinent) and the demoted node was reseeded with it
        assert c.replicas["replica-0"].repository.get(key, (None, None))[1] == value
        assert await c.client.fetch_set(key) == value

    run(go())


def test_proactive_recovery_loop():
    async def go():
        c = Cluster(proactive=True)
        c.supervisor.start()
        await asyncio.sleep(0.4)
        await c.supervisor.stop()
        await c.net.quiesce()
        # at least one swap happened; membership sizes preserved
        assert len(c.supervisor.active) == 7
        assert len(c.supervisor.sentinent) == 2

    run(go())


def test_request_replicas_returns_freshest_half():
    async def go():
        c = Cluster()
        got = []

        async def catcher(sender, msg):
            got.append(msg)

        c.net.register("observer", catcher)
        c.net.send("observer", "supervisor", M.RequestReplicas())
        await c.net.quiesce()
        assert isinstance(got[0], M.ActiveReplicas)
        assert len(got[0].replicas) == 3  # newest half of 7

    run(go())


def test_message_serialization_roundtrip():
    msgs = [
        M.Envelope(M.IWrite("K", [1, "a", None]), 42, b"\x01\x02"),
        M.TagReply(M.ABDTag(3, "replica-1"), "K", None, b"sig", 9),
        M.Sleep({"K": {"tag": [1, "r"], "value": [1]}}, [4, 5]),
        M.ActiveReplicas(["a", "b"]),
        M.Compromise(),
    ]
    for m in msgs:
        assert M.loads(M.dumps(m)) == m


def test_tcp_transport_roundtrip():
    async def go():
        from dds_tpu.core.transport import TcpNet

        net = TcpNet("127.0.0.1", 39471)
        await net.start()
        got = asyncio.get_event_loop().create_future()

        async def handler(sender, msg):
            got.set_result((sender, msg))

        net.register("127.0.0.1:39471/alice", handler)
        net.send("bob", "127.0.0.1:39471/alice", M.ReadTag("K", 77))
        sender, msg = await asyncio.wait_for(got, 3)
        assert msg == M.ReadTag("K", 77)
        await net.stop()

    run(go())


def test_tcp_frame_mac_rejects_spoofed_frames():
    async def go():
        import json as _json

        from dds_tpu.core.transport import TcpNet

        net = TcpNet("127.0.0.1", 0 or 39474, frame_secret=b"cluster-secret")
        await net.start()
        got = []

        async def handler(sender, msg):
            got.append((sender, msg))

        net.register("127.0.0.1:39474/sup", handler)
        # legitimate frame (signed by the transport itself)
        net.send("replica-0", "127.0.0.1:39474/sup", M.ReadTag("K", 1))
        await asyncio.sleep(0.2)
        # forged frame: attacker with socket access but no frame secret
        r, w = await asyncio.open_connection("127.0.0.1", 39474)
        frame = _json.dumps(
            {"src": "replica-1", "dest": "127.0.0.1:39474/sup",
             "msg": M.to_dict(M.Suspect("replica-6", 99))}
        ).encode()
        w.write(len(frame).to_bytes(4, "big") + frame)
        await w.drain()
        await asyncio.sleep(0.2)
        w.close()
        await net.stop()
        assert [type(m).__name__ for _, m in got] == ["ReadTag"]  # spoof dropped

    run(go())


def test_tcp_intranet_mutual_tls_rejects_certless_peer(tmp_path):
    """The replica fabric under mutual TLS (`dds-system.conf:18-58`): a
    certified peer's frames arrive; a peer that completes TCP but presents
    no client certificate fails the handshake and delivers nothing."""

    async def go():
        import ssl as _ssl

        from dds_tpu.core.transport import TcpNet
        from dds_tpu.utils import tlsutil

        paths = tlsutil.generate_ca_and_cert(tmp_path, hosts=("127.0.0.1",))
        ca, cert, key = paths["ca"], paths["cert"], paths["key"]
        server_ctx = tlsutil.server_context(cert, key, ca)
        client_ctx = tlsutil.client_context(ca, cert, key)

        net = TcpNet("127.0.0.1", 39481, ssl_server=server_ctx, ssl_client=client_ctx)
        await net.start()
        got = []

        async def handler(sender, msg):
            got.append((sender, msg))

        net.register("127.0.0.1:39481/sup", handler)
        net.send("replica-0", "127.0.0.1:39481/sup", M.ReadTag("K", 1))
        await asyncio.sleep(0.3)
        assert [type(m).__name__ for _, m in got] == ["ReadTag"]

        # unauthenticated peer: trusts the CA but presents no client cert
        certless = tlsutil.client_context(ca)
        try:
            _, w = await asyncio.open_connection(
                "127.0.0.1", 39481, ssl=certless, server_hostname="localhost"
            )
            frame = b'{"src":"replica-1","dest":"127.0.0.1:39481/sup","msg":{}}'
            w.write(len(frame).to_bytes(4, "big") + frame)
            await w.drain()
            await asyncio.sleep(0.3)
            w.close()
        except (_ssl.SSLError, ConnectionResetError):
            pass  # handshake refusal is the expected outcome
        assert len(got) == 1  # nothing further was delivered
        await net.stop()

    run(go())


def test_launch_tcp_with_intranet_tls_end_to_end(tmp_path):
    """launch() with transport=tcp + intranet mutual TLS: the full quorum
    path (PutSet-style write then read) works over the TLS replica fabric."""

    async def go():
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig

        cfg = DDSConfig()
        cfg.transport.kind = "tcp"
        cfg.transport.port = 39491
        cfg.security.intranet_tls_enabled = True
        cfg.security.tls_dir = str(tmp_path)
        cfg.proxy.port = 0
        dep = await launch(cfg)
        try:
            assert dep.net._ssl_server is not None  # contexts actually wired
            prefix = f"127.0.0.1:39491/"
            abd = dep.server.abd
            k, tag = await abd.write_set_tagged("tls-key", [41, 42])
            assert k == "tls-key" and tag is not None
            value, rtag = await abd.fetch_set_tagged("tls-key")
            assert value == [41, 42] and rtag == tag
            tags = await abd.read_tags(["tls-key"])
            assert tags == [rtag]
        finally:
            await dep.stop()

    run(go())


def test_concurrent_suspects_single_recovery():
    async def go():
        c = Cluster()
        # flood: every replica votes many times against replica-6
        for round_ in range(3):
            for i in range(7):
                c.net.send(
                    f"replica-{i}", "supervisor",
                    M.Suspect("replica-6", sigs.generate_nonce()),
                )
        await c.net.quiesce()
        await asyncio.sleep(0.2)
        await c.net.quiesce()
        # exactly one swap: sizes intact, no duplicate active entries
        names = [a for a, _ in c.supervisor.active]
        assert len(names) == len(set(names)) == 7
        assert len(c.supervisor.sentinent) == 2
        # non-active endpoints are not recoverable
        await c.supervisor.recover("proxy-0")
        assert len(c.supervisor.active) == 7

    run(go())
