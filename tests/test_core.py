"""BFT-ABD protocol tests over the in-memory transport.

The property layer the reference never had (SURVEY.md §4): quorum
read/write semantics, replay/signature rejection, Byzantine tolerance up to
f=2 with n=7/q=5, and the supervisor's swap/recovery choreography.
"""

import asyncio
import random

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.errors import ByzantineError
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.utils import sigs


class Cluster:
    """In-process cluster: n replicas (+spares), a supervisor, one client.

    `net` lets chaos suites inject a fault fabric (e.g. a ChaosNet over
    the default InMemoryNet) without re-plumbing the topology."""

    def __init__(self, n_active=7, n_sentinent=2, quorum=5, proactive=False,
                 net=None):
        self.net = net or InMemoryNet()
        self.rcfg = ReplicaConfig(quorum_size=quorum)
        all_addrs = [f"replica-{i}" for i in range(n_active + n_sentinent)]
        self.active = all_addrs[:n_active]
        self.sentinent = all_addrs[n_active:]
        self.replicas = {
            a: BFTABDNode(a, all_addrs, "supervisor", self.net, self.rcfg)
            for a in all_addrs
        }
        for a in self.sentinent:
            self.replicas[a].behavior = "sentinent"
        self.supervisor = BFTSupervisor(
            "supervisor",
            self.active,
            self.sentinent,
            self.net,
            SupervisorConfig(
                quorum_size=quorum,
                proactive_recovery_enabled=proactive,
                proactive_recovery_warmup=0.05,
                proactive_recovery_interval=0.1,
                sentinent_awake_timeout=0.5,
                # bounded so a dead-host seed path (and the graceful
                # stop() that now awaits it) cannot pin a test for the
                # 12 s production default
                crashed_recovery_timeout=2.0,
            ),
            redeploy=self._redeploy,
            rng=random.Random(3),
        )
        self.client = AbdClient(
            "proxy-0",
            self.net,
            self.active,
            AbdClientConfig(request_timeout=1.0),
        )
        self.client.replicas._rng = random.Random(7)

    async def _redeploy(self, endpoint):
        self.replicas[endpoint] = BFTABDNode(
            endpoint, list(self.replicas), "supervisor", self.net, self.rcfg
        )


def run(coro):
    return asyncio.run(coro)


def test_write_then_read_roundtrip():
    async def go():
        c = Cluster()
        value = [41, "enc-blob", "123456789", None]
        key = sigs.key_from_set(value)
        assert await c.client.write_set(key, value) == key
        assert await c.client.fetch_set(key) == value
        await c.net.quiesce()
        # at least a quorum of replicas hold the value
        holders = [
            r for r in c.replicas.values()
            if r.repository.get(key, (None, None))[1] == value
        ]
        assert len(holders) >= 5

    run(go())


def test_read_missing_key_returns_none():
    async def go():
        c = Cluster()
        assert await c.client.fetch_set("DEADBEEF") is None

    run(go())


def test_remove_via_write_none():
    async def go():
        c = Cluster()
        key = "K1"
        await c.client.write_set(key, [1, 2, 3])
        await c.client.write_set(key, None)
        assert await c.client.fetch_set(key) is None

    run(go())


def test_sequential_writes_last_wins():
    async def go():
        c = Cluster()
        key = "K2"
        for i in range(5):
            await c.client.write_set(key, [i])
        assert await c.client.fetch_set(key) == [4]

    run(go())


def test_byzantine_minority_tolerated():
    async def go():
        c = Cluster()
        # compromise f=2 replicas (not the ones the seeded client rng picks)
        victims = ["replica-5", "replica-6"]
        for v in victims:
            c.net.send("trudy", v, M.Compromise())
        await c.net.quiesce()
        c.client.replicas.reset([a for a in c.active if a not in victims])
        value = [7, "x"]
        key = sigs.key_from_set(value)
        await c.client.write_set(key, value)
        assert await c.client.fetch_set(key) == value

    run(go())


def test_byzantine_coordinator_detected():
    async def go():
        c = Cluster()
        c.client.replicas.reset(["replica-0"])  # force coordinator choice
        c.net.send("trudy", "replica-0", M.Compromise())
        await c.net.quiesce()
        with pytest.raises((ByzantineError, asyncio.TimeoutError)):
            await c.client.fetch_set("ANYKEY")
        assert c.client.replicas._strikes["replica-0"] >= 1

    run(go())


def test_replayed_proxy_nonce_ignored():
    async def go():
        c = Cluster()
        key = "K3"
        nonce = sigs.generate_nonce()
        sig = sigs.proxy_signature(c.rcfg.proxy_mac_secret, key, nonce, [1])
        env = M.Envelope(M.IWrite(key, [1]), nonce, sig)
        c.net.send("proxy-0", "replica-0", env)
        await c.net.quiesce()
        before = c.replicas["replica-1"].repository.get(key)
        # replay the same nonce with different contents
        sig2 = sigs.proxy_signature(c.rcfg.proxy_mac_secret, key, nonce, [2])
        c.net.send("proxy-0", "replica-0", M.Envelope(M.IWrite(key, [2]), nonce, sig2))
        await c.net.quiesce()
        after = c.replicas["replica-1"].repository.get(key)
        assert before == after  # second write never executed

    run(go())


def test_bad_proxy_signature_rejected():
    async def go():
        c = Cluster()
        nonce = sigs.generate_nonce()
        env = M.Envelope(M.IWrite("K4", [1]), nonce, b"forged")
        c.net.send("proxy-0", "replica-0", env)
        await c.net.quiesce()
        assert all("K4" not in r.repository for r in c.replicas.values())

    run(go())


def test_suspicion_quorum_triggers_recovery():
    async def go():
        c = Cluster()
        # 5 distinct replicas vote against replica-6
        for i in range(5):
            c.net.send(
                f"replica-{i}", "supervisor", M.Suspect("replica-6", sigs.generate_nonce())
            )
        await c.net.quiesce()
        await asyncio.sleep(0.1)
        await c.net.quiesce()
        # replica-6 was demoted to sentinent; one spare was promoted
        assert "replica-6" in c.supervisor.sentinent
        active_names = [a for a, _ in c.supervisor.active]
        assert "replica-6" not in active_names
        assert len(active_names) == 7
        assert c.replicas["replica-6"].behavior == "sentinent"

    run(go())


def test_recovery_preserves_data():
    async def go():
        c = Cluster()
        value = [9, "persist"]
        key = sigs.key_from_set(value)
        await c.client.write_set(key, value)
        await c.net.quiesce()
        # recover replica-0 explicitly (as the proactive timer would)
        await c.supervisor.recover("replica-0")
        await c.net.quiesce()
        # the promoted spare holds the data (it observed quorum writes while
        # sentinent) and the demoted node was reseeded with it
        assert c.replicas["replica-0"].repository.get(key, (None, None))[1] == value
        assert await c.client.fetch_set(key) == value

    run(go())


def test_proactive_recovery_loop():
    async def go():
        c = Cluster(proactive=True)
        c.supervisor.start()
        await asyncio.sleep(0.4)
        await c.supervisor.stop()
        await c.net.quiesce()
        # at least one swap happened; membership sizes preserved
        assert len(c.supervisor.active) == 7
        assert len(c.supervisor.sentinent) == 2

    run(go())


def test_request_replicas_returns_freshest_half():
    async def go():
        c = Cluster()
        got = []

        async def catcher(sender, msg):
            got.append(msg)

        c.net.register("observer", catcher)
        c.net.send("observer", "supervisor", M.RequestReplicas())
        await c.net.quiesce()
        assert isinstance(got[0], M.ActiveReplicas)
        assert len(got[0].replicas) == 3  # newest half of 7

    run(go())


def test_message_serialization_roundtrip():
    msgs = [
        M.Envelope(M.IWrite("K", [1, "a", None]), 42, b"\x01\x02"),
        M.TagReply(M.ABDTag(3, "replica-1"), "K", None, b"sig", 9),
        M.Sleep({"K": {"tag": [1, "r"], "value": [1]}}, [4, 5]),
        M.ActiveReplicas(["a", "b"]),
        M.Compromise(),
    ]
    for m in msgs:
        assert M.loads(M.dumps(m)) == m


def test_tcp_transport_roundtrip():
    async def go():
        from dds_tpu.core.transport import TcpNet

        net = TcpNet("127.0.0.1", 39471)
        await net.start()
        got = asyncio.get_event_loop().create_future()

        async def handler(sender, msg):
            got.set_result((sender, msg))

        net.register("127.0.0.1:39471/alice", handler)
        net.send("bob", "127.0.0.1:39471/alice", M.ReadTag("K", 77))
        sender, msg = await asyncio.wait_for(got, 3)
        assert msg == M.ReadTag("K", 77)
        await net.stop()

    run(go())


def test_tcp_frame_mac_rejects_spoofed_frames():
    async def go():
        import json as _json

        from dds_tpu.core.transport import TcpNet

        net = TcpNet("127.0.0.1", 0 or 39474, frame_secret=b"cluster-secret")
        await net.start()
        got = []

        async def handler(sender, msg):
            got.append((sender, msg))

        net.register("127.0.0.1:39474/sup", handler)
        # legitimate frame (signed by the transport itself)
        net.send("replica-0", "127.0.0.1:39474/sup", M.ReadTag("K", 1))
        await asyncio.sleep(0.2)
        # forged frame: attacker with socket access but no frame secret
        r, w = await asyncio.open_connection("127.0.0.1", 39474)
        frame = _json.dumps(
            {"src": "replica-1", "dest": "127.0.0.1:39474/sup",
             "msg": M.to_dict(M.Suspect("replica-6", 99))}
        ).encode()
        w.write(len(frame).to_bytes(4, "big") + frame)
        await w.drain()
        await asyncio.sleep(0.2)
        w.close()
        await net.stop()
        assert [type(m).__name__ for _, m in got] == ["ReadTag"]  # spoof dropped

    run(go())


def test_tcp_intranet_mutual_tls_rejects_certless_peer(tmp_path):
    """The replica fabric under mutual TLS (`dds-system.conf:18-58`): a
    certified peer's frames arrive; a peer that completes TCP but presents
    no client certificate fails the handshake and delivers nothing."""

    async def go():
        import ssl as _ssl

        from dds_tpu.core.transport import TcpNet
        from dds_tpu.utils import tlsutil

        paths = tlsutil.generate_ca_and_cert(tmp_path, hosts=("127.0.0.1",))
        ca, cert, key = paths["ca"], paths["cert"], paths["key"]
        server_ctx = tlsutil.server_context(cert, key, ca)
        client_ctx = tlsutil.client_context(ca, cert, key)

        net = TcpNet("127.0.0.1", 39481, ssl_server=server_ctx, ssl_client=client_ctx)
        await net.start()
        got = []

        async def handler(sender, msg):
            got.append((sender, msg))

        net.register("127.0.0.1:39481/sup", handler)
        net.send("replica-0", "127.0.0.1:39481/sup", M.ReadTag("K", 1))
        await asyncio.sleep(0.3)
        assert [type(m).__name__ for _, m in got] == ["ReadTag"]

        # unauthenticated peer: trusts the CA but presents no client cert
        certless = tlsutil.client_context(ca)
        try:
            _, w = await asyncio.open_connection(
                "127.0.0.1", 39481, ssl=certless, server_hostname="localhost"
            )
            frame = b'{"src":"replica-1","dest":"127.0.0.1:39481/sup","msg":{}}'
            w.write(len(frame).to_bytes(4, "big") + frame)
            await w.drain()
            await asyncio.sleep(0.3)
            w.close()
        except (_ssl.SSLError, ConnectionResetError):
            pass  # handshake refusal is the expected outcome
        assert len(got) == 1  # nothing further was delivered
        await net.stop()

    run(go())


def test_oversized_tcp_frame_drops_connection():
    """A peer declaring a frame above MAX_FRAME (reference parity:
    maximum-frame-size, dds-system.conf:58) gets its connection dropped
    before the receiver buffers anything; normal traffic still flows."""

    async def go():
        from dds_tpu.core.transport import TcpNet

        net = TcpNet("127.0.0.1", 39551)
        await net.start()
        got = []

        async def handler(sender, msg):
            got.append(msg)

        net.register("127.0.0.1:39551/sup", handler)
        try:
            r, w = await asyncio.open_connection("127.0.0.1", 39551)
            w.write((TcpNet.MAX_FRAME + 1).to_bytes(4, "big") + b"x" * 64)
            await w.drain()
            # the server DROPS the connection (not just the frame): EOF
            assert await asyncio.wait_for(r.read(1), 2) == b""
            # a fresh, sane frame on a new connection still works
            net.send("a", "127.0.0.1:39551/sup", M.ReadTag("k", 1))
            await asyncio.sleep(0.2)
            w.close()
            assert [type(m).__name__ for m in got] == ["ReadTag"]
        finally:
            await net.stop()

    run(go())


def test_node_signed_frames_reject_credentialed_src_forgery():
    """Per-node frame signatures (utils/nodeauth): member B holds VALID
    cluster credentials (its own Ed25519 key, registered in the registry)
    but forges frames claiming member A's src addresses. The receiver
    verifies the signature against the claimed src's registered key, so
    B's forgeries are dropped while its honest frames flow — one
    compromised member cannot stuff sender-keyed quorums (WriteAck /
    Suspect / TagBatchReply) with spoofed votes."""

    async def go():
        import json as _json

        from dds_tpu.core.transport import TcpNet
        from dds_tpu.utils import nodeauth

        key_a, key_b = nodeauth.generate(), nodeauth.generate()
        reg = {
            "127.0.0.1:39511": nodeauth.load_public(nodeauth.public_hex(key_a)),
            "127.0.0.1:39512": nodeauth.load_public(nodeauth.public_hex(key_b)),
        }
        net_a = TcpNet("127.0.0.1", 39511, node_key=key_a, peer_keys=reg)
        net_b = TcpNet("127.0.0.1", 39512, node_key=key_b, peer_keys=reg)
        await net_a.start()
        await net_b.start()
        got = []

        async def handler(sender, msg):
            got.append((sender, type(msg).__name__))

        net_a.register("127.0.0.1:39511/sup", handler)
        try:
            # honest frame from B: accepted
            net_b.send("127.0.0.1:39512/replica-2", "127.0.0.1:39511/sup",
                       M.WriteAck("k", 1))
            # forgery: B signs with ITS key but claims A's own replica as src
            net_b.send("127.0.0.1:39511/replica-0", "127.0.0.1:39511/sup",
                       M.WriteAck("k", 2))
            # forgery: B claims an unregistered host
            net_b.send("10.0.0.9:999/replica-9", "127.0.0.1:39511/sup",
                       M.WriteAck("k", 3))
            await asyncio.sleep(0.3)
            assert got == [("127.0.0.1:39512/replica-2", "WriteAck")]

            # an unsigned frame (attacker without any node key) is dropped
            r, w = await asyncio.open_connection("127.0.0.1", 39511)
            frame = _json.dumps(
                {"src": "127.0.0.1:39512/replica-2",
                 "dest": "127.0.0.1:39511/sup",
                 "msg": M.to_dict(M.WriteAck("k", 4))}
            ).encode()
            w.write(len(frame).to_bytes(4, "big") + frame)
            await w.drain()
            await asyncio.sleep(0.2)
            w.close()
            assert len(got) == 1

            # a captured VALID signed frame replayed verbatim is dropped
            # (the signed counter must strictly increase per src host)
            src, dest = "127.0.0.1:39512/replica-2", "127.0.0.1:39511/sup"
            payload = M.to_dict(M.WriteAck("k", 5))
            ctr = 10**30  # far above anything sent so far
            body = TcpNet._frame_body(src, dest, payload, ctr)
            obj = {"src": src, "dest": dest, "msg": payload, "ctr": ctr,
                   "sig": key_b.sign(body).hex()}
            raw = _json.dumps(obj).encode()
            r, w = await asyncio.open_connection("127.0.0.1", 39511)
            for _ in range(2):  # original + replay
                w.write(len(raw).to_bytes(4, "big") + raw)
            await w.drain()
            await asyncio.sleep(0.3)
            w.close()
            assert len(got) == 2  # exactly one of the two was accepted
        finally:
            await net_a.stop()
            await net_b.stop()

    run(go())


def test_launch_tcp_with_intranet_tls_end_to_end(tmp_path):
    """launch() with transport=tcp + intranet mutual TLS: the full quorum
    path (PutSet-style write then read) works over the TLS replica fabric."""

    async def go():
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig

        cfg = DDSConfig()
        cfg.transport.kind = "tcp"
        cfg.transport.port = 39491
        cfg.security.intranet_tls_enabled = True
        cfg.security.tls_dir = str(tmp_path)
        cfg.proxy.port = 0
        dep = await launch(cfg)
        try:
            assert dep.net._ssl_server is not None  # contexts actually wired
            prefix = f"127.0.0.1:39491/"
            abd = dep.server.abd
            k, tag = await abd.write_set_tagged("tls-key", [41, 42])
            assert k == "tls-key" and tag is not None
            value, rtag = await abd.fetch_set_tagged("tls-key")
            assert value == [41, 42] and rtag == tag
            tags = await abd.read_tags(["tls-key"])
            assert tags == [rtag]
        finally:
            await dep.stop()

    run(go())


def test_two_process_deployment_quorum_across_tcp(tmp_path):
    """`Main.scala:90-99` + `dds-system.conf:113-128` parity: the same
    binary runs on multiple hosts, each spawning only ITS replicas, with
    the quorum spanning hosts over the intranet fabric. Two launch()es
    (two TcpNets = two processes in miniature) host disjoint halves of a
    4-replica f=1 quorum under mutual intranet TLS; writes and reads
    coordinate across both, and BOTH proxies see the data."""

    async def go():
        from dds_tpu.run import launch
        from dds_tpu.utils import tlsutil
        from dds_tpu.utils.config import DDSConfig

        from dds_tpu.utils import nodeauth

        port_a, port_b = 39501, 39502
        host_a, host_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"
        paths = tlsutil.generate_ca_and_cert(tmp_path, hosts=("127.0.0.1",))
        # per-process Ed25519 identities, provisioned like the certs
        key_a, key_b = nodeauth.generate(), nodeauth.generate()
        (tmp_path / "node_a.key").write_text(nodeauth.private_hex(key_a))
        (tmp_path / "node_b.key").write_text(nodeauth.private_hex(key_b))
        registry = {host_a: nodeauth.public_hex(key_a),
                    host_b: nodeauth.public_hex(key_b)}

        def make_cfg(port, remote_map, local):
            cfg = DDSConfig()
            cfg.transport.kind = "tcp"
            cfg.transport.port = port
            cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
            cfg.replicas.sentinent = []
            cfg.replicas.byz_quorum_size = 3
            cfg.replicas.addresses = remote_map
            cfg.replicas.local = local
            cfg.replicas.supervisor_address = host_a  # supervisor on A
            cfg.recovery.enabled = False
            cfg.proxy.port = 0
            cfg.security.intranet_tls_enabled = True
            cfg.security.tls_ca = paths["ca"]
            cfg.security.tls_cert = paths["cert"]
            cfg.security.tls_key = paths["key"]
            cfg.security.node_key_path = str(
                tmp_path / ("node_a.key" if port == port_a else "node_b.key")
            )
            cfg.security.node_public_keys = dict(registry)
            return cfg

        cfg_a = make_cfg(
            port_a, {"replica-2": host_b, "replica-3": host_b},
            ["replica-0", "replica-1"],
        )
        cfg_b = make_cfg(
            port_b, {"replica-0": host_a, "replica-1": host_a},
            ["replica-2", "replica-3"],
        )

        dep_a = await launch(cfg_a)
        dep_b = await launch(cfg_b)
        try:
            assert set(dep_a.replicas) == {f"{host_a}/replica-0",
                                           f"{host_a}/replica-1"}
            assert set(dep_b.replicas) == {f"{host_b}/replica-2",
                                           f"{host_b}/replica-3"}
            assert dep_a.supervisor is not None
            assert dep_b.supervisor is None  # remote supervisor

            # write through A's proxy: quorum 3 of 4 must span both hosts
            k, tag = await dep_a.server.abd.write_set_tagged("xhost", [5, 6])
            assert k == "xhost" and tag is not None
            value, rtag = await dep_a.server.abd.fetch_set_tagged("xhost")
            assert value == [5, 6] and rtag == tag
            # B's proxy reads the same data through its own coordinators
            value_b, rtag_b = await dep_b.server.abd.fetch_set_tagged("xhost")
            assert value_b == [5, 6] and rtag_b == tag
            # the batched tag round also spans hosts
            tags = await dep_b.server.abd.read_tags(["xhost"])
            assert tags == [tag]
            # data actually lives on both hosts (quorum intersected)
            holders = [
                node for dep in (dep_a, dep_b)
                for node in dep.replicas.values()
                if node.repository.get("xhost", (None, None))[1] == [5, 6]
            ]
            assert len(holders) >= 3
        finally:
            await dep_b.stop()
            await dep_a.stop()

    run(go())


def test_trudy_crash_and_suspicion_recovery_over_tcp():
    """Fault injection + recovery on the REAL fabric (`Trudy.scala:14-32` +
    `BFTSupervisor.scala:97-153`): Trudy's crash rides the TCP transport as
    a Crash control message, the damaged quorum keeps serving, a suspicion
    quorum then recovers the dead replica over TCP — spare promoted via
    Awake/State, victim redeployed and reseeded via Sleep/Complying — and
    the recovered fabric still completes quorums."""

    async def go():
        import random as _random

        from dds_tpu.core.errors import ByzantineError
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig

        port = 39531
        prefix = f"127.0.0.1:{port}/"
        cfg = DDSConfig()
        cfg.transport.kind = "tcp"
        cfg.transport.port = port
        cfg.attacks.enabled = True    # deployment honors Trudy's injections
        cfg.recovery.enabled = False  # manual recovery only, timing-clean
        cfg.recovery.sentinent_awake_timeout = 1.0
        cfg.recovery.crashed_recovery_timeout = 3.0
        cfg.proxy.port = 0
        cfg.proxy.intranet_request_timeout = 1.0
        dep = await launch(cfg)
        try:
            abd = dep.server.abd
            k, tag = await abd.write_set_tagged("rkey", [1, 2])
            assert tag is not None

            dep.trudy._rng = _random.Random(5)
            victims = dep.trudy.trigger("crash")
            assert len(victims) == 2
            await asyncio.sleep(0.3)
            # crashed endpoints are actually off the transport
            for v in victims:
                assert v.rsplit("/", 1)[-1] not in dep.net._handlers

            # the damaged quorum (7-2=5 = q) still serves; a crashed
            # coordinator draw times out and gets struck, so retry
            for _ in range(8):
                try:
                    value, _ = await abd.fetch_set_tagged("rkey")
                    break
                except (ByzantineError, asyncio.TimeoutError):
                    continue
            else:
                raise AssertionError("quorum never completed after crash")
            assert value == [1, 2]

            # suspicion quorum against one victim, voted over the fabric
            victim = victims[0]
            healthy = [a for a, _ in dep.supervisor.active if a not in victims]
            for voter in healthy[:5]:
                dep.net.send(
                    voter, f"{prefix}supervisor",
                    M.Suspect(victim, sigs.generate_nonce()),
                )
            # recovery: Awake spare (fast), Kill+Sleep victim (1s timeout,
            # dead), redeploy, Sleep again -> Complying
            for _ in range(40):
                await asyncio.sleep(0.2)
                if victim in dep.supervisor.sentinent:
                    break
            assert victim in dep.supervisor.sentinent
            active_now = [a for a, _ in dep.supervisor.active]
            assert victim not in active_now
            assert len(active_now) == 7  # a spare was promoted
            # the redeployed victim is back on the transport, reseeded
            assert victim.rsplit("/", 1)[-1] in dep.net._handlers
            assert dep.replicas[victim].repository.get("rkey", (None, None))[1] \
                == [1, 2]

            # recovered fabric completes fresh quorums (incl. the spare)
            for _ in range(8):
                try:
                    k2, t2 = await abd.write_set_tagged("rkey2", [9])
                    break
                except (ByzantineError, asyncio.TimeoutError):
                    continue
            else:
                raise AssertionError("quorum never completed after recovery")
            assert t2 is not None
        finally:
            await dep.stop()

    run(go())


def test_cross_host_redeploy_recovers_dead_remote_replica():
    """The RemoteScope parity case (`BFTSupervisor.scala:130-149`): the
    supervisor on host A recovers a crashed replica living on host B — the
    spare wakes over TCP, the victim's rebuild goes through B's node-host
    agent, and the Sleep reseed lands on the fresh node."""

    async def go():
        from dds_tpu.core.errors import ByzantineError
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig

        port_a, port_b = 39541, 39542
        host_a, host_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"

        def make_cfg(port, remote_map, local):
            cfg = DDSConfig()
            cfg.transport.kind = "tcp"
            cfg.transport.port = port
            cfg.replicas.endpoints = [f"replica-{i}" for i in range(5)]
            cfg.replicas.sentinent = ["replica-4"]
            cfg.replicas.byz_quorum_size = 3   # n_active=4, f=1
            cfg.replicas.addresses = remote_map
            cfg.replicas.local = local
            cfg.replicas.supervisor_address = host_a
            cfg.attacks.enabled = True
            cfg.recovery.enabled = False
            cfg.recovery.sentinent_awake_timeout = 1.0
            cfg.recovery.crashed_recovery_timeout = 3.0
            cfg.proxy.port = 0
            cfg.proxy.intranet_request_timeout = 1.0
            return cfg

        b_names = ("replica-3", "replica-4")
        cfg_a = make_cfg(port_a, {n: host_b for n in b_names},
                         ["replica-0", "replica-1", "replica-2"])
        cfg_b = make_cfg(port_b,
                         {n: host_a for n in ("replica-0", "replica-1",
                                              "replica-2")},
                         list(b_names))
        dep_a = await launch(cfg_a)
        dep_b = await launch(cfg_b)
        try:
            abd = dep_a.server.abd
            await abd.write_set_tagged("xk", [3])

            victim = f"{host_b}/replica-3"  # lives on B; supervisor on A
            old_node = dep_b.replicas[victim]
            dep_a.net.send(f"{host_a}/trudy", victim, M.Crash())
            await asyncio.sleep(0.3)
            assert "replica-3" not in dep_b.net._handlers  # actually dead

            for voter in (f"{host_a}/replica-0", f"{host_a}/replica-1",
                          f"{host_a}/replica-2"):
                dep_a.net.send(voter, f"{host_a}/supervisor",
                               M.Suspect(victim, sigs.generate_nonce()))
            for _ in range(40):
                await asyncio.sleep(0.2)
                if victim in dep_a.supervisor.sentinent:
                    break
            assert victim in dep_a.supervisor.sentinent
            # B's node agent rebuilt it: new object, re-registered, reseeded
            new_node = dep_b.replicas[victim]
            assert new_node is not old_node
            assert "replica-3" in dep_b.net._handlers
            assert new_node.repository.get("xk", (None, None))[1] == [3]
            assert new_node.behavior == "sentinent"  # demoted after reseed

            # the promoted spare keeps the quorum serving
            for _ in range(8):
                try:
                    value, _ = await abd.fetch_set_tagged("xk")
                    break
                except (ByzantineError, asyncio.TimeoutError):
                    continue
            else:
                raise AssertionError("quorum never completed after recovery")
            assert value == [3]
        finally:
            await dep_b.stop()
            await dep_a.stop()

    run(go())


def test_he_key_persistence_roundtrip(tmp_path):
    """client.conf:81-88 contract: run 1 generates keys (persisted via
    client.he_keys_path) and uploads encrypted rows; run 2's freshly-loaded
    provider (a new process would do exactly this) decrypts SumAll against
    the existing store. A provider with independent keys cannot."""

    async def go():
        import json as _json

        from dds_tpu.http.miniserver import http_request
        from dds_tpu.models.facade import HomoProvider
        from dds_tpu.run import launch, load_provider
        from dds_tpu.utils.config import DDSConfig

        cfg = DDSConfig()
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        cfg.client.paillier_bits = 1024  # keep keygen fast in tests
        cfg.client.he_keys_path = str(tmp_path / "he_keys.json")

        dep = await launch(cfg)
        try:
            host, port = cfg.proxy.host, dep.server.cfg.port
            run1 = load_provider(cfg)  # generates + persists
            vals = [7, 11]
            for v in vals:
                row = run1.encrypt_row([v], 1, ["PSSE"])
                status, _ = await http_request(
                    host, port, "POST", "/PutSet",
                    _json.dumps({"contents": row}).encode(),
                )
                assert status == 200

            run2 = load_provider(cfg)  # fresh object, loaded from disk
            assert run2 is not run1
            nsqr = run2.keys.psse.public.nsquare
            status, body = await http_request(
                host, port, "GET", f"/SumAll?position=0&nsqr={nsqr}"
            )
            assert status == 200
            total = int(_json.loads(body)["result"])
            assert run2.keys.psse.decrypt_signed(total) == sum(vals)

            # and literally from a FRESH PROCESS: only the persisted key
            # file crosses the boundary
            import subprocess
            import sys

            out = subprocess.run(
                [sys.executable, "-c", (
                    "import sys\n"
                    "from dds_tpu.models.keys import HEKeys\n"
                    "k = HEKeys.from_json(open(sys.argv[1]).read())\n"
                    "print(k.psse.decrypt_signed(int(sys.argv[2])))\n"
                ), cfg.client.he_keys_path, str(total)],
                capture_output=True, text=True, timeout=120,
            )
            assert out.returncode == 0, out.stderr
            assert int(out.stdout.strip()) == sum(vals)

            stranger = HomoProvider.generate(1024, 1024)
            assert stranger.keys.psse.decrypt_signed(total) != sum(vals)
        finally:
            await dep.stop()

    run(go())


def test_he_keys_inline_config_wins_over_path(tmp_path):
    """An inline HEKeys blob in the config takes precedence over the keys
    file — the direct analogue of keys shipped inside client.conf."""
    from dds_tpu.models.keys import HEKeys
    from dds_tpu.run import load_provider
    from dds_tpu.utils.config import DDSConfig

    inline = HEKeys.generate(paillier_bits=1024, rsa_bits=1024)
    other = HEKeys.generate(paillier_bits=1024, rsa_bits=1024)
    path = tmp_path / "keys.json"
    path.write_text(other.to_json())

    cfg = DDSConfig()
    cfg.client.he_keys_inline = inline.to_json()
    cfg.client.he_keys_path = str(path)
    p = load_provider(cfg)
    assert p.keys.psse.n == inline.psse.n  # inline won
    cfg.client.he_keys_inline = ""
    p2 = load_provider(cfg)
    assert p2.keys.psse.n == other.psse.n  # falls back to the file


def test_unreachable_replica_struck_then_dropped():
    """A replica that never complies after redeploy stays a (struck) spare
    — one miss may be a slow restart — but DROP_STRIKES consecutive
    failures drop it from membership so a phantom cannot pin future
    recoveries. A transient single miss self-heals on the next contact."""

    async def go():
        c = Cluster()
        victim = "replica-0"
        c.supervisor.cfg.sentinent_awake_timeout = 0.2
        c.supervisor.cfg.crashed_recovery_timeout = 0.2

        async def broken_redeploy(endpoint):
            pass  # rebuild never happens: node stays gone

        c.supervisor.redeploy = broken_redeploy
        c.net.unregister(victim)  # hard-dead: Kill and Sleep go nowhere
        await c.supervisor.recover(victim)
        active_names = [a for a, _ in c.supervisor.active]
        assert victim not in active_names
        assert len(active_names) == 7           # a real spare was promoted
        # strike 1: kept as a spare (could be a slow restart)
        assert victim in c.supervisor.sentinent
        assert c.supervisor._strikes[victim] == 1
        # once it is the ONLY spare left, it gets retried and keeps
        # failing Awake: strikes 2, 3 -> dropped
        c.supervisor.sentinent = [victim]
        await c.supervisor.recover(active_names[0])
        assert c.supervisor._strikes[victim] == 2
        assert victim in c.supervisor.sentinent  # still quarantined-spare
        await c.supervisor.recover(active_names[0])
        assert victim not in c.supervisor.sentinent  # dropped, loudly
        assert victim not in [a for a, _ in c.supervisor.active]
        assert victim not in c.supervisor._strikes  # bookkeeping cleared

    run(go())


def test_dead_spare_deprioritized_and_next_spare_used():
    """A spare whose Awake times out earns a strike and recovery proceeds
    with the next spare in the SAME attempt, so the offender still gets
    swapped; the struck spare is deprioritized for later picks but NOT
    dropped on a single miss."""

    async def go():
        c = Cluster()
        c.supervisor.cfg.sentinent_awake_timeout = 0.2
        dead_spare = "replica-7"
        c.net.unregister(dead_spare)  # cannot Awake
        # deterministic pick order among equal-strike spares
        c.supervisor._rng.choice = lambda seq: sorted(seq)[0]
        victim = "replica-0"
        await c.supervisor.recover(victim)
        # single miss: still a spare, but struck
        assert dead_spare in c.supervisor.sentinent
        assert c.supervisor._strikes[dead_spare] == 1
        active_names = [a for a, _ in c.supervisor.active]
        assert victim not in active_names  # offender really was swapped
        assert "replica-8" in active_names  # the live spare got promoted
        assert victim in c.supervisor.sentinent
        # later recoveries prefer the unstruck spare over the struck one
        await c.supervisor.recover(active_names[0])
        assert dead_spare in c.supervisor.sentinent  # was not even tried
        assert c.supervisor._strikes[dead_spare] == 1

    run(go())


def test_concurrent_suspects_single_recovery():
    async def go():
        c = Cluster()
        # flood: every replica votes many times against replica-6
        for round_ in range(3):
            for i in range(7):
                c.net.send(
                    f"replica-{i}", "supervisor",
                    M.Suspect("replica-6", sigs.generate_nonce()),
                )
        await c.net.quiesce()
        await asyncio.sleep(0.2)
        await c.net.quiesce()
        # exactly one swap: sizes intact, no duplicate active entries
        names = [a for a, _ in c.supervisor.active]
        assert len(names) == len(set(names)) == 7
        assert len(c.supervisor.sentinent) == 2
        # non-active endpoints are not recoverable
        await c.supervisor.recover("proxy-0")
        assert len(c.supervisor.active) == 7

    run(go())
