"""Tag-read protocol + proxy aggregate-cache tests.

The batched tag-only quorum read (`ReadTagBatch`, broadcast by the proxy
itself) and the proxy's tag-validated aggregate cache replace the
reference's per-aggregate full re-read of every stored set
(`dds/http/DDSRestServer.scala:397-446`). These tests pin the safety
argument: a cached value is served only when the quorum-max tag equals its
cached tag, so external writes are always observed and Byzantine replicas
can at worst force spurious re-fetches.
"""

import asyncio
import json

from dds_tpu.core import messages as M
from dds_tpu.core.errors import ByzantineError
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig

from tests.test_core import Cluster, run
from tests.test_rest import PROVIDER, call, rest_stack


# ------------------------------------------------------------ protocol level

def test_read_tags_matches_completed_writes():
    async def go():
        c = Cluster()
        await c.client.write_set("k1", [1])
        await c.client.write_set("k2", [2])
        v1, t1 = await c.client.fetch_set_tagged("k1")
        v2, t2 = await c.client.fetch_set_tagged("k2")
        assert v1 == [1] and v2 == [2]
        tags = await c.client.read_tags(["k1", "k2"])
        assert tags == [t1, t2]
        # a new write must advance the quorum-max tag for that key only
        await c.client.write_set("k1", [10])
        tags2 = await c.client.read_tags(["k1", "k2"])
        assert tags2[0] > t1 and tags2[1] == t2

    run(go())


def test_read_tags_unknown_key_is_zero_seq():
    async def go():
        c = Cluster()
        (tag,) = await c.client.read_tags(["never-written"])
        assert tag.seq == 0

    run(go())


def test_write_reply_tag_matches_quorum():
    """The tag returned by write_set_tagged is exactly what a subsequent
    tag read observes (the cache-update invariant)."""

    async def go():
        c = Cluster()
        _, wtag = await c.client.write_set_tagged("k", [7])
        assert wtag is not None and wtag.seq >= 1
        tags = await c.client.read_tags(["k"])
        assert tags == [wtag]

    run(go())


def test_read_tags_resists_tag_deflation_by_credentialed_minority():
    """The attack the coordinator-mediated tag read was vulnerable to: a
    Byzantine minority holding REAL MAC keys under-reports tags, trying to
    make the proxy serve a superseded cached value. read_tags broadcasts
    itself and maxes over a quorum of verified replies, and any quorum
    intersects the completed write's quorum in an honest replica — so the
    deflated vectors can never lower the result."""
    from dds_tpu.utils import sigs as S

    async def go():
        c = Cluster()  # n=7, q=5, f=2
        await c.client.write_set("k", [1])
        await c.client.write_set("k", [2])  # tag seq >= 2 now
        tags = await c.client.read_tags(["k"])
        true_tag = tags[0]
        assert true_tag.seq >= 2

        secret = c.rcfg.abd_mac_secret

        async def deflate(msg):
            if isinstance(msg, M.TagBatchReply):
                zero = (M.ABDTag(0, "forger"),) * len(msg.tags)
                sig = S.abd_batch_signature(secret, zero, msg.digest, msg.nonce)
                return M.TagBatchReply(zero, msg.digest, sig, msg.nonce)
            return msg

        # two credentialed liars deflate every tag reply on the wire
        c.net.link_filters[("replica-5", "proxy-0")] = deflate
        c.net.link_filters[("replica-6", "proxy-0")] = deflate
        for _ in range(10):
            got = await c.client.read_tags(["k"])
            assert got[0] == true_tag  # never deflated below the true max

    run(go())


def test_read_tags_tolerates_byzantine_minority():
    async def go():
        c = Cluster()  # n=7, q=5, f=2
        await c.client.write_set("k", [3])
        _, t = await c.client.fetch_set_tagged("k")
        for addr in ("replica-5", "replica-6"):
            c.replicas[addr].behavior = "byzantine"
        for _ in range(20):  # byzantine coordinator draws raise; honest wins
            try:
                tags = await c.client.read_tags(["k"])
                break
            except (ByzantineError, asyncio.TimeoutError):
                continue
        else:
            raise AssertionError("read_tags never succeeded past byzantine minority")
        assert tags == [t]

    run(go())


def test_tag_messages_serialization_roundtrip():
    msgs = [
        M.ReadTagBatch(("a", "b"), 42, b"\x07"),
        M.ReadTagBatch(("a", "b"), 42, b"\x07", b"\xfe" * 32),
        M.TagBatchReply((M.ABDTag(3, "r2"),), "digest", b"\x01\x02", 42),
        M.TagBatchReply((), "digest", b"\x01", 42, unchanged=True,
                        fingerprint=b"\xaa" * 32),
    ]
    for m in msgs:
        assert M.loads(M.dumps(m)) == m


def test_tags_blob_packing_is_injective():
    """Tag ids come off the wire uncharset-checked: the packed MAC input
    must stay injective even when ids embed the delimiter characters
    (regression: 'seq:id' joined by ';' let two distinct vectors collide)."""
    from dds_tpu.utils import sigs as S

    a = (M.ABDTag(1, "x;9:y"), M.ABDTag(2, "z"))
    b = (M.ABDTag(1, "x"), M.ABDTag(9, "y;2:z"))
    assert S.tags_blob(a) != S.tags_blob(b)
    assert S.tags_fingerprint(a) != S.tags_fingerprint(b)


def test_read_tags_fingerprint_fast_path_identity():
    """Steady state: when every quorum vote is `unchanged`, read_tags
    returns the caller's cached_tags list BY IDENTITY (the all-fresh
    signal) — and after any write the fingerprint no longer matches, so
    the result is a fresh list carrying the advanced tag."""
    from dds_tpu.utils import sigs as S

    async def go():
        c = Cluster()
        await c.client.write_set("k1", [1])
        await c.client.write_set("k2", [2])
        keys = ["k1", "k2"]
        cached = await c.client.read_tags(keys)
        fp = S.tags_fingerprint(cached)
        digest = S.key_from_set(keys)
        got = await c.client.read_tags(
            keys, digest=digest, fingerprint=fp, cached_tags=cached
        )
        assert got is cached  # every replica answered `unchanged`
        await c.client.write_set("k1", [10])
        got2 = await c.client.read_tags(
            keys, digest=digest, fingerprint=fp, cached_tags=cached
        )
        assert got2 is not cached
        assert got2[0] > cached[0] and got2[1] == cached[1]

    run(go())


def test_forged_unchanged_vote_cannot_hide_a_newer_write():
    """A credentialed minority echoing `unchanged` (valid MAC over the
    proxy's own fingerprint) while a newer write completed: the quorum
    intersects the write's quorum in honest replicas whose full replies
    carry the higher tag, so the max still advances."""
    from dds_tpu.utils import sigs as S

    async def go():
        c = Cluster()  # n=7, q=5, f=2
        await c.client.write_set("k", [1])
        keys = ["k"]
        cached = await c.client.read_tags(keys)
        fp = S.tags_fingerprint(cached)
        digest = S.key_from_set(keys)
        secret = c.rcfg.abd_mac_secret

        async def fake_unchanged(msg):
            if isinstance(msg, M.TagBatchReply):
                sig = S.abd_batch_unchanged_signature(
                    secret, fp, msg.digest, msg.nonce
                )
                return M.TagBatchReply((), msg.digest, sig, msg.nonce,
                                       unchanged=True, fingerprint=fp)
            return msg

        c.net.link_filters[("replica-5", "proxy-0")] = fake_unchanged
        c.net.link_filters[("replica-6", "proxy-0")] = fake_unchanged

        await c.client.write_set("k", [2])  # the write the liars try to hide
        for _ in range(10):
            got = await c.client.read_tags(
                keys, digest=digest, fingerprint=fp, cached_tags=cached
            )
            assert got[0] > cached[0]  # never masked by the forged votes

    run(go())


def test_unsolicited_unchanged_vote_is_rejected():
    """An `unchanged` reply when the proxy sent NO fingerprint (or a
    different one) must not count as a vote — otherwise a replica could
    assert equality to a vector nobody named."""
    from dds_tpu.utils import sigs as S

    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])
        secret = c.rcfg.abd_mac_secret

        async def always_unchanged(msg):
            if isinstance(msg, M.TagBatchReply):
                bogus = b"\x99" * 32
                sig = S.abd_batch_unchanged_signature(
                    secret, bogus, msg.digest, msg.nonce
                )
                return M.TagBatchReply((), msg.digest, sig, msg.nonce,
                                       unchanged=True, fingerprint=bogus)
            return msg

        c.net.link_filters[("replica-0", "proxy-0")] = always_unchanged
        tags = await c.client.read_tags(["k"])  # no fingerprint sent
        assert tags[0].seq >= 1
        # the forger earned a strike, honest replicas carried the quorum
        assert c.client.replicas._strikes["replica-0"] >= 1

    run(go())


def test_crafted_column_values_stay_opaque():
    """Stored set contents are client data: codec markers inside them must
    survive as plain data, never be decoded as protocol objects (that would
    crash or transform messages in the receive path before MAC checks)."""
    row = [1, {"__msg__": "nope"}, {"__tag__": [5, "x"]}, {"__b64__": "AA=="}]
    env = M.Envelope(M.IWrite("k", row), 1, b"s")
    assert M.loads(M.dumps(env)) == env


def test_unauthenticated_tag_batch_is_ignored():
    """A ReadTagBatch without a valid proxy MAC gets no reply and burns no
    anti-replay nonce (else unauthenticated traffic could enumerate tags
    and grow the nonce set without bound)."""

    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])
        target = c.replicas["replica-0"]
        before = dict(target.incoming)
        got = []
        c.net.register("intruder", lambda s, m: (got.append(m), asyncio.sleep(0))[1])
        c.net.send("intruder", "replica-0", M.ReadTagBatch(("k",), 999, b"bogus"))
        await c.net.quiesce()
        assert got == []
        assert target.incoming == before

    run(go())


def test_unauthenticated_tag_batch_cannot_evict_memo_cache():
    """The replica's tag-batch memo cache is probed read-only before the
    proxy MAC verifies and filled only after: unauthenticated traffic with
    rotating bogus key sets must neither grow the cache nor evict the hot
    entry of the legitimate aggregate."""

    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])
        await c.client.read_tags(["k"])  # fills each replica's memo
        target = c.replicas["replica-0"]
        before = dict(target._tagbatch_cache)
        assert before  # the legit entry is resident
        c.net.register("intruder", lambda s, m: asyncio.sleep(0))
        for i in range(12):  # > the cache's eviction bound
            c.net.send(
                "intruder", "replica-0",
                M.ReadTagBatch((f"bogus-{i}",) * 4, 1000 + i, b"bad"),
            )
        await c.net.quiesce()
        assert target._tagbatch_cache == before

    run(go())


def test_read_tags_fails_fast_below_quorum():
    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])
        for r in ("replica-0", "replica-1", "replica-2"):
            for _ in range(3):
                c.client.replicas.increment_suspicion(r)
        try:
            await c.client.read_tags(["k"])
        except ByzantineError:
            return
        raise AssertionError("read_tags should fail fast below quorum")

    run(go())


def test_in_transit_tag_substitution_is_rejected():
    """Reply tags are covered by the proxy HMAC: an attacker on the
    replica->proxy channel who swaps in a guessed (predictable) tag must
    trigger ByzInvalidSignatureError, not poison the tag-validated cache."""
    from dataclasses import replace

    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])

        async def swap_tag(msg):
            if isinstance(msg, M.Envelope):
                inner = msg.call
                if isinstance(inner, (M.IReadReply, M.IWriteReply)) and inner.tag:
                    forged = M.ABDTag(inner.tag.seq + 1, inner.tag.id)
                    return replace(msg, call=replace(inner, tag=forged))
            return msg

        c.net.link_filters["proxy-0"] = swap_tag
        for op in (lambda: c.client.fetch_set_tagged("k"),
                   lambda: c.client.write_set_tagged("k", [2])):
            try:
                await op()
            except ByzantineError:
                continue
            raise AssertionError("forged reply tag was accepted")

    run(go())


def test_read_skips_writeback_when_quorum_agrees():
    """Standard ABD read optimization: when every quorum member reports the
    same (tag, value), the value is already at a full quorum and the read
    answers without the write-back phase; a divergent member still triggers
    the repairing write-back."""

    async def go():
        c = Cluster()
        await c.client.write_set("k", [1])
        await c.net.quiesce()
        writes = []
        orig_send = c.net.send

        def counting_send(src, dest, msg):
            if isinstance(msg, M.Write):
                writes.append((src, dest))
            orig_send(src, dest, msg)

        c.net.send = counting_send
        v, t = await c.client.fetch_set_tagged("k")
        assert v == [1]
        assert writes == []  # all replicas agreed: no write-back round

        # a lagging replica (stale tag) forces the repair write-back
        lagger = c.replicas["replica-3"]
        lagger.repository["k"] = (M.ABDTag(0, lagger.name), None)
        lagger.repo_version += 1
        for _ in range(10):  # until the lagger lands in the read quorum
            writes.clear()
            v, t2 = await c.client.fetch_set_tagged("k")
            assert v == [1] and t2 == t
            if writes:
                break
        else:
            raise AssertionError("divergent replica never triggered write-back")
        await c.net.quiesce()
        assert lagger.repository["k"][1] == [1]  # repaired

    run(go())


def test_defer_to_exclusion_picks_a_different_coordinator():
    """The audit's corroborating re-read must not land on the coordinator
    it is checking: defer_to(exclude) avoids it whenever another trusted
    node exists, and only falls back when no alternative remains."""
    from dds_tpu.utils.trust import TrustedNodesList

    t = TrustedNodesList(["a", "b", "c"])
    assert all(t.defer_to(exclude=("a",)) != "a" for _ in range(50))
    t2 = TrustedNodesList(["a"])
    assert t2.defer_to(exclude=("a",)) == "a"  # fallback, not a crash


# --------------------------------------------------------------- proxy level

def _count_fetches(server):
    """Wrap the proxy's quorum read so tests can count full ABD fetches."""
    counter = {"n": 0}
    orig = server.abd.fetch_set_attributed

    async def counted(key, exclude=(), deadline=None):
        counter["n"] += 1
        return await orig(key, exclude, deadline=deadline)

    server.abd.fetch_set_attributed = counted
    return counter


def test_aggregate_cache_serves_warm_and_sees_external_writes():
    async def go():
        async with rest_stack() as (server, replicas, _):
            server.cfg.aggregate_cache_audit = 0  # counting pure cache hits
            pk = PROVIDER.keys.psse.public
            vals = [11, 22, 33]
            keys = []
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                keys.append(key.decode())
            counter = _count_fetches(server)
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"

            # cold-ish: PutSet already cached each row, so zero full fetches
            _, data = await call(server, "GET", target)
            assert PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"])) == sum(vals)
            assert counter["n"] == 0

            # external writer (another proxy's quorum client) bumps one key
            other = AbdClient(
                "proxy-ext", server.abd.net, list(replicas),
                AbdClientConfig(request_timeout=2.0),
            )
            new_row = PROVIDER.encrypt_row([100], 1, ["PSSE"])
            await other.write_set(keys[0], new_row)

            # tag validation must spot exactly that one stale key
            _, data = await call(server, "GET", target)
            got = PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
            assert got == 100 + 22 + 33
            assert counter["n"] == 1

            # steady state again: all fresh, no fetches
            _, data = await call(server, "GET", target)
            assert counter["n"] == 1

    asyncio.run(go())


def test_audit_costs_exactly_sample_size_fetches():
    """The audit's own cost is pinned: a warm aggregate performs exactly
    min(aggregate_cache_audit, cached-keys) full quorum reads — no more."""

    async def go():
        async with rest_stack() as (server, _, _):
            pk = PROVIDER.keys.psse.public
            vals = [1, 2, 3]
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                await call(server, "POST", "/PutSet", {"contents": row})
            counter = _count_fetches(server)
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            assert server.cfg.aggregate_cache_audit == 2  # default under test
            for i in (1, 2):
                _, data = await call(server, "GET", target)
                assert (
                    PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
                    == sum(vals)
                )
                assert counter["n"] == 2 * i

    asyncio.run(go())


def test_audit_detects_forged_cache_entry_and_flushes():
    """A forged cached value at the TRUE tag (what a Byzantine coordinator
    holding the proxy MAC secret could plant) is caught by the audit: the
    re-read mismatches at the SAME tag, the cache is flushed, and the
    aggregate is computed from quorum reads only."""

    async def go():
        async with rest_stack() as (server, _, _):
            pk = PROVIDER.keys.psse.public
            vals = [11, 22, 33]
            keys = []
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                keys.append(key.decode())
            # audit the whole cache so the poisoned key is sampled for sure
            server.cfg.aggregate_cache_audit = len(keys)
            tag, _ = server._cache[keys[0]]
            forged_row = PROVIDER.encrypt_row([999], 1, ["PSSE"])
            server._cache[keys[0]] = (tag, forged_row)

            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            _, data = await call(server, "GET", target)
            got = PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
            assert got == sum(vals)  # forgery did not reach the result
            # flush: every pre-flush entry (incl. audit refills) was dropped
            assert server._cache == {}

    asyncio.run(go())


def test_audit_benign_concurrent_write_refreshes_without_flush():
    """A write landing between the tag-validation round and the audit
    re-read mismatches at a strictly NEWER tag — the audit must refresh
    that entry and serve the new value, not flush the whole cache."""

    async def go():
        async with rest_stack() as (server, replicas, _):
            pk = PROVIDER.keys.psse.public
            vals = [11, 22, 33]
            keys = []
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                keys.append(key.decode())
            server.cfg.aggregate_cache_audit = len(keys)

            # freeze the validation round at the pre-write tags, simulating
            # the race where read_tags completes just before the write lands
            stale_tags = {k: server._cache[k][0] for k in keys}

            async def frozen_read_tags(ks, **_kw):
                return [stale_tags[k] for k in ks]

            server.abd.read_tags = frozen_read_tags
            other = AbdClient(
                "proxy-ext3", server.abd.net, list(replicas),
                AbdClientConfig(request_timeout=2.0),
            )
            await other.write_set(keys[0], PROVIDER.encrypt_row([100], 1, ["PSSE"]))

            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            _, data = await call(server, "GET", target)
            got = PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
            assert got == 100 + 22 + 33  # the audit's newer value is served
            # no flush: all keys still cached, bumped key at its new tag
            assert set(server._cache) == set(keys)
            assert server._cache[keys[0]][0] > stale_tags[keys[0]]

    asyncio.run(go())


def test_aggregate_cache_disabled_refetches_everything():
    async def go():
        async with rest_stack() as (server, _, _):
            server.cfg.aggregate_cache = False
            pk = PROVIDER.keys.psse.public
            vals = [5, 6]
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                await call(server, "POST", "/PutSet", {"contents": row})
            counter = _count_fetches(server)
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            for i in (1, 2):
                _, data = await call(server, "GET", target)
                assert (
                    PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
                    == sum(vals)
                )
                assert counter["n"] == len(vals) * i  # reference behavior

    asyncio.run(go())


def test_cached_aggregate_reads_are_atomic():
    """The tag-validated cache path must preserve the atomic-register
    properties under concurrent writers: no reads from the future, no
    new/old inversion (same checker as tests/test_linearizability.py)."""

    import random
    import time

    from dds_tpu.http.server import DDSRestServer, ProxyConfig
    from dds_tpu.utils.retry import retry
    from tests.test_linearizability import (
        KEY, Recorder, _writer, check_atomic_register,
    )

    async def go():
        c = Cluster()
        rng = random.Random(11)
        rec = Recorder()
        server = DDSRestServer(
            AbdClient(
                "proxy-lin", c.net, c.active, AbdClientConfig(request_timeout=1.0)
            ),
            ProxyConfig(),
        )
        server.stored_keys.add(KEY)
        t0 = time.monotonic()
        await c.client.write_set(KEY, ["init"])
        rec.record("write", "init", t0, time.monotonic())

        async def cached_reader(n):
            for _ in range(n):
                t0 = time.monotonic()
                pairs = await retry(server._fetch_stored, 0.01, 5)
                v = pairs[0][1][0] if pairs else None
                rec.record("read", v, t0, time.monotonic())
                await asyncio.sleep(rng.uniform(0, 0.002))

        await asyncio.gather(
            _writer(c, rec, 0, 25, random.Random(1)),
            _writer(c, rec, 1, 25, random.Random(2)),
            cached_reader(60),
            cached_reader(60),
        )
        check_atomic_register(rec.ops)
        reads = [o for o in rec.ops if o["kind"] == "read"]
        assert any(o["value"] is not None for o in reads)

    run(go())


def test_search_routes_use_validated_cache():
    """Order/Search routes share _fetch_stored: results stay correct when
    served from the validated cache after an external write."""

    async def go():
        async with rest_stack() as (server, replicas, _):
            rows = {v: PROVIDER.encrypt_row([v], 1, ["OPE"]) for v in (1, 2, 3)}
            keys = {}
            for v, row in rows.items():
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                keys[v] = key.decode()
            _, data = await call(server, "GET", "/OrderSL?position=0")
            assert json.loads(data)["keyset"] == [keys[1], keys[2], keys[3]]

            other = AbdClient(
                "proxy-ext2", server.abd.net, list(replicas),
                AbdClientConfig(request_timeout=2.0),
            )
            await other.write_set(keys[1], PROVIDER.encrypt_row([9], 1, ["OPE"]))
            _, data = await call(server, "GET", "/OrderSL?position=0")
            assert json.loads(data)["keyset"] == [keys[2], keys[3], keys[1]]

    asyncio.run(go())


def test_codec_roundtrip_fuzz():
    """Randomized wire-codec roundtrips: every message type with random
    field content (incl. protocol-marker-shaped client data inside stored
    sets) survives dumps/loads exactly."""
    import random

    rng = random.Random(99)

    def rand_value():
        pool = [
            rng.getrandbits(64),
            str(rng.getrandbits(128)),
            None,
            True,
            {"__msg__": "nope"},
            {"__tag__": [1, "x"]},
            {"__b64__": "AA=="},
            [rng.getrandbits(16), "s", None],
        ]
        return rng.choice(pool)

    def rand_set():
        return [rand_value() for _ in range(rng.randrange(0, 5))]

    def rand_tag():
        return M.ABDTag(rng.getrandbits(32), f"replica-{rng.randrange(9)}")

    for _ in range(200):
        sig = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 33)))
        nonce = rng.getrandbits(63)
        key = str(rng.getrandbits(256))
        msgs = [
            M.Envelope(M.IWrite(key, rand_set()), nonce, sig),
            M.Envelope(M.IRead(key), nonce, sig),
            M.Envelope(M.IReadReply(key, rand_set(), tag=rand_tag()), nonce, sig),
            M.TagReply(rand_tag(), key, rand_set(), sig, nonce),
            M.Write(rand_tag(), key, rand_set(), sig, nonce),
            M.ReadReply(rand_tag(), key, rand_set(), sig, nonce),
            M.ReadTagBatch(tuple(str(rng.getrandbits(64)) for _ in range(3)),
                           nonce, sig, bytes(32) if rng.random() < 0.5 else None),
            M.TagBatchReply(tuple(rand_tag() for _ in range(3)), key, sig,
                            nonce, unchanged=rng.random() < 0.5,
                            fingerprint=bytes(32)),
            M.Suspect(f"host:1/{key[:8]}", nonce),
            M.State({key: {"tag": [1, "r"], "value": rand_set()}}, [nonce]),
            M.Sleep({key: {"tag": [2, "r"], "value": None}}, [nonce, nonce + 1]),
            M.ActiveReplicas([f"h:{i}/r-{i}" for i in range(3)]),
            M.Redeploy(f"h:1/{key[:6]}"),
            M.Redeployed(f"h:1/{key[:6]}"),
        ]
        m = msgs[rng.randrange(len(msgs))]
        assert M.loads(M.dumps(m)) == m


def test_audit_persistence_bound_monte_carlo():
    """Quantify the audit knob (r4 verdict #8): a planted forged cache
    entry survives until an aggregate round (a) samples it into the audit
    AND (b) the audit's random coordinator is honest. Detection is
    geometric with p = (audit/K) * (n-f)/n, so expected persistence is
    K/audit * n/(n-f) rounds. Monte Carlo at the documented operating
    point (K=8192, audit=2, n=4, f=1 -> ~5461) must match within 5%."""
    import numpy as np

    K, AUDIT, N, F = 8192, 2, 4, 1
    rng = np.random.default_rng(42)
    trials = 20_000
    # per round, two independent events: the forged key lands in the audit
    # sample (P = AUDIT/K exactly, for a uniform sample w/o replacement)
    # and the audit read's random coordinator is honest (P = (N-F)/N)
    remaining = np.arange(trials)
    rounds = np.zeros(trials, np.int64)
    block = 4096
    while remaining.size:
        sampled = rng.random((remaining.size, block)) < AUDIT / K
        honest = rng.integers(0, N, (remaining.size, block)) >= F
        hit = sampled & honest
        first = hit.argmax(axis=1)
        found = hit.any(axis=1)
        rounds[remaining[found]] += first[found] + 1
        rounds[remaining[~found]] += block
        remaining = remaining[~found]
    mean = rounds.mean()
    expect = K / AUDIT * N / (N - F)   # 5461.33
    assert abs(mean - expect) / expect < 0.05, (mean, expect)
    # scaling sanity: audit=8 cuts expected persistence 4x
    p2 = (AUDIT / K) * (N - F) / N
    p8 = (8 / K) * (N - F) / N
    assert abs((1 / p8) / (1 / p2) - 0.25) < 1e-9
