"""Argus static-analysis plane: tier-1 gate + engine/CLI contract.

Three layers:

- fixture corpora (tests/fixtures/argus/<pass>/): every must_flag.py
  exits 1 with the expected rule set, every must_pass.py twin is clean
  under ALL passes (a sanctioned idiom must never be noise);
- the finding model: inline suppressions, baseline round-trip (add →
  suppress → resurface when the flagged line changes), malformed
  baseline → exit 2, unknown pass id → exit 2;
- the repo gate: the shipped tree is clean under the default roots +
  baseline, and specifically holds the zero-bare-``ensure_future``
  discipline (utils.tasks.supervised_task everywhere).

Plus runtime tests for the two fixes this plane forced:
``utils.tasks.supervised_task`` (handle retention + crash reporting)
and ``obs.flight.record_async`` (off-loop incident dumps).
"""

import asyncio
import json
import pathlib
import shutil

import pytest

from dds_tpu.obs.flight import FlightRecorder
from dds_tpu.utils import tasks as t
from tools.argus import baseline as bl
from tools.argus import cli
from tools.argus.engine import lint_file, lint_source
from tools.argus.passes import PASSES, build

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "argus"

# pass id -> rules its must_flag corpus must produce
EXPECTED_RULES = {
    "async": {"blocking-call", "dropped-task", "bare-task-spawn",
              "unawaited-coroutine", "lock-across-await"},
    "dispatch": {"jit-per-call", "host-roundtrip", "stray-sync"},
    "trust": {"unverified-store"},
    "secret": {"secret-flow"},
    "metrics": {"empty-help", "unbounded-label"},
}

# the secret corpus must cover every sink class
EXPECTED_SECRET_SINKS = {"ModCtx.make", "jax.jit", "cached_builder",
                         "powmod_batch", "powmod"}


# ------------------------------------------------------------- fixture corpora


@pytest.mark.parametrize("pass_id", sorted(PASSES))
def test_must_flag_corpus_flags(pass_id):
    path = FIXTURES / pass_id / "must_flag.py"
    findings = lint_file(path, build([pass_id]))
    assert findings, f"{path} produced no findings"
    assert {f.rule for f in findings} == EXPECTED_RULES[pass_id]
    # CLI contract: pointing the tool at a must-flag corpus exits 1
    rc = cli.main([str(path), "--passes", pass_id, "--no-baseline"])
    assert rc == 1


@pytest.mark.parametrize("pass_id", sorted(PASSES))
def test_must_pass_twin_is_clean_under_all_passes(pass_id):
    path = FIXTURES / pass_id / "must_pass.py"
    findings = lint_file(path, build())
    assert findings == [], [str(f) for f in findings]
    rc = cli.main([str(path), "--no-baseline"])
    assert rc == 0


def test_secret_corpus_covers_every_sink_class():
    path = FIXTURES / "secret" / "must_flag.py"
    findings = lint_file(path, build(["secret"]))
    assert {f.symbol for f in findings} == EXPECTED_SECRET_SINKS


def test_findings_carry_location_pass_and_trace():
    path = FIXTURES / "secret" / "must_flag.py"
    f = lint_file(path, build(["secret"]))[0]
    d = f.to_dict()
    assert d["line"] > 0 and d["pass"] == "secret" and d["rule"]
    assert d["trace"], "taint findings must carry the propagation trace"
    assert str(f).startswith(f"{f.path}:{f.line}:")


# ------------------------------------------------------------- suppressions


def test_inline_suppression_silences_one_rule():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # argus: ok[async.blocking-call] fixture\n"
        "    time.sleep(2)\n"
    )
    findings = lint_source(src, "x.py", build(["async"]))
    assert [f.line for f in findings] == [4]


def test_blanket_suppression_silences_the_line():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # argus: ok\n"
    )
    assert lint_source(src, "x.py", build(["async"])) == []


def test_wrong_rule_suppression_does_not_silence():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # argus: ok[dispatch.jit-per-call]\n"
    )
    findings = lint_source(src, "x.py", build(["async"]))
    assert len(findings) == 1


# ----------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    """add → suppress → resurface when the flagged line itself changes."""
    work = tmp_path / "corpus.py"
    shutil.copy(FIXTURES / "async" / "must_flag.py", work)
    base = tmp_path / "baseline.json"

    argv = [str(work), "--passes", "async", "--baseline", str(base)]
    assert cli.main(argv) == 1                      # add: findings exist
    assert cli.main(argv + ["--write-baseline"]) == 0
    assert cli.main(argv) == 0                      # suppressed by baseline

    # a pure line shift must NOT resurface anything (snippet-keyed match)
    work.write_text("# a comment pushed everything down one line\n"
                    + work.read_text())
    assert cli.main(argv) == 0

    # but editing a flagged line itself must resurface that finding
    work.write_text(work.read_text().replace(
        "time.sleep(0.1)", "time.sleep(0.25)"))
    assert cli.main(argv) == 1


def test_malformed_baseline_exits_2(tmp_path):
    path = FIXTURES / "async" / "must_flag.py"
    for bad in (
        '{"not": "a list"}',
        '[{"path": "x"}]',                               # missing keys
        json.dumps([{"path": "x", "pass": "async", "rule": "r",
                     "scope": "s", "snippet": "y", "reason": "   "}]),
        "not json at all",
    ):
        base = tmp_path / "baseline.json"
        base.write_text(bad)
        rc = cli.main([str(path), "--passes", "async",
                       "--baseline", str(base)])
        assert rc == 2, f"baseline {bad!r} should be rejected"
    with pytest.raises(bl.BaselineError):
        bl.load_baseline(base)


def test_unknown_pass_exits_2():
    assert cli.main(["--passes", "nonsense"]) == 2


def test_missing_baseline_is_empty(tmp_path):
    assert bl.load_baseline(tmp_path / "absent.json") == []


# ---------------------------------------------------------------- repo gate


def test_repo_is_clean_under_default_roots_and_baseline():
    findings = cli.lint_repo()
    entries = bl.load_baseline()
    new, unused = bl.split_findings(findings, entries)
    assert new == [], "\n".join(str(f) for f in new)
    assert unused == [], f"stale baseline entries: {unused}"


def test_repo_clean_via_cli_exit_code():
    assert cli.main(["--check"]) == 0


def test_no_bare_ensure_future_in_dds_tpu():
    """The satellite discipline: every spawn in dds_tpu/ goes through
    utils.tasks.supervised_task (AST-backed, so docstrings don't count)."""
    findings = cli.lint_repo(pass_ids=["async"])
    spawns = [f for f in findings if f.rule == "bare-task-spawn"]
    assert spawns == [], "\n".join(str(f) for f in spawns)


def test_every_baseline_entry_has_a_real_reason():
    for entry in bl.load_baseline():
        assert len(entry["reason"].strip()) > 20, entry


# ------------------------------------------------- runtime: the forced fixes


def test_supervised_task_retains_handle_and_reports_crash(caplog):
    async def scenario():
        async def ok():
            return 41

        async def boom():
            raise RuntimeError("fixture crash")

        good = t.supervised_task(ok(), name="argus.ok")
        bad = t.supervised_task(boom(), name="argus.boom")
        assert t.supervised_count() >= 2
        assert await good == 41
        with pytest.raises(RuntimeError):
            await bad
        await asyncio.sleep(0)              # let done-callbacks run
        assert good not in t._TASKS and bad not in t._TASKS

    with caplog.at_level("ERROR", logger="dds.tasks"):
        asyncio.run(scenario())
    crash_logs = [r for r in caplog.records if "argus.boom" in r.getMessage()]
    assert crash_logs, "task crash must be logged with the task name"


def test_supervised_task_cancellation_is_silent(caplog):
    async def scenario():
        async def forever():
            await asyncio.sleep(3600)

        task = t.supervised_task(forever(), name="argus.cancelled")
        await asyncio.sleep(0)
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        await asyncio.sleep(0)

    with caplog.at_level("ERROR", logger="dds.tasks"):
        asyncio.run(scenario())
    assert not [r for r in caplog.records
                if "argus.cancelled" in r.getMessage()]


def test_drain_cancels_leftover_tasks():
    async def scenario():
        async def forever():
            await asyncio.sleep(3600)

        t.supervised_task(forever(), name="argus.leftover")
        await t.drain(timeout=1.0)
        assert t.supervised_count() == 0

    asyncio.run(scenario())


def test_flight_record_async_matches_sync_record(tmp_path):
    fr = FlightRecorder(dir=str(tmp_path), min_interval=0.0)

    async def scenario():
        return await fr.record_async("argus_incident", detail="x")

    path = asyncio.run(scenario())
    assert path is not None
    header = json.loads(pathlib.Path(path).read_text().splitlines()[0])
    assert header["incident"] == "argus_incident"
    assert header["info"] == {"detail": "x"}


def test_flight_record_async_disabled_is_none():
    fr = FlightRecorder(dir=None)

    async def scenario():
        return await fr.record_async("nope")

    assert asyncio.run(scenario()) is None
