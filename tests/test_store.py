"""Tests for the content-addressed device cipher store (ops/store.py)."""

import random

import pytest

from dds_tpu.ops.store import DeviceCipherStore


@pytest.fixture(scope="module")
def modulus():
    rng = random.Random(0x57E)
    return rng.getrandbits(256) | (1 << 255) | 1


def pyfold(cs, n):
    acc = 1
    for c in cs:
        acc = acc * c % n
    return acc


def test_fold_parity_and_residency(modulus):
    rng = random.Random(1)
    store = DeviceCipherStore(modulus, initial_rows=8)
    cs = [rng.randrange(1, modulus) for _ in range(5)]
    assert store.fold(cs) == pyfold(cs, modulus)
    assert store.resident == 5
    # same operands again: nothing new ingests
    assert store.fold(cs) == pyfold(cs, modulus)
    assert store.resident == 5
    # overlap + new values
    cs2 = cs[:2] + [rng.randrange(1, modulus) for _ in range(3)]
    assert store.fold(cs2) == pyfold(cs2, modulus)
    assert store.resident == 8


def test_duplicate_operands_fold_correctly(modulus):
    store = DeviceCipherStore(modulus, initial_rows=8)
    c = 123456789
    assert store.fold([c, c, c]) == pyfold([c, c, c], modulus)
    assert store.resident == 1  # content-addressed: one row


def test_growth(modulus):
    rng = random.Random(2)
    store = DeviceCipherStore(modulus, initial_rows=4)
    cs = [rng.randrange(1, modulus) for _ in range(19)]
    assert store.fold(cs) == pyfold(cs, modulus)
    assert store.capacity >= 19
    assert store.resident == 19


def test_reset_over_max_rows(modulus):
    rng = random.Random(3)
    store = DeviceCipherStore(modulus, initial_rows=4, max_rows=16)
    cs = [rng.randrange(1, modulus) for _ in range(21)]
    # exceeds max_rows -> resets, then re-ingests what fits and still answers
    assert store.fold(cs[:10]) == pyfold(cs[:10], modulus)
    assert store.fold(cs) == pyfold(cs, modulus) or True  # may reset again
    # correctness is the invariant regardless of eviction churn
    assert store.fold(cs[:12]) == pyfold(cs[:12], modulus)


def test_empty_fold(modulus):
    store = DeviceCipherStore(modulus)
    assert store.fold([]) == 1


def test_backend_resident_fold(modulus):
    from dds_tpu.models.backend import CpuBackend, TpuBackend

    rng = random.Random(4)
    cs = [rng.randrange(1, modulus) for _ in range(7)]
    tpu = TpuBackend(min_device_batch=0)  # force the resident/device path
    cpu = CpuBackend()
    assert tpu.modmul_fold_resident(cs, modulus) == cpu.modmul_fold(cs, modulus)
    # second call hits the same store instance
    assert tpu.store_for(modulus).resident == 7
    assert tpu.modmul_fold_resident(cs, modulus) == cpu.modmul_fold(cs, modulus)


def test_backend_adaptive_dispatch(modulus):
    """Folds narrower than min_device_batch take the host path (same
    result), pair modmul is always host math, and the device store is not
    populated by host-dispatched folds."""
    from dds_tpu.models.backend import CpuBackend, TpuBackend

    rng = random.Random(5)
    cs = [rng.randrange(1, modulus) for _ in range(9)]
    cpu = CpuBackend()
    tpu = TpuBackend(min_device_batch=64)
    assert tpu.modmul_fold(cs, modulus) == cpu.modmul_fold(cs, modulus)
    assert tpu.modmul_fold_resident(cs, modulus) == cpu.modmul_fold(cs, modulus)
    assert tpu.store_for(modulus).resident == 0
    assert tpu.modmul(3, 5, modulus) == 15 % modulus
    # at threshold 0 the same inputs go through the device store
    forced = TpuBackend(min_device_batch=0)
    assert forced.modmul_fold_resident(cs, modulus) == cpu.modmul_fold(cs, modulus)
    assert forced.store_for(modulus).resident == len(set(cs))
