"""End-to-end REST tests: every route, real HTTP, real quorum, real crypto.

Mirrors the reference's only verification mode — a client driving the full
proxy/ABD stack over HTTP (SURVEY.md §4) — but as a deterministic pytest
suite. Clients encrypt with tier-1 schemes; the proxy computes over
ciphertexts through a CryptoBackend; results decrypt to the expected
plaintext values.
"""

import asyncio
import contextlib
import json
import random

import pytest

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.models import HEKeys, HomoProvider

rng = random.Random(5)
KEYS = HEKeys.generate(paillier_bits=512, rsa_bits=512)
PROVIDER = HomoProvider(KEYS)


@contextlib.asynccontextmanager
async def rest_stack(crypto_backend="cpu", n=7, quorum=5):
    net = InMemoryNet()
    rcfg = ReplicaConfig(quorum_size=quorum)
    addrs = [f"replica-{i}" for i in range(n)]
    replicas = {a: BFTABDNode(a, addrs, "supervisor", net, rcfg) for a in addrs}
    supervisor = BFTSupervisor(
        "supervisor", addrs, [], net,
        SupervisorConfig(quorum_size=quorum, proactive_recovery_enabled=False),
    )
    abd = AbdClient("proxy-0", net, addrs, AbdClientConfig(request_timeout=2.0))
    server = DDSRestServer(
        abd, ProxyConfig(host="127.0.0.1", port=0, crypto_backend=crypto_backend)
    )
    await server.start()
    try:
        yield server, replicas, supervisor
    finally:
        await server.stop()


async def call(server, method, target, obj=None):
    body = json.dumps(obj).encode() if obj is not None else None
    status, data = await http_request(
        "127.0.0.1", server.cfg.port, method, target, body, timeout=10.0
    )
    return status, data


def test_putset_getset_removeset():
    async def go():
        async with rest_stack() as (server, _, _):
            row = PROVIDER.encrypt_row([5, "alice", 100], 3, ["OPE", "CHE", "PSSE"])
            status, key = await call(server, "POST", "/PutSet", {"contents": row})
            assert status == 200
            key = key.decode()
            assert len(key) == 128  # sha-512 hex

            status, data = await call(server, "GET", f"/GetSet/{key}")
            assert status == 200
            assert json.loads(data)["contents"] == row

            status, _ = await call(server, "DELETE", f"/RemoveSet/{key}")
            assert status == 200
            status, _ = await call(server, "GET", f"/GetSet/{key}")
            assert status == 404

    asyncio.run(go())


def test_putset_empty_body_random_key():
    async def go():
        async with rest_stack() as (server, _, _):
            status, key = await call(server, "POST", "/PutSet")
            assert status == 200 and len(key.decode()) == 128
            # empty set stored as None -> GetSet gives 404 (same as reference)
            status, _ = await call(server, "GET", f"/GetSet/{key.decode()}")
            assert status == 404

    asyncio.run(go())


def test_element_routes():
    async def go():
        async with rest_stack() as (server, _, _):
            row = ["a", "b"]
            _, key = await call(server, "POST", "/PutSet", {"contents": row})
            key = key.decode()

            status, _ = await call(server, "PUT", f"/AddElement/{key}", {"value": "c"})
            assert status == 200
            status, data = await call(server, "GET", f"/ReadElement/{key}?position=2")
            assert status == 200 and json.loads(data)["value"] == "c"

            status, _ = await call(
                server, "PUT", f"/WriteElement/{key}?position=0", {"value": "z"}
            )
            assert status == 200
            _, data = await call(server, "GET", f"/GetSet/{key}")
            assert json.loads(data)["contents"] == ["z", "b", "c"]

            # position past end appends
            status, _ = await call(
                server, "PUT", f"/WriteElement/{key}?position=9", {"value": "w"}
            )
            assert status == 200
            _, data = await call(server, "GET", f"/GetSet/{key}")
            assert json.loads(data)["contents"] == ["z", "b", "c", "w"]

            status, data = await call(server, "POST", f"/IsElement/{key}", {"value": "b"})
            assert status == 200 and json.loads(data)["result"] is True
            status, data = await call(server, "POST", f"/IsElement/{key}", {"value": "q"})
            assert json.loads(data)["result"] is False

            status, _ = await call(server, "GET", f"/ReadElement/{key}?position=99")
            assert status == 404
            status, _ = await call(server, "GET", "/ReadElement/NOKEY?position=0")
            assert status == 404

    asyncio.run(go())


@pytest.mark.parametrize("backend", ["cpu", "tpu", "native"])
def test_sum_and_sumall_paillier(backend):
    async def go():
        async with rest_stack(crypto_backend=backend) as (server, _, _):
            pk = KEYS.psse.public
            vals = [rng.randrange(1 << 24) for _ in range(5)]
            keys = []
            for v in vals:
                row = [str(pk.encrypt(v))]
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                keys.append(key.decode())

            nsqr = pk.nsquare
            status, data = await call(
                server,
                "GET",
                f"/Sum?key1={keys[0]}&key2={keys[1]}&position=0&nsqr={nsqr}",
            )
            assert status == 200
            c = int(json.loads(data)["result"])
            assert KEYS.psse.decrypt(c) == vals[0] + vals[1]

            status, data = await call(server, "GET", f"/SumAll?position=0&nsqr={nsqr}")
            assert status == 200
            c = int(json.loads(data)["result"])
            assert KEYS.psse.decrypt(c) == sum(vals)

            # plain (no nsqr) falls back to integer addition of ciphertexts
            status, data = await call(
                server, "GET", f"/Sum?key1={keys[0]}&key2={keys[1]}&position=0"
            )
            assert status == 200

            # bad position -> 404
            status, _ = await call(
                server, "GET", f"/Sum?key1={keys[0]}&key2={keys[1]}&position=5&nsqr={nsqr}"
            )
            assert status == 404

    asyncio.run(go())


@pytest.mark.parametrize("backend", ["cpu", "tpu", "native"])
def test_mult_and_multall_rsa(backend):
    async def go():
        async with rest_stack(crypto_backend=backend) as (server, _, _):
            k = KEYS.mse
            vals = [rng.randrange(1 << 8) for _ in range(4)]
            for v in vals:
                row = [str(k.public.encrypt(v))]
                await call(server, "POST", "/PutSet", {"contents": row})

            status, data = await call(
                server, "GET", f"/MultAll?position=0&pubkey={k.n}"
            )
            assert status == 200
            c = int(json.loads(data)["result"])
            want = 1
            for v in vals:
                want *= v
            assert k.decrypt(c) == want

    asyncio.run(go())


def test_order_and_range_search_ope():
    async def go():
        async with rest_stack() as (server, _, _):
            vals = [50, -3, 1000, 7]
            key_by_val = {}
            for v in vals:
                row = [KEYS.ope.encrypt(v), "pad"]
                _, key = await call(server, "POST", "/PutSet", {"contents": row})
                key_by_val[v] = key.decode()

            _, data = await call(server, "GET", "/OrderLS?position=0")
            ordered = json.loads(data)["keyset"]
            assert ordered == [key_by_val[v] for v in sorted(vals, reverse=True)]

            _, data = await call(server, "GET", "/OrderSL?position=0")
            assert json.loads(data)["keyset"] == [key_by_val[v] for v in sorted(vals)]

            # range search: stored > 7  (ciphertext comparison)
            q = KEYS.ope.encrypt(7)
            _, data = await call(server, "POST", "/SearchGt?position=0", {"value": q})
            got = set(json.loads(data)["keyset"])
            assert got == {key_by_val[50], key_by_val[1000]}

            _, data = await call(server, "POST", "/SearchGtEq?position=0", {"value": q})
            assert set(json.loads(data)["keyset"]) == {
                key_by_val[7], key_by_val[50], key_by_val[1000]
            }
            _, data = await call(server, "POST", "/SearchLt?position=0", {"value": q})
            assert set(json.loads(data)["keyset"]) == {key_by_val[-3]}
            _, data = await call(server, "POST", "/SearchLtEq?position=0", {"value": q})
            assert set(json.loads(data)["keyset"]) == {key_by_val[-3], key_by_val[7]}

    asyncio.run(go())


def test_eq_search_det():
    async def go():
        async with rest_stack() as (server, _, _):
            c_bob = KEYS.che.encrypt("bob")
            c_eve = KEYS.che.encrypt("eve")
            _, k1 = await call(server, "POST", "/PutSet", {"contents": ["x", c_bob]})
            _, k2 = await call(server, "POST", "/PutSet", {"contents": ["y", c_eve]})
            k1, k2 = k1.decode(), k2.decode()

            _, data = await call(server, "POST", "/SearchEq?position=1", {"value": c_bob})
            assert json.loads(data)["keyset"] == [k1]
            _, data = await call(server, "POST", "/SearchNEq?position=1", {"value": c_bob})
            assert json.loads(data)["keyset"] == [k2]

    asyncio.run(go())


def test_entry_search_routes():
    async def go():
        async with rest_stack() as (server, _, _):
            ca, cb, cc = (KEYS.che.encrypt(s) for s in ("aa", "bb", "cc"))
            _, k1 = await call(server, "POST", "/PutSet", {"contents": [ca, cb, cc]})
            _, k2 = await call(server, "POST", "/PutSet", {"contents": [ca, "zz", "ww"]})
            k1, k2 = k1.decode(), k2.decode()

            _, data = await call(server, "POST", "/SearchEntry", {"value": ca})
            assert set(json.loads(data)["keyset"]) == {k1, k2}

            trip = {"value1": ca, "value2": cb, "value3": cc}
            _, data = await call(server, "POST", "/SearchEntryOR", trip)
            assert set(json.loads(data)["keyset"]) == {k1, k2}
            _, data = await call(server, "POST", "/SearchEntryAND", trip)
            assert json.loads(data)["keyset"] == [k1]

    asyncio.run(go())


def test_sync_gossip_ingest():
    async def go():
        async with rest_stack() as (server, _, _):
            status, _ = await call(
                server, "POST", "/_sync", {"keyset": ["AAA", "BBB"]}
            )
            assert status == 204
            assert {"AAA", "BBB"} <= server.stored_keys

    asyncio.run(go())


def test_unknown_route_and_bad_body():
    async def go():
        async with rest_stack() as (server, _, _):
            status, _ = await call(server, "GET", "/Nope")
            assert status == 404
            status, _ = await call(server, "POST", "/PutSet", {"wrong": 1})
            assert status == 400
            status, _ = await call(server, "POST", "/SearchEq?position=0", {"v": 1})
            assert status == 400

    asyncio.run(go())


def test_proxy_gossip_between_two_proxies():
    async def go():
        async with rest_stack() as (s1, replicas, _):
            net = s1.abd.net
            abd2 = AbdClient("proxy-1", net, list(replicas), AbdClientConfig(request_timeout=2.0))
            s2 = DDSRestServer(
                abd2,
                ProxyConfig(
                    host="127.0.0.1",
                    port=0,
                    key_sync_enabled=True,
                    key_sync_warmup=0.05,
                    key_sync_interval=0.2,
                    peers=[f"127.0.0.1:{s1.cfg.port}"],
                ),
            )
            await s2.start()
            try:
                _, key = await call(s2, "POST", "/PutSet", {"contents": [1, 2]})
                await asyncio.sleep(0.4)  # let gossip fire
                assert key.decode() in s1.stored_keys
                # proxy-1's record is aggregatable via proxy-0 now
                _, data = await call(s1, "GET", "/SumAll?position=0")
                assert json.loads(data)["result"] == "1"
            finally:
                await s2.stop()

    asyncio.run(go())


def test_negative_position_rejected():
    async def go():
        async with rest_stack() as (server, _, _):
            _, key = await call(server, "POST", "/PutSet", {"contents": ["a", "b"]})
            key = key.decode()
            status, _ = await call(server, "GET", f"/ReadElement/{key}?position=-1")
            assert status == 400
            status, _ = await call(server, "GET", "/SumAll?position=-1&nsqr=9")
            assert status == 400


    asyncio.run(go())


def test_removeset_stops_aggregation():
    async def go():
        async with rest_stack() as (server, _, _):
            _, k1 = await call(server, "POST", "/PutSet", {"contents": [5]})
            _, k2 = await call(server, "POST", "/PutSet", {"contents": [7]})
            await call(server, "DELETE", f"/RemoveSet/{k1.decode()}")
            assert k1.decode() not in server.stored_keys
            _, data = await call(server, "GET", "/SumAll?position=0")
            assert json.loads(data)["result"] == "7"

    asyncio.run(go())


def test_sumall_executes_sharded_on_mesh(monkeypatch):
    """End-to-end §5.7: a proxy `SumAll` on a 4-device mesh runs the fold
    through the sharded kernel and still decrypts correctly."""
    from dds_tpu.models.backend import TpuBackend
    from dds_tpu.parallel import mesh as pm
    from dds_tpu.parallel.mesh import make_mesh

    calls = {"n": 0}
    orig = pm.sharded_reduce_mul_fixed

    def spy(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(pm, "sharded_reduce_mul_fixed", spy)

    async def go():
        async with rest_stack() as (server, _, _):
            server.backend = TpuBackend(
                pallas=False, min_device_batch=0, mesh=make_mesh(4)
            )
            pk = PROVIDER.keys.psse.public
            vals = [7, 8, 9, 10, 11]
            for v in vals:
                row = PROVIDER.encrypt_row([v], 1, ["PSSE"])
                await call(server, "POST", "/PutSet", {"contents": row})
            _, data = await call(
                server, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}"
            )
            got = PROVIDER.keys.psse.decrypt(int(json.loads(data)["result"]))
            assert got == sum(vals)
            assert calls["n"] >= 1  # the fold actually went through the mesh

    asyncio.run(go())


def test_trace_route_reports_span_summary():
    """GET /_trace exposes the live tracer summary: after a PutSet and a
    GetSet, the quorum spans appear with counts and millisecond stats."""

    async def go():
        async with rest_stack() as (server, _, _):
            from dds_tpu.utils.trace import tracer

            tracer.reset()
            row = PROVIDER.encrypt_row([5], 1, ["PSSE"])
            _, key = await call(server, "POST", "/PutSet", {"contents": row})
            await call(server, "GET", f"/GetSet/{key.decode()}")
            status, _ = await call(server, "GET", "/_trace")
            assert status == 404  # gated off by default (workload shape)
            server.cfg.trace_route_enabled = True
            status, data = await call(server, "GET", "/_trace")
            assert status == 200
            body = json.loads(data)
            assert body["stored_keys"] == 1
            spans = body["spans"]
            assert spans["abd.write"]["count"] >= 1
            assert spans["abd.fetch"]["count"] >= 1
            assert spans["http.POST.PutSet"]["mean_ms"] > 0

    asyncio.run(go())


def test_stored_keys_survive_proxy_restart_via_snapshot(tmp_path):
    """SURVEY.md §7 do-not-copy quirk: the reference loses the proxy's
    aggregate key set on restart, silently shrinking every SumAll. With
    keys_path set, a fresh server object (modeling the restarted process)
    recovers the keys from the snapshot and folds ALL K sets."""

    async def go():
        snap = str(tmp_path / "proxy_keys.json")
        net = InMemoryNet()
        addrs = [f"replica-{i}" for i in range(7)]
        replicas = {
            a: BFTABDNode(a, addrs, "supervisor", net, ReplicaConfig(quorum_size=5))
            for a in addrs
        }
        del replicas  # replicas only need to exist on the net
        abd = AbdClient("proxy-0", net, addrs, AbdClientConfig(request_timeout=2.0))
        pk = KEYS.psse.public
        vals = [rng.randrange(1 << 24) for _ in range(6)]

        s1 = DDSRestServer(abd, ProxyConfig(host="127.0.0.1", port=0, keys_path=snap))
        await s1.start()
        try:
            for v in vals:
                row = [str(pk.encrypt(v))]
                status, _ = await call(s1, "POST", "/PutSet", {"contents": row})
                assert status == 200
            _, data = await call(s1, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}")
            assert KEYS.psse.decrypt(int(json.loads(data)["result"])) == sum(vals)
        finally:
            await s1.stop()  # flushes the debounced snapshot

        # "restart": brand-new server object, same snapshot path
        s2 = DDSRestServer(abd, ProxyConfig(host="127.0.0.1", port=0, keys_path=snap))
        await s2.start()
        try:
            assert len(s2.stored_keys) == len(vals)  # recovered, not empty
            _, data = await call(s2, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}")
            got = KEYS.psse.decrypt(int(json.loads(data)["result"]))
            assert got == sum(vals)  # did NOT silently shrink
        finally:
            await s2.stop()

    asyncio.run(go())


def test_stored_keys_bootstrap_pull_from_peer_on_start():
    """A proxy restarted WITHOUT a snapshot recovers stored_keys by pulling
    GET /_sync from its gossip peers at start, instead of waiting for the
    next periodic push."""

    async def go():
        net = InMemoryNet()
        addrs = [f"replica-{i}" for i in range(7)]
        replicas = {
            a: BFTABDNode(a, addrs, "supervisor", net, ReplicaConfig(quorum_size=5))
            for a in addrs
        }
        del replicas
        abd1 = AbdClient("proxy-0", net, addrs, AbdClientConfig(request_timeout=2.0))
        abd2 = AbdClient("proxy-1", net, addrs, AbdClientConfig(request_timeout=2.0))
        pk = KEYS.psse.public
        vals = [3, 5, 11]

        # serving side of the pull is gated on key_sync_enabled too (with
        # gossip off, GET /_sync would leak the record-key set to clients)
        s1 = DDSRestServer(
            abd1,
            ProxyConfig(host="127.0.0.1", port=0, key_sync_enabled=True,
                        key_sync_warmup=60.0, key_sync_interval=60.0),
        )
        await s1.start()
        try:
            for v in vals:
                await call(s1, "POST", "/PutSet", {"contents": [str(pk.encrypt(v))]})
            # gossip-off proxies refuse the pull (info leak gate)
            st, _ = await call(s1, "GET", "/_sync")
            assert st == 200
            s_off = DDSRestServer(abd2, ProxyConfig(host="127.0.0.1", port=0))
            await s_off.start()
            st, _ = await call(s_off, "GET", "/_sync")
            assert st == 404
            await s_off.stop()
            # restarted peer: no snapshot, pulls from s1 at start (long
            # gossip interval proves it's the pull, not a push, that fills it)
            s2 = DDSRestServer(
                abd2,
                ProxyConfig(
                    host="127.0.0.1", port=0, key_sync_enabled=True,
                    key_sync_warmup=60.0, key_sync_interval=60.0,
                    peers=[f"127.0.0.1:{s1.cfg.port}"],
                ),
            )
            await s2.start()
            try:
                assert len(s2.stored_keys) == len(vals)
                _, data = await call(
                    s2, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}"
                )
                got = KEYS.psse.decrypt(int(json.loads(data)["result"]))
                assert got == sum(vals)
            finally:
                await s2.stop()
        finally:
            await s1.stop()

    asyncio.run(go())


def test_concurrent_small_sumalls_coalesce_into_one_dispatch():
    """R concurrent below-crossover SumAlls must share ONE segmented device
    dispatch (ops/foldmany) and still decrypt to the right totals — the
    cross-request batching of r4 verdict #2."""
    from dds_tpu.models.backend import TpuBackend

    import threading

    async def go():
        async with rest_stack() as (server, _, _):
            # each fold (K=6) is below the crossover (10) so requests enter
            # the window; a group's combined width (>=2 x 6) clears it, so
            # the coalesced dispatch goes to the device
            be = TpuBackend(pallas=False, min_device_batch=10)
            calls = {"many": 0, "single": 0}
            orig_many = be.modmul_fold_many
            orig_res = be.modmul_fold_resident
            # Event-driven determinism (the old form raced the burst
            # against a 2 ms window and hoped): the FIRST host fold — the
            # direct path the first arrival takes — blocks on `coalesced`
            # until a coalesced device dispatch has actually run, so the
            # concurrency signal (folds in flight) deterministically holds
            # open while the rest of the burst piles into the window. The
            # drainer runs on the event loop, never behind this
            # worker-thread wait, so the release is guaranteed; the wider
            # window just keeps the burst in one drain cycle.
            coalesced = threading.Event()

            def gated_single(cs, mod):
                calls["single"] += 1
                if calls["single"] == 1:
                    assert coalesced.wait(30), "coalesced dispatch never ran"
                return orig_res(cs, mod)

            def counting_many(folds, mod):
                calls["many"] += 1
                coalesced.set()
                return orig_many(folds, mod)

            be.modmul_fold_many = counting_many
            be.modmul_fold_resident = gated_single
            server.backend = be
            server.cfg.coalesce_window = 0.05
            pk = KEYS.psse.public
            vals = [rng.randrange(1 << 24) for _ in range(6)]
            for v in vals:
                await call(server, "POST", "/PutSet", {"contents": [str(pk.encrypt(v))]})

            # 5 concurrent SumAlls: the first (no observed concurrency)
            # takes the host path and holds the in-flight signal; every
            # later arrival sees it and coalesces into ONE device
            # dispatch. Assert the shape: at least one coalesced dispatch
            # happened, every result is correct, and dispatches never
            # exceeded request count.
            results = await asyncio.gather(*(
                call(server, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}")
                for _ in range(5)
            ))
            for status, data in results:
                assert status == 200
                assert KEYS.psse.decrypt(int(json.loads(data)["result"])) == sum(vals)
            assert calls["many"] >= 1
            assert calls["many"] + calls["single"] < 5

            # a lone small aggregate pays NO window: straight host path
            # (deterministic: nothing in flight, nothing pending)
            before = dict(calls)
            status, data = await call(
                server, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}"
            )
            assert status == 200
            assert KEYS.psse.decrypt(int(json.loads(data)["result"])) == sum(vals)
            assert calls["many"] == before["many"]
            assert calls["single"] == before["single"] + 1

            # window 0 disables coalescing entirely
            server.cfg.coalesce_window = 0.0
            before = dict(calls)
            await asyncio.gather(*(
                call(server, "GET", f"/SumAll?position=0&nsqr={pk.nsquare}")
                for _ in range(3)
            ))
            assert calls["many"] == before["many"]
            assert calls["single"] == before["single"] + 3

    asyncio.run(go())


def test_coalesced_dispatch_failure_fails_all_waiters_cleanly():
    """A failing coalesced device dispatch must surface as 500s to every
    waiting request (never a hang) and leave the coalescer reusable for
    the next, healthy, burst."""
    from dds_tpu.models.backend import TpuBackend

    async def go():
        async with rest_stack() as (server, _, _):
            be = TpuBackend(pallas=False, min_device_batch=10)
            boom = {"on": True}
            orig_many = be.modmul_fold_many
            orig_resident = be.modmul_fold_resident

            def maybe_boom(folds, mod):
                if boom["on"]:
                    raise RuntimeError("device fell off")
                return orig_many(folds, mod)

            def slow_host(cs, mod):
                # hold the concurrency signal open so the rest of the burst
                # deterministically piles into the coalescing window
                import time as _time

                _time.sleep(0.05)
                return orig_resident(cs, mod)

            be.modmul_fold_many = maybe_boom
            be.modmul_fold_resident = slow_host
            server.backend = be
            pk = KEYS.psse.public
            vals = [2, 3, 5, 7, 11, 13]
            for v in vals:
                await call(server, "POST", "/PutSet", {"contents": [str(pk.encrypt(v))]})

            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            results = await asyncio.wait_for(
                asyncio.gather(*(call(server, "GET", target) for _ in range(5))),
                timeout=15,
            )
            statuses = sorted(st for st, _ in results)
            # the first (host-path) request succeeds; the coalesced group
            # all get the failure as 500s — nobody hangs
            assert statuses[0] == 200 and statuses[-1] == 500
            assert statuses.count(500) >= 1

            # coalescer recovers once the backend is healthy again
            boom["on"] = False
            results = await asyncio.wait_for(
                asyncio.gather(*(call(server, "GET", target) for _ in range(5))),
                timeout=15,
            )
            for st, data in results:
                assert st == 200
                assert KEYS.psse.decrypt(int(json.loads(data)["result"])) == sum(vals)

    asyncio.run(go())
