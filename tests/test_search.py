"""Spyglass encrypted-search tests (dds_tpu/search + ops/predicate).

Covers the ISSUE 13 acceptance surface: predicate kernels bit-for-bit
against host references (packed OPE lanes, digest candidates + confirm,
stable sort permutations, host fallbacks for unpackable columns), every
Search*/Order*/Range route answering identically through the indexed
plane and the legacy scan (same server, same keys — ties included), S=4
vs S=1 row-for-row, exactly ONE batched `abd.read_tags` round and zero
per-key ABD reads per warm query, seeded-ChaosNet writes racing queries
(stale entries detected via the tag round and repaired, zero Watchtower
verdicts), the satellite regressions (Order* position validation and
missing-column exclusion, SearchEntry* triplet parsing, empty-store
consistency, pagination), the /health + /metrics surface, and the
sentry `search latency` record contract.

Values are synthetic ints/strings throughout: DET-style equality runs on
plain strings via `DetKey.compare` (pure hmac), so nothing here needs
the AES-backed schemes.
"""

import asyncio
import json
import random

import pytest

from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.obs.metrics import metrics
from dds_tpu.obs.watchtower import watchtower
from dds_tpu.search import GroupIndex, SearchPlane
from dds_tpu.utils.config import SearchConfig
from dds_tpu.utils.trace import tracer

pytestmark = pytest.mark.search

rng = random.Random(0x5EEC)


def _metric(name, **labels):
    return metrics.value(name, **labels) or 0


def _violations() -> int:
    return sum(watchtower.stats()["violations"].values())


# ------------------------------------------------------------ kernel parity


def test_group_index_kernels_match_host_reference():
    """Every GroupIndex eval against a plain-Python reference over a
    column with ties, and again over an unpackable column (negatives +
    >2^52 ints) that must take the host fallback."""
    idx = GroupIndex()
    vals = [rng.randrange(0, 1 << 45) for _ in range(40)]
    vals[7] = vals[3]  # ties exercise the stable sort
    vals[21] = vals[3]
    rows = {}
    for i, v in enumerate(vals):
        key = f"k{i:03d}"
        rows[key] = [v, f"label{i % 5}", (-v if i % 3 else v << 12)]
        idx.upsert(key, i + 1, rows[key])
    pairs = sorted(rows.items())
    thr = sorted(vals)[len(vals) // 2]

    for pos in (0, 2):  # 0 = packed kernel path, 2 = host fallback
        col = {k: v[pos] for k, v in pairs}
        for op, ref in (("gt", lambda a, b: a > b), ("ge", lambda a, b: a >= b),
                        ("lt", lambda a, b: a < b), ("le", lambda a, b: a <= b)):
            t = thr if pos == 0 else -thr
            assert idx.eval_compare(pos, op, t) == \
                {k for k, v in col.items() if ref(v, t)}, (pos, op)
        lo_b, hi_b = sorted(col.values())[10], sorted(col.values())[30]
        assert idx.eval_range(pos, lo_b, hi_b) == \
            {k for k, v in col.items() if lo_b <= v <= hi_b}
        for desc in (False, True):
            got = idx.eval_order(pos, desc)
            want = sorted(col.items(), key=lambda t: t[1], reverse=desc)
            assert [k for _, k in got] == [k for k, _ in want], (pos, desc)
    # out-of-band thresholds resolve without touching the packed kernel
    assert idx.eval_compare(0, "ge", -5) == {k for k, _ in pairs}
    assert idx.eval_compare(0, "gt", 1 << 60) == set()
    assert idx.eval_range(0, -(1 << 60), 1 << 60) == {k for k, _ in pairs}

    assert idx.eval_eq(1, "label2", True) == \
        {k for k, v in pairs if str(v[1]) == "label2"}
    assert idx.eval_eq(1, "label2", False) == \
        {k for k, v in pairs if str(v[1]) != "label2"}
    assert idx.eval_entry(["label0", "nope", "label4"], "any") == \
        {k for k, v in pairs
         if any(str(e) in ("label0", "nope", "label4") for e in v)}
    some_v = str(pairs[4][1][0])
    assert idx.eval_entry([some_v, "label4"], "all") == \
        {k for k, v in pairs
         if all(any(str(e) == q for e in v) for q in (some_v, "label4"))}


def test_group_index_tombstone_and_tag_discipline():
    idx = GroupIndex()
    idx.upsert("a", 3, [1, "x"])
    idx.upsert("a", 2, [9, "old"])  # older tag must NOT win
    assert idx.eval_compare(0, "ge", 0) == {"a"}
    idx.upsert("a", 4, None)  # tombstone: validatable tag, no rows
    assert idx.tag("a") == 4
    assert idx.eval_compare(0, "ge", 0) == set()
    assert idx.eval_eq(1, "x", True) == set()
    idx.upsert("a", None, [5])  # tag-less writes are never indexed
    assert idx.tag("a") == 4


def test_search_plane_ingest_queue_and_invalidation():
    plane = SearchPlane(max_pending=2)
    plane.register_groups(["s0", "s1"])
    assert plane.note_write("s0", "k1", 1, [5])
    assert plane.note_write("s1", "k2", 1, [6])
    assert not plane.note_write("s0", "k3", 1, [7])  # bounded: dropped
    assert plane.stats()["dropped"] == 1
    assert plane.ingest_pending() == 2
    assert plane.group("s0").tag("k1") == 1
    assert len(plane.group("s1")) == 1
    plane.invalidate()
    st = plane.stats()
    assert st["indexed_keys"] == 0 and st["invalidations"] == 1
    assert st["pending_ingest"] == 0
    plane.export_gauges(metrics)
    assert metrics.value("dds_search_invalidations") == 1


# --------------------------------------------------------- REST route parity

# pos 0: distinct packable ints (kernel compare/order/range); pos 1:
# duplicated labels (DET eq + entry); pos 2: distinct negatives/huge ints
# (host-fallback compare/order), absent on one row (exclusion semantics)
ROWS = [
    [100, "red", -3],
    [250, "blue", 1 << 60],
    [17, "green"],
    [999, "blue", 0],
    [42, "red", 7],
    [500, "yellow", -40],
    [77, "red", 12],
    [360, "green", 5],
]

QUERIES = [
    ("GET", "/OrderLS?position=0", None),
    ("GET", "/OrderSL?position=0", None),
    ("GET", "/OrderSL?position=2", None),
    ("POST", "/SearchEq?position=1", {"value": "red"}),
    ("POST", "/SearchNEq?position=1", {"value": "blue"}),
    ("POST", "/SearchGt?position=0", {"value": 100}),
    ("POST", "/SearchGtEq?position=0", {"value": 100}),
    ("POST", "/SearchLt?position=0", {"value": 360}),
    ("POST", "/SearchLtEq?position=0", {"value": 360}),
    ("POST", "/SearchGt?position=2", {"value": 0}),
    ("POST", "/Range?position=0", {"value1": 42, "value2": 500}),
    ("POST", "/SearchEntry", {"value": "red"}),
    ("POST", "/SearchEntryOR",
     {"value1": "red", "value2": "17", "value3": "nope"}),
    ("POST", "/SearchEntryAND",
     {"value1": "red", "value2": "7", "value3": "42"}),
]


def _spy_server(S, enabled=True, net=None, write_ingest=True,
                ingest_window=0.001):
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.shard import build_constellation

    net = net or InMemoryNet()
    const = build_constellation(net, shard_count=S, vnodes_per_group=8,
                                seed=3, n_active=4, n_sentinent=0, quorum=3)
    cfg = ProxyConfig(
        port=0, crypto_backend="cpu",
        search=SearchConfig(enabled=enabled, write_ingest=write_ingest,
                            ingest_window=ingest_window),
    )
    server = DDSRestServer(const.router, cfg)
    return server, const


async def _put_rows(server, rows):
    key_to_row = {}
    for i, row in enumerate(rows):
        st, body = await http_request(
            "127.0.0.1", server.cfg.port, "POST", "/PutSet",
            json.dumps({"contents": row}).encode(), timeout=10.0,
        )
        assert st == 200
        key_to_row[body.decode()] = i
    return key_to_row


async def _query(server, method, target, obj=None, expect=200):
    body = json.dumps(obj).encode() if obj is not None else None
    st, out = await http_request(
        "127.0.0.1", server.cfg.port, method, target, body, timeout=30.0,
    )
    assert st == expect, (target, st, out[:200])
    return json.loads(out)["keyset"] if st == 200 else None


async def _both_paths(server, method, target, obj=None):
    """(indexed, legacy) keysets for one request on the SAME server —
    the legacy scan is forced by unplugging the plane, so both paths see
    identical keys and the comparison is exact, ties included."""
    indexed = await _query(server, method, target, obj)
    plane, server._search = server._search, None
    try:
        legacy = await _query(server, method, target, obj)
    finally:
        server._search = plane
    return indexed, legacy


def test_indexed_routes_bit_for_bit_vs_legacy_and_across_shards():
    """Acceptance (ISSUE 13): every search/order/range route answers
    bit-for-bit the legacy scan's keyset on the same store (S=1 and
    S=4), and S=4 equals S=1 row-for-row over identical contents."""

    async def serve(S):
        server, const = _spy_server(S)
        await server.start()
        try:
            key_to_row = await _put_rows(server, ROWS)
            if S > 1:  # scatter-gather really spans multiple groups
                assert len(server._spy_partition(
                    sorted(server.stored_keys))) > 1
            out = []
            for method, target, obj in QUERIES:
                indexed, legacy = await _both_paths(server, method, target, obj)
                assert indexed == legacy, (S, target)
                out.append([key_to_row[k] for k in indexed])
            # pagination parity rides the same store: slices of the full
            # ordered keyset, identical across paths
            full = await _query(server, "GET", "/OrderSL?position=0")
            for q in ("offset=2", "limit=3", "offset=1&limit=2",
                      "offset=50", "limit=0"):
                got, leg = await _both_paths(
                    server, "GET", f"/OrderSL?position=0&{q}")
                assert got == leg, q
                off = int(q.split("offset=")[1].split("&")[0]) \
                    if "offset" in q else 0
                lim = int(q.split("limit=")[1]) if "limit" in q else None
                end = None if lim is None else off + lim
                assert got == full[off:end], q
            return out
        finally:
            await server.stop()
            await const.stop()

    async def go():
        single = await serve(1)
        sharded = await serve(4)
        assert sharded == single  # row-for-row across shard counts

    asyncio.run(go())


def test_order_ties_stay_stable_across_paths():
    """Tied order-column values: the device sort's tie order (ascending
    key, via the stable complemented-lane sort and the heapq merge) must
    equal the legacy stable sorted() exactly, ascending and descending."""
    # all rows distinct (keys are content-derived) but pos-0 heavily tied
    rows = [[5, i] for i in range(6)] + [[2, 9], [8, 1], [5, 77]]

    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            await _put_rows(server, rows)
            for route in ("/OrderSL?position=0", "/OrderLS?position=0"):
                indexed, legacy = await _both_paths(server, "GET", route)
                assert indexed == legacy and len(indexed) == len(rows), route
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_warm_query_is_one_tag_round_and_one_kernel_dispatch():
    """Acceptance (ISSUE 13): a warm indexed query spends exactly ONE
    batched tag-validation round — a single `abd.read_tags` span at S=1,
    one concurrent per-group span per non-empty shard group at S=4 (the
    scatter side of the same single round) — ZERO per-key ABD value
    reads, and dispatches the predicate kernel. Asserted via trace
    spans."""

    async def serve(S):
        server, const = _spy_server(S)
        await server.start()
        try:
            await _put_rows(server, ROWS)
            # cold pass: misses repaired through full reads + re-ingest
            await _query(server, "POST", "/SearchGtEq?position=0",
                         {"value": 0})
            groups = len(server._spy_partition(sorted(server.stored_keys)))
            tracer.reset()
            got = await _query(server, "POST", "/SearchGtEq?position=0",
                               {"value": 0})
            assert len(got) == len(ROWS)
            spans = tracer.summary()
            want_rounds = 1 if S == 1 else groups
            assert spans.get("abd.read_tags", {}).get("count") \
                == want_rounds, spans
            assert "abd.fetch" not in spans, spans
            assert spans.get("kernel.predicate.dispatch", {}).get("count", 0) \
                >= 1, spans
            assert spans.get("proxy.search_eval", {}).get("count") == 1
        finally:
            await server.stop()
            await const.stop()

    async def go():
        await serve(1)
        await serve(4)

    asyncio.run(go())


def test_chaosnet_racing_writes_detected_stale_and_repaired():
    """Acceptance (ISSUE 13): under a seeded ChaosNet with delivery
    delays, writes racing indexed queries (write-path ingest OFF, so the
    index can only learn through the freshness protocol) are detected as
    stale by the one tag round, repaired through full reads, and the
    final results are bit-for-bit the legacy scan's — with zero
    Watchtower verdicts."""
    from dds_tpu.core.chaos import ChaosNet, LinkFaults
    from dds_tpu.core.transport import InMemoryNet

    async def go():
        net = ChaosNet(InMemoryNet(), seed=909)
        server, const = _spy_server(2, net=net, write_ingest=False)
        await server.start()
        v0 = _violations()
        try:
            for g in range(2):
                for i in range(4):
                    net.set_dest(f"s{g}-replica-{i}",
                                 LinkFaults(delay=0.001, jitter=0.003))
            key_to_row = await _put_rows(
                server, [[(i + 1) * 10, f"c{i % 3}"] for i in range(6)]
            )
            keys = sorted(key_to_row, key=key_to_row.get)
            await _query(server, "GET", "/OrderSL?position=0")  # warm

            wrote = {}

            async def writer():
                w = random.Random(31)
                for n in range(10):
                    k = keys[w.randrange(len(keys))]
                    val = 1000 + n
                    st, _ = await http_request(
                        "127.0.0.1", server.cfg.port, "PUT",
                        f"/WriteElement/{k}?position=0",
                        json.dumps({"value": val}).encode(), timeout=30.0,
                    )
                    assert st == 200
                    wrote[k] = val
                    await asyncio.sleep(0.002)

            async def querier():
                for _ in range(8):
                    got = await _query(server, "POST",
                                       "/SearchGtEq?position=0", {"value": 0})
                    assert set(got) <= set(keys)  # sane mid-race snapshots
                    await asyncio.sleep(0.003)

            stale0 = _metric("dds_search_index_total", outcome="stale")
            await asyncio.gather(writer(), querier())
            # one deterministic post-race write: with write-path ingest
            # off, the ONLY way the next query can see it is by the tag
            # round flagging the key stale — so the stale counter must
            # move even if the racing queries all lost their races
            st, _ = await http_request(
                "127.0.0.1", server.cfg.port, "PUT",
                f"/WriteElement/{keys[0]}?position=0",
                json.dumps({"value": 5000}).encode(), timeout=30.0,
            )
            assert st == 200
            wrote[keys[0]] = 5000
            # the post-race store: every overwrite must be visible to the
            # indexed path (detected stale, repaired), bit-for-bit legacy
            final = {k: wrote.get(k, (key_to_row[k] + 1) * 10) for k in keys}
            indexed, legacy = await _both_paths(
                server, "POST", "/SearchGt?position=0", {"value": 500})
            assert indexed == legacy
            assert set(indexed) == {k for k, v in final.items() if v > 500}
            order, order_legacy = await _both_paths(
                server, "GET", "/OrderLS?position=0")
            assert order == order_legacy
            assert order == [k for k, _ in sorted(
                final.items(), key=lambda t: (-t[1], t[0]))]
            assert _metric("dds_search_index_total", outcome="stale") \
                > stale0  # the tag round really did catch racing writes
            assert _violations() == v0  # zero Watchtower verdicts
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_removeset_tombstones_the_index_entry():
    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            key_to_row = await _put_rows(server, [[5, "a"], [9, "b"]])
            gone = next(k for k, i in key_to_row.items() if i == 1)
            await _query(server, "POST", "/SearchGtEq?position=0",
                         {"value": 0})  # warm
            st, _ = await http_request(
                "127.0.0.1", server.cfg.port, "DELETE", f"/RemoveSet/{gone}",
                timeout=10.0)
            assert st == 200
            indexed, legacy = await _both_paths(
                server, "POST", "/SearchGtEq?position=0", {"value": 0})
            assert indexed == legacy and gone not in indexed
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


# ------------------------------------------------- satellite b: Order* 400s


def test_order_routes_validate_position_and_exclude_short_rows():
    """Satellite (b): Order* no longer coerces missing columns to -inf —
    short rows are EXCLUDED; non-integer columns and bad positions are a
    400 on BOTH paths, per route."""
    rows = [[5, 100], [3], [9, 50]]  # row [3] lacks position 1

    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            key_to_row = await _put_rows(server, rows)
            short = next(k for k, i in key_to_row.items() if i == 1)
            for route in ("OrderLS", "OrderSL"):
                indexed, legacy = await _both_paths(
                    server, "GET", f"/{route}?position=1")
                assert indexed == legacy
                assert short not in indexed and len(indexed) == 2, route
                for path in ("indexed", "legacy"):
                    plane = server._search
                    if path == "legacy":
                        server._search = None
                    try:
                        # non-numeric position / negative / missing: 400
                        for q in ("position=zz", "position=-1", ""):
                            await _query(server, "GET", f"/{route}?{q}",
                                         expect=400)
                    finally:
                        server._search = plane
            # a non-integer COLUMN is a 400 on both paths too (the int()
            # contract every Search*/Order* route shares)
            await _put_rows(server, [[7, "not-a-number"]])
            for route in ("OrderLS", "OrderSL"):
                i400, l400 = None, None
                i400 = await _query(server, "GET", f"/{route}?position=1",
                                    expect=400)
                plane, server._search = server._search, None
                try:
                    l400 = await _query(server, "GET", f"/{route}?position=1",
                                        expect=400)
                finally:
                    server._search = plane
                assert i400 is None and l400 is None
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


# --------------------------------------- satellite c: triplet edge + empties


def test_entry_triplet_parsing_edge_cases():
    """Satellite (c): SearchEntryOR/AND triplet parsing — non-triplet
    bodies 400 on both paths; duplicate triplet values behave like the
    single-query SearchEntry."""

    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            await _put_rows(server, ROWS)
            bad_bodies = [
                {"value1": "red", "value2": "blue"},   # missing value3
                {"value": "red"},                      # item, not triplet
                ["red", "blue", "green"],              # not a dict
                {},
            ]
            for route in ("SearchEntryOR", "SearchEntryAND"):
                for body in bad_bodies:
                    await _query(server, "POST", f"/{route}", body,
                                 expect=400)
                    plane, server._search = server._search, None
                    try:
                        await _query(server, "POST", f"/{route}", body,
                                     expect=400)
                    finally:
                        server._search = plane
            # duplicated triplet values degenerate to the single query
            dup = {"value1": "red", "value2": "red", "value3": "red"}
            single = await _query(server, "POST", "/SearchEntry",
                                  {"value": "red"})
            for route in ("SearchEntryOR", "SearchEntryAND"):
                got, legacy = await _both_paths(server, "POST", f"/{route}",
                                                dup)
                assert got == legacy == single, route
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_empty_store_answers_empty_keyset_on_every_route():
    """Satellite (c): every search/order/range route on an EMPTY store is
    200 {"keyset": []} — indexed and legacy alike."""

    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            for method, target, obj in QUERIES:
                indexed, legacy = await _both_paths(server, method, target,
                                                    obj)
                assert indexed == legacy == [], target
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_pagination_params_validated():
    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            await _put_rows(server, ROWS)
            for q in ("offset=-1", "limit=-2", "offset=zz", "limit=zz"):
                await _query(server, "GET", f"/OrderSL?position=0&{q}",
                             expect=400)
                plane, server._search = server._search, None
                try:
                    await _query(server, "GET", f"/OrderSL?position=0&{q}",
                                 expect=400)
                finally:
                    server._search = plane
            # Range body contract: both bounds required, ints only
            await _query(server, "POST", "/Range?position=0",
                         {"value1": 3}, expect=400)
            await _query(server, "POST", "/Range?position=0",
                         {"value1": "x", "value2": 5}, expect=400)
            # inverted bounds are a valid, empty selection
            got, legacy = await _both_paths(
                server, "POST", "/Range?position=0",
                {"value1": 500, "value2": 42})
            assert got == legacy == []
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


# ------------------------------------------------------- surface + contract


def test_health_metrics_and_slo_class_surface():
    async def go():
        server, const = _spy_server(2)
        await server.start()
        try:
            await _put_rows(server, ROWS)
            await _query(server, "POST", "/SearchEq?position=1",
                         {"value": "red"})
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", "/health", timeout=10.0)
            assert st == 200
            health = json.loads(body)
            assert health["search"]["indexed_keys"] == len(ROWS)
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", "/metrics", timeout=10.0)
            text = body.decode()
            assert 'dds_search_index_keys{shard="s' in text
            assert "dds_search_pending_ingest" in text
            assert 'dds_search_requests_total{' in text
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", "/slo", timeout=10.0)
            slo = json.loads(body)["slo"]
            assert slo["routes"]["SearchEq"]["class"] == "search"
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_sentry_search_record_contract(tmp_path):
    from benchmarks.sentry import _check_search_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "search latency (gt, N=96)", "value": 480.0,
        "unit": "queries/s", "vs_baseline": 17.9,
        "detail": {"op": "gt", "rows": 96, "hits": 48,
                   "legacy_ms": 38.1, "indexed_ms": 2.1},
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_search_records(str(tmp_path)) == {"rows": 1}
    bad = dict(good, detail={"op": "gt", "rows": 96, "hits": 48,
                             "legacy_ms": 38.1})
    (bench / "results.json").write_text(json.dumps([good, bad]))
    with pytest.raises(ValueError, match="malformed search-latency record"):
        _check_search_records(str(tmp_path))
