"""Atlas geo-distribution tests (dds_tpu/geo + the region plumbing).

Unit layer: WAN profile parsing (presets, ms-spec tables, `a<->b`
expansion, unknown-key rejection), per-region `[retry]` deadline
derivation from `rtt-ms`, region-labeled ShardMaps (signed, wire-compat
with pre-Atlas payloads), the LeaseTable state machine on a fake clock,
the holder-pinned quorum gate, Helmsman's region-death declaration, and
anti-entropy's seeded cross-region peer bias.

Fabric layer: ChaosNet region matrices (resolution precedence, one-way
region partitions with timed heal, seeded determinism), read-local lease
reads on a span constellation (single hop, /health surface), the lease
SAFETY property (a revoked/expired lease NEVER serves a value older than
the last acked write — reads fall back to the full quorum instead), the
holder-death liveness bound (quorums stall at most ~one TTL), placement
modes, and region-preferring standby promotion.

Flagship (slow): a seeded 3-region fleet under WAN latency loses an
entire region mid-load — Helmsman declares `region_down` and promotes
the region-homed group cross-region, anti-entropy converges the
partitioned replicas after heal — while the recorded history stays
linearizable, no acked write on a region-spanning group is lost, and the
Watchtower reports nothing beyond the documented lease-window verdicts.
"""

import asyncio
import json
import random
import time
import types

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.antientropy import AntiEntropy
from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClientConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.fleet import Helmsman
from dds_tpu.geo import wan
from dds_tpu.geo.lease import LeaseTable
from dds_tpu.obs.metrics import metrics
from dds_tpu.obs.watchtower import Watchtower
from dds_tpu.shard import ShardMap, build_constellation
from dds_tpu.utils.config import RetryConfig
from dds_tpu.utils.retry import Deadline, RetryPolicy, retry_deadline
from dds_tpu.utils.trace import Tracer, tracer
from tests.test_core import run
from tests.test_linearizability import Recorder, check_atomic_register

pytestmark = pytest.mark.geo

SECRET = b"intranet-abd-secret"
R3 = ["r0", "r1", "r2"]


def metric_sum(name, **match):
    """Sum a counter family over every label set matching `match`."""
    fam = metrics._families.get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for key, v in fam.samples.items():
        labels = dict(key)
        if all(labels.get(k) == want for k, want in match.items()):
            total += v
    return total


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def geo_constellation(S=2, net=None, seed=7, regions=R3, placement="span",
                      lease_ttl=0.0, client_region="", **kw):
    net = net or InMemoryNet()
    kw.setdefault("n_active", 3)
    kw.setdefault("n_sentinent", 0)
    kw.setdefault("quorum", 2)
    const = build_constellation(
        net, shard_count=S, vnodes_per_group=8, seed=seed,
        regions=list(regions), placement=placement,
        lease_ttl=lease_ttl, client_region=client_region, **kw,
    )
    return const, net


# ------------------------------------------------------- WAN profile loader


def test_wan_presets_scale_one_way_delay():
    f = wan.preset_faults("wan-200")
    assert f.delay == pytest.approx(0.100)          # one-way = RTT/2
    assert f.jitter == pytest.approx(0.020)         # ~10% of RTT
    scaled = wan.preset_faults("wan-300", scale=0.02)
    assert scaled.delay == pytest.approx(0.003)
    with pytest.raises(ValueError):
        wan.preset_faults("wan-9000")


def test_wan_spec_tables_ms_keys_and_rejection():
    f = wan.faults_from_spec({"delay-ms": 120, "jitter-ms": 18, "drop": 0.01})
    assert (f.delay, f.jitter, f.drop) == (pytest.approx(0.120),
                                           pytest.approx(0.018), 0.01)
    # a preset base with one explicit override; scale hits delays only
    f = wan.faults_from_spec({"preset": "wan-100", "drop": 0.2}, scale=0.5)
    assert f.delay == pytest.approx(0.025)
    assert f.drop == 0.2
    with pytest.raises(ValueError):
        wan.faults_from_spec({"delay-ms": 10, "latency": 3})
    with pytest.raises(ValueError):
        wan.faults_from_spec(42)


def test_wan_profile_pairs_and_mesh():
    prof = wan.parse_profiles({"eu<->us": "wan-100",
                               "us->ap": {"delay-ms": 5}})
    assert set(prof) == {("eu", "us"), ("us", "eu"), ("us", "ap")}
    assert prof[("eu", "us")].delay == prof[("us", "eu")].delay
    with pytest.raises(ValueError):
        wan.parse_profiles({"eu/us": "wan-100"})
    m = wan.mesh(R3, "wan-100")
    assert set(m) == {"r0<->r1", "r0<->r2", "r1<->r2"}
    assert wan.parse_profiles(m)[("r2", "r0")].delay == pytest.approx(0.05)


def test_retry_profiles_derive_deadlines_from_rtt():
    rc = RetryConfig(profiles={
        "eu": {"rtt-ms": 100},
        "ap": {"rtt-ms": 200, "request-budget": 9.0},
        "us": {"retry-backoff": 0.05},
    })
    eu = rc.overrides_for("eu")
    assert eu["retry_backoff"] == pytest.approx(0.2)    # 2R
    assert eu["retry_max_delay"] == pytest.approx(0.8)  # 8R
    assert eu["request_budget"] == pytest.approx(2.4)   # 24R
    assert eu["retry_after_hint"] == pytest.approx(0.2)
    # an explicit key wins over its rtt-derived value
    ap = rc.overrides_for("ap")
    assert ap["request_budget"] == 9.0
    assert ap["retry_backoff"] == pytest.approx(0.4)
    assert rc.overrides_for("us") == {"retry_backoff": 0.05}
    assert rc.overrides_for("nowhere") == {}
    with pytest.raises(ValueError):
        RetryConfig(profiles={"eu": {"budget": 1}}).overrides_for("eu")


# --------------------------------------------------- ChaosNet region matrix


def _region_net(seed=3):
    net = ChaosNet(InMemoryNet(), seed=seed)
    got = []

    async def handler(sender, msg):
        got.append((sender, msg))

    for name in ("a0", "a1", "b0"):
        net.register(name, handler)
    net.set_regions({"a0": "A", "a1": "A", "b0": "B"})
    return net, got


def test_region_link_matrix_and_precedence():
    async def go():
        net, got = _region_net()
        net.set_region_link("A", "B", LinkFaults(drop=1.0))
        net.send("a0", "b0", M.ReadTag("k", 1))   # region matrix: dropped
        net.send("a0", "a1", M.ReadTag("k", 2))   # intra-region: clean
        net.send("b0", "a0", M.ReadTag("k", 3))   # no B->A entry: clean
        await net.quiesce()
        assert [m.nonce for _, m in got] == [2, 3]
        # a surgical per-link override still beats the blanket WAN matrix
        net.set_link("a0", "b0", LinkFaults())
        net.send("a0", "b0", M.ReadTag("k", 4))
        await net.quiesce()
        assert [m.nonce for _, m in got] == [2, 3, 4]
        assert net.region_of("a1") == "A"
        assert net.region_members("A") == ["a0", "a1"]
        with pytest.raises(ValueError):
            net.region_partition("pacific")

    run(go())


def test_one_way_region_partition_with_timed_heal():
    async def go():
        net, got = _region_net()
        # asymmetric: B still HEARS the world but cannot answer
        net.region_partition("B", symmetric=False, duration=0.05)
        net.send("b0", "a0", M.ReadTag("k", 1))   # leaving B: cut
        net.send("a0", "b0", M.ReadTag("k", 2))   # into B: delivered
        await net.quiesce()
        assert [m.nonce for _, m in got] == [2]
        assert [r for r in net.trace if r[4] == "partition_drop"]
        await asyncio.sleep(0.08)                  # the timed heal fires
        net.send("b0", "a0", M.ReadTag("k", 3))
        await net.quiesce()
        assert [m.nonce for _, m in got] == [2, 3]
        assert [r for r in net.trace if r[3] == "partition"
                and r[4] == "heal"]

    run(go())


async def _wan_schedule(seed):
    """A fixed send schedule through a lossy WAN matrix + a one-way
    region partition; the trace must be a pure function of the seed."""
    net, got = _region_net(seed=seed)
    wan.apply_profiles(net, {"A<->B": {"preset": "wan-100", "drop": 0.3}},
                       scale=0.01)
    p = None
    for i in range(40):
        if i == 20:
            p = net.region_partition("B", symmetric=False)
        src, dst = ("a0", "b0") if i % 2 else ("b0", "a0")
        net.send(src, dst, M.ReadTag(f"k{i}", i))
    await net.quiesce()
    p.heal()
    return list(net.trace), got


def test_wan_fault_trace_is_seeded_deterministic():
    t1, _ = run(_wan_schedule(11))
    t2, _ = run(_wan_schedule(11))
    t3, _ = run(_wan_schedule(12))
    assert t1 == t2
    assert t1 != t3
    # the cut really was one-way: only traffic LEAVING B partition-drops
    cut = [(r[1], r[2]) for r in t1 if r[4] == "partition_drop"]
    assert cut and all(src == "b0" for src, _ in cut)


# ------------------------------------------------- region-labeled ShardMap


def test_shardmap_region_labels_signed_and_wire_compat():
    m = ShardMap.build(["s0", "s1"], 8,
                       regions={"s0": "r0", "s1": "r1"}).sign(SECRET)
    assert m.verify(SECRET)
    assert m.region_of("s0") == "r0" and m.region_of("s9") == ""
    back = ShardMap.from_wire(m.to_wire())
    assert back.verify(SECRET) and back.region_of("s1") == "r1"
    # labels follow the map through its whole lifecycle
    assert m.split("s0", "s2").region_of("s2") == "r0"
    assert m.merge("s1").region_of("s1") == ""
    assert m.relabel("s0", "s7").region_of("s7") == "r0"
    # relabeling region state invalidates the signature until re-signed
    relabeled = m.with_regions({"s0": "ap", "s1": "eu"})
    assert not relabeled.verify(SECRET)
    assert relabeled.sign(SECRET).verify(SECRET)
    # pre-Atlas byte-compat: an unlabeled map's wire payload carries no
    # `regions` key at all, and unlabeled wire dicts still parse
    plain = ShardMap.build(["s0", "s1"], 8).sign(SECRET)
    assert "regions" not in plain.to_wire()
    assert ShardMap.from_wire(plain.to_wire()).verify(SECRET)


# --------------------------------------------------------- LeaseTable unit


def test_lease_table_grant_revoke_expire_and_forgery():
    clk = _Clock()
    t = LeaseTable("s0", SECRET, clock=clk)
    lease = t.grant("r0", "s0-replica-0", ttl=5.0)
    assert lease.expires == pytest.approx(clk.t + 5.0)
    assert t.valid("r0", "s0-replica-0", lease.token)
    assert t.holders() == frozenset({"s0-replica-0"})
    assert t.held_by("s0-replica-0") and not t.held_by("s0-replica-1")
    assert t.census()["r0"]["replica"] == "s0-replica-0"
    # forged/mismatched tokens never validate
    assert not t.valid("r0", "s0-replica-0", "f" * len(lease.token))
    assert not t.valid("r1", "s0-replica-0", lease.token)
    assert not t.valid("r0", "s0-replica-1", lease.token)
    # a renewal replaces the grant; the OLD token dies with it
    renewed = t.grant("r0", "s0-replica-0", ttl=5.0)
    assert renewed.token != lease.token
    assert not t.valid("r0", "s0-replica-0", lease.token)
    # revocation is immediate
    t.revoke("r0")
    assert not t.valid("r0", "s0-replica-0", renewed.token)
    assert t.holders() == frozenset()
    # expiry is lazy on the table clock
    gone = t.grant("r0", "s0-replica-0", ttl=5.0)
    clk.t += 5.1
    assert not t.valid("r0", "s0-replica-0", gone.token)
    assert t.active("r0") is None
    assert t.holders() == frozenset()


def test_quorum_gate_is_pinned_on_active_holders():
    async def go():
        const, _ = geo_constellation(S=1, lease_ttl=5.0, client_region="r0")
        try:
            g = const.groups[0]
            node = next(iter(g.replicas.values()))
            clk = _Clock()
            g.lease_table.clock = clk
            others = {"s0-replica-1", "s0-replica-2"}
            assert node._quorum_met(others)            # no leases: plain >= q
            g.lease_table.grant("r0", "s0-replica-0", ttl=5.0)
            assert not node._quorum_met(others)        # holder missing
            assert node._quorum_met(others | {"s0-replica-0"})
            g.lease_table.revoke("r0")
            assert node._quorum_met(others)            # unpinned again
            g.lease_table.grant("r0", "s0-replica-0", ttl=5.0)
            clk.t += 5.1                               # TTL bounds the stall
            assert node._quorum_met(others)
        finally:
            await const.stop()

    run(go())


# ------------------------------------------------- read-local lease reads


def test_read_local_lease_serves_in_region_single_hop():
    async def go():
        const, _ = geo_constellation(S=2, lease_ttl=5.0, client_region="r0")
        try:
            r = const.router
            served0 = metric_sum("dds_geo_local_reads_total", result="served")
            await r.write_set("atlas-key", ["v1"])
            assert await r.fetch_set("atlas-key") == ["v1"]
            g = const.group(r.owner("atlas-key"))
            assert g.lease_table.holders()             # the read took a lease
            state = g.client.lease_state()
            assert state and state["region"] == "r0"
            assert state["replica"] in g.lease_table.holders()
            assert metric_sum("dds_geo_local_reads_total",
                              result="served") > served0
            # freshness through the pinned quorum: write-then-read on the
            # SAME lease session returns the new value, not a stale echo
            await r.write_set("atlas-key", ["v2"])
            assert await r.fetch_set("atlas-key") == ["v2"]
            # the lease surfaces on the health plane, with its region
            health = r.shards_health()
            row = health[g.gid]
            assert row["region"] == g.home_region
            assert row["lease"] and row["lease"]["region"] == "r0"
        finally:
            await const.stop()

    run(go())


def test_lease_safety_revoked_or_expired_never_serves_stale():
    """SAFETY property (seeded): interleave acked writes with lease
    revocations and expiries — every read returns exactly the last acked
    write, because a revoked/expired lease degrades to the full quorum
    path instead of serving whatever the ex-holder has."""

    async def one_seed(seed):
        const, _ = geo_constellation(S=1, seed=seed, lease_ttl=50.0,
                                     client_region="r0")
        g = const.groups[0]
        clk = _Clock()
        g.lease_table.clock = clk
        g.client._now = clk
        rng = random.Random(seed)
        last: dict = {}
        refusals = 0
        try:
            for i in range(36):
                clk.t += 0.6                  # time flows between ops
                key = f"k{rng.randrange(3)}"
                roll = rng.random()
                if roll < 0.55:
                    value = [f"s{seed}-{i}"]
                    await g.client.write_set(key, value)
                    last[key] = value
                elif roll < 0.75 and g.lease_table.active("r0"):
                    g.lease_table.revoke("r0")
                    refusals += 1
                elif roll < 0.85:
                    clk.t += 60.0             # past both TTL and session
                got = await g.client.fetch_set(key)
                assert got == last.get(key), (seed, i, key, got, last.get(key))
        finally:
            await const.stop()
        return refusals

    async def go():
        before = metric_sum("dds_geo_local_reads_total")
        fallbacks = metric_sum("dds_geo_local_read_fallbacks_total")
        revoked = 0
        for seed in (101, 202, 303):
            revoked += await one_seed(seed)
        assert revoked > 0                    # the schedule really revoked
        assert metric_sum("dds_geo_local_reads_total") > before
        assert metric_sum("dds_geo_local_read_fallbacks_total") > fallbacks

    run(go())


def test_holder_death_stalls_quorums_at_most_one_ttl():
    """Liveness bound: partitioning the lease holder pins quorums only
    until the table-side TTL lapses — writes stall, then complete."""

    async def go():
        net = ChaosNet(InMemoryNet(), seed=5)
        const, _ = geo_constellation(
            S=1, net=net, lease_ttl=0.6, client_region="r0",
            abd_cfg=AbdClientConfig(quorum_size=2, request_timeout=0.25),
        )
        try:
            g = const.groups[0]
            await g.client.write_set("k", ["v0"])
            assert await g.client.fetch_set("k") == ["v0"]
            holder = next(iter(g.lease_table.holders()))
            p = net.partition([holder])
            t0 = time.monotonic()
            dl = Deadline(6.0)
            await retry_deadline(
                lambda: g.client.write_set("k", ["v1"], deadline=dl),
                dl, RetryPolicy(base=0.05, multiplier=2.0, max_delay=0.2),
                rng=random.Random(1), retry_on=(Exception,),
            )
            elapsed = time.monotonic() - t0
            assert 0.2 < elapsed < 4.0, elapsed
            p.heal()
            # the rejoined ex-holder missed the write (it was acked in the
            # unpinned window — the documented pre-grant residual); one
            # anti-entropy pull repairs it, after which even a freshly
            # granted lease serves the acked value
            peer = next(e for e in g.all_replicas() if e != holder)
            await g.replicas[holder].antientropy.sync_once(peer)
            assert await g.client.fetch_set("k") == ["v1"]
        finally:
            await const.stop()
            await net.quiesce()

    run(go())


# ------------------------------------------------- Watchtower lease audit


def _lease_wt(lease_lookup):
    wt = Watchtower(quorum_size=2, n_replicas=3)
    wt.configure(lease_lookup=lease_lookup)
    t = Tracer()
    wt.attach(t)
    return wt, t


def _commit_write(t, key, seq, tid):
    reps = [f"replica-{i}" for i in range(3)]
    with t.span("http.write"):
        with t.span("abd.write", coordinator="replica-0", ok=True,
                    op="write", key=key, seq=seq, tag_id=tid):
            for r in reps[:2]:
                with t.span("replica.handle", replica=r, msg="ReadTag",
                            key=key):
                    pass
            for r in reps[:2]:
                with t.span("replica.handle", replica=r, msg="Write",
                            key=key):
                    pass


def _lease_read(t, key, seq, tid, replica):
    with t.span("http.read"):
        with t.span("abd.fetch", ok=True, op="read", key=key, seq=seq,
                    tag_id=tid, lease=True, replica=replica):
            pass


def test_watchtower_accepts_clean_lease_read():
    wt, t = _lease_wt(lambda r: r == "replica-1")
    _commit_write(t, "k", 1, "replica-0")
    _lease_read(t, "k", 1, "replica-0", replica="replica-1")
    assert wt.verdicts() == []


def test_watchtower_flags_lease_read_by_non_holder():
    wt, t = _lease_wt(lambda r: r == "replica-1")
    _commit_write(t, "k", 1, "replica-0")
    _lease_read(t, "k", 1, "replica-0", replica="replica-2")  # forged
    assert [v.invariant for v in wt.verdicts()] == ["lease_intersection"]


def test_watchtower_files_stale_lease_read_as_lease_staleness():
    """A stale LEASE read is the documented lease-window bound — it must
    be filed under `lease_staleness`, never escalated to the BFT
    invariants a quorum read would violate."""
    wt, t = _lease_wt(lambda r: True)
    _commit_write(t, "k", 1, "replica-0")
    _commit_write(t, "k", 2, "replica-0")
    _lease_read(t, "k", 1, "replica-0", replica="replica-1")  # trails seq 2
    invariants = {v.invariant for v in wt.verdicts()}
    assert "lease_staleness" in invariants
    assert not invariants & {"read_sees_latest", "tag_monotonicity",
                             "quorum_intersection"}


# ------------------------------------------- Helmsman region-death logic


def test_helmsman_declares_region_down_and_promotes_with_label():
    async def go():
        clock = _Clock()
        census = {"s0": 50, "s1": 50, "s2": 50}
        ages = {"s0": 0.1, "s1": 0.1, "s2": 0.1}
        regions = {"s0": "r0", "s1": "r0", "s2": "r2"}
        promoted = []

        async def promote(gid):
            promoted.append(gid)

        hm = Helmsman(
            load_census=lambda: dict(census),
            slo_alerts=lambda: [],
            shed_level=lambda: 0,
            source_ages=lambda: dict(ages),
            split=lambda g: None,
            merge=lambda g: None,
            promote=promote,
            moved_bytes=lambda: 0,
            reshard_busy=lambda: False,
            regions=lambda: dict(regions),
            clock=clock,
            heartbeat_timeout=5.0,
            cooldown=30.0,
            min_ops=10_000,
        )
        before = metric_sum("dds_helmsman_region_down_total", region="r2")
        await hm.step()                      # learn the census
        clock.t += 60
        # one stale group in a LIVE region: a process crash, not a region
        ages["s0"] = 99.0
        assert await hm.step() == "promote"
        assert promoted == ["s0"]
        assert not any(r["action"] == "region_down" for r in hm.history)
        # the r2-homed group ages out wholesale: region_down + takeover
        clock.t += 120
        ages["s0"], ages["s2"] = 0.1, 99.0
        assert await hm.step() == "promote"
        assert promoted == ["s0", "s2"]
        down = [r for r in hm.history if r["action"] == "region_down"]
        assert down and down[0]["region"] == "r2"
        assert down[0]["groups"] == ["s2"]
        take = [r for r in hm.history if r["action"] == "promote"][-1]
        assert take["dead"] == "s2" and take["region"] == "r2"
        assert metric_sum("dds_helmsman_region_down_total",
                          region="r2") == before + 1
        # heal: fresh heartbeats clear the declaration
        ages["s2"] = 0.1
        await hm.step()
        assert "r2" not in hm._regions_down

    run(go())


# --------------------------------- placement, census, standby preference


def test_placement_modes_census_and_signed_homes():
    async def go():
        const, net = geo_constellation(S=3, placement={"s2": "home"})
        try:
            homes = {g.gid: g.home_region for g in const.groups}
            assert homes == {"s0": "r0", "s1": "r1", "s2": "r2"}
            span, packed = const.group("s0"), const.group("s2")
            assert span.region_census() == {"r0": 1, "r1": 1, "r2": 1}
            assert packed.region_census() == {"r2": 3}
            # homes ride the signed map
            smap = const.manager.current()
            assert smap.verify(SECRET) and smap.region_of("s2") == "r2"
            # every fabric endpoint is labeled (replica, supervisor, client)
            labels = const.regions_of_endpoints()
            assert labels[packed.supervisor.addr] == "r2"
            assert labels[span.client.addr] == "r0"  # span client -> home
            for e in span.all_replicas():
                assert labels[e] in R3
        finally:
            await const.stop()

    run(go())


def test_promotion_prefers_standby_homed_in_dead_groups_region():
    async def go():
        const, _ = geo_constellation(S=3, placement={"s2": "home"})
        extra = []
        try:
            # seed two warm standbys homed in different regions
            sb_r0 = const._acquire_standby(prefer_region="r0")
            sb_r1 = const._acquire_standby(prefer_region="r1")
            assert (sb_r0.home_region, sb_r1.home_region) == ("r0", "r1")
            extra += [sb_r0, sb_r1]
            const.standbys.extend([sb_r0, sb_r1])
            # the takeover picks by geography, not queue order
            assert const._acquire_standby(prefer_region="r1") is sb_r1
            const.standbys.insert(1, sb_r1)
            # no r9 standby exists: fall back to the first in the queue
            assert const._acquire_standby(prefer_region="r9") is sb_r0
            # a real takeover with NO warm standby left: the replacement
            # is built fresh, homed where the dead group lived, and the
            # relabeled slice serves new writes immediately (availability
            # over data)
            const.standbys.clear()
            dead = const.group("s2")
            reborn = await const.promote("s2")
            extra.append(dead)
            assert reborn.home_region == "r2"
            assert const.manager.current().region_of(reborn.gid) == "r2"
            assert "s2" not in const.gids
            key = next(f"K{i}" for i in range(200)
                       if const.router.owner(f"K{i}") == reborn.gid)
            await const.router.write_set(key, ["post-takeover"])
            assert await const.router.fetch_set(key) == ["post-takeover"]
        finally:
            await const.stop()
            for g in extra:
                if g not in const.standbys:
                    await g.stop()

    run(go())


# ------------------------------------------- anti-entropy cross-region


def test_antientropy_cross_region_peer_bias_is_seeded():
    node = types.SimpleNamespace(addr="s0-replica-0", name="s0-replica-0")
    regions = {"s0-replica-0": "r0", "s0-replica-1": "r0",
               "s0-replica-2": "r1", "s0-replica-3": "r2"}
    peers = ["s0-replica-1", "s0-replica-2", "s0-replica-3"]

    def picks(bias, seed=9, n=24):
        ae = AntiEntropy(node)
        ae.configure(rng=random.Random(seed), regions=regions,
                     cross_region_bias=bias)
        return [ae._pick_peer(peers) for _ in range(n)]

    assert all(cross and regions[p] != "r0" for p, cross in picks(1.0))
    assert all(not cross and p == "s0-replica-1" for p, cross in picks(0.0))
    mixed = picks(0.5)
    assert {c for _, c in mixed} == {True, False}
    assert mixed == picks(0.5)               # same seed, same pairing
    assert mixed != picks(0.5, seed=10)
    # geo-unaware fabrics draw uniformly and never report cross
    ae = AntiEntropy(node)
    ae.configure(rng=random.Random(9))
    assert all(not cross for _, cross in
               (ae._pick_peer(peers) for _ in range(8)))


# ------------------------------------------------ flagship: region death


@pytest.mark.slow
def test_region_death_drill_zero_loss_and_only_lease_verdicts():
    """Acceptance (ISSUE 16): a seeded 3-region fleet under WAN latency
    loses region r2 wholesale mid-load. Helmsman declares `region_down`
    and promotes the r2-homed group cross-region; the span groups keep
    serving from the surviving 4-of-6 quorums (their r0 lease holders
    stay pinned INTO every quorum, so leased reads stay fresh through
    the cut); after heal, anti-entropy converges the partitioned
    replicas. The recorded per-key histories linearize, no acked write
    on a span group is lost, and the Watchtower reports nothing beyond
    the documented `lease_staleness` window."""

    async def go():
        net = ChaosNet(InMemoryNet(), seed=0xA71A5)
        const, _ = geo_constellation(
            S=4, net=net, seed=13, placement={"s2": "home"},
            lease_ttl=1.5, client_region="r0",
            n_active=6, quorum=4,
            abd_cfg=AbdClientConfig(quorum_size=4, request_timeout=0.4),
        )
        # the identical mesh topology the benchmark runs at scale=1.0
        wan.apply_profiles(net, wan.mesh(R3, "wan-100"), scale=0.02)
        r = const.router
        doomed = const.group("s2")

        # keys: two span-owned registers under writers, one s2-owned
        # prober key (its data dies with the region — beyond <= f), one
        # fresh post-takeover key on the relabeled slice
        def owned_by(gid, skip=()):
            return next(k for i in range(400)
                        if (k := f"K{i}") not in skip and r.owner(k) == gid)

        span_gids = [g for g in const.gids if g != "s2"]
        wkeys = [owned_by(g) for g in span_gids]
        # the prober beats through FRESH s2-owned keys: pre-death keys die
        # with the region (beyond <= f — the documented loss boundary), so
        # the relabeled group must never REWRITE one (its tag history
        # would regress and trip the auditor on a non-violation)
        doom_pool = [k for i in range(2000)
                     if r.owner(k := f"D{i}") == "s2"][:120]

        counts: dict = {}
        last_ok: dict = {}
        recs = {k: Recorder() for k in wkeys}
        stop = asyncio.Event()
        _POLICY = RetryPolicy(base=0.02, multiplier=2.0, max_delay=0.15)

        def mark(gid):
            last_ok[gid] = time.monotonic()

        async def writer(key, wid):
            w_rng, i = random.Random(40 + wid), 0
            while not stop.is_set():
                value, i = [f"w{wid}-{i}"], i + 1
                gid = r.owner(key)
                counts[gid] = counts.get(gid, 0) + 1
                t0 = time.monotonic()
                dl = Deadline(6.0)
                await retry_deadline(
                    lambda: r.write_set(key, value, deadline=dl),
                    dl, _POLICY, rng=w_rng, retry_on=(Exception,),
                )
                recs[key].record("write", value[0], t0, time.monotonic())
                mark(gid)
                await asyncio.sleep(w_rng.uniform(0.01, 0.04))

        async def reader():
            r_rng = random.Random(77)
            while not stop.is_set():
                key = wkeys[r_rng.randrange(len(wkeys))]
                gid = r.owner(key)
                counts[gid] = counts.get(gid, 0) + 1
                t0 = time.monotonic()
                dl = Deadline(6.0)
                got = await retry_deadline(
                    lambda: r.fetch_set(key, deadline=dl),
                    dl, _POLICY, rng=r_rng, retry_on=(Exception,),
                )
                recs[key].record("read", got[0] if got else None,
                                 t0, time.monotonic())
                mark(gid)
                await asyncio.sleep(r_rng.uniform(0.005, 0.02))

        doom_acks: list = []

        async def doom_prober():
            """Keeps a heartbeat (and a census row) on the r2-homed
            group; its failures after the cut are what age it out."""
            idx = 0
            while not stop.is_set():
                key, idx = doom_pool[idx], idx + 1
                gid = r.owner(key)
                counts[gid] = counts.get(gid, 0) + 1
                try:
                    value = [f"beat-{idx}"]
                    await r.write_set(key, value, deadline=Deadline(0.5))
                    doom_acks.append((key, value))
                    mark(gid)
                except Exception:
                    pass
                await asyncio.sleep(0.12)

        hm = Helmsman(
            load_census=lambda: dict(counts),
            slo_alerts=lambda: [],
            shed_level=lambda: 0,
            source_ages=lambda: {
                g: time.monotonic() - t for g, t in last_ok.items()
                if g in set(const.gids)
            },
            split=const.split,
            merge=const.merge,
            promote=const.promote,
            moved_bytes=lambda: 0,
            reshard_busy=lambda: False,
            regions=lambda: {g.gid: g.home_region for g in const.groups
                             if g.home_region},
            heartbeat_timeout=0.9,
            cooldown=10.0,
            min_ops=10_000,
        )
        hm.pinned = True                     # promotion-only drill

        async def steer():
            while not stop.is_set():
                await hm.step()
                await asyncio.sleep(0.08)

        wt = Watchtower(quorum_size=4, n_replicas=6)
        wt.configure(
            group_geometry={f"s{i}": (4, 6) for i in range(10)},
            lease_lookup=lambda name: any(
                g.lease_table is not None and g.lease_table.held_by(name)
                for g in const.groups
            ),
        )
        wt.attach(tracer)
        partition = None
        try:
            tasks = [asyncio.ensure_future(t) for t in (
                *(writer(k, i) for i, k in enumerate(wkeys)), reader(),
                doom_prober(), steer(),
            )]
            await asyncio.sleep(0.7)          # leases granted, census warm
            assert all(const.group(g).lease_table.holders()
                       for g in span_gids)
            partition = net.region_partition("r2", symmetric=True)

            async def takeover_done():
                while "s2" in const.gids:
                    await asyncio.sleep(0.05)
            await asyncio.wait_for(takeover_done(), timeout=8.0)
            reborn = next(g for g in const.groups if g.home_region == "r2"
                          and g.gid != "s2")
            # the relabeled slice serves new writes while r2 is still
            # dark: the prober's beats start acking again on its own
            n0 = len(doom_acks)

            async def doom_alive():
                while len(doom_acks) <= n0:
                    await asyncio.sleep(0.05)
            await asyncio.wait_for(doom_alive(), timeout=5.0)
            await asyncio.sleep(0.4)          # load continues post-takeover
            stop.set()
            await asyncio.gather(*tasks)

            # heal ONLY the cut — the WAN matrix must survive the drill
            partition.heal()
            assert net.region_links          # mesh still installed
            # converge the rejoining r2 replicas via anti-entropy pulls
            repaired = 0
            for g in const.groups:
                peers = {e: reg for e, reg in g.replica_regions.items()}
                healthy = next(e for e, reg in peers.items() if reg == "r0")
                for e, reg in peers.items():
                    if reg == "r2":
                        repaired += await g.replicas[e].antientropy \
                            .sync_once(healthy)
                roots = {n.merkle.root() for n in g.replicas.values()
                         if not n.crashed} if hasattr(
                             next(iter(g.replicas.values())), "crashed") \
                    else {n.merkle.root() for n in g.replicas.values()}
                assert len(roots) == 1, f"{g.gid} diverged after heal"
            assert repaired > 0              # the cut really caused drift

            # zero lost acked writes + per-key linearizability
            for key in wkeys:
                ops = recs[key].ops
                writes = [o for o in ops if o["kind"] == "write"]
                assert writes, key
                t0 = time.monotonic()
                final = await r.fetch_set(key)
                recs[key].record("read", final[0] if final else None,
                                 t0, time.monotonic())
                assert final == [writes[-1]["value"]], (key, final)
                check_atomic_register(recs[key].ops)
            # the last doom beat ACKED on the reborn group is durable too
            dkey, dvalue = doom_acks[-1]
            assert r.owner(dkey) == reborn.gid
            assert await r.fetch_set(dkey) == dvalue

            # the controller told the story the drill scripted
            actions = [row["action"] for row in hm.history]
            down = [row for row in hm.history
                    if row["action"] == "region_down"]
            assert down and down[0]["region"] == "r2"
            take = next(row for row in hm.history
                        if row["action"] == "promote" and row["dead"] == "s2")
            assert take["region"] == "r2"
            assert "split" not in actions and "merge" not in actions

            # only the documented lease-window verdicts, nothing BFT
            invariants = {v.invariant for v in wt.verdicts()}
            assert invariants <= {"lease_staleness"}, sorted(invariants)
        finally:
            wt.detach()
            if partition is not None:
                partition.heal()
            stop.set()
            await const.stop()
            await doomed.stop()
            await net.quiesce()

    run(go())


# ----------------------------------------------------------------- sentry


def test_sentry_check_parses_geo_records(tmp_path):
    from benchmarks.sentry import _check_geo_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "geo latency",
        "value": 2.41, "unit": "x", "vs_baseline": 2.41,
        "detail": {
            "local_p95_ms": 4.1, "quorum_p95_ms": 104.2,
            "reads": 400, "leased_reads": 310, "fallbacks": 24,
            "revoked_mid_run": True, "stale_reads": 0,
            "wan_preset": "wan-100",
        },
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_geo_records(str(tmp_path)) == {"rows": 1}
    # a geo row must prove the speedup came from leases (leased reads,
    # both p95s), that revocation was exercised, and that NO read was
    # stale — a row that can't say so is malformed
    for broken in (
        dict(good, value=-1),
        dict(good, detail=dict(good["detail"], stale_reads=1)),
        dict(good, detail=dict(good["detail"], revoked_mid_run=False)),
        dict(good, detail=dict(good["detail"], leased_reads=None)),
        dict(good, detail={"local_p95_ms": 1.0}),
        dict(good, detail=dict(good["detail"], wan_preset="lan")),
    ):
        (bench / "results.json").write_text(json.dumps([good, broken]))
        with pytest.raises(ValueError):
            _check_geo_records(str(tmp_path))
    # other record families are ignored by this checker
    (bench / "results.json").write_text(
        json.dumps([{"metric": "autoscale goodput", "value": -1}])
    )
    assert _check_geo_records(str(tmp_path)) == {"rows": 0}
