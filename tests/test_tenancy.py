"""Bastion tenant-isolation unit tests: crypto domains and edges.

Four layers, none needing a live fleet:

- `core.tenant.validate_tenant`: the wire-supplied tenant label is
  bounded and typed-rejected BEFORE it can key any server-side state;
- `models.tenancy.TenantKeyring`: per-tenant key families — lazy
  onboarding, rotation with a grace window (re-encrypt-on-read), and
  crypto-shredding as deletion, including the scrub-under-churn drill
  (rotation and shred racing in-flight decrypt traffic) and the
  gc/weakref residue check the Sanctum suite established;
- the metrics registry's cardinality cap at its exact boundary (the
  satellite: per-tenant labels must never be a memory DoS);
- Bulwark's Bastion additions on a fake clock: weighted-fair bucket
  contraction under contention and burn-driven tenant self-shedding;
- the `tenant isolation` benchmark record contract in sentry --check.
"""

import gc
import json
import threading
import weakref

import pytest

from dds_tpu.core.admission import AdmissionController
from dds_tpu.core.tenant import (
    DEFAULT_TENANT,
    TenantError,
    validate_tenant,
)
from dds_tpu.models.tenancy import (
    TenantKeyError,
    TenantKeyring,
    TenantShredded,
)
from dds_tpu.obs.metrics import OVERFLOW_COUNTER, OVERFLOW_LABEL, Registry

pytestmark = pytest.mark.tenancy

BITS = 256  # tiny primes: lifecycle math, not crypto strength


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _keyring(**kw) -> tuple[TenantKeyring, FakeClock]:
    clk = FakeClock()
    kw.setdefault("paillier_bits", BITS)
    kw.setdefault("rsa_bits", 512)
    kw.setdefault("grace", 60.0)
    return TenantKeyring(clock=clk, **kw), clk


# ------------------------------------------------------- header validation


def test_validate_tenant_empty_and_none_map_to_default():
    assert validate_tenant(None) == DEFAULT_TENANT
    assert validate_tenant("") == DEFAULT_TENANT


@pytest.mark.parametrize("name", [
    "acme", "ACME-corp", "t.0", "a" * 64, "9lives", "x_y-z.w",
])
def test_validate_tenant_accepts_bounded_names(name):
    assert validate_tenant(name) == name


@pytest.mark.parametrize("raw,reason_part", [
    ("a" * 65, "longer than 64"),     # over-length
    ("-leading", "must match"),       # must start alphanumeric
    (".hidden", "must match"),
    ("sp ace", "must match"),
    ('quo"te', "must match"),
    ("new\nline", "must match"),
    ("nul\x00", "must match"),
    ("ümlaut", "must match"),
])
def test_validate_tenant_rejects_typed(raw, reason_part):
    with pytest.raises(TenantError) as ei:
        validate_tenant(raw)
    # the typed error carries the raw value (truncated for over-length
    # inputs) and a reason the REST edge serializes into its 400 body
    assert ei.value.raw.startswith(raw[:16])
    assert reason_part in ei.value.reason
    assert isinstance(ei.value, ValueError)


# ------------------------------------------------------- keyring lifecycle


def test_keyring_lazy_onboard_and_roundtrip():
    kr, _clk = _keyring()
    ct, ver = kr.encrypt("acme", 41)
    assert ver == 1 and kr.version("acme") == 1
    assert kr.decrypt("acme", ct, ver) == 41
    assert kr.known("acme") and not kr.known("ghost")
    with pytest.raises(TenantKeyError):
        kr._domain("ghost", create=False)


def test_tenants_never_share_a_modulus():
    kr, _clk = _keyring()
    assert kr.keys_for("a").psse.n != kr.keys_for("b").psse.n
    # per-tenant HMAC secrets differ too (transport signing domain)
    assert kr.hmac_secret("a") != kr.hmac_secret("b")


def test_rotation_grace_window_reencrypt_on_read():
    kr, clk = _keyring(grace=60.0)
    ct1, v1 = kr.encrypt("acme", 7)
    assert kr.rotate("acme") == 2
    # inside grace: the old epoch still decrypts, reencrypt migrates
    assert kr.decrypt("acme", ct1, v1) == 7
    ct2, v2, migrated = kr.reencrypt("acme", ct1, v1)
    assert migrated and v2 == 2
    assert kr.decrypt("acme", ct2, v2) == 7
    # an already-current ciphertext is handed back unchanged
    same, ver, migrated = kr.reencrypt("acme", ct2, v2)
    assert same == ct2 and ver == 2 and not migrated
    # hmac family rotates with the epoch
    kr2, _ = _keyring()
    assert kr.hmac_secret("acme") != kr2.hmac_secret("acme")
    # past grace: the old epoch is typed-refused, the new one lives on
    clk.advance(61.0)
    with pytest.raises(TenantKeyError):
        kr.decrypt("acme", ct1, v1)
    assert kr.decrypt("acme", ct2, v2) == 7


def test_shred_is_terminal_typed_and_idempotent():
    kr, _clk = _keyring()
    ct, ver = kr.encrypt("acme", 3)
    kr.rotate("acme")
    summary = kr.shred("acme")
    assert summary == {"tenant": "acme", "already": False,
                       "epochs_scrubbed": 2}
    for op in (lambda: kr.keys_for("acme"),
               lambda: kr.decrypt("acme", ct, ver),
               lambda: kr.encrypt("acme", 1),
               lambda: kr.rotate("acme"),
               lambda: kr.hmac_secret("acme")):
        with pytest.raises(TenantShredded):
            op()
    assert kr.is_shredded("acme") and not kr.known("acme")
    assert kr.shred("acme")["already"] is True
    # other tenants are untouched — the blast radius IS one tenant
    assert kr.decrypt("b", kr.encrypt("b", 5)[0]) == 5
    stats = kr.stats()
    assert stats["shredded"] == 1 and stats["tenants"] == 2


def test_shred_leaves_no_reachable_key_state():
    """The Sanctum residue discipline applied to a whole tenant domain:
    after shred(), no strong reference to the tenant's PaillierKey (or
    its HEKeys wrapper) survives inside the keyring, so gc reclaims the
    secret material."""
    kr, _clk = _keyring()
    keys = kr.keys_for("acme")
    kr.rotate("acme")
    refs = [weakref.ref(keys), weakref.ref(keys.psse),
            weakref.ref(kr.keys_for("acme")),
            weakref.ref(kr.keys_for("acme").psse)]
    del keys
    kr.shred("acme")
    gc.collect()
    assert all(r() is None for r in refs)


def test_keyring_capacity_is_typed_refusal():
    kr, _clk = _keyring(max_tenants=2)
    kr.keys_for("a")
    kr.keys_for("b")
    with pytest.raises(TenantKeyError, match="full"):
        kr.keys_for("c")


def test_scrub_under_churn_rotation_and_shred_race_decrypts():
    """Satellite 3: rotation and crypto-shredding race in-flight decrypt
    traffic from worker threads. Every decrypt either returns the right
    plaintext or raises a TYPED refusal (TenantShredded/TenantKeyError)
    — never garbage, never an untyped crash — and after the dust
    settles the shredded tenant is terminally refused while the control
    tenant still works."""
    kr, _clk = _keyring(grace=60.0)
    ct, ver = kr.encrypt("victim", 11)
    control_ct, control_ver = kr.encrypt("control", 22)
    stop = threading.Event()
    outcomes: list[str] = []
    errors: list[BaseException] = []

    def churn():
        while not stop.is_set():
            try:
                got = kr.decrypt("victim", ct, ver)
                if got != 11:  # wrong-epoch garbage would be a real bug
                    errors.append(AssertionError(f"garbage decrypt {got}"))
                    return
                outcomes.append("ok")
            except (TenantShredded, TenantKeyError):
                outcomes.append("refused")
            except BaseException as e:  # noqa: BLE001 - the assertion
                errors.append(e)
                return

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(3):
            kr.rotate("victim")
        kr.shred("victim")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert "refused" in outcomes or outcomes.count("ok") > 0
    with pytest.raises(TenantShredded):
        kr.decrypt("victim", ct, ver)
    assert kr.decrypt("control", control_ct, control_ver) == 22


# ------------------------------------------------- metrics cardinality cap


def test_registry_cap_boundary_folds_new_labels_into_overflow():
    reg = Registry(max_series=3)
    for t in ("a", "b", "c"):
        reg.inc("dds_x_total", tenant=t)
    # AT the cap: every existing series is intact and still writable
    assert reg.value("dds_x_total", tenant="a") == 1
    reg.inc("dds_x_total", tenant="a")
    assert reg.value("dds_x_total", tenant="a") == 2
    # one past the cap: the new label folds into the overflow series and
    # the overflow counter names the family
    reg.inc("dds_x_total", tenant="d")
    assert reg.value("dds_x_total", tenant="d") is None
    assert reg.value("dds_x_total", tenant=OVERFLOW_LABEL) == 1
    assert reg.value(OVERFLOW_COUNTER, family="dds_x_total") == 1
    # repeat offenders keep folding; existing series keep passing through
    reg.inc("dds_x_total", tenant="e")
    assert reg.value("dds_x_total", tenant=OVERFLOW_LABEL) == 2
    reg.inc("dds_x_total", tenant="b")
    assert reg.value("dds_x_total", tenant="b") == 2
    # the cap is per family, not global
    reg.inc("dds_y_total", tenant="d")
    assert reg.value("dds_y_total", tenant="d") == 1


# ------------------------------------------- Bulwark Bastion: fair + burn


def _bulwark(clk, **kw):
    state = {"alerts": set()}
    kw.setdefault("rates", {"aggregate": (8.0, 8.0)})
    c = AdmissionController(
        eval_interval=1.0,
        alerts=lambda: state["alerts"],
        clock=clk,
        **kw,
    )
    return c, state


def test_weighted_fair_contracts_buckets_under_contention():
    clk = FakeClock()
    c, _state = _bulwark(clk, tenant_weights={"gold": 3.0},
                         default_weight=1.0)
    # both tenants demand far over the 8/s class rate in one window
    for _ in range(20):
        c.decide("SumAll", tenant="gold")
        c.decide("SumAll", tenant="lead")
    clk.advance(1.0)
    c.evaluate()
    gold = c._bucket("gold", 1)
    lead = c._bucket("lead", 1)
    # contention: refill contracts to the weight share of the class rate
    assert gold.rate == pytest.approx(6.0)
    assert lead.rate == pytest.approx(2.0)
    # demand subsides -> work-conserving restore to the full class rate
    clk.advance(1.0)
    c.evaluate()
    assert gold.rate == pytest.approx(8.0)
    assert lead.rate == pytest.approx(8.0)


def test_burn_shed_is_scoped_to_the_burning_tenant():
    clk = FakeClock()
    c, state = _bulwark(clk, rates={})
    # the noisy tenant owns the window's bad outcomes; the SLO alert fires
    for _ in range(6):
        c.decide("SumAll", tenant="noisy")
        c.note_outcome("noisy", "aggregate", good=False)
    c.decide("SumAll", tenant="quiet")
    c.note_outcome("quiet", "aggregate", good=True)
    state["alerts"] = {"SumAll"}
    clk.advance(1.0)
    c.evaluate()
    assert c.shed_tenants() == ["noisy"]
    # the fleet ratchet HELD: distress was one tenant's, not everyone's
    assert c.shed_level == 0
    d = c.decide("SumAll", tenant="noisy")
    assert not d.admitted and d.status == 429 and "burn-driven" in d.reason
    assert c.decide("SumAll", tenant="quiet").admitted
    assert c.decide("GetSet", tenant="noisy").admitted  # interactive exempt
    # burn stops -> hysteresis ages the shed out after tenant_shed_hold
    state["alerts"] = set()
    for _ in range(c.tenant_shed_hold):
        clk.advance(1.0)
        c.evaluate()
    assert c.shed_tenants() == []
    assert c.decide("SumAll", tenant="noisy").admitted
    dirs = [t["direction"] for t in c.tenant_transitions]
    assert dirs == ["shed", "unshed"]


def test_default_tenant_burn_ratchets_the_fleet_not_itself():
    clk = FakeClock()
    c, state = _bulwark(clk, rates={})
    for _ in range(6):
        c.decide("SumAll")
        c.note_outcome("default", "aggregate", good=False)
    state["alerts"] = {"SumAll"}
    clk.advance(1.0)
    c.evaluate()
    # single-tenant deployments: "default" IS the fleet — the global
    # ratchet handles it, self-shedding would be a self-DoS
    assert c.shed_tenants() == []
    assert c.shed_level == 1


def test_tenant_tracking_is_bounded_by_overflow_identity():
    clk = FakeClock()
    c, _state = _bulwark(clk, max_tracked_tenants=2)
    assert c._track("a") == "a"
    assert c._track("b") == "b"
    assert c._track("z") == "overflow"
    assert c._track("a") == "a"  # known tenants keep their identity


# ------------------------------------------------- benchmark record contract


def _tenant_row(**over):
    detail = {
        "victim_p95_base_ms": 3.2, "victim_p95_flood_ms": 3.4,
        "degradation_pct": 6.2, "flooder_requests": 240,
        "flooder_429": 200, "tenants": 5, "open_loop": True,
    }
    detail.update(over)
    return {"metric": "tenant isolation victim p95", "value": 3.4,
            "unit": "ms", "vs_baseline": 1.06, "detail": detail}


def test_sentry_check_parses_tenant_isolation_records(tmp_path):
    from benchmarks.sentry import _check_tenant_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    (bench / "results.json").write_text(json.dumps([_tenant_row()]))
    assert _check_tenant_records(str(tmp_path)) == {"rows": 1}

    for bad in (
        _tenant_row(victim_p95_base_ms=0),
        _tenant_row(flooder_requests=0),
        _tenant_row(flooder_429=300),      # more 429s than requests
        _tenant_row(tenants=1),
        _tenant_row(open_loop=False),
        {"metric": "tenant isolation victim p95", "value": 3.4},  # no detail
    ):
        (bench / "results.json").write_text(json.dumps([bad]))
        with pytest.raises(ValueError, match="tenant-isolation"):
            _check_tenant_records(str(tmp_path))

    # foreign records are not this family's problem
    (bench / "results.json").write_text(json.dumps([{"metric": "other"}]))
    assert _check_tenant_records(str(tmp_path)) == {"rows": 0}
