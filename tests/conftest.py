"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware is single-chip in this environment; multi-chip sharding is
validated on forced host-platform devices (see also __graft_entry__.py's
dryrun_multichip, which the driver runs the same way).

Must run before the first `import jax` anywhere in the test process.
"""

import os
import sys
import pathlib

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize may import jax before this file runs (it
# registers the TPU plugin for every interpreter), in which case jax has
# already captured JAX_PLATFORMS from the parent env — override via config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
